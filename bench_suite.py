"""The five BASELINE.md benchmark configs plus two beyond-BASELINE
full-loop configs, end to end.

  1. single cpu-stress pod, 3-node sim cluster, default policy
  2. 1k pods / 1k nodes, cpu+mem avg_5m priority weights only
  3. 10k pods / 10k nodes, full predicate+priority+hotValue policy
  4. 50k nodes with 12 syncPolicy metrics, streaming annotation refresh
  5. 100k-pod burst gang-schedule, mesh-sharded across all devices
  6. full loop (columnar burst) at 10k AND 50k nodes, parity-gated
  7. kube-boundary loop through a stub apiserver (mirror + patch storm)
  8. bind-burst write path: round-5 serial vs pipelined multi-connection
     through the same wire stub (POST-safety asserted by the stub)
  9. read path: 50k-node mirror bootstrap/relist + cold store ingest +
     watch-storm apply, round-6 per-object decode vs columnar streaming
     decode + coalesced apply (mirror parity asserted across legs)
 10. serving path: closed-loop concurrent /v1/score clients against a
     live sidecar at 5k AND 50k nodes, r07 serving (HTTP/1.0
     connection-per-request + per-request refresh + per-node render
     loop) vs the keep-alive coalesced/cached front end (verdict
     parity and response byte-identity asserted in-run)
 11. closed placement control loop through the wire stub: induced
     hotspot, annotate -> descheduler evicts (budgeted, gated) ->
     drip scheduler re-places -> next sweep observes the move;
     no-descheduler vs descheduler legs in the same process, >=2x
     max/mean utilization-imbalance reduction gated, stub eviction
     oracle (no daemonset/system victims, no duplicate POSTs)
 12. chaos soak: scripted Prometheus outage through the breaker +
     degraded-mode controller, recovery time vs a no-resilience leg
 13. placement e2e latency over the wire stub, lifecycle-tracked
     first-seen -> watch-confirm with traceparent on every bind POST
 14. columnar drip storm: 1k schedule_one+bind cycles at 5k/50k
     nodes, scalar plugin loop vs version-cached columns; placement
     prefix parity, stub bind oracle, >=100x per-pod gate at 50k
 15. device-resident drip batch engine through the wire stub
 16. kill-recover soak over the bind-intent journal + warm standby
 17. overload storm: seeded open-loop 3x-capacity storm through the
     admission-controlled async front end at 5k AND 50k nodes —
     goodput >= 80% of pre-storm peak, accepted p99 <= 2x unloaded,
     zero expired requests at device dispatch, /healthz 200
     throughout, deterministic shed/admit replay
 18. sharded placement plane: 250k-node mirror split across 1/2/4
     concurrent drip schedulers (deterministic node shards, per-shard
     version fences, optimistic bind conflict resolution) — O(dirty)
     column refresh after a named patch, >=1.8x/3x storm throughput
     on disjoint shards, <=5% conflict rate on overlapping shards
     with a per-pod bind POST oracle, shard_map kernel parity on a
     forced 8-device mesh
 19. replicated scoring tier: 50k-node primary + delta-stream feed +
     N shared-nothing serving replicas behind the consistent-hash
     router — >=3x storm goodput vs the in-run single-replica
     baseline, byte-identical verdicts at the same version fence
 20. fleet observability plane over the replicated tier: 1 Hz
     federation under the storm (goodput within 3% of the unscraped
     leg), /fleet/metrics strict-parsed with role labels, SLO
     burn-rate kill/heal round-trip, timelines identical across two
     same-seed runs

Each config reports a JSON line to stdout with wall-clock timings, and
(once the suite's overhead meter is up) the in-run telemetry scrape
overhead as telemetry_overhead_pct, gated < 3% per row.
Configs 1-3 run the full loop (annotator sync through real annotation
strings -> bulk ingest -> score -> assign -> bind). Config 4 measures the
streaming refresh path (string parse + H2D) separately from the scoring
step. Config 5 is the headline (same as bench.py).

Usage: python bench_suite.py [--device cpu|default] [--configs 1,...,7]
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import time

import numpy as np


def log(msg):
    print(msg, file=sys.stderr, flush=True)


_ENV_META = None


def env_meta():
    """Shard-scaling runs must be self-describing: device mesh shape,
    device/host counts, and platform ride along in every result blob so
    numbers from different machines (1-device laptop CI vs forced
    8-device host mesh vs a real TPU slice) are comparable at a
    glance."""
    global _ENV_META
    if _ENV_META is None:
        import jax

        from crane_scheduler_tpu.parallel.mesh import (
            make_placement_mesh,
            mesh_shape,
        )

        _ENV_META = {
            "device_count": jax.device_count(),
            "host_count": jax.process_count(),
            "platform": jax.devices()[0].platform,
            "mesh": mesh_shape(make_placement_mesh()),
        }
    return dict(_ENV_META)


class TelemetryOverheadMeter:
    """In-run cost of being observed (ISSUE 17): a MetricsFederator
    scrapes THIS bench process's registry over the real wire at 1 Hz
    for the whole suite; every ``emit()`` row reports the scrape wall
    seconds spent since the previous row as a percentage of the window
    (``telemetry_overhead_pct``), gated < 3% like PR 2's bar."""

    GATE_PCT = 3.0

    def __init__(self):
        import threading

        from crane_scheduler_tpu.service.http import HealthServer
        from crane_scheduler_tpu.telemetry import Telemetry
        from crane_scheduler_tpu.telemetry.fleet import (
            MetricsFederator,
            ScrapeTarget,
            register_build_info,
        )

        tel = Telemetry()
        register_build_info(tel.registry, "bench", set_role=False)
        self.server = HealthServer(port=0, telemetry=tel)
        self.server.start()
        self.federator = MetricsFederator(
            [ScrapeTarget("bench", port=self.server.port, role="bench")]
        )
        self._lock = threading.Lock()
        self._scrape_s = 0.0
        self._window_t0 = time.perf_counter()
        self._window_scrape0 = 0.0
        self._stop = threading.Event()
        threading.Thread(
            target=self._pump, name="bench-overhead-meter", daemon=True
        ).start()

    def _pump(self):
        while not self._stop.wait(1.0):
            t0 = time.perf_counter()
            try:
                self.federator.scrape_once()
            except Exception:
                pass
            with self._lock:
                self._scrape_s += time.perf_counter() - t0

    def pct(self) -> float:
        """Scrape cost as % of wall time since the last call (one
        emit-to-emit window), then reset the window."""
        now = time.perf_counter()
        with self._lock:
            wall = now - self._window_t0
            scrape = self._scrape_s - self._window_scrape0
            self._window_t0 = now
            self._window_scrape0 = self._scrape_s
        if wall <= 0:
            return 0.0
        return 100.0 * scrape / wall

    def stop(self):
        self._stop.set()
        self.server.stop()


_METER: TelemetryOverheadMeter | None = None


def emit(payload):
    env = env_meta()
    # configs that run N concurrent schedulers set "schedulers" in
    # their payload; everything else is the classic single loop
    env["schedulers"] = payload.pop("schedulers", 1)
    # replicated-tier configs (ISSUE 16) set the serving replica count
    # and router mode; every row records them so a replicated number
    # can never be mistaken for a single-process one
    env["replicas"] = payload.pop("replicas", 0)
    env["router"] = payload.pop("router", None)
    payload.setdefault("env", env)
    if _METER is not None:
        pct = round(_METER.pct(), 3)
        payload.setdefault("telemetry_overhead_pct", pct)
        assert pct < TelemetryOverheadMeter.GATE_PCT, \
            f"telemetry overhead gate: {pct}% >= " \
            f"{TelemetryOverheadMeter.GATE_PCT}%"
    print(json.dumps(payload), flush=True)


def engage_sync_mode():
    """Force a device->host fetch so axon's block_until_ready stops lying
    (it is a no-op until the process's first fetch), then measure the
    per-sync round-trip with a trivial kernel."""
    import jax
    import jax.numpy as jnp

    tiny = jax.jit(lambda x: x + 1)
    x = jnp.zeros((8,), jnp.int32)
    int(tiny(x)[0])  # fetch -> truthful timing from here on
    rtt = []
    for _ in range(10):
        t0 = time.perf_counter()
        jax.block_until_ready(tiny(x))
        rtt.append((time.perf_counter() - t0) * 1e3)
    return float(np.median(rtt))


def _sim(n_nodes, policy=None, seed=0):
    from crane_scheduler_tpu.policy import DEFAULT_POLICY
    from crane_scheduler_tpu.sim import SimConfig, Simulator

    sim = Simulator(SimConfig(n_nodes=n_nodes, seed=seed), policy=policy or DEFAULT_POLICY)
    sim.sync_metrics()
    return sim


def config1(dtype):
    """BASELINE config 1 as a parity run: the reference-shaped plugin
    scheduler and the TPU batch path must pick the same node for the
    canonical cpu-stress pod (ref: examples/cpu_stress.yaml e2e check,
    README.md:155-197)."""
    sim = _sim(3)
    sched = sim.build_scheduler()
    pod = sim.make_pod(cpu_milli=1000, mem=1 << 30)
    t0 = time.perf_counter()
    result = sched.schedule_one(pod)
    ms = (time.perf_counter() - t0) * 1e3
    # TPU path on an identical twin cluster: same placement, bit-for-bit
    twin = _sim(3)
    batch = twin.build_batch_scheduler(dtype=dtype, bucket=8)
    twin_pod = twin.make_pod(cpu_milli=1000, mem=1 << 30)
    batch_result = batch.schedule_batch([twin_pod], bind=True)
    batch_node = batch_result.assignments.get(twin_pod.key())
    emit({"config": 1, "desc": "1 cpu-stress pod, 3 nodes, default policy",
          "node": result.node, "latency_ms": round(ms, 3),
          "tpu_batch_node": batch_node,
          "parity": "ok" if batch_node == result.node else "FAIL"})


def _policy_cpu_mem_5m():
    from crane_scheduler_tpu.policy.types import (
        DynamicSchedulerPolicy, PolicySpec, PriorityPolicy, SyncPolicy,
    )

    return DynamicSchedulerPolicy(spec=PolicySpec(
        sync_period=(SyncPolicy("cpu_usage_avg_5m", 180.0),
                     SyncPolicy("mem_usage_avg_5m", 180.0)),
        priority=(PriorityPolicy("cpu_usage_avg_5m", 0.5),
                  PriorityPolicy("mem_usage_avg_5m", 0.5)),
    ))


def _run_batch(sim, n_pods, dtype, rtt, bucket=2048):
    batch = sim.build_batch_scheduler(dtype=dtype, bucket=bucket)
    pods = [sim.make_pod() for _ in range(n_pods)]
    t0 = time.perf_counter()
    batch.schedule_batch(pods, bind=False)
    warm_ms = (time.perf_counter() - t0) * 1e3  # includes refresh+compile
    lat = []
    for _ in range(5):
        t0 = time.perf_counter()
        result = batch.schedule_batch(pods, bind=False)
        lat.append((time.perf_counter() - t0) * 1e3)
    steady = float(np.median(lat))
    parity = _batch_parity(batch, result, n_pods)
    # schedule_batch performs exactly one device fetch; on the tunneled dev
    # runtime that sync costs `rtt` ms that no local deployment pays
    return result, warm_ms, steady, max(steady - rtt, 0.0), parity


def _batch_parity(batch, result, n_pods) -> str:
    """Device verdicts + per-node placement counts vs the exact f64/Go
    host path on the same store snapshot (computed, not assumed; shared
    gate: crane_scheduler_tpu.scorer.parity)."""
    from crane_scheduler_tpu.scorer.parity import ParityError, check_placement_parity

    snap = batch.store.snapshot()
    now = result.now  # the time the device actually scored at
    names = snap.node_names
    n = snap.n_nodes
    index = {name: i for i, name in enumerate(names)}
    got = np.zeros(n, np.int64)
    for node in result.assignments.values():
        got[index[node]] += 1
    try:
        check_placement_parity(
            values=snap.values[:n], ts=snap.ts[:n],
            hot_value=snap.hot_value[:n], hot_ts=snap.hot_ts[:n],
            node_valid=snap.node_valid[:n], now=now, tensors=batch.tensors,
            schedulable=np.asarray([result.schedulable[m] for m in names]),
            scores=np.asarray([result.scores[m] for m in names]),
            counts=got, num_pods=n_pods,
            unassigned=len(result.unassigned),
        )
    except ParityError as e:
        return f"FAIL: {e}"
    return "ok"


def config2(dtype, rtt):
    sim = _sim(1000, policy=_policy_cpu_mem_5m(), seed=2)
    result, warm, steady, exec_ms, parity = _run_batch(sim, 1000, dtype, rtt)
    emit({"config": 2, "desc": "1k pods / 1k nodes, cpu+mem avg_5m weights",
          "assigned": len(result.assignments), "first_ms": round(warm, 1),
          "steady_ms": round(steady, 2), "minus_rtt_ms": round(exec_ms, 2),
          "parity": parity})


def config3(dtype, rtt):
    sim = _sim(10_000, seed=3)
    result, warm, steady, exec_ms, parity = _run_batch(
        sim, 10_000, dtype, rtt, bucket=16384
    )
    emit({"config": 3, "desc": "10k pods / 10k nodes, full policy",
          "assigned": len(result.assignments), "first_ms": round(warm, 1),
          "steady_ms": round(steady, 2), "minus_rtt_ms": round(exec_ms, 2),
          "parity": parity})


def _amortized_step_ms(step, prepared, num_pods, rtt, batches=8, k=20):
    """Per-step device execution: K enqueued steps drained by one sync."""
    import jax

    samples = []
    for _ in range(batches):
        t0 = time.perf_counter()
        for _ in range(k):
            result = step(prepared, num_pods)
        jax.block_until_ready(result.counts)
        samples.append(max((time.perf_counter() - t0) * 1e3 - rtt, 1e-3) / k)
    return samples, result


def config4(dtype, rtt):
    from crane_scheduler_tpu.policy import compile_policy, load_policy_from_file
    from crane_scheduler_tpu.loadstore import NodeLoadStore
    from crane_scheduler_tpu.parallel import ShardedScheduleStep, make_node_mesh
    from crane_scheduler_tpu.utils import format_local_time

    policy = load_policy_from_file("deploy/dynamic/policy-12metrics.yaml")
    tensors = compile_policy(policy)
    n = 50_000
    now = time.time()
    rng = np.random.default_rng(4)
    ts_str = format_local_time(now)
    log(f"config4: building {n} nodes x {tensors.num_metrics} metric annotations")
    annos = []
    for i in range(n):
        annos.append((f"node-{i:05d}", {
            m: f"{rng.uniform(0, 1):.5f},{ts_str}" for m in tensors.metric_names
        }))
    store = NodeLoadStore(tensors, initial_capacity=n)
    t0 = time.perf_counter()
    store.bulk_ingest(annos)
    ingest_ms = (time.perf_counter() - t0) * 1e3
    t0 = time.perf_counter()
    snap = store.snapshot()
    snapshot_ms = (time.perf_counter() - t0) * 1e3
    import jax

    step = ShardedScheduleStep(tensors, make_node_mesh(1), dtype=dtype)
    t0 = time.perf_counter()
    prepared = step.prepare(snap, now)
    jax.block_until_ready(prepared.values)
    upload_ms = (time.perf_counter() - t0) * 1e3
    burst = 10_000
    int(step(prepared, burst).unassigned)  # compile + fetch
    lat, result = _amortized_step_ms(step, prepared, burst, rtt)

    # steady-state streaming refresh: one full annotator-style sweep as
    # column writes, replayed against the resident arrays (per-column
    # [N] uploads + scalar timestamps) instead of re-uploading matrices
    node_names = [name for name, _ in annos]
    rng2 = np.random.default_rng(44)

    def sweep(t):
        for metric in tensors.metric_names:
            # scalar ts: bulk_set_by_name broadcasts it (uniform sweep)
            store.bulk_set_by_name(metric, node_names, rng2.uniform(0, 1, n), t)

    def column_entries(v):
        # guarded like the production path (scheduler._prepare): a broken
        # version chain or layout change means no column replay
        cols = store.column_delta_since(v)
        assert cols is not None, "column log chain broke mid-bench"
        _, layout, entries = cols
        assert layout == store.layout_version
        return entries

    v = store.version
    sweep(now + 60.0)
    prepared = step.apply_columns(prepared, column_entries(v), n)  # compile
    jax.block_until_ready(prepared.values)
    column_ms = []
    for k in range(3):
        v = store.version
        sweep(now + 120.0 + k)
        entries = column_entries(v)
        t0 = time.perf_counter()
        prepared = step.apply_columns(prepared, entries, n)
        jax.block_until_ready(prepared.values)
        column_ms.append((time.perf_counter() - t0) * 1e3)
    emit({"config": 4,
          "desc": "50k nodes x 12 metrics streaming refresh + score",
          "bulk_ingest_ms": round(ingest_ms, 1),
          "snapshot_ms": round(snapshot_ms, 1),
          "upload_ms": round(upload_ms, 1),
          "column_refresh_ms": round(float(np.median(column_ms)), 1),
          "step_ms_median": round(float(np.median(lat)), 3),
          "schedulable": int(np.asarray(result.schedulable).sum())})


def config5(dtype, rtt):
    import jax

    from crane_scheduler_tpu.loadstore.store import DeviceSnapshot
    from crane_scheduler_tpu.parallel import ShardedScheduleStep, make_node_mesh
    from crane_scheduler_tpu.policy import DEFAULT_POLICY, compile_policy

    tensors = compile_policy(DEFAULT_POLICY)
    n, p = 50_000, 100_000
    now = time.time()
    rng = np.random.default_rng(5)
    snap = DeviceSnapshot(
        values=rng.uniform(0, 1, size=(n, tensors.num_metrics)),
        ts=np.full((n, tensors.num_metrics), now - 30.0),
        hot_value=rng.integers(0, 3, size=(n,)).astype(np.float64),
        hot_ts=np.full((n,), now - 30.0),
        node_valid=np.ones((n,), dtype=bool),
        n_nodes=n,
        node_names=(),
    )
    mesh = make_node_mesh(len(jax.devices()))
    step = ShardedScheduleStep(tensors, mesh, dtype=dtype)
    prepared = step.prepare(snap, now, capacity=np.full((n,), 110, dtype=np.int64))
    t0 = time.perf_counter()
    result = step(prepared, p)
    first_unassigned = int(result.unassigned)  # compile + real fetch
    first = (time.perf_counter() - t0) * 1e3
    lat, result = _amortized_step_ms(step, prepared, p, rtt, batches=12, k=25)
    emit({"config": 5,
          "desc": "100k-pod burst gang-schedule, mesh-sharded",
          "devices": len(jax.devices()),
          "first_ms": round(first, 1),
          "p50_ms": round(float(np.percentile(lat, 50)), 3),
          "p99_ms": round(float(np.percentile(lat, 99)), 3),
          "unassigned_first": first_unassigned,
          "assigned": int(np.asarray(result.counts).sum())})


def _burst_parity(batch, result, n_pods) -> str:
    """Burst-path placement parity vs the exact f64/Go host path on the
    same store snapshot (arrays, no per-pod dict materialization)."""
    from crane_scheduler_tpu.scorer.parity import ParityError, check_placement_parity

    snap = batch.store.snapshot()
    n = snap.n_nodes
    idx = np.asarray(result.node_idx)
    counts = np.bincount(idx[idx >= 0], minlength=n).astype(np.int64)
    try:
        check_placement_parity(
            values=snap.values[:n], ts=snap.ts[:n],
            hot_value=snap.hot_value[:n], hot_ts=snap.hot_ts[:n],
            node_valid=snap.node_valid[:n], now=result.now,
            tensors=batch.tensors,
            schedulable=np.asarray(result.schedulable_row),
            scores=np.asarray(result.scores_row),
            counts=counts, num_pods=n_pods,
            unassigned=int((idx < 0).sum()),
        )
    except ParityError as e:
        return f"FAIL: {e}"
    return "ok"


def config6(dtype, rtt, node_scales=(10_000, 50_000)):
    """Beyond BASELINE: FULL-LOOP sustained throughput in columnar burst
    mode, at 10k AND 50k nodes. Each cycle pays everything a real
    deployment pays on one box: device filter+score+gang solve, the
    packed fetch (pipelined, depth 4), columnar bind application
    (``ClusterState.bind_burst``), Scheduled-event feedback into the
    binding heap (columnar delivery), the deferred annotation-contract
    flush, and a bulk annotator sync (direct-store mode) every cycle —
    the reference syncs each metric every 3m-3h (policy.yaml), so
    per-cycle is the worst case. Placements are parity-gated against the
    f64/Go host path before the timed loop."""
    from crane_scheduler_tpu.framework.scheduler import BatchScheduler

    for n_nodes in node_scales:
        # two bursts per sync cycle: the reference's scores are static
        # between annotator syncs (metrics re-sync every 3m-3h), so
        # scheduling several bursts per sweep is its real operating
        # shape; per-sweep-per-cycle remains far above real cadence
        pods_per_cycle, bursts_per_sync, cycles = 100_000, 2, 6
        sim = _sim(n_nodes, seed=6)
        ann = sim.annotator
        ann.config.bulk_sync = True
        ann.config.direct_store = True
        batch = BatchScheduler(
            sim.cluster, sim.policy, dtype=dtype, clock=sim.clock,
            snapshot_bucket=16384, refresh_from_cluster=False,
        )
        ann.attach_store(batch.store)
        ann.sync_all_once_bulk(sim.clock())

        seq = [0]

        def make_names():
            base = seq[0] * pods_per_cycle
            seq[0] += 1
            return [f"bench6-{base + i}" for i in range(pods_per_cycle)]

        # parity gate on the live store state (bind=False probe), then
        # warm the compiled path with one bound burst
        probe = batch.schedule_pod_burst("bench", make_names(), bind=False)
        parity = _burst_parity(batch, probe, pods_per_cycle)
        for _ in batch.schedule_bursts_pipelined(
            [("bench", make_names())], bind=True
        ):
            pass

        phase = {"sync": 0.0, "flush": 0.0}

        def cycle_stream():
            for _ in range(cycles):
                t0 = time.perf_counter()
                ann.sync_all_once_bulk(sim.clock())  # feedback -> store
                phase["sync"] += time.perf_counter() - t0
                t0 = time.perf_counter()
                ann.flush_annotations()  # annotation contract catch-up
                phase["flush"] += time.perf_counter() - t0
                for _ in range(bursts_per_sync):
                    yield ("bench", make_names())

        t0 = time.perf_counter()
        assigned = 0
        for result in batch.schedule_bursts_pipelined(cycle_stream(), bind=True):
            assigned += result.n_assigned
        wall = time.perf_counter() - t0
        emit({"config": 6,
              "desc": "full loop, columnar burst: solve+fetch+bind+events+"
                      "hot-values+annotator sync+annotation flush "
                      f"({n_nodes} nodes, {pods_per_cycle} pods/burst, "
                      f"{bursts_per_sync} bursts/sync cycle, pipelined)",
              "cycles": cycles,
              "assigned": assigned,
              "parity": parity,
              "wall_s": round(wall, 2),
              "pods_per_sec": round(assigned / wall),
              "ms_per_cycle": round(wall / cycles * 1e3, 1),
              "sync_ms_per_cycle": round(phase["sync"] / cycles * 1e3, 1),
              "flush_ms_per_cycle": round(phase["flush"] / cycles * 1e3, 1)})


def _load_kube_stub():
    import importlib.util
    import os

    stub_path = os.path.join(os.path.dirname(__file__), "tests", "kube_stub.py")
    spec = importlib.util.spec_from_file_location("kube_stub", stub_path)
    kube_stub = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(kube_stub)
    return kube_stub


def _client_write_ceiling(kube_stub, n_writes=20_000, workers=4,
                          force_pool=False):
    """Client write-path ceiling: hammer a null-responder apiserver
    (separate process, near-zero server CPU). This is the number that
    shows the FRAMEWORK's client is not the cap when the stub-bound
    rate below it is lower — round-4 VERDICT item 1's done-criterion.
    ``force_pool=True`` disables the native C++ flush engine AND the
    Python pipelined fan-out so the round-5-comparable pooled-writer
    ceiling is measured."""
    from crane_scheduler_tpu.cluster.kube import KubeClusterClient

    null = kube_stub.KubeStubSubprocess(null=True)
    try:
        c = KubeClusterClient(null.url, concurrent_syncs=workers)
        if force_pool:
            c._native_flush_disabled = True
            c._pipeline_disabled = True
        per_node = {
            f"node-{i:05d}": {"m": "0.5,ts", "m2": "0.6,ts"}
            for i in range(n_writes)
        }
        t0 = time.perf_counter()
        patched = c.patch_node_annotations_bulk(per_node)
        dt = time.perf_counter() - t0
        c.stop()
        return round(patched / dt)
    finally:
        null.stop()


def _tls_patch_rate(kube_stub, n_nodes=5_000, passes=3, workers=4):
    """Annotation-flush rate over TLS (the production transport —
    client-go always talks https, ref: options.go:91-136): since round
    6 this rides the PYTHON PIPELINED fan-out over ssl-wrapped
    keep-alive sockets (the native engine is plain-http only), so the
    https path inherits the pipelining win too."""
    import ssl

    from crane_scheduler_tpu.cluster.kube import KubeClusterClient

    server = kube_stub.KubeStubSubprocess(tls=True)
    try:
        server.seed(n_nodes, "node-")
        ctx = ssl.create_default_context(cafile=kube_stub.STUB_CERT_PATH)
        c = KubeClusterClient(server.url, context=ctx,
                              concurrent_syncs=workers)
        per_node = {
            f"node-{i:05d}": {"m": "0.5,ts", "m2": "0.6,ts"}
            for i in range(n_nodes)
        }
        rates = []
        c.patch_node_annotations_bulk(per_node)  # warm (handshakes)
        for _ in range(passes):
            t0 = time.perf_counter()
            patched = c.patch_node_annotations_bulk(per_node)
            rates.append(patched / (time.perf_counter() - t0))
        c.stop()
        rates.sort()
        return round(rates[len(rates) // 2])
    finally:
        server.stop()


def config7(dtype, rtt):
    """Kube-boundary full loop: everything crosses a real HTTP apiserver
    (the stub from tests/kube_stub.py) running in its OWN process, so
    client and server don't share a GIL and the split is measurable.
    Reports the mirror costs the reference pays through client-go —
    paginated list bootstrap, rv-resumed reconnect (O(delta), no
    relist) — a full annotation sweep landing as concurrent pooled
    merge-PATCHes (one per node per sweep vs the reference's
    2x|nodes|x|syncPolicy| serial patch storm, node.go:123-146), a
    dedicated binding-subresource burst, the full loop, and the client
    write ceiling vs a null responder (proving the framework's client
    is not the cap — the stub is)."""
    from crane_scheduler_tpu.annotator import AnnotatorConfig, NodeAnnotator
    from crane_scheduler_tpu.cluster import Pod
    from crane_scheduler_tpu.cluster.kube import KubeClusterClient
    from crane_scheduler_tpu.framework.scheduler import BatchScheduler
    from crane_scheduler_tpu.metrics import FakeMetricsSource
    from crane_scheduler_tpu.policy import DEFAULT_POLICY

    kube_stub = _load_kube_stub()
    n_nodes, pods_per_cycle, cycles = 5000, 500, 3
    concurrent_syncs = 4
    server = kube_stub.KubeStubSubprocess()
    try:
        server.seed(n_nodes, "node-")
        client = KubeClusterClient(server.url, concurrent_syncs=concurrent_syncs)
        t0 = time.perf_counter()
        client.start()
        bootstrap_ms = (time.perf_counter() - t0) * 1e3

        # rv-resumed reconnect cost: one delta, no relist. Warm the
        # stream first (deliver something + live >= 1s) so the client's
        # healthy-stream immediate-reconnect path is measured, not the
        # deliberate cold-stream backoff sleep. The relist counter
        # snapshots after warm-up: each watcher's INITIAL list (events,
        # NRT) completes asynchronously after start() returns.
        server.add_node("node-warm", "10.9.9.8")
        while client.get_node("node-warm") is None:
            time.sleep(0.005)
        time.sleep(1.1)
        relists_initial = client.relists
        server.close_watches()
        server.add_node("node-extra", "10.9.9.9")
        t0 = time.perf_counter()
        while client.get_node("node-extra") is None:
            time.sleep(0.005)
        reconnect_ms = (time.perf_counter() - t0) * 1e3
        relists_after_reconnect = client.relists - relists_initial

        fake = FakeMetricsSource()
        metric_names = [sp.name for sp in DEFAULT_POLICY.spec.sync_period]
        for i in range(n_nodes):
            ip = f"10.{(i >> 16) & 255}.{(i >> 8) & 255}.{i & 255}"
            for m in metric_names:
                fake.set(m, ip, 0.1 + 0.8 * (i % 97) / 97, by="ip")
        ann = NodeAnnotator(client, fake, DEFAULT_POLICY,
                            AnnotatorConfig(bulk_sync=True, direct_store=True))
        ann.event_ingestor.start()
        batch = BatchScheduler(client, DEFAULT_POLICY, dtype=dtype,
                               snapshot_bucket=8192, refresh_from_cluster=False)
        ann.attach_store(batch.store)
        ann.sync_all_once_bulk()

        # annotation flush: N>=3 passes, median/best (VERDICT item 3).
        # Rate counted in HTTP PATCHes (one per node per sweep), from
        # the stub's request log — not annotation keys. The default
        # path rides the native C++ flush engine; a forced-Python-pool
        # pass is measured alongside for the comparison row.
        flush_rates = []
        for _ in range(3):
            ann.sync_all_once_bulk()
            before = server.stats()["requests"].get("PATCH", 0)
            t0 = time.perf_counter()
            ann.flush_annotations()  # one merge-PATCH per node
            dt = time.perf_counter() - t0
            patches = server.stats()["requests"].get("PATCH", 0) - before
            flush_rates.append(patches / dt)
        # python PIPELINED path (https-environment twin), then the
        # round-5 pooled writers (both flags off = serial pool)
        pipe_rates = []
        client._native_flush_disabled = True
        for _ in range(3):
            ann.sync_all_once_bulk()
            before = server.stats()["requests"].get("PATCH", 0)
            t0 = time.perf_counter()
            ann.flush_annotations()
            dt = time.perf_counter() - t0
            patches = server.stats()["requests"].get("PATCH", 0) - before
            pipe_rates.append(patches / dt)
        pool_rates = []
        client._pipeline_disabled = True
        for _ in range(3):
            ann.sync_all_once_bulk()
            before = server.stats()["requests"].get("PATCH", 0)
            t0 = time.perf_counter()
            ann.flush_annotations()
            dt = time.perf_counter() - t0
            patches = server.stats()["requests"].get("PATCH", 0) - before
            pool_rates.append(patches / dt)
        client._native_flush_disabled = False
        client._pipeline_disabled = False
        client._native_flusher = None

        # dedicated bind burst through the binding subresource
        bind_n = 2000
        bind_pods_list = [
            Pod(name=f"bindburst-{i}", namespace="bench") for i in range(bind_n)
        ]
        for pod in bind_pods_list:
            client.add_pod(pod)
        t0 = time.perf_counter()
        bound = client.bind_pods(
            [(p.key(), f"node-{i % n_nodes:05d}")
             for i, p in enumerate(bind_pods_list)]
        )
        binds_per_sec = round(len(bound) / (time.perf_counter() - t0))

        seq = [0]

        def full_cycle() -> int:
            ann.sync_all_once_bulk()
            ann.flush_annotations()
            names = [f"kube-{seq[0] * pods_per_cycle + i}"
                     for i in range(pods_per_cycle)]
            seq[0] += 1
            pods = [Pod(name=n, namespace="bench") for n in names]
            for pod in pods:
                client.add_pod(pod)  # POST /pods (arrival through the API)
            result = batch.schedule_batch(pods, bind=True)  # binding POSTs
            return len(result.assignments)

        full_cycle()  # warmup: compile the batch step OUTSIDE the wall
        t0 = time.perf_counter()
        assigned = 0
        for _ in range(cycles):
            assigned += full_cycle()
        wall = time.perf_counter() - t0

        # burst-mode loop through the SAME apiserver: columnar burst
        # create + bind via KubeClusterClient's burst contract
        # (round-5: kube burst API), sync+flush per cycle like above
        def burst_stream():
            for c in range(cycles):
                ann.sync_all_once_bulk()
                ann.flush_annotations()
                base = (c + 100) * pods_per_cycle
                yield ("bench", [f"kburst-{base + i}"
                                 for i in range(pods_per_cycle)])

        for _ in batch.schedule_bursts_pipelined(
            [("bench", [f"kburst-w{i}" for i in range(pods_per_cycle)])],
            bind=True,
        ):
            pass  # warm the burst path
        t0 = time.perf_counter()
        burst_assigned = sum(
            r.n_assigned
            for r in batch.schedule_bursts_pipelined(burst_stream(), bind=True)
        )
        burst_wall = time.perf_counter() - t0
        client.stop()
        ceiling = _client_write_ceiling(kube_stub, workers=concurrent_syncs)
        ceiling_pool = _client_write_ceiling(
            kube_stub, workers=concurrent_syncs, force_pool=True
        )
        tls_rate = _tls_patch_rate(kube_stub, n_nodes=n_nodes,
                                   workers=concurrent_syncs)
        rates = sorted(flush_rates)
        emit({"config": 7,
              "desc": "kube-boundary loop via subprocess stub apiserver "
                      f"({n_nodes}-node mirror; {pods_per_cycle} pods/cycle "
                      "through binding subresource; "
                      f"concurrent_syncs={concurrent_syncs})",
              "mirror_bootstrap_ms": round(bootstrap_ms, 1),
              "reconnect_delta_ms": round(reconnect_ms, 1),
              "relists_after_reconnect": relists_after_reconnect,
              "patches_per_sec_median": round(rates[len(rates) // 2]),
              "patches_per_sec_best": round(rates[-1]),
              "patches_per_sec_python_pipelined": round(
                  sorted(pipe_rates)[len(pipe_rates) // 2]),
              "patches_per_sec_python_pool": round(
                  sorted(pool_rates)[len(pool_rates) // 2]),
              "patches_per_sec_tls_pool": tls_rate,
              "binds_per_sec": binds_per_sec,
              "client_write_ceiling_per_sec": ceiling,
              "client_write_ceiling_python_pool": ceiling_pool,
              "cycles": cycles,
              "assigned": assigned,
              "pods_per_sec_through_api": round(assigned / wall),
              "pods_per_sec_through_api_burst": round(
                  burst_assigned / burst_wall),
              "note": "through-API rates are bound by the single-process "
                      "Python stub apiserver, not the client: the native "
                      "flush ceiling vs the null responder is the "
                      "client's own cap"})
    finally:
        server.stop()


def config7b(dtype, rtt):
    """Round-4 VERDICT item 2: the kube boundary at north-star scale.
    50k-node mirror bootstrap (paginated lists), rv-resumed reconnect,
    one full 12-metric annotation sweep flushed as pooled concurrent
    merge-PATCHes, and a 500-pod-per-cycle bind loop — all against the
    subprocess stub. Mirror memory and bootstrap time reported (the
    informer machinery this replaces: factory.go:16-33)."""
    import resource

    from crane_scheduler_tpu.annotator import AnnotatorConfig, NodeAnnotator
    from crane_scheduler_tpu.cluster import Pod
    from crane_scheduler_tpu.cluster.kube import KubeClusterClient
    from crane_scheduler_tpu.framework.scheduler import BatchScheduler
    from crane_scheduler_tpu.metrics import FakeMetricsSource
    from crane_scheduler_tpu.policy import load_policy_from_file

    kube_stub = _load_kube_stub()
    policy = load_policy_from_file("deploy/dynamic/policy-12metrics.yaml")
    n_nodes, pods_per_cycle, cycles = 50_000, 500, 3
    concurrent_syncs = 4
    server = kube_stub.KubeStubSubprocess()
    try:
        t0 = time.perf_counter()
        server.seed(n_nodes, "node-")
        seed_ms = (time.perf_counter() - t0) * 1e3
        rss_before = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss

        client = KubeClusterClient(
            server.url, concurrent_syncs=concurrent_syncs,
            list_page_limit=2000,
        )
        t0 = time.perf_counter()
        client.start()
        bootstrap_ms = (time.perf_counter() - t0) * 1e3
        rss_after = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        log(f"config7b: 50k mirror bootstrap {bootstrap_ms:.0f}ms, "
            f"client RSS delta ~{(rss_after - rss_before) / 1024:.0f}MB")

        # rv-resumed reconnect at scale: one delta, no 50k relist
        server.add_node("node-warm", "10.9.9.8")
        while client.get_node("node-warm") is None:
            time.sleep(0.005)
        time.sleep(1.1)
        relists_initial = client.relists
        server.close_watches()
        server.add_node("node-extra", "10.9.9.9")
        t0 = time.perf_counter()
        while client.get_node("node-extra") is None:
            time.sleep(0.005)
        reconnect_ms = (time.perf_counter() - t0) * 1e3
        relists_after_reconnect = client.relists - relists_initial

        fake = FakeMetricsSource()
        metric_names = [sp.name for sp in policy.spec.sync_period]
        ips = [f"10.{(i >> 16) & 255}.{(i >> 8) & 255}.{i & 255}"
               for i in range(n_nodes)]
        rng = np.random.default_rng(7)
        for m in metric_names:
            col = {ip: f"{v:.5f}"
                   for ip, v in zip(ips, rng.uniform(0, 1, n_nodes))}
            fake.set_column(m, lambda col=col: dict(col))
        ann = NodeAnnotator(client, fake, policy,
                            AnnotatorConfig(bulk_sync=True, direct_store=True))
        ann.event_ingestor.start()
        batch = BatchScheduler(client, policy, dtype=dtype,
                               snapshot_bucket=8192, refresh_from_cluster=False)
        ann.attach_store(batch.store)

        t0 = time.perf_counter()
        ann.sync_all_once_bulk()
        sweep_ms = (time.perf_counter() - t0) * 1e3
        before = server.stats()["requests"].get("PATCH", 0)
        t0 = time.perf_counter()
        ann.flush_annotations()  # 50k merge-PATCHes, 12+ keys each
        flush_s = time.perf_counter() - t0
        patched = server.stats()["requests"].get("PATCH", 0) - before
        log(f"config7b: sweep {sweep_ms:.0f}ms, flush {patched} patches "
            f"in {flush_s:.1f}s = {patched / flush_s:,.0f}/s")

        seq = [0]

        def full_cycle() -> int:
            names = [f"kube-{seq[0] * pods_per_cycle + i}"
                     for i in range(pods_per_cycle)]
            seq[0] += 1
            pods = [Pod(name=n, namespace="bench") for n in names]
            for pod in pods:
                client.add_pod(pod)
            result = batch.schedule_batch(pods, bind=True)
            return len(result.assignments)

        full_cycle()  # warmup: compile the batch step OUTSIDE the wall
        t0 = time.perf_counter()
        assigned = 0
        for _ in range(cycles):
            assigned += full_cycle()
        wall = time.perf_counter() - t0
        client.stop()
        stats = server.stats()
        emit({"config": "7b",
              "desc": "kube boundary at 50k nodes x 12 metrics "
                      f"(subprocess stub; concurrent_syncs={concurrent_syncs})",
              "seed_ms": round(seed_ms, 1),
              "mirror_bootstrap_ms": round(bootstrap_ms, 1),
              "client_rss_delta_mb": round((rss_after - rss_before) / 1024, 1),
              "stub_maxrss_mb": round(stats.get("maxrss_kb", 0) / 1024, 1),
              "reconnect_delta_ms": round(reconnect_ms, 1),
              "relists_after_reconnect": relists_after_reconnect,
              "sweep_ms": round(sweep_ms, 1),
              "flush_patches": patched,
              "patches_per_sec": round(patched / flush_s),
              "cycles": cycles,
              "assigned": assigned,
              "pods_per_sec_through_api": round(assigned / wall)})
    finally:
        server.stop()


def config8(dtype, rtt):
    """Round-6 tentpole gate: bind-burst pods/s through the SAME wire
    stub, before vs after the pipelined multi-connection write path.

    Four legs, each a fresh subprocess stub + mirror-started client
    (watches running — the full informer cost rides the same core),
    binding 4000 pods through the binding subresource:

      r05_pool        — Python pooled writers (round-5 slow path)
      r05_native      — serial native engine, workers=max(syncs,8)
                        (the exact round-5 shipped default, convoy
                        collapse included)
      pipelined_python— Python pipelined fan-out (the https-path twin)
      pipelined_native— pipelined native engine (the new default;
                        headline ``binds_per_sec``)

    The stub is the POST-safety oracle: ``duplicate_binds`` must be 0
    in every leg (no bind is ever double-POSTed). 3 passes per leg,
    median reported (best kept as a field)."""
    from crane_scheduler_tpu.cluster.kube import KubeClusterClient
    from crane_scheduler_tpu.native.httpflush import NativeHTTPFlusher

    kube_stub = _load_kube_stub()
    n_nodes, n_pods, passes = 1000, 4000, 3
    concurrent_syncs = 4

    def leg(configure):
        server = kube_stub.KubeStubSubprocess()
        try:
            server.seed(n_nodes, "node-")
            client = KubeClusterClient(
                server.url, concurrent_syncs=concurrent_syncs
            )
            client.start()
            configure(client)
            rates = []
            for p in range(passes):
                ns = f"bb{p}"
                handle = client.add_pod_burst(
                    ns, [f"p{i}" for i in range(n_pods)]
                )
                assert not handle.failed, "stub refused creations"
                pairs = [
                    (f"{ns}/p{i}", f"node-{i % n_nodes:05d}")
                    for i in range(n_pods)
                ]
                t0 = time.perf_counter()
                bound = client.bind_pods(pairs)
                dt = time.perf_counter() - t0
                assert len(bound) == n_pods, f"only {len(bound)} bound"
                rates.append(len(bound) / dt)
            stats = server.stats()
            client.stop()
            rates.sort()
            assert stats.get("duplicate_binds", 0) == 0, "double-POSTed bind!"
            return {
                "median": round(rates[len(rates) // 2]),
                "best": round(rates[-1]),
                "bind_posts": stats.get("bind_posts", 0),
                "duplicate_binds": stats.get("duplicate_binds", 0),
            }
        finally:
            server.stop()

    def r05_pool(client):
        client._native_flush_disabled = True
        client._pipeline_disabled = True

    def r05_native(client):
        # the round-5 shipped default: serial engine, workers floor 8
        client._pipeline_disabled = True
        client._native_flusher = NativeHTTPFlusher(
            client._host, client._port or 80,
            workers=max(concurrent_syncs, 8), timeout=client._timeout,
        )

    def pipelined_python(client):
        client._native_flush_disabled = True

    legs = {
        "r05_pool": leg(r05_pool),
        "r05_native": leg(r05_native),
        "pipelined_python": leg(pipelined_python),
        "pipelined_native": leg(lambda c: None),
    }
    # "round-5 pods/s" = what round-5's SHIPPED code does on this stub:
    # a >=128 bind batch rode the serial native engine (workers>=8
    # floor included). The forced-pool leg is recorded too, and the
    # conservative ratio against the best r05 path ships alongside.
    before = legs["r05_native"]["median"]
    before_best = max(legs["r05_pool"]["median"], before)
    after = legs["pipelined_native"]["median"]
    emit({"config": 8,
          "desc": "bind-burst write path through the wire stub: "
                  f"{n_pods} binding POSTs, {n_nodes}-node mirror with "
                  "watches running, before (round-5 serial) vs after "
                  "(pipelined multi-connection)",
          "binds_per_sec": after,
          "binds_per_sec_r05_default": before,
          "binds_per_sec_best_r05_path": before_best,
          "speedup_vs_r05": round(after / max(before, 1), 2),
          "speedup_vs_best_r05_path": round(after / max(before_best, 1), 2),
          "legs": legs,
          "duplicate_binds": sum(
              v["duplicate_binds"] for v in legs.values()),
          "note": "duplicate_binds asserted 0 by the stub in every leg "
                  "(no bind is ever double-POSTed); r05_native is the "
                  "exact round-5 default incl. its workers>=8 floor; "
                  "r05_pool is the forced non-default slow path"})


def config9(dtype, rtt, n_nodes=50_000, storm_events=20_000):
    """Round-7 tentpole gate: the READ path through the wire stub,
    before (round-6 per-object LIST decode, one mirror transaction per
    watch event) vs after (columnar streaming decode, coalesced apply).

    One stub subprocess seeded with ``n_nodes`` nodes x 12 wire-shaped
    metric annotations; two sequential clients over the same state:

      r06_object — ``_list_decode_disabled`` + ``_coalesce_disabled``
                   (the exact round-6 shipped read path)
      columnar   — the new default (native streaming decode when the
                   .so is present, Python twin otherwise)

    Per leg: mirror bootstrap (client.start(): paginated LIST ->
    mirror), cold store ingest (BatchScheduler.refresh(); the columnar
    leg must be served by the decoded columns, asserted), a forced
    node relist, and a ``storm_events``-node MODIFIED watch storm
    (applied events/s, measured at the client's mirror). Decode parity
    is asserted in-run: both legs' mirrors must be annotation-identical
    node for node."""
    from crane_scheduler_tpu.cluster.kube import KubeClusterClient
    from crane_scheduler_tpu.framework.scheduler import BatchScheduler
    from crane_scheduler_tpu.policy import load_policy_from_file

    kube_stub = _load_kube_stub()
    policy = load_policy_from_file("deploy/dynamic/policy-12metrics.yaml")
    metric_names = [sp.name for sp in policy.spec.sync_period]
    legs = {}
    parity_sample = {}
    seed_ms = 0.0
    # every leg gets a FRESH stub subprocess (config8's methodology): a
    # reused stub carries the previous leg's abandoned watch handlers
    # for up to their idle timeout, which perturbs the storm leg
    import gc

    for mode in ("r06_object", "columnar"):
        server = kube_stub.KubeStubSubprocess()
        # keep the interpreter+jax baseline heap out of the collector's
        # generational scans: the legs measure decode and apply, not
        # gen2 sweeps over a 300MB jax runtime — applied identically to
        # both legs (and standard practice for serving processes)
        gc.collect()
        gc.freeze()
        try:
            t0 = time.perf_counter()
            server.seed(n_nodes, "node-", metrics=metric_names)
            seed_ms = (time.perf_counter() - t0) * 1e3
            client = KubeClusterClient(server.url, list_page_limit=2000)
            if mode == "r06_object":
                client._list_decode_disabled = True
                client._coalesce_disabled = True
            t0 = time.perf_counter()
            client.start()
            bootstrap_ms = (time.perf_counter() - t0) * 1e3

            batch = BatchScheduler(client, policy, dtype=dtype,
                                   snapshot_bucket=8192)
            t0 = time.perf_counter()
            batch.refresh()
            store_ingest_ms = (time.perf_counter() - t0) * 1e3
            columnar_served = batch.refresh_stats["columnar_ingest"]
            if mode == "columnar":
                assert columnar_served == 1, \
                    "columnar leg fell back to the object path"
            assert len(batch.store) == n_nodes

            # steady-state relist: one warm-up pass absorbs the one-time
            # post-bootstrap gen2 collection (measured ~4x the steady
            # cost), then median of 3
            client._relist_nodes()
            relist_passes = []
            for _ in range(3):
                t0 = time.perf_counter()
                client._relist_nodes()
                relist_passes.append((time.perf_counter() - t0) * 1e3)
            relist_ms = sorted(relist_passes)[1]

            # storm oracle: MIRROR CONVERGENCE, not the applied counter —
            # a mid-storm reconnect may recover part of the storm via a
            # 410 relist, which is correct behavior the counter misses.
            # The last full round over the node cycle defines the final
            # annotation value per node.
            final = {
                f"node-{i % n_nodes:05d}": str(i)
                for i in range(storm_events)
            }
            sample = list(final.items())
            sample = sample[:: max(1, len(sample) // 499)]

            def converged():
                for name, want in sample:
                    node = client.get_node(name)
                    if node is None or node.annotations.get(
                        "crane.io/storm"
                    ) != want:
                        return False
                return True

            t0 = time.perf_counter()
            server.storm("nodes", storm_events)
            deadline = time.time() + 300
            while not converged():
                if time.time() > deadline:
                    raise RuntimeError("watch storm never converged")
                time.sleep(0.01)
            storm_s = time.perf_counter() - t0

            # parity oracle: both legs' mirrors end annotation-identical
            sample_names = [f"node-{i:05d}"
                            for i in range(0, n_nodes, n_nodes // 997)]
            parity_sample[mode] = {
                name: dict(client.get_node(name).annotations)
                for name in sample_names
            }
            legs[mode] = {
                "bootstrap_ms": round(bootstrap_ms, 1),
                "store_ingest_ms": round(store_ingest_ms, 1),
                "columnar_refreshes": columnar_served,
                "relist_ms": round(relist_ms, 1),
                "watch_storm_events_per_sec": round(storm_events / storm_s),
                "watch_batches": client.watch_batches,
                "watch_coalesced": client.watch_coalesced,
                "relists": client.relists,
            }
            log(f"config9[{mode}]: bootstrap {bootstrap_ms:.0f}ms, "
                f"ingest {store_ingest_ms:.0f}ms, relist {relist_ms:.0f}ms, "
                f"storm {storm_events / storm_s:,.0f} ev/s")
            client.stop()
        finally:
            server.stop()
            gc.unfreeze()  # the leg's own objects must stay collectable
    # both legs replay the identical storm over identical seeds, so
    # the mirrors must match exactly — the in-run parity gate
    assert parity_sample["r06_object"] == parity_sample["columnar"], \
        "read-path parity violation: mirrors diverged between legs"
    before, after = legs["r06_object"], legs["columnar"]
    emit({"config": 9,
          "desc": "read path through the wire stub: "
                  f"{n_nodes}-node x {len(metric_names)}-metric "
                  "mirror bootstrap/relist + cold store ingest + "
                  f"{storm_events}-event watch storm, round-6 "
                  "per-object path vs columnar decode + coalesced "
                  "apply (same stub, same run)",
          "seed_ms": round(seed_ms, 1),
          "bootstrap_ms": after["bootstrap_ms"],
          "relist_ms": after["relist_ms"],
          "store_ingest_ms": after["store_ingest_ms"],
          "watch_storm_events_per_sec":
              after["watch_storm_events_per_sec"],
          "speedup_bootstrap": round(
              before["bootstrap_ms"] / max(after["bootstrap_ms"], 1e-9),
              2),
          "speedup_relist": round(
              before["relist_ms"] / max(after["relist_ms"], 1e-9), 2),
          "speedup_store_ingest": round(
              before["store_ingest_ms"]
              / max(after["store_ingest_ms"], 1e-9), 2),
          "speedup_watch_storm": round(
              after["watch_storm_events_per_sec"]
              / max(before["watch_storm_events_per_sec"], 1), 2),
          "legs": legs,
          "mirror_parity": "ok",
          "note": "r06_object reproduces the round-6 shipped read "
              "path (_list_decode_disabled + _coalesce_disabled) "
              "in the same run; mirror parity asserted over a "
              "~1k-node annotation sample across legs"})


def config10(dtype, rtt, node_scales=(5_000, 50_000)):
    """Round-8 tentpole gate: concurrent ``POST /v1/score`` throughput
    against a LIVE sidecar, before vs after the serving-path rebuild.

    Two legs per node scale, same simulated cluster:

      r07_serving — the round-7 shipped serving path, reproduced
                    in-run: ``ThreadingHTTPServer`` forced to HTTP/1.0
                    (one TCP connection per request), the service in
                    ``legacy_mode`` (forced full refresh per request,
                    per-node bool()/int() render loop, the whole
                    request under the one service lock);
      coalesced   — the new default: selectors keep-alive front end +
                    version-gated single-flight refresh + coalesced
                    dispatch + version-keyed pre-rendered responses.

    Closed loop: ``clients`` threads each run one request at a time
    for ``duration_s`` (keep-alive when the server allows it,
    reconnect when it closes — exactly what the leg's protocol
    dictates). In-run gates: verdicts byte-for-byte identical across
    legs at a fixed ``now`` (minus the staleness field), and on the
    after leg a cold render, a cache hit, and a concurrent storm all
    return the SAME bytes."""
    import http.client
    import threading

    from crane_scheduler_tpu.policy import DEFAULT_POLICY
    from crane_scheduler_tpu.service import ScoringHTTPServer, ScoringService

    clients, duration_s = 8, 2.0
    results = {}

    def run_clients(port, n, stop_at):
        lats = []
        lock = threading.Lock()
        errors = []

        def loop():
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
            mine = []
            body = b"{}"
            try:
                while time.perf_counter() < stop_at:
                    t0 = time.perf_counter()
                    try:
                        conn.request(
                            "POST", "/v1/score", body=body,
                            headers={"Content-Type": "application/json"},
                        )
                        resp = conn.getresponse()
                        data = resp.read()
                        if resp.status != 200 or not data:
                            errors.append(f"status {resp.status}")
                            return
                        mine.append(time.perf_counter() - t0)
                        if resp.will_close:
                            conn.close()
                            conn = http.client.HTTPConnection(
                                "127.0.0.1", port, timeout=60
                            )
                    except (http.client.HTTPException, OSError) as e:
                        # server-side close racing our write: reconnect
                        conn.close()
                        conn = http.client.HTTPConnection(
                            "127.0.0.1", port, timeout=60
                        )
            finally:
                conn.close()
                with lock:
                    lats.extend(mine)

        threads = [threading.Thread(target=loop) for _ in range(n)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        assert not errors, errors[:3]
        assert lats, "no requests completed"
        arr = np.asarray(sorted(lats))
        return {
            "requests": len(lats),
            "requests_per_sec": round(len(lats) / wall, 1),
            "p50_ms": round(float(np.percentile(arr, 50)) * 1e3, 2),
            "p99_ms": round(float(np.percentile(arr, 99)) * 1e3, 2),
        }

    for n_nodes in node_scales:
        t0 = time.perf_counter()
        sim = _sim(n_nodes, seed=8)
        seed_ms = (time.perf_counter() - t0) * 1e3
        fixed_now = sim.clock.now()
        legs, parity = {}, {}
        for mode in ("r07_serving", "coalesced"):
            svc = ScoringService(sim.cluster, DEFAULT_POLICY, dtype=dtype)
            svc.refresh()
            if mode == "r07_serving":
                svc.legacy_mode = True
                server = ScoringHTTPServer(
                    svc, port=0, frontend="threaded", protocol="HTTP/1.0"
                )
            else:
                server = ScoringHTTPServer(svc, port=0)
            server.start()
            try:
                # warm the jit cache outside the timed window
                svc.score_response_bytes(now=fixed_now, refresh=False)
                legs[mode] = run_clients(
                    server.port, clients,
                    time.perf_counter() + duration_s,
                )
                body = svc.score_response_bytes(now=fixed_now, refresh=True)
                verdicts = json.loads(body)
                verdicts.pop("stalenessSeconds")
                parity[mode] = verdicts
                if mode == "coalesced":
                    # cold render == cache hit == concurrent storm bytes
                    svc._resp_cache.clear()
                    cold = svc.score_response_bytes(
                        now=fixed_now, refresh=False
                    )
                    hit = svc.score_response_bytes(
                        now=fixed_now, refresh=False
                    )
                    stormed = []
                    barrier = threading.Barrier(6)

                    def one():
                        barrier.wait()
                        stormed.append(svc.score_response_bytes(
                            now=fixed_now, refresh=False
                        ))

                    ts = [threading.Thread(target=one) for _ in range(6)]
                    for t in ts:
                        t.start()
                    for t in ts:
                        t.join()
                    assert len({bytes(b) for b in
                                [cold, hit, *stormed]}) == 1, \
                        "coalesced/cached responses not byte-identical"
                    m = svc.metrics()
                    legs[mode]["coalesced_scores"] = m["coalesced_scores"]
                    legs[mode]["response_cache_hits"] = \
                        m["response_cache_hits"]
                    legs[mode]["refresh_skips"] = m["refresh_skips"]
                    legs[mode]["refreshes"] = m["refreshes"]
                    legs[mode]["connections_accepted"] = \
                        server.connections_accepted
                else:
                    legs[mode]["refreshes"] = svc.metrics()["refreshes"]
            finally:
                server.stop()
            log(f"config10[{n_nodes}n/{mode}]: "
                f"{legs[mode]['requests_per_sec']:,.0f} req/s, "
                f"p50 {legs[mode]['p50_ms']}ms, "
                f"p99 {legs[mode]['p99_ms']}ms")
        # the serving rebuild must not change a single verdict
        assert parity["r07_serving"] == parity["coalesced"], \
            "serving parity violation: verdicts diverged between legs"
        before, after = legs["r07_serving"], legs["coalesced"]
        results[n_nodes] = {
            "seed_ms": round(seed_ms, 1),
            "legs": legs,
            "speedup_rps": round(
                after["requests_per_sec"]
                / max(before["requests_per_sec"], 1e-9), 2),
            "p99_ratio": round(
                after["p99_ms"] / max(before["p99_ms"], 1e-9), 3),
            "verdict_parity": "ok",
        }
    big = results[max(node_scales)]
    emit({"config": 10,
          "desc": "serving path, live sidecar: "
                  f"{clients} closed-loop /v1/score clients x "
                  f"{duration_s:.0f}s per leg at "
                  f"{'/'.join(str(n) for n in node_scales)} nodes, "
                  "r07 serving (HTTP/1.0 conn-per-request + forced "
                  "refresh + per-node render under one lock) vs "
                  "keep-alive coalesced/cached front end (same sim, "
                  "same run)",
          "requests_per_sec": big["legs"]["coalesced"]["requests_per_sec"],
          "requests_per_sec_r07": big["legs"]["r07_serving"]["requests_per_sec"],
          "speedup_rps": big["speedup_rps"],
          "p99_ms": big["legs"]["coalesced"]["p99_ms"],
          "p99_ms_r07": big["legs"]["r07_serving"]["p99_ms"],
          "scales": {str(k): v for k, v in results.items()},
          "verdict_parity": "ok",
          "note": "r07_serving reproduces the round-7 shipped path "
                  "in-run (legacy_mode + ThreadingHTTPServer/HTTP1.0); "
                  "gates: verdict parity across legs, byte-identical "
                  "cold/cached/stormed responses on the after leg"})
    big_speedup = big["speedup_rps"]
    assert big_speedup >= 3.0, \
        f"serving speedup gate: {big_speedup}x < 3x at 50k nodes"
    assert big["p99_ratio"] <= 1.0, \
        f"p99 regression: ratio {big['p99_ratio']}"


def config11(dtype, rtt, n_cool=6, n_hot=2, cycles=12):
    """Round-9 tentpole gate: the CLOSED placement control loop through
    the wire stub — annotate -> descheduler evicts from sustained
    hotspots -> drip scheduler re-places the displaced pods -> the next
    annotation sweep observes the moved load.

    Two legs, same process, fresh stub each: a cluster of ``n_hot``
    overloaded nodes (14 of 16 cpus requested, incl. one daemonset and
    one kube-system decoy pod each) and ``n_cool`` near-idle nodes.
    Every cycle the driver derives per-node utilization from the
    MIRROR's pod requests, PATCHes it back as the standard
    ``value,timestamp`` annotations through the write path, and then:

      no_descheduler — nothing else runs; the hotspot persists
      descheduler    — LoadAwareDescheduler (live, budgeted: <=2
                       evictions/node, <=4/cycle) + a drip Scheduler
                       (ResourceFit + Dynamic) re-placing each evictee

    Headline: ``imbalance_reduction`` — max/mean node utilization of
    the no-descheduler leg over the descheduler leg after the same
    number of cycles; the gate requires >= 2x. The stub is the eviction
    oracle: zero daemonset/system-namespace victims, zero duplicate
    eviction POSTs, and every cycle report within both budgets."""
    from crane_scheduler_tpu.cluster import (
        Container,
        Pod,
        ResourceRequirements,
    )
    from crane_scheduler_tpu.cluster.kube import KubeClusterClient
    from crane_scheduler_tpu.descheduler import (
        DeschedulerConfig,
        LoadAwareDescheduler,
        WatermarkPolicy,
    )
    from crane_scheduler_tpu.fit import FitTracker, ResourceFitPlugin, pod_fit_request
    from crane_scheduler_tpu.framework.scheduler import Scheduler
    from crane_scheduler_tpu.plugins import DynamicPlugin
    from crane_scheduler_tpu.policy import DEFAULT_POLICY
    from crane_scheduler_tpu.utils import format_local_time

    kube_stub = _load_kube_stub()
    alloc_milli = 16_000
    metrics = (
        "cpu_usage_avg_5m", "cpu_usage_max_avg_1h", "cpu_usage_max_avg_1d",
        "mem_usage_avg_5m", "mem_usage_max_avg_1h", "mem_usage_max_avg_1d",
    )
    watermarks = (
        WatermarkPolicy("cpu_usage_avg_5m", target=0.32, threshold=0.35),
    )
    t0_epoch = 1753776000.0
    step_s = 60.0

    def seed(server):
        hot = [f"hot-{i}" for i in range(n_hot)]
        cool = [f"cool-{i}" for i in range(n_cool)]
        for i, name in enumerate(hot + cool):
            server.state.add_node(
                name, f"10.0.0.{i + 1}",
                allocatable={"cpu": "16", "pods": "110"},
            )
        spec = lambda node: {  # noqa: E731 - local literal builder
            "nodeName": node,
            "containers": [{"resources": {"requests": {"cpu": "1"}}}],
        }
        for node in hot:
            for j in range(12):
                server.state.add_pod("default", f"{node}-w{j}", spec=spec(node))
            # gate decoys: same 1-cpu weight, must never be evicted
            server.state.add_pod(
                "default", f"{node}-ds", spec=spec(node),
                owner_references=[{"kind": "DaemonSet", "name": "agent"}],
            )
            server.state.add_pod("kube-system", f"{node}-sys", spec=spec(node))
        for node in cool:
            server.state.add_pod("default", f"{node}-w0", spec=spec(node))
        return hot + cool, n_hot * (12 + 2) * 1000 + n_cool * 1000

    def utilization(client, names):
        return {
            name: sum(
                pod_fit_request(p).milli_cpu
                for p in client.list_pods(name)
            ) / alloc_milli
            for name in names
        }

    def annotate(client, util, now):
        stamp = format_local_time(now)
        client.patch_node_annotations_bulk({
            name: {m: f"{u:.5f},{stamp}" for m in metrics}
            for name, u in util.items()
        })

    def imbalance(util):
        vals = list(util.values())
        return max(vals) / (sum(vals) / len(vals))

    def leg(with_descheduler):
        server = kube_stub.KubeStubServer().start()
        try:
            names, total_milli = seed(server)
            client = KubeClusterClient(server.url)
            client.start()
            deadline = time.time() + 10.0
            want_pods = n_hot * 14 + n_cool
            while time.time() < deadline:
                if (len(client.list_pods()) == want_pods
                        and len(client.list_nodes()) == len(names)):
                    break
                time.sleep(0.02)
            util = utilization(client, names)
            assert abs(sum(util.values()) * alloc_milli - total_milli) < 1, \
                "mirror lost pod requests"
            start_imbalance = imbalance(util)

            desched = sched = None
            clock_now = t0_epoch
            if with_descheduler:
                desched = LoadAwareDescheduler(
                    client, DEFAULT_POLICY,
                    DeschedulerConfig(
                        watermarks=watermarks, consecutive_syncs=2,
                        max_evictions_per_node=2, max_evictions_per_cycle=4,
                        node_cooldown_seconds=0.0,
                    ),
                    clock=lambda: clock_now,
                )
                sched = Scheduler(client, clock=lambda: clock_now)
                sched.register(ResourceFitPlugin(FitTracker(client)), weight=1)
                sched.register(
                    DynamicPlugin(DEFAULT_POLICY, clock=lambda: clock_now),
                    weight=3,
                )

            moved, unplaced = 0, 0
            wall0 = time.perf_counter()
            for cycle in range(cycles):
                clock_now = t0_epoch + cycle * step_s
                annotate(client, utilization(client, names), clock_now)
                if desched is None:
                    continue
                report = desched.sync_once(clock_now)
                # budget oracle: every cycle within both eviction budgets
                assert len(report.evicted) <= 4, "cycle budget overrun"
                per_node = {}
                for ev in report.evicted:
                    per_node[ev.node] = per_node.get(ev.node, 0) + 1
                assert all(c <= 2 for c in per_node.values()), \
                    "node budget overrun"
                for i, ev in enumerate(report.evicted):
                    replacement = Pod(
                        name=f"moved-{cycle}-{i}", namespace="default",
                        containers=(Container(
                            "c", ResourceRequirements(requests={"cpu": "1"}),
                        ),),
                    )
                    client.add_pod(replacement)
                    result = sched.schedule_one(replacement)
                    if result.node is None:
                        unplaced += 1
                    else:
                        moved += 1
            wall = time.perf_counter() - wall0

            util = utilization(client, names)
            final_imbalance = imbalance(util)
            # total requested cpu is conserved across the whole loop:
            # every eviction was matched by a re-placed pod
            assert unplaced == 0, f"{unplaced} evictees failed to re-place"
            assert abs(sum(util.values()) * alloc_milli - total_milli) < 1, \
                "closed loop lost or duplicated pods"

            evictions = list(server.state.evictions)
            assert server.state.duplicate_evictions() == 0, \
                "double-POSTed eviction!"
            assert sum(server.state.evict_posts.values()) == len(evictions), \
                "eviction POST count drifted from the processed log"
            assert all(not e["daemonset"] for e in evictions), \
                "daemonset pod evicted!"
            assert all(e["namespace"] == "default" for e in evictions), \
                "system-namespace pod evicted!"
            client.stop()
            return {
                "imbalance_start": round(start_imbalance, 3),
                "imbalance_final": round(final_imbalance, 3),
                "max_util_final": round(max(util.values()), 4),
                "mean_util_final": round(
                    sum(util.values()) / len(util), 4),
                "evictions": len(evictions),
                "replaced": moved,
                "eviction_posts": sum(server.state.evict_posts.values()),
                "duplicate_evictions": server.state.duplicate_evictions(),
                "cycles": cycles,
                "wall_ms": round(wall * 1e3, 1),
            }
        finally:
            server.stop()

    legs = {
        "no_descheduler": leg(False),
        "descheduler": leg(True),
    }
    before = legs["no_descheduler"]["imbalance_final"]
    after = legs["descheduler"]["imbalance_final"]
    reduction = round(before / after, 2)
    assert reduction >= 2.0, \
        f"closed-loop gate: imbalance reduction {reduction}x < 2x"
    emit({"config": 11,
          "desc": "closed placement loop through the wire stub: "
                  f"{n_hot} hot + {n_cool} cool nodes, {cycles} "
                  "annotate->evict->re-place cycles, no-descheduler vs "
                  "budgeted descheduler + drip re-placement (same "
                  "process, fresh stub per leg)",
          "imbalance_no_descheduler": before,
          "imbalance_descheduler": after,
          "imbalance_reduction": reduction,
          "evictions": legs["descheduler"]["evictions"],
          "replaced": legs["descheduler"]["replaced"],
          "duplicate_evictions":
              legs["descheduler"]["duplicate_evictions"],
          "legs": legs,
          "note": "stub eviction oracle asserted in-run: zero "
                  "daemonset/kube-system victims, zero duplicate "
                  "eviction POSTs, every cycle within the <=2/node and "
                  "<=4/cycle budgets; requested cpu conserved across "
                  "the loop (every evictee re-placed)"})


def config12(dtype, rtt, n_nodes=6, steps=24, outage_at=4, heal_at=16):
    """Round-10 tentpole gate: chaos soak — a scripted Prometheus outage
    against the annotation score path, resilience layer on vs off.

    Both legs run the same annotator-shaped loop on a virtual 60s-step
    clock against a fresh kube stub + ChaosPromServer: bulk metric query
    -> ``value,timestamp`` annotations PATCHed through the write path ->
    the mirror feeds the degraded-mode staleness evaluation. The stub
    Prometheus goes dark at step ``outage_at`` (connections close
    unanswered) and heals at ``heal_at``.

      resilience    — breaker-wrapped client (trip at 3 failures,
                      half-open probe after 1.5 virtual steps) + bounded
                      retry + DegradedModeController over the mirror
      no_resilience — PrometheusClient(retry_policy=None, breaker=None):
                      every sweep hammers the dead endpoint

    Headline: ``recovery_steps``/``recovery_ms`` — fault-heal to the
    first step where the sweep succeeds, the breaker is closed, and
    degraded mode has exited (the healthy score path). Gates: the
    resilience leg recovers within 3 steps of heal without a restart,
    fail-fasts at least one sweep with zero network attempts while the
    breaker is open, and enters+exits degraded mode; the no-resilience
    leg fails every outage sweep and never stops hitting the endpoint."""
    from crane_scheduler_tpu.cluster.kube import KubeClusterClient
    from crane_scheduler_tpu.metrics import PrometheusClient
    from crane_scheduler_tpu.metrics.source import MetricsTransportError
    from crane_scheduler_tpu.policy import (
        DynamicSchedulerPolicy,
        PolicySpec,
        PredicatePolicy,
        PriorityPolicy,
        SyncPolicy,
    )
    from crane_scheduler_tpu.resilience import (
        BreakerState,
        CircuitBreaker,
        DegradedModeController,
        HealthRegistry,
        RetryPolicy,
    )
    from crane_scheduler_tpu.utils import format_local_time

    kube_stub = _load_kube_stub()
    metric = "cpu_usage_avg_5m"
    policy = DynamicSchedulerPolicy(
        spec=PolicySpec(
            sync_period=(SyncPolicy(metric, 180.0),),
            predicate=(PredicatePolicy(metric, 0.65),),
            priority=(PriorityPolicy(metric, 1.0),),
        )
    )
    t0_epoch = 1753776000.0
    step_s = 60.0

    def leg(with_resilience):
        server = kube_stub.KubeStubServer().start()
        prom = kube_stub.ChaosPromServer().start()
        try:
            names, ips = [], {}
            for i in range(n_nodes):
                name, ip = f"n{i}", f"10.0.0.{i + 1}"
                server.state.add_node(name, ip)
                names.append(name)
                ips[name] = ip
            prom.set_all(ips.values(), 0.40)
            client = KubeClusterClient(server.url)
            client.start()
            deadline = time.time() + 10.0
            while time.time() < deadline:
                if len(client.list_nodes()) == n_nodes:
                    break
                time.sleep(0.02)

            clock_now = t0_epoch
            breaker = degraded = None
            if with_resilience:
                breaker = CircuitBreaker(
                    "prometheus", failure_threshold=3,
                    window_s=10 * step_s, reset_timeout_s=1.5 * step_s,
                    clock=lambda: clock_now,
                )
                HealthRegistry().watch_breaker(breaker)
                degraded = DegradedModeController(
                    policy.spec, min_eval_interval_s=0.0
                )
                promc = PrometheusClient(
                    prom.url, timeout=2.0,
                    retry_policy=RetryPolicy(
                        max_attempts=2, base_delay_s=0.0, max_delay_s=0.0,
                        deadline_s=30.0, retryable=(MetricsTransportError,),
                        seed=0, sleep=lambda s: None,
                    ),
                    breaker=breaker,
                )
            else:
                promc = PrometheusClient(
                    prom.url, timeout=2.0,
                    retry_policy=None, breaker=None,
                )

            def healthy():
                if not with_resilience:
                    return True
                return (breaker.state == BreakerState.CLOSED
                        and not degraded.active)

            failed = failfast = 0
            outage_attempts = 0
            breaker_opened = degraded_steps = 0
            recovery_steps = recovery_ms = None
            heal_wall = None
            for step in range(steps):
                clock_now = t0_epoch + step * step_s
                if step == outage_at:
                    prom.outage = True
                if step == heal_at:
                    prom.outage = False
                    heal_wall = time.perf_counter()
                hits_before = prom.hits
                sweep_ok = False
                try:
                    by_inst = promc.query_all_by_metric(metric)
                    stamp = format_local_time(clock_now)
                    client.patch_node_annotations_bulk({
                        name: {metric: f"{by_inst[ips[name]]},{stamp}"}
                        for name in names if ips[name] in by_inst
                    })
                    want = f",{stamp}"
                    deadline = time.time() + 2.0
                    while time.time() < deadline:
                        if any((n.annotations or {}).get(metric, "")
                               .endswith(want)
                               for n in client.list_nodes()):
                            break
                        time.sleep(0.01)
                    sweep_ok = True
                except MetricsTransportError:
                    failed += 1
                    if prom.hits == hits_before:
                        failfast += 1
                if outage_at <= step < heal_at:
                    outage_attempts += prom.hits - hits_before
                if with_resilience:
                    degraded.update(
                        (dict(n.annotations or {})
                         for n in client.list_nodes()),
                        clock_now,
                    )
                    if breaker.state == BreakerState.OPEN:
                        breaker_opened = 1
                    if degraded.active:
                        degraded_steps += 1
                if (recovery_steps is None and step >= heal_at
                        and sweep_ok and healthy()):
                    recovery_steps = step - heal_at
                    recovery_ms = (time.perf_counter() - heal_wall) * 1e3
            client.stop()
            return {
                "failed_sweeps": failed,
                "failfast_sweeps": failfast,
                "outage_network_attempts": outage_attempts,
                "breaker_opened": bool(breaker_opened),
                "degraded_steps": degraded_steps,
                "recovery_steps": recovery_steps,
                "recovery_ms": (round(recovery_ms, 1)
                                if recovery_ms is not None else None),
                "steps": steps,
            }
        finally:
            server.stop()
            prom.stop()

    legs = {
        "resilience": leg(True),
        "no_resilience": leg(False),
    }
    res, base = legs["resilience"], legs["no_resilience"]
    outage_len = heal_at - outage_at
    # chaos-soak gates: recovery without restart, breaker load-shedding,
    # degraded-mode engagement — and the baseline showing what they buy
    assert res["recovery_steps"] is not None, "resilience leg never healed"
    assert res["recovery_steps"] <= 3, \
        f"recovery took {res['recovery_steps']} steps > 3"
    assert res["breaker_opened"], "breaker never opened under outage"
    assert res["failfast_sweeps"] >= 1, "no sweep ever failed fast"
    assert res["degraded_steps"] >= 1, "degraded mode never engaged"
    assert base["failed_sweeps"] == outage_len, \
        "no-resilience leg should fail every outage sweep"
    assert base["outage_network_attempts"] >= outage_len, \
        "no-resilience leg should hammer the dead endpoint every step"
    emit({"config": 12,
          "desc": "chaos soak through the wire stubs: scripted "
                  f"prometheus outage (steps {outage_at}->{heal_at} of "
                  f"{steps}, {n_nodes} nodes), breaker+retry+degraded "
                  "resilience leg vs bare-client baseline",
          "recovery_steps": res["recovery_steps"],
          "recovery_ms": res["recovery_ms"],
          "failfast_sweeps": res["failfast_sweeps"],
          "outage_attempts_resilience": res["outage_network_attempts"],
          "outage_attempts_no_resilience": base["outage_network_attempts"],
          "legs": legs,
          "note": "recovery = fault-heal to the first step with a "
                  "successful sweep, a closed breaker, and degraded "
                  "mode exited (the healthy score path); while open "
                  "the breaker fails sweeps fast (zero network "
                  "attempts, bounded probes) where the baseline blocks "
                  "on the dead endpoint every step"})


def config13(dtype, rtt, n_nodes=6, n_pods=48, target_s=5.0):
    """Round-11 tentpole gate: placement e2e latency over the wire stub,
    measured by the pod-lifecycle tracker (ISSUE 9).

    One live loop: annotated nodes through the write path, ``n_pods``
    pods batch-scheduled by the TPU batch scheduler, bindings POSTed
    over HTTP (each carrying the pod's W3C ``traceparent``), the stub's
    watch events confirming every placement. The lifecycle tracker
    stitches first-seen -> watch-confirm per pod; headline is the e2e
    p50/p99 plus the per-stage breakdown.

    Gates: every pod's record finalizes with ``bind_post`` AND
    ``watch_confirm``; every binding POST carried a well-formed
    traceparent matching its record; and the SLO report computed from
    RAW records matches the ``crane_placement_e2e_seconds`` histogram
    the same completions fed — same count, same sum (1e-6), and every
    raw value consistent with the cumulative bucket counts (strict
    exposition parse). The report and the scrape can never disagree."""
    from crane_scheduler_tpu.cluster.kube import KubeClusterClient
    from crane_scheduler_tpu.framework.scheduler import BatchScheduler
    from crane_scheduler_tpu.policy import DEFAULT_POLICY
    from crane_scheduler_tpu.telemetry import Telemetry, slo_report, tracing
    from crane_scheduler_tpu.telemetry.expfmt import parse_exposition
    from crane_scheduler_tpu.utils import format_local_time

    kube_stub = _load_kube_stub()
    metrics = (
        "cpu_usage_avg_5m", "cpu_usage_max_avg_1h", "cpu_usage_max_avg_1d",
        "mem_usage_avg_5m", "mem_usage_max_avg_1h", "mem_usage_max_avg_1d",
    )
    server = kube_stub.KubeStubServer().start()
    tel = Telemetry()
    tel.lifecycle.batch_sample = n_pods  # track every pod, not a sample
    client = None
    try:
        rng = random.Random(13)
        names = [f"n{i}" for i in range(n_nodes)]
        for i, name in enumerate(names):
            server.state.add_node(name, f"10.0.0.{i + 1}")
        client = KubeClusterClient(server.url, telemetry=tel)
        client.start()
        deadline = time.time() + 10.0
        while time.time() < deadline:
            if len(client.list_nodes()) == n_nodes:
                break
            time.sleep(0.02)
        stamp = format_local_time(time.time())
        client.patch_node_annotations_bulk({
            name: {m: f"{rng.uniform(0.05, 0.45):.5f},{stamp}"
                   for m in metrics}
            for name in names
        })
        for i in range(n_pods):
            server.state.add_pod("default", f"slo-{i}")
        keys = [f"default/slo-{i}" for i in range(n_pods)]
        deadline = time.time() + 10.0
        while time.time() < deadline:
            if len(client.list_pods()) == n_pods and any(
                "," in v for n in client.list_nodes()
                for v in n.annotations.values()
            ):
                break
            time.sleep(0.02)

        sched = BatchScheduler(client, DEFAULT_POLICY, dtype=dtype,
                               telemetry=tel)
        t0 = time.perf_counter()
        result = sched.schedule_batch(
            [client.get_pod(k) for k in keys], bind=True
        )
        assert len(result.assignments) == n_pods, \
            f"only {len(result.assignments)}/{n_pods} pods assigned"
        deadline = time.time() + 20.0
        while time.time() < deadline:
            if tel.lifecycle.confirmed_total >= n_pods:
                break
            time.sleep(0.02)
        wall_ms = (time.perf_counter() - t0) * 1e3

        records = [r for r in tel.lifecycle.records()
                   if r.get("pod") in set(keys)]
        assert len(records) == n_pods, \
            f"only {len(records)}/{n_pods} lifecycle records finalized"
        for rec in records:
            assert "bind_post" in rec["stages"], rec
            assert "watch_confirm" in rec["stages"], rec
        by_pod = {r["pod"]: r for r in records}
        posts = [(p, tp) for _, p, tp in server.state.trace_headers
                 if "/binding" in p]
        assert len(posts) >= n_pods, "missing binding POSTs"
        for path, tp in posts:
            pod = "default/" + path.split("/pods/")[1].split("/")[0]
            assert tracing.parse_traceparent(tp) is not None, (path, tp)
            assert by_pod[pod]["trace_id"] in tp, (path, tp)

        report = slo_report(records, target_seconds=target_s)
        # cross-check the raw-record report against the histogram the
        # same completions observed, via the strict exposition parser
        families = parse_exposition(tel.render_prometheus(openmetrics=True))
        e2e_raw = sorted(
            rec["mono"]["watch_confirm"] - rec["mono"]["seen"]
            for rec in records
        )
        samples = families["crane_placement_e2e_seconds"]["samples"]
        hist_count = hist_sum = None
        for name, labels, value in samples:
            if name.endswith("_count"):
                hist_count = value
            elif name.endswith("_sum"):
                hist_sum = value
            elif name.endswith("_bucket"):
                le = dict(labels)["le"]
                bound = float("inf") if le == "+Inf" else float(le)
                raw_le = sum(1 for v in e2e_raw if v <= bound)
                assert raw_le == int(value), \
                    f"bucket le={le}: raw {raw_le} != histogram {int(value)}"
        assert hist_count == len(e2e_raw) == report["e2e"]["count"]
        assert abs(hist_sum - sum(e2e_raw)) < 1e-6
        assert abs(report["e2e"]["sum"] - hist_sum) < 1e-6
        stage_p99_ms = {
            s: round(v["p99"] * 1e3, 3)
            for s, v in report["stages"].items()
        }
        log(f"config13: {n_pods} placements confirmed in {wall_ms:.0f}ms "
            f"wall; e2e p50 {report['e2e']['p50'] * 1e3:.1f}ms "
            f"p99 {report['e2e']['p99'] * 1e3:.1f}ms; stage p99 "
            f"{stage_p99_ms}")
        emit({"config": 13,
              "desc": f"placement e2e latency through the wire stub: "
                      f"{n_pods} pods batch-scheduled over {n_nodes} "
                      "annotated nodes, lifecycle-tracked first-seen -> "
                      "watch-confirm with traceparent on every binding "
                      "POST",
              "pods": n_pods,
              "confirmed": report["confirmed"],
              "e2e_p50_ms": round(report["e2e"]["p50"] * 1e3, 3),
              "e2e_p99_ms": round(report["e2e"]["p99"] * 1e3, 3),
              "stage_p99_ms": stage_p99_ms,
              "slo_target_s": target_s,
              "slo_compliance": report["slo"]["compliance"],
              "slo_burn_rate": report["slo"]["burn_rate"],
              "histogram_count": int(hist_count),
              "note": "SLO report computed from raw lifecycle records; "
                      "gate proves it matches the "
                      "crane_placement_e2e_seconds histogram the same "
                      "completions fed (count, sum, and every "
                      "cumulative bucket) via the strict exposition "
                      "parser"})
    finally:
        if client is not None:
            client.stop()
        server.stop()


def config14(dtype, rtt, node_scales=(5_000, 50_000), n_pods=1_000):
    """Round-12 tentpole gate: the columnar drip path at scale, through
    the wire stub — a 1k-pod drip storm (one ``schedule_one`` + one
    binding POST per pod) against a mirror of ``n_nodes`` annotated
    nodes, scalar plugin loop vs cached-column fast path.

    Two legs per node scale, fresh stub subprocess each, identically
    seeded (wire-shaped ``value,timestamp`` annotations, value keyed on
    the node index so the cluster has distinct score classes AND real
    tie sets), same ``tie_break_seed``:

      scalar   — ``columnar=False``: the exact O(plugins x nodes) loop
                 shipped through round 9, scheduling a K-pod prefix
                 (the full storm would take ~45 min at 50k nodes);
      columnar — the new default: version-cached Filter/Score columns,
                 one masked argmax per pod, binds folded into the
                 cached fit column.

    In-run gates: the columnar leg's first K placements equal the
    scalar leg's K placements node for node (the seeded-tiebreak RNG
    must be consumed identically); every pod places; the stub asserts
    zero duplicate binding POSTs on both legs; the columnar leg took
    zero scalar fallbacks; and the 50k speedup is >= 100x per pod."""
    from crane_scheduler_tpu.cluster import (
        Container,
        Pod,
        ResourceRequirements,
    )
    from crane_scheduler_tpu.cluster.kube import KubeClusterClient
    from crane_scheduler_tpu.fit import FitTracker, ResourceFitPlugin
    from crane_scheduler_tpu.framework.scheduler import Scheduler
    from crane_scheduler_tpu.plugins import DynamicPlugin
    from crane_scheduler_tpu.policy import DEFAULT_POLICY
    from crane_scheduler_tpu.utils import parse_local_time

    kube_stub = _load_kube_stub()
    metric_names = [sp.name for sp in DEFAULT_POLICY.spec.sync_period]
    # the stub seeds annotations stamped 2026-07-30T00:00:00Z; score 30s
    # after that so every row is fresh for the 5m windows
    now = parse_local_time("2026-07-30T00:00:00Z") + 30.0
    seed = 14

    def leg(n_nodes, columnar, count):
        server = kube_stub.KubeStubSubprocess()
        try:
            server.seed(n_nodes, "node-", metrics=metric_names)
            client = KubeClusterClient(server.url, list_page_limit=2000)
            client.start()
            assert len(client.list_nodes()) == n_nodes
            sched = Scheduler(
                client, clock=lambda: now, columnar=columnar,
                tie_break_seed=seed,
            )
            sched.register(ResourceFitPlugin(FitTracker(client)), weight=1)
            sched.register(
                DynamicPlugin(DEFAULT_POLICY, clock=lambda: now), weight=3
            )
            placements = []
            t0 = time.perf_counter()
            for i in range(count):
                pod = Pod(
                    name=f"drip-{i:04d}", namespace="default",
                    containers=(Container("c", ResourceRequirements(
                        requests={"cpu": "100m", "memory": "128Mi"},
                    )),),
                )
                client.add_pod(pod)
                result = sched.schedule_one(pod)
                assert result.node is not None, \
                    f"pod {i} unplaced: {result.reason}"
                placements.append(result.node)
            wall_s = time.perf_counter() - t0
            stats = server.stats()
            assert stats["duplicate_binds"] == 0, "double-POSTed bind!"
            assert stats["bind_posts"] == count, \
                f"bind POSTs {stats['bind_posts']} != {count} pods"
            drip = sched.drip_stats()
            if columnar:
                assert not drip["fallbacks"], \
                    f"unexpected scalar fallbacks: {drip['fallbacks']}"
            client.stop()
            return {
                "pods": count,
                "wall_ms": round(wall_s * 1e3, 1),
                "per_pod_ms": round(wall_s * 1e3 / count, 3),
                "pods_per_sec": round(count / wall_s, 1),
                "drip": drip,
            }, placements
        finally:
            server.stop()

    results = {}
    for n_nodes in node_scales:
        # the scalar prefix is sized so each leg stays ~O(10s) of wall
        k = 40 if n_nodes <= 5_000 else 5
        scalar, scalar_placed = leg(n_nodes, columnar=False, count=k)
        columnar, col_placed = leg(n_nodes, columnar=True, count=n_pods)
        # bit-identical placement prefix: same cluster, same seed, same
        # RNG consumption -> the K scalar placements must match the
        # columnar storm's first K node for node
        assert col_placed[:k] == scalar_placed, \
            f"placement divergence at {n_nodes} nodes: " \
            f"{scalar_placed} != {col_placed[:k]}"
        speedup = round(scalar["per_pod_ms"] / columnar["per_pod_ms"], 1)
        results[n_nodes] = {
            "scalar": scalar,
            "columnar": columnar,
            "speedup_per_pod": speedup,
            "placement_prefix": "ok",
        }
        log(f"config14[{n_nodes}n]: scalar {scalar['per_pod_ms']:.1f} "
            f"ms/pod (K={k}), columnar {columnar['per_pod_ms']:.2f} "
            f"ms/pod x {n_pods} pods ({columnar['pods_per_sec']:,.0f} "
            f"pods/s), speedup {speedup}x, "
            f"drip {columnar['drip']}")
    big = results[max(node_scales)]
    emit({"config": 14,
          "desc": f"columnar drip storm through the wire stub: {n_pods} "
                  "schedule_one+bind cycles against "
                  f"{'/'.join(str(n) for n in node_scales)}-node "
                  "mirrors, scalar plugin loop (K-pod prefix) vs "
                  "version-cached columns (same seed, fresh stub per "
                  "leg)",
          "pods": n_pods,
          "per_pod_ms": big["columnar"]["per_pod_ms"],
          "pods_per_sec": big["columnar"]["pods_per_sec"],
          "per_pod_ms_scalar": big["scalar"]["per_pod_ms"],
          "speedup_per_pod": big["speedup_per_pod"],
          "drip_stats": big["columnar"]["drip"],
          "scales": {str(n): v for n, v in results.items()},
          "placement_prefix_parity": "ok",
          "note": "gates: scalar-prefix placements bit-identical under "
                  "the shared tie_break_seed, zero duplicate binding "
                  "POSTs (stub oracle) on every leg, zero columnar->"
                  "scalar fallbacks, >=100x per-pod speedup at 50k"})
    assert big["speedup_per_pod"] >= 100.0, \
        f"drip speedup gate: {big['speedup_per_pod']}x < 100x at 50k"


def config15(dtype, rtt, node_scales=(5_000, 50_000)):
    """Round-13 tentpole gate: the device-resident drip batch engine
    through the wire stub — pending pods coalesced into dispatch
    windows, one jitted mask+argmax+fold program per window (later pods
    see earlier folds in-program), one D2H transfer, one bulk binding
    POST batch.

    Per node scale, fresh stub subprocess per leg, identically seeded
    annotations (same generator as config14, so real score classes and
    real tie sets exist):

      scalar — ``columnar=False`` schedule_one over a K-pod prefix
               (the full storm would take minutes at 50k nodes);
      batch  — ``schedule_queue`` over the full storm, window=128
               (larger windows amortize the per-window bulk-bind
               pipeline overhead; the kernel itself is ~flat per pod).

    The timed legs run WITHOUT a tie-break seed: first-max selection is
    deterministic, so batch placements must equal the scalar prefix
    node for node with no RNG involved. The seeded slow path — any
    window whose kernel reports a real tie replays per-pod, consuming
    the RNG exactly like the scalar loop — is asserted separately on
    the small scale (three seeded legs, placements AND replay counter
    checked) so the optimistic split is exercised in-run without
    polluting the timing.

    Gates: batch <0.5 ms/pod at 50k nodes (columnar baseline 3.87),
    >=5k binds/s sustained at 5k nodes, placement-prefix parity at both
    scales, seeded-replay parity, zero duplicate binding POSTs (stub
    oracle) and bind_posts == pods on every leg, zero scalar fallbacks,
    and every accepted bind folded exactly once."""
    from crane_scheduler_tpu.cluster import (
        Container,
        Pod,
        ResourceRequirements,
    )
    from crane_scheduler_tpu.cluster.kube import KubeClusterClient
    from crane_scheduler_tpu.fit import FitTracker, ResourceFitPlugin
    from crane_scheduler_tpu.framework.scheduler import Scheduler
    from crane_scheduler_tpu.plugins import DynamicPlugin
    from crane_scheduler_tpu.policy import DEFAULT_POLICY
    from crane_scheduler_tpu.utils import parse_local_time

    kube_stub = _load_kube_stub()
    metric_names = [sp.name for sp in DEFAULT_POLICY.spec.sync_period]
    now = parse_local_time("2026-07-30T00:00:00Z") + 30.0

    def make_pod(i):
        return Pod(
            name=f"drip-{i:04d}", namespace="default",
            containers=(Container("c", ResourceRequirements(
                requests={"cpu": "100m", "memory": "128Mi"},
            )),),
        )

    def leg(n_nodes, count, mode, seed=None, window=128):
        """mode: scalar | perpod | queue."""
        server = kube_stub.KubeStubSubprocess()
        try:
            # real allocatable so the bounded fit path runs (folds have
            # consequences: a filled node stops winning) and the warm-up
            # pods below can be made genuinely infeasible
            server.seed(
                n_nodes, "node-", metrics=metric_names,
                allocatable={"cpu": "16", "memory": "64Gi",
                             "ephemeral-storage": "100Gi", "pods": "110"},
            )
            client = KubeClusterClient(server.url, list_page_limit=2000)
            client.start()
            assert len(client.list_nodes()) == n_nodes
            sched = Scheduler(
                client, clock=lambda: now, columnar=(mode != "scalar"),
                tie_break_seed=seed,
            )
            sched.register(ResourceFitPlugin(FitTracker(client)), weight=1)
            sched.register(
                DynamicPlugin(DEFAULT_POLICY, clock=lambda: now), weight=3
            )
            pods = [make_pod(i) for i in range(count)]
            for pod in pods:
                client.add_pod(pod)
            pre_disp = 0
            if mode == "queue":
                # Warm the one-time costs outside the timed storm: the
                # first ensure() builds the O(n) drip columns and the
                # first dispatch jit-compiles this shape bucket. The
                # warm pods request more CPU than any node offers, so
                # every verdict is "infeasible" — no binds, no folds,
                # no cluster-state change: the storm below starts from
                # exactly the seeded cluster, which is what keeps the
                # scalar placement-prefix parity valid. ("Sustained"
                # throughput is the steady state; the one-time costs
                # are real but amortize over a scheduler's lifetime.)
                warm = [
                    Pod(
                        name=f"warm-{i:03d}", namespace="default",
                        containers=(Container("c", ResourceRequirements(
                            requests={"cpu": "100000", "memory": "128Mi"},
                        )),),
                    )
                    for i in range(window)
                ]
                for pod in warm:
                    client.add_pod(pod)
                warm_res = sched.schedule_queue(warm, window=window)
                assert all(r.node is None for r in warm_res), \
                    "warm-up pod unexpectedly placed (would break parity)"
                pre_disp = sched.drip_stats()["batch"]["dispatches"]
            t0 = time.perf_counter()
            if mode == "queue":
                results = sched.schedule_queue(pods, window=window)
            else:
                results = [sched.schedule_one(p) for p in pods]
            wall_s = time.perf_counter() - t0
            placements = []
            for i, r in enumerate(results):
                assert r.node is not None, f"pod {i} unplaced: {r.reason}"
                placements.append(r.node)
            stats = server.stats()
            assert stats["duplicate_binds"] == 0, "double-POSTed bind!"
            assert stats["bind_posts"] == count, \
                f"bind POSTs {stats['bind_posts']} != {count} pods"
            drip = sched.drip_stats()
            if mode != "scalar":
                assert not drip["fallbacks"], \
                    f"unexpected scalar fallbacks: {drip['fallbacks']}"
                assert drip["folds"] == count, \
                    f"folds {drip['folds']} != {count} accepted binds"
            if mode == "queue":
                assert drip["batch"]["dispatches"] > pre_disp, \
                    "kernel never ran on the storm"
            client.stop()
            b = drip.get("batch", {})
            # drop the warm-up dispatches: storm numbers only
            ks = list(b.get("kernel_seconds", ()))[pre_disp:]
            # steady-state wall: the first dispatch per shape bucket
            # carries the one-time jit compile; "sustained" throughput
            # replaces it with the mean warm dispatch
            steady_s = wall_s
            if len(ks) > 1:
                warm_mean = sum(ks[1:]) / len(ks[1:])
                steady_s = wall_s - (ks[0] - warm_mean)
            return {
                "pods": count,
                "wall_ms": round(wall_s * 1e3, 1),
                "per_pod_ms": round(wall_s * 1e3 / count, 3),
                "per_pod_ms_steady": round(steady_s * 1e3 / count, 3),
                "pods_per_sec": round(count / wall_s, 1),
                "pods_per_sec_steady": round(count / steady_s, 1),
                "dispatches": b.get("dispatches", 0) - pre_disp,
                "replays": b.get("replays", 0),
                "kernel_ms_mean": round(
                    sum(ks) * 1e3 / max(1, len(ks)), 2),
                "kernel_ms_warm": round(
                    sum(ks[1:]) * 1e3 / len(ks[1:]), 2) if len(ks) > 1
                else None,
                "folds": drip.get("folds", 0),
            }, placements, drip
        finally:
            server.stop()

    results = {}
    for n_nodes in node_scales:
        k = 40 if n_nodes <= 5_000 else 5
        n_pods = 2_000 if n_nodes <= 5_000 else 1_000
        scalar, scalar_placed, _ = leg(n_nodes, k, "scalar")
        batch, batch_placed, drip = leg(n_nodes, n_pods, "queue")
        assert batch_placed[:k] == scalar_placed, \
            f"placement divergence at {n_nodes} nodes: " \
            f"{scalar_placed} != {batch_placed[:k]}"
        assert batch["replays"] == 0, \
            "unseeded leg must never take the replay slow path"
        speedup = round(3.87 / batch["per_pod_ms_steady"], 1) \
            if n_nodes == 50_000 else None
        results[n_nodes] = {
            "scalar": scalar,
            "batch": batch,
            "placement_prefix": "ok",
            "vs_columnar_baseline": speedup,
        }
        log(f"config15[{n_nodes}n]: scalar {scalar['per_pod_ms']:.1f} "
            f"ms/pod (K={k}), batch {batch['per_pod_ms_steady']:.3f} "
            f"ms/pod steady ({batch['per_pod_ms']:.3f} incl. compile) "
            f"x {n_pods} pods ({batch['pods_per_sec_steady']:,.0f} "
            f"binds/s, {batch['dispatches']} windows, kernel "
            f"{batch['kernel_ms_warm']} ms warm), folds "
            f"{batch['folds']}")

    # seeded slow path: three legs over the same 5k mirror, identical
    # tie_break_seed — placements must match call for call, and the
    # queue leg must actually have hit the replay path (the seeded
    # cluster has real tie sets)
    seed = 15
    small = min(node_scales)
    _, sca_placed, _ = leg(small, 40, "scalar", seed=seed)
    _, col_placed, _ = leg(small, 40, "perpod", seed=seed)
    q, q_placed, q_drip = leg(small, 40, "queue", seed=seed, window=8)
    assert sca_placed == col_placed == q_placed, \
        "seeded placement divergence between scalar/per-pod/queue legs"
    assert q["replays"] > 0, \
        "seeded leg never exercised the tie replay slow path"
    log(f"config15[seeded]: 40 pods x 3 legs bit-identical, "
        f"{q['replays']} window replays")

    big = results[max(node_scales)]
    small_r = results[min(node_scales)]
    emit({"config": 15,
          "desc": "device-resident drip batch engine through the wire "
                  "stub: schedule_queue dispatch windows (jitted "
                  "mask+argmax+fold, one D2H per window, bulk binding "
                  "POSTs) vs scalar plugin-loop prefix, per node scale "
                  f"{'/'.join(str(n) for n in node_scales)}",
          "per_pod_ms": big["batch"]["per_pod_ms_steady"],
          "per_pod_ms_incl_compile": big["batch"]["per_pod_ms"],
          "pods_per_sec_5k": small_r["batch"]["pods_per_sec_steady"],
          "pods_per_sec_50k": big["batch"]["pods_per_sec_steady"],
          "kernel_ms_warm_50k": big["batch"]["kernel_ms_warm"],
          "dispatch_windows_50k": big["batch"]["dispatches"],
          "vs_columnar_baseline_ms": 3.87,
          "speedup_vs_columnar": big["vs_columnar_baseline"],
          "scales": {str(n): v for n, v in results.items()},
          "placement_prefix_parity": "ok",
          "seeded_replay_parity": "ok",
          "note": "gates: batch <0.5 ms/pod sustained at 50k (columnar "
                  "baseline 3.87; one-time jit compile accounted "
                  "separately as per_pod_ms_incl_compile), >=5000 "
                  "binds/s sustained at 5k, placement prefixes "
                  "bit-identical to the scalar oracle, seeded tie "
                  "windows replay per-pod with identical RNG "
                  "consumption, zero duplicate binding POSTs, every "
                  "accepted bind folded exactly once"})
    assert big["batch"]["per_pod_ms_steady"] < 0.5, \
        f"drip batch gate: {big['batch']['per_pod_ms_steady']} ms/pod " \
        f">= 0.5 sustained at 50k"
    assert small_r["batch"]["pods_per_sec_steady"] >= 5_000, \
        f"bind throughput gate: " \
        f"{small_r['batch']['pods_per_sec_steady']} < 5000/s"


def config16(dtype, rtt, n_nodes=64, kills=8):
    """Round-14 tentpole gate: the crash-safe placement plane through
    the wire stub — a kill-recover soak driven by a seeded ChaosPlan of
    ``kill_process``/``restart_process`` events.

    Three kill sites per soak, each a SIGKILL at a journal byte offset
    (the KillSwitch tears the in-flight line exactly where a real kill
    would):

      mid-pipeline-fill — the kill lands inside a bind batch's intent/
                          outcome journal stream; the restarted process
                          reconciles unresolved intents against live
                          GETs and re-POSTs only the provably-unbound;
      mid-window        — the kill abandons a half-filled DripQueue
                          window (nothing journaled, nothing POSTed);
                          the restart's pending sweep re-offers;
      mid-eviction      — the eviction response is lost in transport
                          (stub reads the request, never answers); the
                          restart re-arms the cooldown, never re-POSTs.

    Plus a warm-standby leg: two electors on one lease, the leader
    dies, the standby reconciles the shared journal directory and lands
    its first bind.

    Gates: zero duplicate AND zero lost binds across every kill (the
    stub's per-pod ``bind_posts`` oracle), zero duplicate evictions,
    failover-to-first-bind <= 5 s on the wire stub, and deterministic
    replay — the same seed produces the same kill/recover timeline."""
    import os
    import shutil
    import tempfile

    from crane_scheduler_tpu.cluster import (
        Container,
        Pod,
        ResourceRequirements,
    )
    from crane_scheduler_tpu.cluster.kube import KubeClusterClient
    from crane_scheduler_tpu.fit import FitTracker, ResourceFitPlugin
    from crane_scheduler_tpu.framework.scheduler import Scheduler
    from crane_scheduler_tpu.plugins import DynamicPlugin
    from crane_scheduler_tpu.policy import DEFAULT_POLICY
    from crane_scheduler_tpu.resilience import ChaosPlan
    from crane_scheduler_tpu.resilience.recovery import (
        IntentJournal,
        KillSwitch,
        Reconciler,
        SimulatedCrash,
        WarmStandby,
    )
    from crane_scheduler_tpu.utils import parse_local_time

    kube_stub = _load_kube_stub()
    metric_names = [sp.name for sp in DEFAULT_POLICY.spec.sync_period]
    now = parse_local_time("2026-07-30T00:00:00Z") + 30.0

    def die():
        raise SimulatedCrash("config16 kill")

    def make_pods(ns, count):
        return [
            Pod(
                name=f"soak-{i:04d}", namespace=ns,
                containers=(Container("c", ResourceRequirements(
                    requests={"cpu": "100m", "memory": "128Mi"},
                )),),
            )
            for i in range(count)
        ]

    def build_sched(client):
        sched = Scheduler(client, clock=lambda: now, columnar=True)
        sched.register(ResourceFitPlugin(FitTracker(client)), weight=1)
        sched.register(
            DynamicPlugin(DEFAULT_POLICY, clock=lambda: now), weight=3
        )
        return sched

    def soak(seed, root):
        """One full kill-recover soak; returns its (pure-data) timeline
        for the deterministic-replay gate."""
        plan = ChaosPlan.generate(
            seed, steps=kills * 4, n_faults=kills,
            kinds=("kill_process",),
        )
        timeline = []
        server = kube_stub.KubeStubServer().start()
        try:
            for i in range(n_nodes):
                anno = {
                    m: f"{(i % 97) / 97:.5f},2026-07-30T00:00:00Z"
                    for m in metric_names
                }
                server.state.add_node(
                    f"node-{i}", f"10.0.0.{i % 250}", annotations=anno,
                    allocatable={"cpu": "16", "memory": "64Gi",
                                 "ephemeral-storage": "100Gi",
                                 "pods": "110"},
                )

            # -- mid-pipeline-fill: one life per kill_process event ----
            batch = 8
            for li, ev in enumerate(
                e for e in plan.events if e.kind == "kill_process"
            ):
                ns = f"kill{li}"
                jdir = os.path.join(root, ns)
                for p in make_pods(ns, batch):
                    server.state.add_pod(ns, p.name)
                pairs = [(f"{ns}/soak-{i:04d}", f"node-{i % n_nodes}")
                         for i in range(batch)]
                # fold the plan's 1..4096 offset into the ~1.1 KB this
                # batch actually journals, so most kills land mid-stream
                # (intent phase AND outcome phase) instead of past EOF
                off = 1 + ev.param("offset") % 1100
                journal = IntentJournal(jdir)
                journal.kill_switch = KillSwitch(off, action=die)
                client = KubeClusterClient(server.url)
                client.attach_intent_journal(journal)
                crashed = False
                try:
                    client.bind_pods(pairs)
                except SimulatedCrash:
                    crashed = True
                client.stop()
                journal.close()
                # restart_process: reconcile BEFORE scheduling reopens
                journal2 = IntentJournal(jdir)
                client2 = KubeClusterClient(server.url)
                client2.attach_intent_journal(journal2)
                report = Reconciler(
                    journal2, client2.get_pod_live
                ).reconcile()
                redo = {k: n for k, n, _t, _a in report.reschedule}
                if redo:
                    client2.bind_pods(list(redo.items()))
                pending = [
                    (k, n) for k, n in pairs
                    if k not in redo
                    and not client2.get_pod_live(k).node_name
                ]
                if pending:
                    client2.bind_pods(pending)
                client2.stop()
                journal2.close()
                for k, n in pairs:
                    posts = server.state.bind_posts.get(k, 0)
                    assert posts == 1, \
                        f"{k}: {posts} binding POSTs after kill at " \
                        f"offset {ev.param('offset')}"
                timeline.append({
                    "leg": "pipeline", "offset": off,
                    "crashed": crashed,
                    "outcomes": dict(sorted(report.outcomes.items())),
                    "reposted": len(redo), "swept": len(pending),
                })

            # -- mid-window: SIGKILL with a half-filled drip window ----
            ns = "window"
            win_pods = make_pods(ns, 5)
            for p in win_pods:
                server.state.add_pod(ns, p.name)
            client = KubeClusterClient(server.url)
            client.start()
            sched = build_sched(client)
            queue = sched.open_queue(window=64)
            for p in client.list_pods():
                if p.namespace == ns:
                    queue.offer(p)
            held = len(queue)
            assert held == 5 and not queue.results, \
                "window leg: pods dispatched before the kill"
            # the kill: the queue dies undrained — nothing reached the
            # wire, so the restart's pending sweep owns all five
            client.stop()
            client2 = KubeClusterClient(server.url)
            client2.start()
            sched2 = build_sched(client2)
            queue2 = sched2.open_queue(window=64)
            for p in client2.list_pods():
                if p.namespace == ns and not p.node_name:
                    queue2.offer(p)
            drained = queue2.drain()
            bound = [r for r in queue2.take_results() if r.node]
            client2.stop()
            assert drained == held == len(bound), \
                f"window leg: {held} held, {drained} drained, " \
                f"{len(bound)} bound"
            for p in win_pods:
                assert server.state.bind_posts.get(p.key(), 0) == 1
            timeline.append({"leg": "window", "held": held,
                             "rebound": len(bound)})

            # -- mid-eviction: response lost in transport --------------
            ns = "evict"
            server.state.add_pod(ns, "victim", spec={"nodeName": "node-0"})
            server.state.inject_write_faults((0, {}))
            jdir = os.path.join(root, "evict")
            journal = IntentJournal(jdir)
            client = KubeClusterClient(server.url)
            client.attach_intent_journal(journal)
            assert client.evict_pod(f"{ns}/victim") is False
            client.stop()
            journal.close()
            journal2 = IntentJournal(jdir)
            client2 = KubeClusterClient(server.url)
            report = Reconciler(journal2, client2.get_pod_live).reconcile()
            client2.stop()
            journal2.close()
            assert report.rearm_cooldowns == ["node-0"], \
                f"eviction leg: cooldowns {report.rearm_cooldowns}"
            assert sum(server.state.evict_posts.values()) == 0, \
                "eviction leg: a second eviction POST went out"
            timeline.append({
                "leg": "eviction",
                "outcomes": dict(sorted(report.outcomes.items())),
            })

            dups = server.state.duplicate_binds()
            dup_ev = server.state.duplicate_evictions()
            assert dups == 0, f"{dups} duplicate binding POSTs"
            assert dup_ev == 0, f"{dup_ev} duplicate evictions"

            # -- warm standby: leader dies, standby lands a bind -------
            server.state.add_pod("failover", "first")
            lock = os.path.join(root, "leader.lock")
            jdir = os.path.join(root, "standby-intents")
            fo_client = KubeClusterClient(server.url)
            first_bind = []

            def promote(rep):
                fo_client.attach_intent_journal(standby_b.journal)
                okb = fo_client.bind_pods([("failover/first", "node-1")])
                first_bind.append(time.perf_counter())
                assert okb == ["failover/first"]

            standby_a = WarmStandby(
                lock, "sched-a", jdir, fo_client.get_pod_live,
                lease_duration=1.0, renew_deadline=0.6, retry_period=0.1,
            ).start()
            assert standby_a.wait_ready(10.0), "leader never led"
            standby_b = WarmStandby(
                lock, "sched-b", jdir, fo_client.get_pod_live,
                on_promote=promote,
                lease_duration=1.0, renew_deadline=0.6, retry_period=0.1,
            ).start()
            t_kill = time.perf_counter()
            standby_a.stop()  # the leader dies
            assert standby_b.wait_ready(10.0), "standby never took over"
            failover_s = first_bind[0] - t_kill
            standby_b.stop()
            fo_client.stop()
            assert server.state.bind_posts.get("failover/first", 0) == 1
            assert failover_s <= 5.0, \
                f"failover-to-first-bind {failover_s:.2f}s > 5s"
            timeline.append({"leg": "failover", "first_bind": "ok"})
            return timeline, failover_s
        finally:
            server.stop()

    seed = 16
    t0 = time.perf_counter()
    root1 = tempfile.mkdtemp(prefix="crane-c16a-")
    root2 = tempfile.mkdtemp(prefix="crane-c16b-")
    try:
        timeline1, failover_s = soak(seed, root1)
        wall_s = time.perf_counter() - t0
        timeline2, _ = soak(seed, root2)
        assert timeline1 == timeline2, \
            "same seed produced different kill/recover timelines"
    finally:
        shutil.rmtree(root1, ignore_errors=True)
        shutil.rmtree(root2, ignore_errors=True)

    pipeline_legs = [t for t in timeline1 if t["leg"] == "pipeline"]
    reposted = sum(t["reposted"] for t in pipeline_legs)
    swept = sum(t["swept"] for t in pipeline_legs)
    crashes = sum(1 for t in pipeline_legs if t["crashed"])
    log(f"config16: {len(pipeline_legs)} seeded kills ({crashes} landed "
        f"mid-stream), {reposted} reconciler re-POSTs + {swept} sweep "
        f"binds, 0 duplicate / 0 lost; failover-to-first-bind "
        f"{failover_s * 1e3:.0f} ms; timeline deterministic")
    emit({"config": 16,
          "desc": "kill-recover soak: seeded kill_process/"
                  "restart_process plan over the intent journal "
                  "(mid-pipeline-fill, mid-window, mid-eviction) plus "
                  "warm-standby failover, through the wire stub",
          "seed": seed,
          "kills": len(pipeline_legs),
          "kills_landed": crashes,
          "reconciler_reposts": reposted,
          "sweep_binds": swept,
          "duplicate_binds": 0,
          "lost_binds": 0,
          "duplicate_evictions": 0,
          "failover_to_first_bind_s": round(failover_s, 3),
          "soak_wall_s": round(wall_s, 2),
          "deterministic_replay": "ok",
          "note": "gates: every pod exactly one binding POST across a "
                  "SIGKILL at any seeded journal offset, eviction "
                  "never re-POSTed (cooldown re-armed instead), "
                  "failover-to-first-bind <= 5 s, same seed => same "
                  "timeline"})


def config17(dtype, rtt, node_scales=(5_000, 50_000)):
    """Round-15 tentpole gate: overload-resilient serving — a seeded
    open-loop storm at 3x the sidecar's measured capacity, through the
    real async front end with admission control + brownout enabled.

    Per node scale:

      unloaded — sequential /v1/score with a unique ``now`` per request
                 (cache-busting: every accepted request costs a real
                 render); yields the unloaded p99;
      peak     — closed-loop saturation (4 workers) over the same
                 cache-busting bodies; yields the pre-storm peak rps;
      storm    — seeded open-loop Poisson arrivals at 3x peak (capped
                 to bound the thread-per-request harness), every
                 request carrying a crane-deadline-ms budget, while a
                 prober hits /healthz throughout;
      deadline — a burst of already-expired and 1 ms budgets: sheds at
                 parse/queue/dispatch, never inside the device path.

    Gates: storm goodput >= 80% of the pre-storm peak; accepted p99
    <= 2x unloaded p99 (+50 ms scheduling-noise grace — the adaptive
    limiter is what holds this: it cuts concurrency when observed
    latency inflates past 2x baseline); zero expired requests reach
    device dispatch (``expired_at_dispatch`` == 0); /healthz answers
    200 on the IO thread for every probe; and the shed/admit timeline
    is deterministic — the same seed replayed twice through the
    virtual-time admission harness produces identical timelines."""
    import threading
    import urllib.request

    from crane_scheduler_tpu.policy import DEFAULT_POLICY
    from crane_scheduler_tpu.resilience import (
        StormSchedule,
        replay_admission,
        run_open_loop,
    )
    from crane_scheduler_tpu.service import (
        AdmissionController,
        BrownoutController,
        GradientLimiter,
        ScoringHTTPServer,
        ScoringService,
        TenantQueues,
    )
    from crane_scheduler_tpu.sim import SimConfig, Simulator

    seed = 17
    max_storm_requests = 600
    scales = []

    def admission_factory(clock=None):
        return AdmissionController(
            limiter=GradientLimiter(min_limit=2, max_limit=4, initial=4),
            queues=TenantQueues(depth=2),
            clock=clock or time.monotonic,
        )

    for n_nodes in node_scales:
        sim = Simulator(SimConfig(n_nodes=n_nodes, seed=seed))
        sim.sync_metrics()
        svc = ScoringService(
            sim.cluster, DEFAULT_POLICY, dtype=dtype, now_bucket_s=0.0
        )
        svc.refresh()
        brownout = BrownoutController(telemetry=svc.telemetry)
        admission = AdmissionController(
            limiter=GradientLimiter(min_limit=2, max_limit=4, initial=4),
            queues=TenantQueues(depth=2),
            brownout=brownout,
            telemetry=svc.telemetry,
        )
        server = ScoringHTTPServer(
            svc, port=0, frontend="async", admission=admission,
            brownout=brownout, idle_timeout_s=5.0,
        )
        server.start()
        base = f"http://127.0.0.1:{server.port}"
        now0 = sim.clock.now()
        counter = [0]
        lock = threading.Lock()

        def fresh_body():
            # a unique `now` per request defeats the response cache and
            # single-flight coalescing: accepted => a real render
            with lock:
                counter[0] += 1
                return json.dumps(
                    {"now": now0 + counter[0] * 1e-4, "refresh": False}
                ).encode()

        def post(body, headers=None, timeout=30.0):
            req = urllib.request.Request(
                f"{base}/v1/score", data=body, method="POST",
                headers={"Content-Type": "application/json",
                         **(headers or {})},
            )
            t0 = time.perf_counter()
            try:
                with urllib.request.urlopen(req, timeout=timeout) as r:
                    r.read()
                    return r.status, time.perf_counter() - t0
            except urllib.error.HTTPError as e:
                e.read()
                return e.code, time.perf_counter() - t0

        try:
            # warm: JIT + columns + first renders, outside every timing
            for _ in range(6):
                assert post(fresh_body())[0] == 200

            # unloaded p99: sequential cache-busting requests
            lat = []
            for _ in range(40):
                status, dt = post(fresh_body())
                assert status == 200
                lat.append(dt)
            unloaded_p99 = float(np.percentile(lat, 99))

            # pre-storm peak: closed-loop saturation for ~0.8 s
            peak_stop = time.perf_counter() + 0.8
            served = [0] * 4

            def closed_loop(slot):
                while time.perf_counter() < peak_stop:
                    status, _ = post(fresh_body())
                    if status == 200:
                        served[slot] += 1
                    else:
                        time.sleep(0.002)

            workers = [
                threading.Thread(target=closed_loop, args=(i,))
                for i in range(4)
            ]
            t0 = time.perf_counter()
            for w in workers:
                w.start()
            for w in workers:
                w.join()
            peak_rps = max(sum(served) / (time.perf_counter() - t0), 1.0)

            # the storm: 3x peak, open loop, seeded, deadline-carrying
            storm_rps = 3.0 * peak_rps
            duration = min(1.5, max_storm_requests / storm_rps)
            schedule = StormSchedule(
                seed, duration_s=duration, phases=[(0.0, storm_rps)],
                deadline_ms=10_000.0,
            )
            health_codes = []
            health_stop = threading.Event()

            def health_probe():
                while not health_stop.is_set():
                    try:
                        with urllib.request.urlopen(
                            f"{base}/healthz", timeout=5
                        ) as r:
                            health_codes.append(r.status)
                    except Exception:
                        health_codes.append(0)
                    health_stop.wait(0.05)

            prober = threading.Thread(target=health_probe, daemon=True)
            prober.start()
            results = run_open_loop(
                "127.0.0.1", server.port, schedule.arrivals,
                target="/v1/score",
                body_fn=lambda i, a: fresh_body(),
                timeout_s=60.0,
            )
            health_stop.set()
            prober.join(timeout=5.0)

            accepted = [r for r in results if r.status == 200]
            shed = [r for r in results if r.status in (429, 503, 504)]
            errors = [r for r in results if r.status == 0]
            assert not errors, f"transport errors under storm: {errors[:3]}"
            assert len(accepted) + len(shed) == len(results)
            goodput_rps = len(accepted) / duration
            accepted_p99 = float(np.percentile(
                [r.latency_s for r in accepted], 99
            ))
            assert goodput_rps >= 0.8 * peak_rps, \
                f"{n_nodes} nodes: storm goodput {goodput_rps:.0f} rps " \
                f"< 80% of pre-storm peak {peak_rps:.0f} rps"
            assert accepted_p99 <= 2.0 * unloaded_p99 + 0.050, \
                f"{n_nodes} nodes: accepted p99 {accepted_p99 * 1e3:.1f} " \
                f"ms > 2x unloaded {unloaded_p99 * 1e3:.1f} ms"
            assert health_codes and all(c == 200 for c in health_codes), \
                f"{n_nodes} nodes: /healthz faltered: " \
                f"{[c for c in health_codes if c != 200]}"

            # deadline leg: expired budgets shed before the device path
            for _ in range(10):
                status, _ = post(
                    fresh_body(), headers={"crane-deadline-ms": "-1"}
                )
                assert status == 504
            tight = 0
            for _ in range(10):
                status, _ = post(
                    fresh_body(), headers={"crane-deadline-ms": "0.001"}
                )
                tight += status == 504
            assert tight >= 1, "1 us budgets all survived to completion?"
            expired_at_dispatch = svc.metrics()["expired_at_dispatch"]
            assert expired_at_dispatch == 0, \
                f"{expired_at_dispatch} expired requests reached dispatch"

            # determinism: the same seed through the virtual-time
            # admission harness, twice — identical shed/admit timelines
            t1 = replay_admission(
                schedule.arrivals, admission_factory,
                service_time_s=max(1.0 / peak_rps, 1e-4),
            )
            t2 = replay_admission(
                schedule.arrivals, admission_factory,
                service_time_s=max(1.0 / peak_rps, 1e-4),
            )
            assert t1 == t2, "same seed produced different timelines"

            log(f"config17 [{n_nodes} nodes]: peak {peak_rps:.0f} rps, "
                f"storm {storm_rps:.0f} rps x {duration:.2f}s -> "
                f"goodput {goodput_rps:.0f} rps "
                f"({goodput_rps / peak_rps:.0%}), "
                f"{len(shed)} shed, accepted p99 "
                f"{accepted_p99 * 1e3:.1f} ms (unloaded "
                f"{unloaded_p99 * 1e3:.1f} ms), "
                f"{len(health_codes)} healthz probes green, "
                f"0 expired at dispatch, replay deterministic")
            scales.append({
                "nodes": n_nodes,
                "peak_rps": round(peak_rps, 1),
                "storm_rps": round(storm_rps, 1),
                "storm_s": round(duration, 3),
                "arrivals": len(results),
                "served": len(accepted),
                "shed": len(shed),
                "goodput_rps": round(goodput_rps, 1),
                "goodput_frac": round(goodput_rps / peak_rps, 3),
                "unloaded_p99_ms": round(unloaded_p99 * 1e3, 2),
                "accepted_p99_ms": round(accepted_p99 * 1e3, 2),
                "healthz_probes": len(health_codes),
                "expired_at_dispatch": 0,
                "deterministic_replay": "ok",
            })
        finally:
            server.stop()

    emit({"config": 17,
          "desc": "overload storm: seeded open-loop 3x-capacity storm "
                  "through the admission-controlled async front end "
                  "(deadline propagation, brownout, healthz-on-IO-"
                  "thread), per node scale",
          "seed": seed,
          "scales": scales,
          "note": "gates: goodput >= 80% of pre-storm peak, accepted "
                  "p99 <= 2x unloaded p99, zero expired requests at "
                  "device dispatch, /healthz 200 throughout, same seed "
                  "=> same virtual-time shed/admit timeline"})


def config18(dtype, rtt, n_nodes=250_000):
    """Round-14 tentpole gate: the sharded placement plane — one
    >=250k-node mirror partitioned across N concurrent drip schedulers
    (``framework.shardplane``), per-shard version-fenced columns, and
    optimistic bind conflict resolution.

    Legs (all over ONE in-process 250k-node ``ClusterState`` unless
    noted — the wire stub would dominate at this scale and the write
    path already has its own gates in configs 8/15):

      columns  — 4-shard plane; per shard, the first probe pays the
                 column build over its 1/N node slice, then ONE named
                 annotation patch on a shard-0 node and a re-probe:
                 only shard 0 may reparse (one row), the other shards'
                 fences never moved — the O(dirty) contract at 250k;
      scaling  — 1 vs 2 vs 4 schedulers over disjoint shards, same
                 total pod storm (threaded ``run_storm``); host cores
                 don't help here (CI pins one), the speedup is the
                 1/N-sized per-shard scan column — exactly the claim;
      conflict — 2 schedulers with overlapping shards over the wire
                 stub, disjoint pod queues: conflicts come from
                 stale-window fences on co-owned binds, never from the
                 arbiter; rate gated <=5%, per-pod bind POST oracle;
      parity   — the forced-8-device shard_map kernel + scheduler
                 parity workers (``tests/test_sharded_drip.py``) in
                 subprocesses, bit-identical to single-device.

    Gates: after a named patch, untouched shards re-probe from cache
    (<5 ms each — their fences never moved) and the dirtied shard pays
    an identity-gated sweep, total <= build/10; >=1.8x (2 sched) and
    >=3x (4 sched) storm throughput vs 1; conflict rate <=5% with zero
    duplicate POSTs and bind_posts == pods; both parity workers exit
    0."""
    import os
    import subprocess
    import threading  # noqa: F401  (shardplane storms are threaded)

    from crane_scheduler_tpu.cluster import (
        ClusterState,
        Container,
        Node,
        Pod,
        ResourceRequirements,
    )
    from crane_scheduler_tpu.cluster.kube import KubeClusterClient
    from crane_scheduler_tpu.cluster.shards import shard_owners
    from crane_scheduler_tpu.fit import FitTracker, ResourceFitPlugin
    from crane_scheduler_tpu.framework.scheduler import Scheduler
    from crane_scheduler_tpu.framework.shardplane import ShardedPlacementPlane
    from crane_scheduler_tpu.plugins import DynamicPlugin
    from crane_scheduler_tpu.policy import DEFAULT_POLICY
    from crane_scheduler_tpu.utils import format_local_time, parse_local_time

    now = parse_local_time("2026-07-30T00:00:00Z") + 30.0
    metric_names = [sp.name for sp in DEFAULT_POLICY.spec.sync_period]
    alloc = {"cpu": "64", "memory": "256Gi",
             "ephemeral-storage": "100Gi", "pods": "1100"}

    # -- the 250k-node mirror ------------------------------------------------
    # A handful of shared annotation dicts (patch copies on write, so
    # sharing is safe) keeps 250k nodes at tens of MB: real score
    # classes, real tie sets, fresh timestamps.
    ts = format_local_time(now - 20.0)
    variants = [
        {m: f"{0.20 + 0.01 * ((j + k) % 11):.5f},{ts}"
         for k, m in enumerate(metric_names)}
        for j in range(8)
    ]
    t0 = time.perf_counter()
    cluster = ClusterState()
    cluster.replace_nodes(
        Node(name=f"node-{i:06d}", annotations=variants[i % 8],
             allocatable=alloc)
        for i in range(n_nodes)
    )
    log(f"config18: {n_nodes} nodes mirrored in "
        f"{time.perf_counter() - t0:.1f}s")

    def factory(view):
        sched = Scheduler(view, clock=lambda: now, columnar=True)
        sched.register(ResourceFitPlugin(FitTracker(view)), weight=1)
        sched.register(DynamicPlugin(DEFAULT_POLICY, clock=lambda: now),
                       weight=3)
        return sched

    def make_pods(tag, count, cpu="100m"):
        pods = [
            Pod(name=f"p18-{tag}-{i:04d}", namespace="default",
                containers=(Container("c", ResourceRequirements(
                    requests={"cpu": cpu, "memory": "128Mi"},
                )),))
            for i in range(count)
        ]
        cluster.add_pods(pods)
        return pods

    # -- leg 1: column build vs named-write refresh (O(dirty)) --------------
    plane = ShardedPlacementPlane(cluster, 4, overlap=0.0)
    scheds = plane.add_scheduler(factory)
    probes = make_pods("probe", 8, cpu="100000")  # infeasible: no binds
    build_s = []
    for i, sched in enumerate(scheds):
        t0 = time.perf_counter()
        r = sched.schedule_one(probes[i])
        build_s.append(time.perf_counter() - t0)
        assert r.node is None, "infeasible probe placed?!"
    # one named write on a node only shard 0 observes
    victim = next(n.name for n in cluster.list_nodes()
                  if shard_owners(n.name, 4, 0.0) == (0,))
    assert cluster.patch_node_annotation(
        victim, metric_names[0], f"0.90000,{ts}")
    refresh_s = []
    for i, sched in enumerate(scheds):
        t0 = time.perf_counter()
        sched.schedule_one(probes[4 + i])
        refresh_s.append(time.perf_counter() - t0)
    build_total, refresh_total = sum(build_s), sum(refresh_s)
    log(f"config18[columns]: 4-shard build {build_total * 1e3:.0f} ms "
        f"({'/'.join(f'{s * 1e3:.0f}' for s in build_s)}), refresh after "
        f"1 named patch {refresh_total * 1e3:.1f} ms "
        f"({'/'.join(f'{s * 1e3:.1f}' for s in refresh_s)})")

    # -- leg 2: 1 vs 2 vs 4 schedulers, disjoint shards ----------------------
    # 512 divides into whole 128-pod windows at every scheduler count,
    # so each leg's warm-up compiles the one (window, shard-size) shape
    # bucket the timed storm uses — no jit compile inside the timing
    total_pods, window = 512, 128

    def storm_leg(count):
        plane = ShardedPlacementPlane(cluster, count, overlap=0.0)
        scheds = plane.add_scheduler(factory)
        per = total_pods // count
        # warm outside the timing: first ensure() builds this leg's
        # 1/N columns, first dispatch jit-compiles the shape bucket;
        # the warm pods are infeasible so no state changes
        warm = [make_pods(f"w{count}-{i}", window, cpu="100000")
                for i in range(count)]
        for res in plane.run_storm(warm, window=window, threaded=False):
            assert all(r.node is None for r in res), "warm pod placed"
        queues = [make_pods(f"s{count}-{i}", per) for i in range(count)]
        t0 = time.perf_counter()
        results = plane.run_storm(queues, window=window, threaded=True)
        wall_s = time.perf_counter() - t0
        for i, res in enumerate(results):
            assert len(res) == per
            for r in res:
                assert r.node is not None, f"shard {i} unplaced: {r.reason}"
                assert i in shard_owners(r.node, count, 0.0), \
                    f"shard {i} placed outside its shard: {r.node}"
        # disjoint shards cannot contest a node or a pod: any conflict
        # here is a fence-discipline bug, not bad luck
        assert not plane.conflict_stats(), plane.conflict_stats()
        disp = sum(s.drip_stats()["batch"]["dispatches"] for s in scheds)
        return {
            "schedulers": count,
            "pods": total_pods,
            "wall_ms": round(wall_s * 1e3, 1),
            "pods_per_sec": round(total_pods / wall_s, 1),
            "per_pod_ms": round(wall_s * 1e3 / total_pods, 3),
            "dispatch_windows": disp,
        }, wall_s

    scaling = {}
    walls = {}
    for count in (1, 2, 4):
        scaling[count], walls[count] = storm_leg(count)
        log(f"config18[scaling]: {count} sched x "
            f"{total_pods // count} pods -> "
            f"{scaling[count]['pods_per_sec']:,.0f} pods/s "
            f"({scaling[count]['per_pod_ms']} ms/pod)")
    speedup2 = round(walls[1] / walls[2], 2)
    speedup4 = round(walls[1] / walls[4], 2)
    log(f"config18[scaling]: speedup 1->2 {speedup2}x, 1->4 {speedup4}x")

    # -- leg 3: overlapping shards over the wire stub (conflict rate) --------
    kube_stub = _load_kube_stub()
    stub_nodes, stub_pods, overlap = 4_000, 800, 0.25
    server = kube_stub.KubeStubSubprocess()
    try:
        server.seed(stub_nodes, "node-", metrics=metric_names,
                    allocatable={"cpu": "16", "memory": "64Gi",
                                 "ephemeral-storage": "100Gi",
                                 "pods": "110"})
        client = KubeClusterClient(server.url, list_page_limit=2000)
        client.start()
        assert len(client.list_nodes()) == stub_nodes
        wire_plane = ShardedPlacementPlane(client, 2, overlap=overlap)
        wire_plane.add_scheduler(factory)
        half = stub_pods // 2
        queues = []
        for i in range(2):
            pods = [
                Pod(name=f"c18-{i}-{j:04d}", namespace="default",
                    containers=(Container("c", ResourceRequirements(
                        requests={"cpu": "100m", "memory": "128Mi"},
                    )),))
                for j in range(half)
            ]
            for pod in pods:
                client.add_pod(pod)
            queues.append(pods)
        results = wire_plane.run_storm(queues, window=16, threaded=True)
        for i, res in enumerate(results):
            for r in res:
                assert r.node is not None, f"shard {i} unplaced: {r.reason}"
                assert i in shard_owners(r.node, 2, overlap), \
                    f"shard {i} placed outside its shard: {r.node}"
        stats = server.stats()
        assert stats["duplicate_binds"] == 0, "double-POSTed bind!"
        assert stats["bind_posts"] == stub_pods, \
            f"bind POSTs {stats['bind_posts']} != {stub_pods} pods"
        conflicts = wire_plane.conflict_stats()
        # disjoint pod queues: the arbiter must never fire — every
        # conflict is a stale window on a co-owned node
        assert conflicts.get("claim_lost", 0) == 0, conflicts
        conflict_rate = sum(conflicts.values()) / stub_pods
        client.stop()
    finally:
        server.stop()
    log(f"config18[conflict]: {stub_pods} pods, 2 scheds overlap "
        f"{overlap}: conflicts {conflicts or '{}'} "
        f"(rate {conflict_rate:.3%}), per-pod bind POST oracle ok")

    # -- leg 4: shard_map kernel parity on a forced 8-device mesh ------------
    root = os.path.dirname(os.path.abspath(__file__))
    parity = {}
    for leg, marker in (("kernel", "kernel-parity OK"),
                        ("scheduler", "scheduler-parity OK")):
        env = dict(os.environ)
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = os.pathsep.join(
            [root, os.path.join(root, "tests"),
             env.get("PYTHONPATH", "")])
        proc = subprocess.run(
            [sys.executable,
             os.path.join(root, "tests", "test_sharded_drip.py"),
             "worker", leg],
            capture_output=True, text=True, env=env, timeout=600)
        assert proc.returncode == 0, (
            f"parity worker {leg} rc={proc.returncode}\n"
            f"{proc.stdout}\n{proc.stderr}")
        assert marker in proc.stdout, proc.stdout
        parity[leg] = "ok"
        log(f"config18[parity]: {leg} worker ok (8-device mesh)")

    emit({"config": 18,
          "schedulers": 4,
          "desc": "sharded placement plane: 250k-node mirror, "
                  "mesh-partitioned drip columns, 1/2/4 concurrent "
                  "schedulers over deterministic node shards, "
                  "optimistic bind conflict resolution",
          "n_nodes": n_nodes,
          "column_build_ms": round(build_total * 1e3, 1),
          "column_refresh_ms": round(refresh_total * 1e3, 2),
          "build_over_refresh": round(build_total / max(refresh_total,
                                                        1e-9), 1),
          "scaling": {str(k): v for k, v in scaling.items()},
          "speedup_2_sched": speedup2,
          "speedup_4_sched": speedup4,
          "conflict": {"nodes": stub_nodes, "pods": stub_pods,
                       "overlap": overlap,
                       "outcomes": conflicts,
                       "rate": round(conflict_rate, 4)},
          "parity": parity,
          "note": "gates: named-patch refresh — untouched shards <5 ms "
                  "each (per-shard fences never moved), total <= "
                  "build/10 at 250k (the dirtied shard's sweep is "
                  "identity-gated, only the patched row reparses), "
                  ">=1.8x 2-sched and >=3x 4-sched storm "
                  "throughput on disjoint shards, <=5% conflict rate "
                  "on overlapping shards with zero duplicate binding "
                  "POSTs and bind_posts == pods, shard_map kernel + "
                  "scheduler bit-identical to single-device on a "
                  "forced 8-device mesh"})
    for i, s in enumerate(refresh_s[1:], start=1):
        assert s < 0.005, \
            f"O(dirty) gate: untouched shard {i} re-probed in " \
            f"{s * 1e3:.1f} ms (fence must not have moved)"
    assert refresh_total <= build_total / 10, \
        f"O(dirty) gate: refresh {refresh_total * 1e3:.1f} ms > " \
        f"build {build_total * 1e3:.1f} ms / 10"
    assert speedup2 >= 1.8, \
        f"scaling gate: 2 schedulers {speedup2}x < 1.8x"
    assert speedup4 >= 3.0, \
        f"scaling gate: 4 schedulers {speedup4}x < 3.0x"
    assert conflict_rate <= 0.05, \
        f"conflict gate: rate {conflict_rate:.3%} > 5%"


def config19(dtype, rtt, n_nodes=50_000, n_replicas=4):
    """Round-16 tentpole gate: the replicated scoring tier — one
    50k-node primary publishing the delta-stream feed, N shared-nothing
    serving replicas (each a private mirror + store + cache + breaker +
    admission stack fed over the wire), and the consistent-hash router
    in front.

    Methodology on the 1-core CI host: real CPU parallelism can't carry
    a replica-scaling claim here, so each replica's scorer is paced by a
    simulated accelerator dispatch — a ``device_sim_ms`` sleep under a
    per-replica device lock. Dispatches serialize per device exactly
    like a real one-TPU-per-replica deployment, and the sleep releases
    the GIL so different replicas' devices overlap the way separate
    hosts would; the parse/render/transport CPU stays real and shared.
    The baseline is measured IN-RUN: the same seeded closed-loop client
    population through a single-replica router first.

    Legs:

      baseline — closed-loop clients (one per tenant, cache-busting
                 unique ``now`` per request, 10 s deadlines) through a
                 1-replica router;
      storm    — the same client population through the N-replica hash
                 router, tenants pre-picked via ``route_for`` to cover
                 every replica (a thin deterministic stand-in for a
                 large tenant population), with annotation churn
                 publishing delta windows and replica lag sampled
                 throughout both legs;
      identity — at a quiesced version fence (bootstrap, and again
                 after churn with a forced refresh), the same explicit
                 ``now`` posted to every replica directly: bodies must
                 be byte-identical and stamp the published version.

    Gates: storm goodput >= 3x the in-run baseline at 4 replicas;
    byte-identical verdicts across replicas at the same version key;
    every lag sample <= the configured version budget; 0
    expired-at-dispatch on every replica; the router's per-replica
    request counters (strict-parsed from /metrics) show every replica
    served."""
    import threading
    import urllib.error
    import urllib.request

    from crane_scheduler_tpu.cluster.replication import DeltaPublisher
    from crane_scheduler_tpu.policy import DEFAULT_POLICY
    from crane_scheduler_tpu.service import (
        ReplicaRouter,
        ScoringHTTPServer,
        ScoringService,
        ServingReplica,
    )
    from crane_scheduler_tpu.sim import SimConfig, Simulator
    from crane_scheduler_tpu.telemetry.expfmt import parse_exposition

    seed = 19
    # sized so the per-replica device term dominates the shared-CPU
    # render/transport term on the 1-core CI host: scaling then
    # measures replica overlap, not host cores
    device_sim_ms = 800.0
    lag_budget = 64
    churn_patches = 16
    baseline_s = 12.0
    storm_s = 14.0
    rng = random.Random(seed)

    sim = Simulator(SimConfig(n_nodes=n_nodes, seed=seed))
    sim.sync_metrics()
    svc = ScoringService(
        sim.cluster, DEFAULT_POLICY, dtype=dtype, now_bucket_s=0.0
    )
    svc.refresh()
    pub = DeltaPublisher(sim.cluster, telemetry=svc.telemetry)
    server = ScoringHTTPServer(
        svc, port=0, frontend="async", replication=pub
    )
    server.start()
    # windows are published explicitly below (deterministic churn),
    # never from the wall-clock timer
    pub.publish_window()

    def paced(inner):
        # one simulated accelerator per replica: dispatches serialize
        # on the device lock, and the sleep releases the GIL so OTHER
        # replicas' devices run concurrently — the scaling axis under
        # test
        lock = threading.Lock()

        def scorer(*args, **kwargs):
            with lock:
                time.sleep(device_sim_ms / 1e3)
                return inner(*args, **kwargs)

        return scorer

    replicas = []
    routers = []
    try:
        for i in range(n_replicas):
            r = ServingReplica(
                DEFAULT_POLICY,
                name=f"replica-{i}",
                feed=("127.0.0.1", server.port),
                dtype=dtype,
                now_bucket_s=0.0,
                scorer_wrap=paced,
            )
            r.start()
            replicas.append(r)
        for r in replicas:
            assert r.wait_caught_up(pub.published_version, timeout_s=60.0), \
                f"{r.name} never caught up to v{pub.published_version}"

        now0 = sim.clock.now()
        counter = [0]
        counter_lock = threading.Lock()

        def fresh_now():
            # a unique `now` per request defeats the response cache and
            # single-flight coalescing: every request is a real dispatch
            with counter_lock:
                counter[0] += 1
                return now0 + counter[0] * 1e-4

        def post(port, body, headers=None, timeout=30.0):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/v1/score", data=body,
                method="POST",
                headers={"Content-Type": "application/json",
                         **(headers or {})},
            )
            t0 = time.perf_counter()
            try:
                with urllib.request.urlopen(req, timeout=timeout) as resp:
                    payload = resp.read()
                    return resp.status, time.perf_counter() - t0, payload
            except urllib.error.HTTPError as e:
                e.read()
                return e.code, time.perf_counter() - t0, b""

        # warm every replica: the first request ingests the mirror into
        # the columnar store (refresh=True) and pays the jit compile
        for r in replicas:
            for refresh in (True, False):
                body = json.dumps(
                    {"now": fresh_now(), "refresh": refresh}
                ).encode()
                status, _, _ = post(r.port, body)
                assert status == 200, f"warmup {r.name}: HTTP {status}"

        def identity_check(refresh):
            # same version fence + same explicit now at every replica
            # => byte-identical bodies stamping the published version
            v = pub.published_version
            for r in replicas:
                assert r.wait_caught_up(v, timeout_s=60.0), \
                    f"{r.name} stuck behind v{v}"
            body = json.dumps(
                {"now": fresh_now(), "refresh": refresh}
            ).encode()
            rendered = []
            for r in replicas:
                status, _, payload = post(r.port, body)
                assert status == 200, f"identity {r.name}: HTTP {status}"
                rendered.append(payload)
            assert all(p == rendered[0] for p in rendered), \
                "replicas at the same version rendered different bytes"
            doc = json.loads(rendered[0])
            assert doc["version"] == v, (doc["version"], v)
            return len(rendered[0])

        ident_boot = identity_check(refresh=False)

        router1 = ReplicaRouter(
            [(replicas[0].name, "127.0.0.1", replicas[0].port)],
            primary=("127.0.0.1", server.port), mode="hash",
            lag_budget_versions=lag_budget, port=0,
        )
        router1.start()
        routers.append(router1)
        routerN = ReplicaRouter(
            [(r.name, "127.0.0.1", r.port) for r in replicas],
            primary=("127.0.0.1", server.port), mode="hash",
            lag_budget_versions=lag_budget, port=0,
        )
        routerN.start()
        routers.append(routerN)

        # tenant population: 3 closed-loop clients per replica (enough
        # in-flight depth to keep each device busy across the shared
        # parse/render hops), names picked deterministically off the
        # static ring so the hash router spreads them over every
        # replica (what a large real tenant population looks like,
        # without needing thousands of client threads)
        per_replica = {r.name: [] for r in replicas}
        i = 0
        while any(len(v) < 3 for v in per_replica.values()):
            i += 1
            assert i < 10_000, "ring never covered every replica"
            t = f"tenant-{i}"
            owner = routerN.route_for(t)
            if owner is not None and len(per_replica[owner]) < 3:
                per_replica[owner].append(t)
        tenants = [t for ts in per_replica.values() for t in ts]

        # annotation churn + lag sampling across both legs: patch a
        # seeded handful of nodes, publish the delta window, sample
        # every replica's lag vs the published fence
        node_names = [n.name for n in sim.cluster.list_nodes()]
        churn_stop = threading.Event()
        lag_samples = []
        windows = [0]

        def churn_loop():
            j = 0
            while not churn_stop.is_set():
                for _ in range(churn_patches):
                    j += 1
                    sim.cluster.patch_node_annotation(
                        rng.choice(node_names),
                        "crane.io/bench-churn", str(j),
                    )
                pub.publish_window()
                windows[0] += 1
                for _ in range(4):
                    v = pub.published_version
                    lag_samples.extend(
                        max(0, v - r.applied_version) for r in replicas
                    )
                    if churn_stop.wait(0.5):
                        return

        churn = threading.Thread(target=churn_loop, daemon=True)
        churn.start()

        def closed_loop(port, duration_s):
            stop_at = time.perf_counter() + duration_s
            results = []
            res_lock = threading.Lock()

            def client(tenant):
                while time.perf_counter() < stop_at:
                    body = json.dumps(
                        {"now": fresh_now(), "refresh": False}
                    ).encode()
                    status, lat, _ = post(
                        port, body,
                        headers={"crane-tenant": tenant,
                                 "crane-deadline-ms": "10000"},
                    )
                    with res_lock:
                        results.append((status, lat))

            threads = [
                threading.Thread(target=client, args=(t,)) for t in tenants
            ]
            t0 = time.perf_counter()
            for th in threads:
                th.start()
            for th in threads:
                th.join()
            elapsed = time.perf_counter() - t0
            ok = sorted(lat for status, lat in results if status == 200)
            return {
                "clients": len(tenants),
                "duration_s": round(elapsed, 3),
                "requests": len(results),
                "served": len(ok),
                "rps": round(len(ok) / elapsed, 2),
                "p99_ms": round(
                    ok[int(0.99 * (len(ok) - 1))] * 1e3, 1
                ) if ok else None,
            }

        base = closed_loop(router1.port, baseline_s)
        storm = closed_loop(routerN.port, storm_s)
        churn_stop.set()
        churn.join(timeout=10.0)

        # post-churn identity at the settled fence, forced refresh:
        # every replica re-ingests its mirror and must still render the
        # same bytes
        ident_churn = identity_check(refresh=True)

        # strict-parse the router's per-replica served counters
        with urllib.request.urlopen(
            f"http://127.0.0.1:{routerN.port}/metrics", timeout=10.0
        ) as resp:
            families = parse_exposition(resp.read().decode())
        per_replica_requests = {
            labels[0][1]: value
            for _, labels, value in
            families["crane_router_requests_total"]["samples"]
        }

        scaling = storm["rps"] / max(base["rps"], 1e-9)
        lag_max = max(lag_samples) if lag_samples else 0
        expired = {
            r.name: r.service.stats.expired_at_dispatch for r in replicas
        }

        assert scaling >= 3.0, \
            f"scaling gate: {n_replicas} replicas {scaling:.2f}x < 3x " \
            f"({storm['rps']} vs {base['rps']} rps)"
        assert lag_max <= lag_budget, \
            f"lag gate: max sampled lag {lag_max} > budget {lag_budget}"
        assert all(v == 0 for v in expired.values()), \
            f"expired requests reached a replica device: {expired}"
        assert all(
            per_replica_requests.get(r.name, 0) > 0 for r in replicas
        ), f"router starved a replica: {per_replica_requests}"

        log(f"config19 [{n_nodes} nodes, {n_replicas} replicas, "
            f"device {device_sim_ms:.0f} ms]: baseline {base['rps']} rps "
            f"-> storm {storm['rps']} rps ({scaling:.2f}x), "
            f"{windows[0]} churn windows, lag max {lag_max}/"
            f"{lag_budget}, identity {ident_boot}/{ident_churn} B, "
            f"0 expired at dispatch")
        emit({"config": 19,
              "replicas": n_replicas,
              "router": "hash",
              "desc": "replicated scoring tier: delta-stream mirror "
                      "replication, shared-nothing serving replicas "
                      "(simulated per-replica accelerator dispatch), "
                      "consistent-hash router; in-run single-replica "
                      "baseline",
              "seed": seed,
              "n_nodes": n_nodes,
              "device_sim_ms": device_sim_ms,
              "lag_budget_versions": lag_budget,
              "baseline": base,
              "storm": storm,
              "scaling_x": round(scaling, 2),
              "churn_windows": windows[0],
              "churn_patches_per_window": churn_patches,
              "lag_samples": len(lag_samples),
              "lag_max_versions": lag_max,
              "identity_bytes_bootstrap": ident_boot,
              "identity_bytes_post_churn": ident_churn,
              "per_replica_requests": per_replica_requests,
              "expired_at_dispatch": expired,
              "note": "gates: storm goodput >= 3x in-run single-replica "
                      "baseline, byte-identical verdicts across "
                      "replicas at the same version key (bootstrap + "
                      "post-churn forced refresh), every lag sample <= "
                      "budget, 0 expired-at-dispatch per replica, "
                      "every replica served through the router"})
    finally:
        for router in routers:
            router.stop()
        for r in replicas:
            r.stop()
        server.stop()


def config20(dtype, rtt, n_nodes=4_000, n_replicas=2):
    """Round-17 tentpole gate: the fleet observability plane riding the
    replicated tier — primary + N serving replicas + the hash router (a
    4-process fleet at the default N=2) federated on ``/fleet/metrics``
    with the SLO burn-rate engine behind it.

    Legs:

      unscraped — the config-19 storm shape (closed-loop tenants, paced
                  per-replica devices) through the hash router with NO
                  federation running: the in-run goodput baseline;
      scraped   — the same seeded client population with the fleet
                  plane scraping every process at 1 Hz throughout;
      alert     — mid-storm (survivor-directed traffic still flowing)
                  replica-1 is killed: ``scrape_availability`` must
                  leave ``ok`` within one fast burn window of synthetic
                  ticks, and a same-port heal must clear it back with
                  the forced counter reset absorbed;
      replay    — a second same-seed alert leg against a fresh plane:
                  the SLO transition timeline and the crane-top
                  snapshot timeline must be byte-identical.

    Gates: scraped-leg goodput within 3% of the unscraped leg;
    ``/fleet/metrics`` strict-parses over the wire with every fleet
    role labeled; the kill/heal alert round-trip lands (ok -> warning
    -> ok) with the counter reset merged monotonically; both same-seed
    timelines identical."""
    import threading
    import urllib.error
    import urllib.request

    from crane_scheduler_tpu.cluster.replication import DeltaPublisher
    from crane_scheduler_tpu.policy import DEFAULT_POLICY
    from crane_scheduler_tpu.service import (
        ReplicaRouter,
        ScoringHTTPServer,
        ScoringService,
        ServingReplica,
    )
    from crane_scheduler_tpu.sim import SimConfig, Simulator
    from crane_scheduler_tpu.telemetry.expfmt import parse_exposition
    from crane_scheduler_tpu.telemetry.fleet import (
        FleetPlane,
        ScrapeTarget,
        register_build_info,
    )

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tools"))
    import crane_top

    seed = 20
    device_sim_ms = 150.0
    leg_s = 8.0
    lag_budget = 32
    # short burn windows + a synthetic 1s-per-tick clock keep the
    # alert assertions deterministic and fast: the fast windows span
    # 5/15 ticks instead of 5m/1h
    slo_kwargs = {"fast_windows": (5.0, 15.0), "slow_windows": (30.0, 60.0)}

    sim = Simulator(SimConfig(n_nodes=n_nodes, seed=seed))
    sim.sync_metrics()
    svc = ScoringService(
        sim.cluster, DEFAULT_POLICY, dtype=dtype, now_bucket_s=0.0
    )
    register_build_info(svc.telemetry.registry, "scorer", set_role=False)
    svc.refresh()
    pub = DeltaPublisher(sim.cluster, telemetry=svc.telemetry)
    plane = FleetPlane(
        registry=svc.telemetry.registry,
        local_registry=svc.telemetry.registry,
        local_role="scorer", local_name="primary",
        slo_kwargs=dict(slo_kwargs),
    )
    server = ScoringHTTPServer(
        svc, port=0, frontend="async", replication=pub, fleet=plane
    )
    server.start()
    pub.publish_window()

    def paced(inner):
        lock = threading.Lock()

        def scorer(*args, **kwargs):
            with lock:
                time.sleep(device_sim_ms / 1e3)
                return inner(*args, **kwargs)

        return scorer

    def make_replica(i, port=0):
        r = ServingReplica(
            DEFAULT_POLICY, name=f"replica-{i}",
            feed=("127.0.0.1", server.port),
            dtype=dtype, now_bucket_s=0.0,
            scorer_wrap=paced, port=port,
        )
        register_build_info(r.telemetry.registry, "replica", set_role=False)
        r.start()
        assert r.wait_caught_up(pub.published_version, timeout_s=60.0), \
            f"{r.name} never caught up to v{pub.published_version}"
        return r

    replicas = [make_replica(i) for i in range(n_replicas)]
    router = None
    plane2 = None
    try:
        router = ReplicaRouter(
            [(r.name, "127.0.0.1", r.port) for r in replicas],
            primary=("127.0.0.1", server.port), mode="hash",
            lag_budget_versions=lag_budget, port=0,
        )
        register_build_info(
            router.telemetry.registry, "router", set_role=False
        )
        router.start()
        for r in replicas:
            plane.federator.add_target(ScrapeTarget(
                name=r.name, port=r.port, role=None,
            ))
        plane.federator.add_target(ScrapeTarget(
            name="router", port=router.port, role=None,
        ))

        now0 = sim.clock.now()
        counter = [0]
        counter_lock = threading.Lock()

        def fresh_now():
            with counter_lock:
                counter[0] += 1
                return now0 + counter[0] * 1e-4

        def post(port, body, headers=None, timeout=30.0):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/v1/score", data=body,
                method="POST",
                headers={"Content-Type": "application/json",
                         **(headers or {})},
            )
            try:
                with urllib.request.urlopen(req, timeout=timeout) as resp:
                    return resp.status, resp.read()
            except urllib.error.HTTPError as e:
                e.read()
                return e.code, b""

        for r in replicas:
            for refresh in (True, False):
                body = json.dumps(
                    {"now": fresh_now(), "refresh": refresh}
                ).encode()
                status, _ = post(r.port, body)
                assert status == 200, f"warmup {r.name}: HTTP {status}"

        # deterministic tenant cover, config19-style: 3 closed-loop
        # clients per replica off the static hash ring
        per_replica = {r.name: [] for r in replicas}
        i = 0
        while any(len(v) < 3 for v in per_replica.values()):
            i += 1
            assert i < 10_000, "ring never covered every replica"
            t = f"tenant-{i}"
            owner = router.route_for(t)
            if owner is not None and len(per_replica[owner]) < 3:
                per_replica[owner].append(t)
        tenants = [t for ts in per_replica.values() for t in ts]

        def closed_loop(port, duration_s, pool=None):
            stop_at = time.perf_counter() + duration_s
            results = []
            res_lock = threading.Lock()

            def client(tenant):
                while time.perf_counter() < stop_at:
                    body = json.dumps(
                        {"now": fresh_now(), "refresh": False}
                    ).encode()
                    status, _ = post(
                        port, body,
                        headers={"crane-tenant": tenant,
                                 "crane-deadline-ms": "10000"},
                    )
                    with res_lock:
                        results.append(status)

            threads = [
                threading.Thread(target=client, args=(t,))
                for t in (pool or tenants)
            ]
            t0 = time.perf_counter()
            for th in threads:
                th.start()
            for th in threads:
                th.join()
            elapsed = time.perf_counter() - t0
            served = sum(1 for s in results if s == 200)
            return {
                "clients": len(pool or tenants),
                "duration_s": round(elapsed, 3),
                "requests": len(results),
                "served": served,
                "rps": round(served / elapsed, 2),
            }

        # settle leg: absorb any residual jit before the measured pair
        closed_loop(router.port, 2.0)

        clock = [1000.0]
        healthy_ticks = [0]

        def tick(p):
            clock[0] += 1.0
            return p.tick(now=clock[0])

        # -- leg 1: unscraped baseline (no federation running) ------------
        unscraped = closed_loop(router.port, leg_s)

        # -- leg 2: same population with 1 Hz federation throughout -------
        scrape_stop = threading.Event()

        def scrape_pump():
            while not scrape_stop.is_set():
                tick(plane)
                healthy_ticks[0] += 1
                if scrape_stop.wait(1.0):
                    return

        pump = threading.Thread(target=scrape_pump, daemon=True)
        pump.start()
        scraped = closed_loop(router.port, leg_s)
        scrape_stop.set()
        pump.join(timeout=10.0)

        overhead_pct = abs(scraped["rps"] - unscraped["rps"]) \
            / max(unscraped["rps"], 1e-9) * 100.0
        assert overhead_pct <= 3.0, \
            f"federation overhead gate: scraped {scraped['rps']} vs " \
            f"unscraped {unscraped['rps']} rps ({overhead_pct:.2f}% > 3%)"

        # /fleet/metrics over the real wire, strict-parsed, role-labeled
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/fleet/metrics",
            headers={"Accept": "text/plain; version=0.0.4"},
        )
        with urllib.request.urlopen(req, timeout=10.0) as resp:
            families = parse_exposition(resp.read().decode())
        roles = {
            dict(labels).get("role")
            for doc in families.values()
            for _, labels, _ in doc["samples"]
            if dict(labels).get("role")
        }
        assert {"scorer", "replica", "router"} <= roles, \
            f"missing fleet roles on /fleet/metrics: {sorted(roles)}"
        assert not plane.federator.quarantined, \
            f"quarantined families: {plane.federator.quarantined}"

        def federated_count(fams, proc):
            fam = fams.get("crane_service_request_seconds", {"samples": []})
            return sum(
                v for name, labels, v in fam["samples"]
                if name == "crane_service_request_seconds_count"
                and dict(labels).get("process") == proc
            )

        def alert_leg(p, kill_idx, mid_storm):
            """One deterministic kill/heal round against plane ``p``:
            saturate the burn windows with healthy ticks, kill
            replica-1, assert the flip within one fast window, heal on
            the same port, tick until clear. Returns the transition
            timeline."""
            while healthy_ticks[0] < 16:
                tick(p)
                healthy_ticks[0] += 1
            storm_stop = threading.Event()
            storm = None
            if mid_storm:
                # survivor-directed traffic keeps flowing through the
                # kill so the alert fires under load
                def light_storm():
                    while not storm_stop.is_set():
                        body = json.dumps(
                            {"now": fresh_now(), "refresh": False}
                        ).encode()
                        post(replicas[0].port, body,
                             headers={"crane-deadline-ms": "10000"})

                storm = threading.Thread(target=light_storm, daemon=True)
                storm.start()
            before = federated_count(
                parse_exposition(p.render_metrics()), f"replica-{kill_idx}"
            )
            old_port = replicas[kill_idx].port
            replicas[kill_idx].stop()
            state = "ok"
            flipped_at = None
            for j in range(6):  # one fast window (5 ticks) + margin
                tick(p)
                s = p.slo.alert_state("scrape_availability")
                if s != "ok" and flipped_at is None:
                    state, flipped_at = s, j + 1
            assert flipped_at is not None and flipped_at <= 5, \
                f"kill never flipped scrape_availability " \
                f"(state {state}, flip {flipped_at})"
            replicas[kill_idx] = make_replica(kill_idx, port=old_port)
            body = json.dumps(
                {"now": fresh_now(), "refresh": False}
            ).encode()
            post(replicas[kill_idx].port, body)
            cleared_at = None
            for j in range(40):
                tick(p)
                if p.slo.alert_state("scrape_availability") == "ok":
                    cleared_at = j + 1
                    break
            if storm is not None:
                storm_stop.set()
                storm.join(timeout=10.0)
            assert cleared_at is not None, "heal never cleared the alert"
            after = federated_count(
                parse_exposition(p.render_metrics()), f"replica-{kill_idx}"
            )
            assert after >= before and p.federator.reset_count() >= 1, \
                f"counter reset went backward: {before} -> {after}, " \
                f"{p.federator.reset_count()} resets"
            return {
                "state": state,
                "flipped_at_tick": flipped_at,
                "cleared_at_tick": cleared_at,
                "resets": p.federator.reset_count(),
                "timeline": p.slo.timeline(),
            }

        # -- leg 3: mid-storm kill/heal on the live plane -----------------
        alert1 = alert_leg(plane, 1, mid_storm=True)
        snap1 = crane_top.snapshot(
            parse_exposition(plane.render_metrics()), plane.slo_status(),
            lag_budget=lag_budget,
        )

        # -- leg 4: same-seed replay against a fresh plane ----------------
        plane2 = FleetPlane(slo_kwargs=dict(slo_kwargs))
        for name, port in (
            [("primary", server.port)]
            + [(r.name, r.port) for r in replicas]
            + [("router", router.port)]
        ):
            plane2.federator.add_target(ScrapeTarget(
                name=name, port=port, role=None,
            ))
        clock[0] = 1000.0
        healthy_ticks[0] = 0
        alert2 = alert_leg(plane2, 1, mid_storm=False)
        snap2 = crane_top.snapshot(
            parse_exposition(plane2.render_metrics()), plane2.slo_status(),
            lag_budget=lag_budget,
        )

        assert alert1["timeline"] == alert2["timeline"], \
            f"same-seed SLO timelines diverged: " \
            f"{alert1['timeline']} vs {alert2['timeline']}"
        assert snap1["timeline"] == snap2["timeline"], \
            f"same-seed crane-top timelines diverged: " \
            f"{snap1['timeline']} vs {snap2['timeline']}"
        assert len(snap1["rows"]) >= n_replicas + 2, \
            f"crane-top table incomplete: {snap1['rows']}"

        log(f"config20 [{n_nodes} nodes, {n_replicas} replicas, "
            f"device {device_sim_ms:.0f} ms]: unscraped "
            f"{unscraped['rps']} rps vs scraped {scraped['rps']} rps "
            f"({overhead_pct:.2f}% delta), kill flip at tick "
            f"{alert1['flipped_at_tick']} -> clear at tick "
            f"{alert1['cleared_at_tick']}, {alert1['resets']} resets, "
            f"timelines identical across same-seed runs")
        emit({"config": 20,
              "replicas": n_replicas,
              "router": "hash",
              "desc": "fleet observability plane over the replicated "
                      "tier: 1 Hz federation under the storm, SLO "
                      "burn-rate kill/heal round-trip, deterministic "
                      "same-seed timelines",
              "seed": seed,
              "n_nodes": n_nodes,
              "device_sim_ms": device_sim_ms,
              "unscraped": unscraped,
              "scraped": scraped,
              "federation_overhead_pct": round(overhead_pct, 3),
              "fleet_roles": sorted(roles),
              "fleet_families": len(families),
              "alert": {k: v for k, v in alert1.items()
                        if k != "timeline"},
              "slo_timeline": [list(t) for t in alert1["timeline"]],
              "snapshot_rows": len(snap1["rows"]),
              "note": "gates: scraped goodput within 3% of unscraped, "
                      "/fleet/metrics strict-parses with every role "
                      "labeled, kill flips scrape_availability within "
                      "one fast window and heals back to ok with the "
                      "counter reset merged monotonically, SLO + "
                      "crane-top timelines identical across two "
                      "same-seed runs"})
    finally:
        if plane2 is not None:
            plane2.stop()
        plane.stop()
        if router is not None:
            router.stop()
        for r in replicas:
            try:
                r.stop()
            except Exception:
                pass
        pub.stop()
        server.stop()


def config21(dtype, rtt, n_nodes=1_000_000):
    """Round-16 tentpole gate: the O(dirty) shard plane at a 1M-node
    mirror (annotation columns only, no pod bodies) on a dynamic
    consistent-hash ring.

    Legs (one in-process 1M-node ``ClusterState`` under a
    ``HashRing(4)`` keyspace unless noted):

      dirty    — 4-ring-shard plane; per shard the first probe pays the
                 column build over its ~250k-row slice, then ONE named
                 annotation patch and a re-probe: the owning shard
                 patches exactly one row end to end (journal replay ->
                 store/fit/drip row patch -> device-side scatter of the
                 dirty column row), the other shards' fences never
                 moved. The dirty-patched columns are then asserted
                 bit-identical to a from-scratch scheduler built over
                 the same view;
      sweep    — the SAME patch shape with journal coverage dropped
                 (``forget_dirty_names`` = what a relist does): the
                 owning shard pays the pre-journal identity-gated sweep
                 over its whole slice — the in-run baseline the O(dirty)
                 path is gated against;
      reshard  — one-token vs eight-token ring moves through the live
                 mirror: migration bookkeeping must price per MOVED
                 name, not per node (the crc index bisects the moved
                 arcs); after the small move the dirtied shards refresh
                 by splicing only the migrated rows;
      storm    — 4 schedulers x 512 pods, static crc keyspace (config
                 18's plane) vs the ring keyspace over the same mirror:
                 dynamic sharding must not tax steady-state throughput;
      wire     — 2 ring-sharded schedulers over the wire stub with a
                 ring move landing MID-storm: every pod still binds
                 exactly once (per-pod bind POST oracle, zero
                 duplicates).

    Gates: dirty refresh of the patched shard < 5 ms at 1M nodes and
    >= 20x faster than the in-run identity sweep; untouched shards
    < 5 ms (fences never moved); dirty-patched columns bit-identical
    to the from-scratch rebuild; per-moved-name reshard cost of the
    8-token move <= 3x the 1-token move's; ring storm throughput
    >= 0.9x the static keyspace's; zero duplicate binding POSTs and
    bind_posts == pods across the mid-storm move."""
    import os
    import threading

    from crane_scheduler_tpu.cluster import (
        ClusterState,
        Container,
        Node,
        Pod,
        ResourceRequirements,
    )
    from crane_scheduler_tpu.cluster.kube import KubeClusterClient
    from crane_scheduler_tpu.cluster.shards import HashRing
    from crane_scheduler_tpu.fit import FitTracker, ResourceFitPlugin
    from crane_scheduler_tpu.framework.scheduler import Scheduler
    from crane_scheduler_tpu.framework.shardplane import ShardedPlacementPlane
    from crane_scheduler_tpu.plugins import DynamicPlugin
    from crane_scheduler_tpu.policy import DEFAULT_POLICY
    from crane_scheduler_tpu.utils import format_local_time, parse_local_time

    now = parse_local_time("2026-07-30T00:00:00Z") + 30.0
    metric_names = [sp.name for sp in DEFAULT_POLICY.spec.sync_period]
    alloc = {"cpu": "64", "memory": "256Gi",
             "ephemeral-storage": "100Gi", "pods": "1100"}

    # -- the 1M-node mirror: annotation columns only, no pod bodies.
    # Eight shared annotation dicts (patches copy on write) keep the
    # node table itself the only O(n) cost.
    ts = format_local_time(now - 20.0)
    variants = [
        {m: f"{0.20 + 0.01 * ((j + k) % 11):.5f},{ts}"
         for k, m in enumerate(metric_names)}
        for j in range(8)
    ]
    t0 = time.perf_counter()
    # journal sized for the scale: a reshard notes every moved name
    # (arcs run ~n/tokens names), so a 4096-cap journal would overrun
    # on every token move at 1M nodes and degrade moves to sweeps
    cluster = ClusterState(dirty_journal_cap=65536)
    cluster.replace_nodes(
        Node(name=f"node-{i:07d}", annotations=variants[i % 8],
             allocatable=alloc)
        for i in range(n_nodes)
    )
    mirror_s = time.perf_counter() - t0
    log(f"config21: {n_nodes} nodes mirrored in {mirror_s:.1f}s")

    def factory(view):
        sched = Scheduler(view, clock=lambda: now, columnar=True)
        sched.register(ResourceFitPlugin(FitTracker(view)), weight=1)
        sched.register(DynamicPlugin(DEFAULT_POLICY, clock=lambda: now),
                       weight=3)
        return sched

    def make_pods(tag, count, cpu="100m"):
        pods = [
            Pod(name=f"p21-{tag}-{i:04d}", namespace="default",
                containers=(Container("c", ResourceRequirements(
                    requests={"cpu": cpu, "memory": "128Mi"},
                )),))
            for i in range(count)
        ]
        cluster.add_pods(pods)
        return pods

    def drip_of(sched):
        rec = sched._recognition()
        drip = sched._ensure_drip(rec)
        drip.ensure(now)
        return drip

    # -- leg 1: dirty vs identity-sweep refresh at 1M ------------------------
    ring = HashRing(4, vnodes=64)
    plane = ShardedPlacementPlane(cluster, 4, layout=ring)
    scheds = plane.add_scheduler(factory)
    probes = make_pods("probe", 16, cpu="100000")  # infeasible: no binds
    build_s = []
    for i, sched in enumerate(scheds):
        t0 = time.perf_counter()
        r = sched.schedule_one(probes[i])
        build_s.append(time.perf_counter() - t0)
        assert r.node is None, "infeasible probe placed?!"
    victim = next(f"node-{i:07d}" for i in range(n_nodes)
                  if ring.owners(f"node-{i:07d}") == (0,))
    assert cluster.patch_node_annotation(
        victim, metric_names[0], f"0.90000,{ts}")
    refresh_s = []
    for i, sched in enumerate(scheds):
        t0 = time.perf_counter()
        sched.schedule_one(probes[4 + i])
        refresh_s.append(time.perf_counter() - t0)
    build_total = sum(build_s)
    dirty_ms = refresh_s[0] * 1e3
    log(f"config21[dirty]: 4-ring-shard build {build_total * 1e3:.0f} ms "
        f"({'/'.join(f'{s * 1e3:.0f}' for s in build_s)}), refresh after "
        f"1 named patch {'/'.join(f'{s * 1e3:.2f}' for s in refresh_s)} ms "
        f"(shard 0 dirty-patched {dirty_ms:.2f} ms)")
    d_stats = scheds[0].drip_stats()
    assert d_stats["dirty_patches"] >= 1, d_stats
    # bit-identity: the dirty-patched columns == a from-scratch build
    patched = drip_of(scheds[0])
    fresh = drip_of(factory(plane.views[0]))
    assert patched.names == fresh.names
    for col in ("schedulable", "fail_entry", "weighted"):
        assert np.array_equal(getattr(patched, col), getattr(fresh, col)), \
            f"dirty-patched column {col} != from-scratch rebuild"
    log("config21[dirty]: patched columns bit-identical to rebuild")

    # -- leg 2: in-run identity-sweep baseline (journal coverage dropped,
    # exactly the pre-journal relist path) -----------------------------------
    assert cluster.patch_node_annotation(
        victim, metric_names[0], f"0.10000,{ts}")
    cluster.forget_dirty_names()
    t0 = time.perf_counter()
    scheds[0].schedule_one(probes[8])
    sweep_ms = (time.perf_counter() - t0) * 1e3
    speedup = sweep_ms / max(dirty_ms, 1e-6)
    log(f"config21[sweep]: identity-sweep baseline {sweep_ms:.1f} ms "
        f"-> O(dirty) {dirty_ms:.2f} ms = {speedup:.0f}x")

    # -- leg 3: reshard cost prices per moved name ---------------------------
    # warm the one-time sorted crc index (O(n log n), amortized across
    # every later move) with a token moved there and back, so the timed
    # moves measure the steady-state per-moved-name bisection
    points, owners = ring.tokens()
    w = next(i for i, s in enumerate(owners) if s == 1)
    t0 = time.perf_counter()
    cluster.reshard(ring.with_moves([(w, 2)]))
    cluster.reshard(ring.with_moves([(w, 1)]))
    index_warm_ms = (time.perf_counter() - t0) * 1e3
    points, owners = ring.tokens()
    one = [next(i for i, s in enumerate(owners) if s == 0)]
    t0 = time.perf_counter()
    moved_small = cluster.reshard(ring.with_moves([(i, 1) for i in one]))
    reshard_small_ms = (time.perf_counter() - t0) * 1e3
    # the dirtied shards refresh by splicing only the migrated rows
    t0 = time.perf_counter()
    for i, sched in enumerate(scheds):
        sched.schedule_one(probes[12 + i])
    reshard_refresh_ms = (time.perf_counter() - t0) * 1e3
    points, owners = ring.tokens()
    eight = [i for i, s in enumerate(owners) if s == 0][:8]
    t0 = time.perf_counter()
    moved_large = cluster.reshard(ring.with_moves([(i, 1) for i in eight]))
    reshard_large_ms = (time.perf_counter() - t0) * 1e3
    per_small = reshard_small_ms / max(len(moved_small), 1)
    per_large = reshard_large_ms / max(len(moved_large), 1)
    log(f"config21[reshard]: index warm {index_warm_ms:.0f} ms; "
        f"1 token = {len(moved_small)} names in "
        f"{reshard_small_ms:.1f} ms ({per_small * 1e3:.1f} us/name), "
        f"8 tokens = {len(moved_large)} names in {reshard_large_ms:.1f} ms "
        f"({per_large * 1e3:.1f} us/name); post-move refresh "
        f"{reshard_refresh_ms:.1f} ms")

    # -- leg 4: storm throughput, static keyspace vs the ring ----------------
    total_pods, window = 512, 128

    def storm_leg(tag, layout):
        plane = ShardedPlacementPlane(cluster, 4, overlap=0.0, layout=layout)
        plane.add_scheduler(factory)
        warm = [make_pods(f"w{tag}-{i}", window, cpu="100000")
                for i in range(4)]
        for res in plane.run_storm(warm, window=window, threaded=False):
            assert all(r.node is None for r in res), "warm pod placed"
        queues = [make_pods(f"s{tag}-{i}", total_pods // 4)
                  for i in range(4)]
        t0 = time.perf_counter()
        results = plane.run_storm(queues, window=window, threaded=True)
        wall_s = time.perf_counter() - t0
        for i, res in enumerate(results):
            for r in res:
                assert r.node is not None, f"shard {i} unplaced: {r.reason}"
        assert not plane.conflict_stats(), plane.conflict_stats()
        return {
            "pods": total_pods,
            "wall_ms": round(wall_s * 1e3, 1),
            "pods_per_sec": round(total_pods / wall_s, 1),
        }

    static_leg = storm_leg("st", None)
    ring_leg = storm_leg("rg", HashRing(4, vnodes=64))
    ring_vs_static = round(
        ring_leg["pods_per_sec"] / static_leg["pods_per_sec"], 3)
    log(f"config21[storm]: static {static_leg['pods_per_sec']:,.0f} pods/s "
        f"vs ring {ring_leg['pods_per_sec']:,.0f} pods/s "
        f"({ring_vs_static}x)")

    # -- leg 5: mid-storm ring move over the wire stub -----------------------
    kube_stub = _load_kube_stub()
    stub_nodes, stub_pods = 4_000, 800
    server = kube_stub.KubeStubSubprocess()
    try:
        server.seed(stub_nodes, "node-", metrics=metric_names,
                    allocatable={"cpu": "16", "memory": "64Gi",
                                 "ephemeral-storage": "100Gi",
                                 "pods": "110"})
        client = KubeClusterClient(server.url, list_page_limit=2000)
        client.start()
        assert len(client.list_nodes()) == stub_nodes
        wire_ring = HashRing(2, vnodes=32)
        wire_plane = ShardedPlacementPlane(client, 2, layout=wire_ring)
        wire_plane.add_scheduler(factory)
        half = stub_pods // 2
        queues = []
        for i in range(2):
            pods = [
                Pod(name=f"c21-{i}-{j:04d}", namespace="default",
                    containers=(Container("c", ResourceRequirements(
                        requests={"cpu": "100m", "memory": "128Mi"},
                    )),))
                for j in range(half)
            ]
            for pod in pods:
                client.add_pod(pod)
            queues.append(pods)
        moved_mid: list = []

        def move_mid_storm():
            pts, own = wire_ring.tokens()
            idx = next(i for i, s in enumerate(own) if s == 0)
            moved_mid.extend(
                wire_plane.reshard(wire_ring.with_moves([(idx, 1)])))

        timer = threading.Timer(0.3, move_mid_storm)
        timer.start()
        results = wire_plane.run_storm(queues, window=16, threaded=True)
        timer.join()
        for i, res in enumerate(results):
            for r in res:
                assert r.node is not None, f"shard {i} unplaced: {r.reason}"
        stats = server.stats()
        assert stats["duplicate_binds"] == 0, "double-POSTed bind!"
        assert stats["bind_posts"] == stub_pods, \
            f"bind POSTs {stats['bind_posts']} != {stub_pods} pods"
        assert moved_mid, "mid-storm ring move moved no names"
        wire_conflicts = wire_plane.conflict_stats()
        client.stop()
    finally:
        server.stop()
    log(f"config21[wire]: {stub_pods} pods across a mid-storm ring move "
        f"of {len(moved_mid)} nodes: conflicts {wire_conflicts or '{}'}, "
        f"per-pod bind POST oracle ok")

    emit({"config": 21,
          "schedulers": 4,
          "desc": "O(dirty) shard plane: 1M-node mirror on a "
                  "consistent-hash ring, dirty-name journal refresh vs "
                  "in-run identity sweep, per-moved-name resharding, "
                  "mid-storm ring move over the wire",
          "n_nodes": n_nodes,
          "mirror_build_s": round(mirror_s, 1),
          "column_build_ms": round(build_total * 1e3, 1),
          "dirty_refresh_ms": round(dirty_ms, 3),
          "untouched_refresh_ms": [round(s * 1e3, 3)
                                   for s in refresh_s[1:]],
          "identity_sweep_ms": round(sweep_ms, 1),
          "dirty_speedup": round(speedup, 1),
          "reshard": {
              "index_warm_ms": round(index_warm_ms, 1),
              "small": {"moved": len(moved_small),
                        "ms": round(reshard_small_ms, 1),
                        "us_per_name": round(per_small * 1e3, 2)},
              "large": {"moved": len(moved_large),
                        "ms": round(reshard_large_ms, 1),
                        "us_per_name": round(per_large * 1e3, 2)},
              "post_move_refresh_ms": round(reshard_refresh_ms, 1),
          },
          "storm": {"static": static_leg, "ring": ring_leg,
                    "ring_vs_static": ring_vs_static},
          "wire": {"nodes": stub_nodes, "pods": stub_pods,
                   "moved_mid_storm": len(moved_mid),
                   "outcomes": wire_conflicts},
          "note": "gates: named-patch refresh of the owning shard <5 ms "
                  "at 1M nodes and >=20x over the in-run identity "
                  "sweep (journal coverage dropped, the pre-journal "
                  "relist path); untouched shards <5 ms (fences never "
                  "moved); dirty-patched columns bit-identical to a "
                  "from-scratch rebuild; 8-token reshard per-moved-name "
                  "cost <=3x the 1-token move's (migration bisects the "
                  "moved arcs, never rehashes the table); ring storm "
                  ">=0.9x static keyspace throughput; zero duplicate "
                  "binding POSTs and bind_posts == pods across a "
                  "mid-storm ring move"})
    assert dirty_ms < 5.0, \
        f"O(dirty) gate: patched shard refreshed in {dirty_ms:.2f} ms"
    for i, s in enumerate(refresh_s[1:], start=1):
        assert s < 0.005, \
            f"O(dirty) gate: untouched shard {i} re-probed in " \
            f"{s * 1e3:.1f} ms (fence must not have moved)"
    assert speedup >= 20.0, \
        f"O(dirty) gate: {speedup:.1f}x < 20x vs the identity sweep"
    assert per_large <= per_small * 3.0, \
        f"reshard gate: 8-token move {per_large * 1e3:.1f} us/name > " \
        f"3x 1-token move {per_small * 1e3:.1f} us/name"
    assert ring_vs_static >= 0.9, \
        f"storm gate: ring keyspace {ring_vs_static}x < 0.9x static"


def config22(dtype, rtt, n_nodes=50_000, wire_nodes=5_000):
    """Round-17 tentpole gate: the device-resident multi-gang engine —
    version-cached gang columns, batched water-filling windows with
    in-program capacity folds, heterogeneous multi-template queues.

    Legs (twin in-process 50k-node clusters seeded identically via the
    config21 shared-annotation-variant idiom, unless noted):

      sequential — ``schedule_gang(template, count, bind=True)`` loop
                   over 24 heterogeneous gangs: the pre-engine path
                   pays a full ``_prepare`` (filter+score columns, fit
                   capacity) per gang — the in-run baseline;
      window     — ``schedule_gang_queue`` over the SAME 24 gangs,
                   window=8: version-cached GangColumns build once,
                   then each window is ONE jitted lax.scan
                   (water-filling per gang against the in-program
                   capacity fold carry, one D2H), host fold replay
                   keeps device==host. A warm-up window of infeasible
                   gangs (no binds, no state change) absorbs the
                   one-time column build + jit compile, config15-style;
                   steady accounting subtracts any residual compile;
      oracle     — in-run parity: every window gang replayed through
                   ``gang_window_host`` over the engine's own columns
                   (capacity un-folded by hand), and the first 2 gangs
                   through the O(P*N)-Python ``gang_assign_oracle`` —
                   counts must match the storm's placements node for
                   node (the sequential leg inherits the same oracle
                   parity through the bit-identical-placements assert);
      dirty      — ONE named annotation patch after the storm: the next
                   ``ensure()`` must refresh the gang columns O(dirty)
                   (journal replay, one row re-scored), vs the same
                   patch with journal coverage dropped
                   (``forget_dirty_names`` = relist) paying the
                   identity sweep — the in-run full-prepare baseline;
      wire       — the same gang storm through a 5k-node stub-apiserver
                   mirror: every placed pod binds exactly once (stub
                   ``bind_posts``/``duplicate_binds`` oracle).

    Gates: window leg >= 20x faster per gang than the sequential leg
    (steady), placements bit-identical across sequential/window/host/
    oracle legs, dirty gang-column refresh < 5 ms at 50k nodes, zero
    duplicate binding POSTs and bind_posts == placed on the wire."""
    import numpy as np

    from crane_scheduler_tpu.cluster import (
        ClusterState,
        Container,
        Node,
        Pod,
        ResourceRequirements,
    )
    from crane_scheduler_tpu.cluster.kube import KubeClusterClient
    from crane_scheduler_tpu.constants import MAX_NODE_SCORE
    from crane_scheduler_tpu.fit import (
        copy_counts_rows,
        pod_fit_request,
        request_vec,
    )
    from crane_scheduler_tpu.framework.scheduler import BatchScheduler
    from crane_scheduler_tpu.policy import DEFAULT_POLICY
    from crane_scheduler_tpu.scorer.gang_batch import gang_window_host
    from crane_scheduler_tpu.scorer.topk import gang_assign_oracle
    from crane_scheduler_tpu.utils import format_local_time, parse_local_time

    now = parse_local_time("2026-07-30T00:00:00Z") + 30.0
    metric_names = [sp.name for sp in DEFAULT_POLICY.spec.sync_period]
    alloc = {"cpu": "16", "memory": "64Gi",
             "ephemeral-storage": "100Gi", "pods": "110"}
    ts = format_local_time(now - 20.0)
    variants = [
        {m: f"{0.20 + 0.01 * ((j + k) % 11):.5f},{ts}"
         for k, m in enumerate(metric_names)}
        for j in range(8)
    ]

    def build_cluster(n):
        cluster = ClusterState()
        cluster.replace_nodes(
            Node(name=f"node-{i:05d}", annotations=variants[i % 8],
                 allocatable=alloc)
            for i in range(n)
        )
        return cluster

    # 24 heterogeneous gangs: 6 request/size shapes cycled 4x
    shapes = ((500, 12), (1000, 8), (250, 16), (1500, 6), (750, 10),
              (2000, 4)) * 4

    def make_gangs(tag):
        return [
            (Pod(
                name=f"g22-{tag}-{j:03d}", namespace="default",
                containers=(Container("c", ResourceRequirements(
                    requests={"cpu": f"{cpu}m", "memory": "256Mi"},
                )),),
            ), count)
            for j, (cpu, count) in enumerate(shapes)
        ]

    total_pods = sum(c for _, c in shapes)
    window = 8

    # -- sequential leg ------------------------------------------------------
    batch_a = BatchScheduler(build_cluster(n_nodes), DEFAULT_POLICY,
                             clock=lambda: now)
    gangs_a = make_gangs("seq")
    seq_out = []
    t0 = time.perf_counter()
    for t, c in gangs_a:
        r = batch_a.schedule_gang(t, c, bind=True)
        assert not r.unassigned, f"sequential gang {t.name} unplaced"
        seq_out.append(dict(r.assignments))
    seq_wall = time.perf_counter() - t0
    seq_per_gang = seq_wall * 1e3 / len(shapes)
    log(f"config22[seq]: {len(shapes)} gangs x {n_nodes} nodes in "
        f"{seq_wall * 1e3:.0f} ms ({seq_per_gang:.1f} ms/gang)")

    # -- window leg ----------------------------------------------------------
    cluster_b = build_cluster(n_nodes)
    batch_b = BatchScheduler(cluster_b, DEFAULT_POLICY, clock=lambda: now)
    # warm-up: a full window of infeasible gangs (every request exceeds
    # any node) in the storm's own shape bucket — builds the gang
    # columns and pays the jit compile with zero binds and zero
    # cluster-state change, so placement parity with the sequential
    # leg still holds
    warm = [
        (Pod(
            name=f"g22-warm-{i}", namespace="default",
            containers=(Container("c", ResourceRequirements(
                requests={"cpu": f"{100_000 + i * 1000}m",
                          "memory": "256Mi"},
            )),),
        ), 1)
        for i in range(window)
    ]
    warm_out = batch_b.schedule_gang_queue(warm, window=window)
    assert all(not o.assignments for o in warm_out), \
        "warm-up gang unexpectedly placed (would break parity)"
    pre = batch_b.gang_stats()
    gangs_b = make_gangs("seq")  # same names as the sequential leg
    t0 = time.perf_counter()
    win_out = batch_b.schedule_gang_queue(gangs_b, window=window)
    win_wall = time.perf_counter() - t0
    stats = batch_b.gang_stats()
    assert stats["fallbacks"] == 0, "window leg fell back to sequential"
    assert all(o.source == "window" for o in win_out)
    ks = stats["kernel_seconds"][len(pre["kernel_seconds"]):]
    steady_s = win_wall
    if len(ks) > 1:
        warm_mean = sum(ks[1:]) / len(ks[1:])
        steady_s = win_wall - max(0.0, ks[0] - warm_mean)
    win_per_gang = steady_s * 1e3 / len(shapes)
    placed = sum(len(o.assignments) for o in win_out)
    assert placed == total_pods, f"window leg placed {placed}/{total_pods}"
    assert [dict(o.assignments) for o in win_out] == seq_out, \
        "window placements diverged from the sequential schedule_gang loop"
    speedup = seq_per_gang / win_per_gang
    windows = stats["windows"] - pre["windows"]
    log(f"config22[window]: {len(shapes)} gangs in {windows} windows, "
        f"{win_per_gang:.2f} ms/gang steady ({win_wall * 1e3 / len(shapes):.2f} "
        f"incl. residual compile), speedup {speedup:.1f}x")

    # -- oracle leg (window columns, capacity un-folded by hand) -------------
    eng = batch_b._gang_engine
    cols = eng["cols"]
    cols.ensure(now)
    pos = {name: i for i, name in enumerate(cols.names)}
    free0 = cols.free.copy()
    vecs = [request_vec(pod_fit_request(t)) for t, _c in gangs_b]
    for (t, _c), o, vec in zip(gangs_b, win_out, vecs):
        for node in o.assignments.values():
            free0[pos[node]] += vec
    host_res, _ = gang_window_host(
        cols.score, cols.schedulable, cols.bounded, free0,
        [(c, vec, None) for (_t, c), vec in zip(gangs_b, vecs)],
        batch_b.tensors.hv_count, dynamic_weight=3,
        max_offset=MAX_NODE_SCORE * 2,
    )
    free_c = free0.astype(np.int64).copy()
    oracle_gangs = 0
    for j, ((t, c), o, vec) in enumerate(zip(gangs_b, win_out, vecs)):
        got = np.zeros(len(cols.names), np.int64)
        for node in o.assignments.values():
            got[pos[node]] += 1
        assert np.array_equal(got, np.asarray(host_res[j].counts)), \
            f"gang {j} diverged from gang_window_host"
        if j < 2:  # the Python oracle is O(P*N) per gang
            cap = copy_counts_rows(free_c, cols.bounded, vec)
            orc = gang_assign_oracle(
                cols.score, cols.schedulable, c,
                batch_b.tensors.hv_count, capacity=cap,
                dynamic_weight=3, max_offset=MAX_NODE_SCORE * 2,
            )
            assert np.array_equal(got, np.asarray(orc.counts)), \
                f"gang {j} diverged from gang_assign_oracle"
            oracle_gangs += 1
        free_c -= got[:, None] * np.asarray(vec, np.int64)[None, :]
    log(f"config22[oracle]: {len(shapes)} gangs host-replayed, "
        f"{oracle_gangs} oracle-checked — bit-identical")

    # -- dirty leg: O(dirty) gang-column refresh vs identity sweep -----------
    def patch_one(name):
        node = cluster_b.get_node(name)
        k = next(iter(node.annotations))
        v = node.annotations[k]
        cluster_b.patch_node_annotation(name, k, v.replace("0.2", "0.3", 1))

    pre_patches = dict(cols.stats)
    patch_one("node-00017")
    t0 = time.perf_counter()
    cols.ensure(now)
    dirty_ms = (time.perf_counter() - t0) * 1e3
    assert cols.stats["dirty_patches"] > pre_patches["dirty_patches"], \
        "named patch did not take the O(dirty) journal path"
    # the same patch shape with journal coverage dropped AFTER the
    # write (what a relist does): the entry falls below the journal
    # floor, so the consumer pays the pre-journal identity sweep
    patch_one("node-00018")
    cluster_b.forget_dirty_names()
    t0 = time.perf_counter()
    cols.ensure(now)
    sweep_ms = (time.perf_counter() - t0) * 1e3
    log(f"config22[dirty]: O(dirty) refresh {dirty_ms:.2f} ms vs "
        f"identity sweep {sweep_ms:.1f} ms "
        f"({sweep_ms / max(dirty_ms, 1e-9):.0f}x)")

    # -- wire leg ------------------------------------------------------------
    kube_stub = _load_kube_stub()
    server = kube_stub.KubeStubSubprocess()
    try:
        server.seed(wire_nodes, "node-", metrics=metric_names,
                    allocatable=alloc)
        client = KubeClusterClient(server.url, list_page_limit=2000)
        client.start()
        assert len(client.list_nodes()) == wire_nodes
        batch_w = BatchScheduler(client, DEFAULT_POLICY, clock=lambda: now)
        t0 = time.perf_counter()
        wire_out = batch_w.schedule_gang_queue(make_gangs("wire"),
                                               window=window)
        wire_wall = time.perf_counter() - t0
        wire_placed = sum(len(o.assignments) for o in wire_out)
        assert wire_placed == total_pods, \
            f"wire leg placed {wire_placed}/{total_pods}"
        assert batch_w.gang_stats()["fallbacks"] == 0
        wstats = server.stats()
        assert wstats["duplicate_binds"] == 0, "double-POSTed gang bind!"
        assert wstats["bind_posts"] == wire_placed, \
            f"bind POSTs {wstats['bind_posts']} != {wire_placed} placed"
        client.stop()
        log(f"config22[wire]: {wire_placed} pods over {wire_nodes}-node "
            f"stub in {wire_wall * 1e3:.0f} ms — bind_posts=="
            f"{wstats['bind_posts']}, zero duplicates")
    finally:
        server.stop()

    emit({"config": 22,
          "desc": "device-resident multi-gang engine: sequential "
                  "schedule_gang loop vs batched schedule_gang_queue "
                  f"windows over twin {n_nodes}-node clusters, 24 "
                  "heterogeneous gangs (6 template shapes), in-run "
                  "host/oracle parity, O(dirty) gang-column refresh, "
                  "wire bind oracle",
          "n_nodes": n_nodes,
          "gangs": len(shapes),
          "pods": total_pods,
          "per_gang_ms_sequential": round(seq_per_gang, 1),
          "per_gang_ms_window": round(win_per_gang, 2),
          "per_gang_ms_window_incl_compile": round(
              win_wall * 1e3 / len(shapes), 2),
          "speedup_per_gang": round(speedup, 1),
          "dispatch_windows": windows,
          "kernel_ms_warm": round(
              sum(ks[1:]) * 1e3 / len(ks[1:]), 2) if len(ks) > 1 else None,
          "dirty_refresh_ms": round(dirty_ms, 2),
          "identity_sweep_ms": round(sweep_ms, 1),
          "dirty_speedup": round(sweep_ms / max(dirty_ms, 1e-9), 1),
          "gang_stats": {k: stats[k] for k in
                         ("windows", "gangs", "pods", "fallbacks")},
          "columns": dict(cols.stats),
          "wire": {"nodes": wire_nodes, "pods": wire_placed,
                   "wall_ms": round(wire_wall * 1e3, 1),
                   "bind_posts": wstats["bind_posts"],
                   "duplicate_binds": wstats["duplicate_binds"]},
          "placement_parity": "ok",
          "note": "gates: window leg >=20x faster per gang than the "
                  "in-run sequential schedule_gang baseline (steady; "
                  "one-time column build + jit compile absorbed by an "
                  "infeasible warm-up window, residual compile "
                  "accounted), placements bit-identical across "
                  "sequential/window/gang_window_host legs and "
                  "gang_assign_oracle on the first 2 gangs, named-patch "
                  "gang-column refresh <5 ms at 50k nodes (journal "
                  "O(dirty) vs identity sweep), zero duplicate binding "
                  "POSTs and bind_posts == placed on the wire"})
    assert speedup >= 20.0, \
        f"gang dispatch gate: {speedup:.1f}x < 20x vs sequential"
    assert dirty_ms < 5.0, \
        f"O(dirty) gate: gang columns refreshed in {dirty_ms:.2f} ms"


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--device", choices=["cpu", "default"], default="default")
    parser.add_argument(
        "--configs",
        default="1,2,3,4,5,6,7,7b,8,9,10,11,12,13,14,15,16,17,18,19,20,21,22",
    )
    parser.add_argument("--f64", action="store_true")
    args = parser.parse_args(argv)

    import jax

    jax.config.update("jax_enable_x64", True)
    if args.device == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    dtype = jnp.float64 if args.f64 else jnp.float32
    rtt = engage_sync_mode()
    # the overhead meter federates THIS process at 1 Hz for the whole
    # suite; every emit() row carries telemetry_overhead_pct, gated <3%
    global _METER
    _METER = TelemetryOverheadMeter()
    log(f"devices: {jax.devices()}, dtype: {dtype}, sync rtt: {rtt:.2f} ms")
    todo = {c.strip() for c in args.configs.split(",")}
    todo = {int(c) if c.isdigit() else c for c in todo}
    if 1 in todo:
        config1(dtype)
    if 2 in todo:
        config2(dtype, rtt)
    if 3 in todo:
        config3(dtype, rtt)
    if 4 in todo:
        config4(dtype, rtt)
    if 5 in todo:
        config5(dtype, rtt)
    if 6 in todo:
        config6(dtype, rtt)
    if 7 in todo:
        config7(dtype, rtt)
    if "7b" in todo:
        config7b(dtype, rtt)
    if 8 in todo:
        config8(dtype, rtt)
    if 9 in todo:
        config9(dtype, rtt)
    if 10 in todo:
        config10(dtype, rtt)
    if 11 in todo:
        config11(dtype, rtt)
    if 12 in todo:
        config12(dtype, rtt)
    if 13 in todo:
        config13(dtype, rtt)
    if 14 in todo:
        config14(dtype, rtt)
    if 15 in todo:
        config15(dtype, rtt)
    if 16 in todo:
        config16(dtype, rtt)
    if 17 in todo:
        config17(dtype, rtt)
    if 18 in todo:
        config18(dtype, rtt)
    if 19 in todo:
        config19(dtype, rtt)
    if 20 in todo:
        config20(dtype, rtt)
    if 21 in todo:
        config21(dtype, rtt)
    if 22 in todo:
        config22(dtype, rtt)
    if _METER is not None:
        _METER.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
