"""Gang (burst) scheduling: batched top-k with hot-value feedback.

The reference scheduler places one pod per cycle: Filter, Score, pick the
best node (ref: k8s scheduleOne; the Dynamic score is pod-independent).
Within one annotator sync window the node scores don't change, so a naive
burst of P pods piles onto the argmax node — the hotspot problem the
``node_hot_value`` penalty exists to mitigate at sync granularity
(ref: pkg/plugins/dynamic/plugins.go:89-91, pkg/controller/annotator/
node.go:113-121). For gang scheduling we apply the reference's own
correction *inside the batch*:

    After a node receives c in-batch pods, its effective score is
        eff_n(c) = clamp(S_n - 10 * h(c), 0, 100)
        h(c)     = Σ_p  floor(c / count_p)          (hotValue policy)
    i.e. the hot-value formula applied to the batch-local bindings
    (all in-batch bindings fall inside every hotValue window).

**Sequential semantics (the oracle)**: pods are placed one at a time on
the current max-``eff`` schedulable node, ties broken by lowest node
index (the reference randomizes among ties; we fix determinism), skipping
nodes at capacity.

**Batched equivalent (water-filling)**: because every node shares the
same penalty staircase h, the sequential greedy is exactly "take the P
most valuable tokens", where node n's t-th token has value
``max(S_n - 10·h(t), 0)`` and equal-valued tokens order by node index.
Scores are integers in [0,100], so allocation reduces to 101 discrete
levels: count each node's tokens per level, find the waterline level
where cumulative capacity crosses P, and split the waterline level by
prefix-sum in node-index order. Everything is O(101·N) tensor work — no
sequential loop over pods — and shards over the node axis.

Entries with ``count <= 0`` are skipped in h (the reference would panic
on integer division by zero; a policy that does this is invalid).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..constants import MAX_NODE_SCORE
from ..utils.score import normalize_score


def _idtype():
    """Widest available integer dtype (int64 under x64, else int32)."""
    return jnp.int64 if jax.config.jax_enable_x64 else jnp.int32

# "unbounded tokens at this level" — kept int32-safe so the no-x64 path
# (where jnp.int64 silently narrows) can't overflow-wrap to negative.
_INF_TOKENS = np.int64(1) << 30


def hot_penalty_steps(hv_counts: Sequence[int]) -> np.ndarray:
    """g[x] = min assignments c such that h(c) > x, for x = 0..10.

    h(c) = Σ_p floor(c / count_p). g bounds how many pods a node can take
    before its score drops by more than 10*x. When no (valid) hotValue
    entries exist, h == 0 and every g[x] is unbounded.
    """
    counts = [int(c) for c in hv_counts if int(c) > 0]
    g = np.full((11,), _INF_TOKENS, dtype=np.int64)
    if not counts:
        return g
    # h increases by >= 1 at least every min(counts) steps, so h(c) > 10
    # within c <= 11 * min(counts).
    limit = 11 * min(counts) + 1
    h = np.zeros((limit,), dtype=np.int64)
    for c in range(limit):
        h[c] = sum(c // k for k in counts)
    for x in range(11):
        above = np.nonzero(h > x)[0]
        if len(above):
            g[x] = above[0]
    return g


@dataclass
class GangResult:
    counts: Any  # [N] int32 — pods assigned per node
    unassigned: Any  # scalar int — pods that found no capacity
    waterline: Any  # scalar int — the score level where allocation stopped


def gang_assign_oracle(
    scores: Sequence[int],
    schedulable: Sequence[bool],
    num_pods: int,
    hv_counts: Sequence[int],
    capacity: Sequence[int] | None = None,
) -> GangResult:
    """Sequential greedy reference implementation (slow; parity oracle)."""
    n = len(scores)
    counts = [int(c) for c in hv_counts if int(c) > 0]
    cap = [num_pods] * n if capacity is None else [int(c) for c in capacity]
    assigned = [0] * n

    def h(c: int) -> int:
        return sum(c // k for k in counts)

    unassigned = 0
    for _ in range(num_pods):
        best, best_eff = -1, -1
        for i in range(n):
            if not schedulable[i] or assigned[i] >= cap[i]:
                continue
            eff = normalize_score(int(scores[i]) - 10 * h(assigned[i]), MAX_NODE_SCORE, 0)
            if eff > best_eff:
                best, best_eff = i, eff
        if best < 0:
            unassigned += 1
            continue
        assigned[best] += 1
    waterline = 0 if unassigned == 0 else -1
    return GangResult(np.array(assigned, np.int32), unassigned, waterline)


class GangScheduler:
    """Jitted water-filling gang assignment.

    Static over (policy hotValue table); jitted per (N,) shape with
    ``num_pods`` and per-node capacity as traced inputs.
    """

    def __init__(self, hv_counts: Sequence[int]):
        self._g_host = hot_penalty_steps(hv_counts)  # [11] np.int64
        self._jit = jax.jit(self._assign_impl)

    def _g_lookup(self, xq):
        """g[xq] for a traced int array xq in [0, 10].

        Unrolled select chain over the 11 static table entries: a
        dynamic-index gather (``g[xq]``) is pathologically slow on TPU
        even for a tiny table, while 11 fused selects are free.
        """
        out = jnp.asarray(int(self._g_host[10]), jnp.int32)
        out = jnp.broadcast_to(out, xq.shape)
        for x in range(9, -1, -1):
            out = jnp.where(xq <= x, jnp.int32(int(self._g_host[x])), out)
        return out

    def __call__(self, scores, schedulable, num_pods, capacity=None) -> GangResult:
        scores = jnp.asarray(scores, dtype=jnp.int32)
        n = scores.shape[0]
        num_pods = int(min(int(num_pods), 2**31 - 1))
        if capacity is None:
            capacity = np.full((n,), num_pods, dtype=np.int64)
        capacity = np.minimum(np.asarray(capacity, dtype=np.int64), 2**31 - 1)
        out = self._jit(
            scores,
            jnp.asarray(schedulable, dtype=jnp.bool_),
            jnp.asarray(num_pods, dtype=jnp.int32),
            jnp.asarray(capacity, dtype=jnp.int32),
        )
        return GangResult(*out)

    def _assign_impl(self, scores, schedulable, num_pods, capacity):
        # All internal arithmetic is int32: int64 cumsum/reductions lower
        # to u32-pair reduce-windows that blow TPU vmem at 50k nodes. This
        # is exact because per-node tokens are clipped to (2^31-1)/N (so
        # level totals fit int32); the only divergence from the sequential
        # oracle would need a single node to absorb > 2^31/N pods.
        n = scores.shape[0]
        num_pods = jnp.minimum(num_pods, jnp.asarray(2**31 - 1)).astype(jnp.int32)
        capacity = jnp.clip(capacity, 0, 2**31 - 1).astype(jnp.int32)
        k_cap = jnp.where(schedulable, capacity, 0)  # [N] i32
        # No node ever needs more than num_pods tokens; clipping also keeps
        # the level-total reductions within int32.
        k_cap = jnp.minimum(k_cap, jnp.maximum(num_pods, 0))
        k_cap = jnp.minimum(k_cap, (2**31 - 1) // max(n, 1))

        s = scores.astype(jnp.int32)
        levels = jnp.arange(102, dtype=jnp.int32)  # [102]

        # totals[L] = Σ_n A_n(L), the number of tokens valued >= L, where
        # A_n(L) = min(k_cap_n, g[floor((s_n - L)/10)]) for s_n >= L >= 1.
        # Materialize the [102, N] level table directly (elementwise ops +
        # one reduction over N — 5.1M int32 lanes, trivial for the VPU).
        # An earlier formulation scattered breakpoint deltas into a [102]
        # histogram; TPU lowers 1D scatter-adds poorly (and the scatter
        # emitter can abort in fusion: scatter_emitter.cc operand check),
        # so the dense table is both faster and safer here.
        lv = levels[:, None]  # [102, 1]
        xq = jnp.clip((s[None, :] - lv) // 10, 0, 10)  # [102, N]
        unlocked = jnp.where(s[None, :] >= lv, self._g_lookup(xq), 0)
        a_table = jnp.minimum(k_cap[None, :], unlocked)  # [102, N]
        totals = a_table.sum(axis=1, dtype=jnp.int32)  # [102]
        totals = totals.at[0].set(k_cap.sum(dtype=jnp.int32))

        meets = totals >= num_pods  # True for L <= L*
        l_star = jnp.max(jnp.where(meets, levels, -1))  # -1 => capacity short

        def a_of(level):
            """A_n(level) for a traced scalar level >= 1, elementwise."""
            xq = jnp.clip((s - level) // 10, 0, 10)
            unlocked = jnp.where(s >= level, self._g_lookup(xq), 0)
            return jnp.minimum(k_cap, unlocked)

        def full_capacity(_):
            counts = k_cap
            unassigned = num_pods - totals[0]
            return counts, unassigned, jnp.asarray(-1, jnp.int32)

        def waterline(l_star):
            upper = jnp.where(l_star + 1 >= 102, 0, a_of(l_star + 1))
            at_or_above = jnp.where(l_star >= 1, a_of(l_star), k_cap)
            exact = at_or_above - upper  # tokens exactly at L*
            remainder = num_pods - jnp.take(totals, jnp.minimum(l_star + 1, 101))
            remainder = jnp.where(l_star + 1 >= 102, num_pods, remainder)
            # exclusive prefix sum in node-index order (int32 pinned: int64
            # cumsum lowers to a vmem-hungry u32-pair reduce-window on TPU)
            prefix = jnp.cumsum(exact, dtype=jnp.int32) - exact
            take = jnp.clip(remainder - prefix, 0, exact)
            counts = upper + take
            return counts, jnp.asarray(0, jnp.int32), l_star

        counts, unassigned, lvl = jax.lax.cond(
            l_star < 0, full_capacity, waterline, l_star
        )
        return counts.astype(jnp.int32), unassigned, lvl
