"""Gang (burst) scheduling: batched top-k with hot-value feedback.

The reference scheduler places one pod per cycle: Filter, Score, pick the
best node (ref: k8s scheduleOne; the Dynamic score is pod-independent).
Within one annotator sync window the node scores don't change, so a naive
burst of P pods piles onto the argmax node — the hotspot problem the
``node_hot_value`` penalty exists to mitigate at sync granularity
(ref: pkg/plugins/dynamic/plugins.go:89-91, pkg/controller/annotator/
node.go:113-121). For gang scheduling we apply the reference's own
correction *inside the batch*:

    After a node receives c in-batch pods, its effective score is
        eff_n(c) = clamp(S_n - 10 * h(c), 0, 100)
        h(c)     = Σ_p  floor(c / count_p)          (hotValue policy)
    i.e. the hot-value formula applied to the batch-local bindings
    (all in-batch bindings fall inside every hotValue window).

**Sequential semantics (the oracle)**: pods are placed one at a time on
the current max-``eff`` schedulable node, ties broken by lowest node
index (the reference randomizes among ties; we fix determinism), skipping
nodes at capacity.

**Batched equivalent (water-filling)**: because every node shares the
same penalty staircase h, the sequential greedy is exactly "take the P
most valuable tokens", where node n's t-th token has value
``max(S_n - 10·h(t), 0)`` and equal-valued tokens order by node index.
Scores are integers in [0,100], so allocation reduces to 101 discrete
levels: count each node's tokens per level, find the waterline level
where cumulative capacity crosses P, and split the waterline level by
prefix-sum in node-index order. Everything is O(101·N) tensor work — no
sequential loop over pods — and shards over the node axis.

**Combined-score mode**: the scheduler framework sums weighted plugin
scores (deploy configs: Dynamic weight 3, NodeResourceTopologyMatch
weight 2 — ref: deploy/manifests/*/scheduler-config.yaml). Only the
Dynamic component moves with in-batch assignments; other plugins'
scores are pod-independent within a burst of identical pods. So token
values generalize to

    value_n(t) = dynamic_weight * max(S_n - 10·h(t), 0) + offset_n

with ``offset_n = Σ_other w_i·score_i(n)`` a per-node constant. The
level grid widens to [0, 100·dynamic_weight + max_offset] and the
per-level token count inverts through the same g staircase:
``A_n(L) = k_cap`` when L <= offset_n, else ``min(k_cap, g[(S_n-q)//10])``
with ``q = ceil((L-offset_n)/dynamic_weight)`` (0 when q > 100 or
S_n < q). Defaults (weight 1, offsets 0) reproduce the plain grid.

**Sparse level grid**: the dense grid scans every integer level in
[0, 100·w + max_offset + 1] — 5,102 levels for an exotic
``dynamic_weight=50`` config. But the waterline L* (the highest level
whose cumulative token count covers P) can only land on an *achievable
token value* ``w·d + offset_n`` (d in 0..100), on level 0, or on the
grid top (the empty-batch sentinel): totals(L) is piecewise-constant
between achievable values, so the max L with totals(L) >= P is always
an interval right-endpoint = an achievable value. ``candidate_levels``
builds that set from the offsets actually present (101·|distinct
offsets| + 2 entries, padded to a lane multiple to bound recompiles)
and the solver evaluates totals only there — bit-identical results,
O(101·|offsets|·N) instead of O(100·w·N) work.

Entries with ``count <= 0`` are skipped in h (the reference would panic
on integer division by zero; a policy that does this is invalid).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..constants import MAX_NODE_SCORE
from ..utils.score import normalize_score


def _idtype():
    """Widest available integer dtype (int64 under x64, else int32)."""
    return jnp.int64 if jax.config.jax_enable_x64 else jnp.int32

# "unbounded tokens at this level" — kept int32-safe so the no-x64 path
# (where jnp.int64 silently narrows) can't overflow-wrap to negative.
_INF_TOKENS = np.int64(1) << 30


def hot_penalty_steps(hv_counts: Sequence[int]) -> np.ndarray:
    """g[x] = min assignments c such that h(c) > x, for x = 0..10.

    h(c) = Σ_p floor(c / count_p). g bounds how many pods a node can take
    before its score drops by more than 10*x. When no (valid) hotValue
    entries exist, h == 0 and every g[x] is unbounded.
    """
    counts = [int(c) for c in hv_counts if int(c) > 0]
    g = np.full((11,), _INF_TOKENS, dtype=np.int64)
    if not counts:
        return g
    # h increases by >= 1 at least every min(counts) steps, so h(c) > 10
    # within c <= 11 * min(counts).
    limit = 11 * min(counts) + 1
    h = np.zeros((limit,), dtype=np.int64)
    for c in range(limit):
        h[c] = sum(c // k for k in counts)
    for x in range(11):
        above = np.nonzero(h > x)[0]
        if len(above):
            g[x] = above[0]
    return g


def candidate_levels(
    dynamic_weight: int,
    max_offset: int,
    offsets,
    n_levels: int,
) -> np.ndarray | None:
    """Sparse waterline candidates (see module docstring): achievable
    token values ``w·d + o`` over the distinct offsets present, plus
    level 0 (the full-capacity total lives there) and the grid top
    (``num_pods == 0`` sentinel). Sorted ascending with ``levels[0] ==
    0``; padded to a multiple of 128 with the top value so the jit
    specializes per size bucket, not per offset multiset. Returns None
    when the dense grid is at least as small (e.g. plain mode: 101
    distinct values vs 102 dense levels)."""
    w = int(dynamic_weight)
    uniq = np.unique(np.clip(np.asarray(offsets, np.int64), 0, int(max_offset)))
    if 101 * len(uniq) + 2 >= n_levels:
        return None
    d = np.arange(MAX_NODE_SCORE + 1, dtype=np.int64) * w
    cand = np.unique(np.concatenate([
        np.zeros((1,), np.int64),
        (d[:, None] + uniq[None, :]).ravel(),
        np.asarray([n_levels - 1], np.int64),
    ]))
    if len(cand) >= n_levels:
        return None
    pad = (-len(cand)) % 128
    if pad:
        cand = np.concatenate([cand, np.full((pad,), n_levels - 1, np.int64)])
    return cand.astype(np.int32)


def waterline_take(exact, remainder, order=None) -> np.ndarray:
    """Split ``remainder`` waterline tokens across the nodes holding
    ``exact`` tokens at L*. Default (``order=None``) is the sequential
    oracle's rule — node-index prefix order, exactly the ``cumsum``
    split the solver jits. ``order`` (a permutation of node indices)
    fills greedily in that order instead: the gang queue's
    fragmentation-aware / seeded tie policies reorder ONLY this split —
    the waterline level, the token multiset, and every count away from
    L* are policy-independent by construction."""
    exact = np.asarray(exact)
    if order is None:
        prefix = np.cumsum(exact) - exact
        return np.clip(remainder - prefix, 0, exact)
    take = np.zeros_like(exact)
    ex = exact[order]
    prefix = np.cumsum(ex) - ex
    take[order] = np.clip(remainder - prefix, 0, ex)
    return take


@dataclass
class GangResult:
    counts: Any  # [N] int32 — pods assigned per node
    unassigned: Any  # scalar int — pods that found no capacity
    waterline: Any  # scalar int — the score level where allocation stopped


def gang_assign_oracle(
    scores: Sequence[int],
    schedulable: Sequence[bool],
    num_pods: int,
    hv_counts: Sequence[int],
    capacity: Sequence[int] | None = None,
    offsets: Sequence[int] | None = None,
    dynamic_weight: int = 1,
    max_offset: int | None = None,
    prior: Sequence[int] | None = None,
) -> GangResult:
    """Sequential greedy reference implementation (slow; parity oracle).

    ``waterline`` follows the solver's convention: the effective value of
    the least valuable token taken (== the solver's L*, the highest level
    whose cumulative token count covers ``num_pods``); -1 when capacity
    runs short; the grid top (``100*w + max_offset + 1``) when no pod was
    requested. ``max_offset`` should match the solver's static bound
    (defaults to max(offsets)).

    ``prior`` (per node) counts in-batch assignments made by an earlier
    pass: the hot-penalty staircase continues at h(prior + t) while
    ``capacity`` still bounds only this pass's assignments.
    """
    n = len(scores)
    counts = [int(c) for c in hv_counts if int(c) > 0]
    cap = [num_pods] * n if capacity is None else [int(c) for c in capacity]
    offs = [0] * n if offsets is None else [int(o) for o in offsets]
    base = [0] * n if prior is None else [int(p) for p in prior]
    w = int(dynamic_weight)
    if max_offset is None:
        max_offset = max(offs, default=0)
    assigned = [0] * n

    def h(c: int) -> int:
        return sum(c // k for k in counts)

    unassigned = 0
    min_eff: int | None = None
    for _ in range(num_pods):
        best, best_eff = -1, -1
        for i in range(n):
            if not schedulable[i] or assigned[i] >= cap[i]:
                continue
            dyn = normalize_score(
                int(scores[i]) - 10 * h(base[i] + assigned[i]), MAX_NODE_SCORE, 0
            )
            eff = w * dyn + offs[i]
            if eff > best_eff:
                best, best_eff = i, eff
        if best < 0:
            unassigned += 1
            continue
        assigned[best] += 1
        min_eff = best_eff if min_eff is None else min(min_eff, best_eff)
    if unassigned > 0:
        waterline = -1
    elif min_eff is None:  # num_pods == 0: nothing constrains the level
        waterline = MAX_NODE_SCORE * w + int(max_offset) + 1
    else:
        waterline = min_eff
    return GangResult(np.array(assigned, np.int32), unassigned, waterline)


def gang_assign_host(
    scores,
    schedulable,
    num_pods: int,
    hv_counts: Sequence[int],
    capacity=None,
    offsets=None,
    dynamic_weight: int = 1,
    max_offset: int = 0,
    prior=None,
    tie_order=None,
) -> GangResult:
    """Vectorized numpy twin of ``GangScheduler._assign_impl``.

    Same water-filling math (level table, waterline search, node-order
    prefix split) with the same int32-range clipping, so results are
    bit-identical to the device solver — fast enough to verify placements
    at benchmark scale (O(levels*N) numpy) without a device round-trip.

    ``prior`` shifts each node's hot-penalty staircase past assignments
    an earlier pass already made (token t is valued at h(prior + t));
    ``capacity`` bounds this pass only.

    ``tie_order`` is the gang queue's waterline-split policy hook:
    ``tie_order(exact, upper, l_star) -> order | None`` may return a
    node-index permutation for ``waterline_take``. None (default, and a
    None return) keeps the oracle's node-index prefix split.
    """
    s = np.asarray(scores, np.int64)
    n = s.shape[0]
    w = int(dynamic_weight)
    g = hot_penalty_steps(hv_counts)  # [11] int64 (values <= 2^30)
    num_pods = int(min(int(num_pods), 2**31 - 1))
    if capacity is None:
        capacity = np.full((n,), num_pods, dtype=np.int64)
    capacity = np.clip(np.asarray(capacity, np.int64), 0, 2**31 - 1)
    if offsets is None:
        offsets = np.zeros((n,), dtype=np.int64)
    offs = np.clip(np.asarray(offsets, np.int64), 0, int(max_offset))
    if prior is None:
        prior = np.zeros((n,), dtype=np.int64)
    prior = np.clip(np.asarray(prior, np.int64), 0, 2**31 - 1)
    n_levels = MAX_NODE_SCORE * w + int(max_offset) + 2

    k_cap = np.where(np.asarray(schedulable, bool), capacity, 0)
    k_cap = np.minimum(k_cap, max(num_pods, 0))
    k_cap = np.minimum(k_cap, (2**31 - 1) // max(n, 1))

    def a_table(lv):
        """A_n(L) for lv broadcastable against the node axis."""
        qnum = lv - offs
        q = (qnum + (w - 1)) // w
        xq = np.clip((s - q) // 10, 0, 10)
        unlocked = np.where((q <= MAX_NODE_SCORE) & (s >= q), g[xq], 0)
        unlocked = np.maximum(unlocked - prior, 0)  # tokens an earlier pass took
        unlocked = np.where(qnum <= 0, k_cap, unlocked)
        return np.minimum(k_cap, unlocked)

    levels = np.arange(n_levels, dtype=np.int64)
    totals = a_table(levels[:, None]).sum(axis=1)  # [n_levels]
    meets = np.nonzero(totals >= num_pods)[0]
    l_star = int(meets.max()) if len(meets) else -1

    if l_star < 0:  # capacity short: everything binds, rest unassigned
        counts = k_cap
        return GangResult(
            counts.astype(np.int32), int(num_pods - totals[0]), -1
        )
    upper = a_table(np.int64(l_star + 1)) if l_star + 1 < n_levels else np.zeros_like(k_cap)
    at_or_above = a_table(np.int64(l_star))
    exact = at_or_above - upper
    if l_star + 1 >= n_levels:
        remainder = num_pods
    else:
        remainder = num_pods - int(totals[l_star + 1])
    order = None if tie_order is None else tie_order(exact, upper, l_star)
    take = waterline_take(exact, remainder, order)
    counts = upper + take
    return GangResult(counts.astype(np.int32), 0, l_star)


class GangScheduler:
    """Jitted water-filling gang assignment.

    Static over (policy hotValue table, dynamic_weight, max_offset);
    jitted per (N,) shape with ``num_pods``, per-node capacity, and
    per-node combined-score offsets as traced inputs. Defaults
    (``dynamic_weight=1``, ``max_offset=0``, zero offsets) are the plain
    Dynamic-score domain.
    """

    def __init__(
        self,
        hv_counts: Sequence[int],
        dynamic_weight: int = 1,
        max_offset: int = 0,
    ):
        if dynamic_weight < 1:
            raise ValueError("dynamic_weight must be >= 1")
        if max_offset < 0:
            raise ValueError("max_offset must be >= 0")
        self._g_host = hot_penalty_steps(hv_counts)  # [11] np.int64
        self._weight = int(dynamic_weight)
        self._max_offset = int(max_offset)
        # token values live in [0, 100*w + max_offset]; one extra level so
        # waterline+1 indexing stays in range
        self._n_levels = MAX_NODE_SCORE * self._weight + self._max_offset + 2
        self._jit = jax.jit(self._assign_impl)

    def _g_lookup(self, xq):
        """g[xq] for a traced int array xq in [0, 10].

        Unrolled select chain over the 11 static table entries: a
        dynamic-index gather (``g[xq]``) is pathologically slow on TPU
        even for a tiny table, while 11 fused selects are free.
        """
        out = jnp.asarray(int(self._g_host[10]), jnp.int32)
        out = jnp.broadcast_to(out, xq.shape)
        for x in range(9, -1, -1):
            out = jnp.where(xq <= x, jnp.int32(int(self._g_host[x])), out)
        return out

    def __call__(
        self, scores, schedulable, num_pods, capacity=None, offsets=None,
        prior=None, sparse_levels: bool | None = None,
    ) -> GangResult:
        """``sparse_levels``: True forces the sparse candidate grid,
        False forces the dense one, None (default) picks whichever is
        smaller for this call's offsets (plain mode stays dense; exotic
        weight/offset configs go sparse). Results are bit-identical
        either way (parity-pinned in tests/test_gang.py)."""
        scores = jnp.asarray(scores, dtype=jnp.int32)
        n = scores.shape[0]
        num_pods = int(min(int(num_pods), 2**31 - 1))
        if capacity is None:
            capacity = np.full((n,), num_pods, dtype=np.int64)
        capacity = np.minimum(np.asarray(capacity, dtype=np.int64), 2**31 - 1)
        if offsets is None:
            offsets = np.zeros((n,), dtype=np.int32)
        if prior is None:
            prior = np.zeros((n,), dtype=np.int32)
        levels = None
        if sparse_levels or sparse_levels is None:
            levels = candidate_levels(
                self._weight, self._max_offset, offsets, self._n_levels
            )
            if levels is None and sparse_levels:
                # forced sparse on a config where dense is smaller:
                # honor it anyway (parity testing hook)
                uniq = np.unique(
                    np.clip(np.asarray(offsets, np.int64), 0, self._max_offset)
                )
                d = np.arange(MAX_NODE_SCORE + 1, dtype=np.int64) * self._weight
                levels = np.unique(np.concatenate([
                    np.zeros((1,), np.int64),
                    (d[:, None] + uniq[None, :]).ravel(),
                    np.asarray([self._n_levels - 1], np.int64),
                ])).astype(np.int32)
        out = self._jit(
            scores,
            jnp.asarray(schedulable, dtype=jnp.bool_),
            jnp.asarray(num_pods, dtype=jnp.int32),
            jnp.asarray(capacity, dtype=jnp.int32),
            jnp.asarray(offsets, dtype=jnp.int32),
            jnp.asarray(prior, dtype=jnp.int32),
            None if levels is None else jnp.asarray(levels, jnp.int32),
        )
        return GangResult(*out)

    def _a_table(self, s, offsets, k_cap, prior, lv):
        """A_n(L): tokens of node n valued >= level L, for L broadcast
        against the node axis. Level 0 (and any L <= offset) is always
        the full k_cap: token values never drop below the offset.
        ``prior`` tokens per node were consumed by an earlier pass and
        come off the unlocked count (but not off k_cap, which already
        bounds only this pass)."""
        qnum = lv - offsets  # may broadcast [L, N] or [N]
        w = self._weight
        q = (qnum + (w - 1)) // w  # ceil; only meaningful when qnum > 0
        xq = jnp.clip((s - q) // 10, 0, 10)
        unlocked = jnp.where((q <= MAX_NODE_SCORE) & (s >= q), self._g_lookup(xq), 0)
        unlocked = jnp.maximum(unlocked - prior, 0)
        unlocked = jnp.where(qnum <= 0, k_cap, unlocked)
        return jnp.minimum(k_cap, unlocked)

    def _totals(self, s, offs, k_cap, pri):
        """totals[L] = Σ_n A_n(L), the number of tokens valued >= L.

        Materialize the [n_levels, N] level table directly (elementwise
        ops + one reduction over N — int32 lanes, trivial for the VPU).
        An earlier formulation scattered breakpoint deltas into a
        histogram; TPU lowers 1D scatter-adds poorly (and the scatter
        emitter can abort in fusion: scatter_emitter.cc operand check),
        so the dense table is both faster and safer here. Overridden by
        ``pallas_gang.PallasGangScheduler`` with a fused kernel that
        never round-trips the table through HBM.
        """
        levels = jnp.arange(self._n_levels, dtype=jnp.int32)
        a_table = self._a_table(s[None, :], offs[None, :], k_cap[None, :],
                                pri[None, :], levels[:, None])
        return a_table.sum(axis=1, dtype=jnp.int32)

    def _assign_impl(self, scores, schedulable, num_pods, capacity, offsets,
                     prior, levels=None):
        # All internal arithmetic is int32: int64 cumsum/reductions lower
        # to u32-pair reduce-windows that blow TPU vmem at 50k nodes. This
        # is exact because per-node tokens are clipped to (2^31-1)/N (so
        # level totals fit int32); the only divergence from the sequential
        # oracle would need a single node to absorb > 2^31/N pods.
        #
        # ``levels=None`` scans the dense grid (via ``_totals``, which
        # Pallas overrides); a candidate array from ``candidate_levels``
        # scans only achievable token values — bit-identical l_star (see
        # module docstring), smaller table for exotic weight configs.
        n = scores.shape[0]
        n_levels = self._n_levels
        num_pods = jnp.minimum(num_pods, jnp.asarray(2**31 - 1)).astype(jnp.int32)
        capacity = jnp.clip(capacity, 0, 2**31 - 1).astype(jnp.int32)
        k_cap = jnp.where(schedulable, capacity, 0)  # [N] i32
        # No node ever needs more than num_pods tokens; clipping also keeps
        # the level-total reductions within int32.
        k_cap = jnp.minimum(k_cap, jnp.maximum(num_pods, 0))
        k_cap = jnp.minimum(k_cap, (2**31 - 1) // max(n, 1))

        s = scores.astype(jnp.int32)
        offs = jnp.clip(offsets.astype(jnp.int32), 0, self._max_offset)
        pri = jnp.clip(prior.astype(jnp.int32), 0, 2**31 - 1)

        if levels is None:
            levels = jnp.arange(n_levels, dtype=jnp.int32)
            totals = self._totals(s, offs, k_cap, pri)  # [n_levels]
        else:
            levels = levels.astype(jnp.int32)  # [C], levels[0] == 0
            a_table = self._a_table(
                s[None, :], offs[None, :], k_cap[None, :], pri[None, :],
                levels[:, None],
            )
            totals = a_table.sum(axis=1, dtype=jnp.int32)  # [C]

        meets = totals >= num_pods  # True for L <= L*
        l_star = jnp.max(jnp.where(meets, levels, -1))  # -1 => capacity short

        def full_capacity(_):
            counts = k_cap
            # levels[0] == 0 in both grids: totals[0] = every token
            unassigned = num_pods - totals[0]
            return counts, unassigned, jnp.asarray(-1, jnp.int32)

        def waterline(l_star):
            upper = jnp.where(
                l_star + 1 >= n_levels,
                0,
                self._a_table(s, offs, k_cap, pri, l_star + 1),
            )
            at_or_above = self._a_table(s, offs, k_cap, pri, l_star)
            exact = at_or_above - upper  # tokens exactly at L*
            # sum(upper) == totals(l_star + 1) exactly (int32 sums), so
            # neither grid needs a dense totals lookup here
            remainder = num_pods - jnp.sum(upper, dtype=jnp.int32)
            # exclusive prefix sum in node-index order (int32 pinned: int64
            # cumsum lowers to a vmem-hungry u32-pair reduce-window on TPU)
            prefix = jnp.cumsum(exact, dtype=jnp.int32) - exact
            take = jnp.clip(remainder - prefix, 0, exact)
            counts = upper + take
            return counts, jnp.asarray(0, jnp.int32), l_star

        counts, unassigned, lvl = jax.lax.cond(
            l_star < 0, full_capacity, waterline, l_star
        )
        return counts.astype(jnp.int32), unassigned, lvl


# ---------------------------------------------------------------------------
# Incremental first-argmax: segment-max tree over a masked score column.
# ---------------------------------------------------------------------------

_SEG_MIN = np.int64(np.iinfo(np.int64).min)


class SegMaxTree:
    """Segment-max tree over a masked weighted-score column.

    The drip fast path picks ``argmax(where(mask, weighted, INT64_MIN))``
    per pod — O(n) even when only one node changed since the last pod
    (the previous bind's ``free -= request`` fold). This tree makes the
    common drip cadence O(log n): build once per (column, request-vec)
    pair, then each bind updates exactly the folded leaf.

    Per heap node it keeps ``(max, count-of-max, feasible-count)`` over
    the subtree, which is enough to reproduce every read the scalar
    oracle's selection makes:

    - ``argmax_first()`` — leftmost leaf attaining the root max, i.e.
      exactly ``np.argmax``'s first-maximum rule (snapshot order).
    - ``tie_count`` — how many *feasible* leaves attain the root max:
      the seeded tie-break's ``ties.size`` without materializing ties.
    - ``select_tie(r)`` — the r-th (0-based, snapshot order) leaf
      attaining the root max: ``ties[r]`` without flatnonzero.
    - ``feasible_count`` — ``count_nonzero(mask)``.

    Build is O(n) vectorized (bottom-up level merges); ``update`` is
    O(log n) Python scalars. Infeasible leaves carry ``INT64_MIN`` with
    zero counts, so an all-infeasible (sub)tree reports max=INT64_MIN,
    counts 0 — callers gate on ``feasible_count`` before selecting.
    """

    __slots__ = ("n", "_size", "_mx", "_cnt", "_feas")

    def __init__(self, values: np.ndarray, feasible: np.ndarray):
        """``values``: int64[n], already masked (INT64_MIN where the
        node is infeasible). ``feasible``: bool[n]."""
        n = int(len(values))
        self.n = n
        size = 1 << max(0, n - 1).bit_length() if n > 1 else 1
        self._size = size
        mx = np.full(2 * size, _SEG_MIN, dtype=np.int64)
        cnt = np.zeros(2 * size, dtype=np.int64)
        feas = np.zeros(2 * size, dtype=np.int64)
        mx[size:size + n] = values
        f = feasible.astype(np.int64)
        cnt[size:size + n] = f
        feas[size:size + n] = f
        k = size
        while k > 1:
            k //= 2
            lm, rm = mx[2 * k:4 * k:2], mx[2 * k + 1:4 * k:2]
            lc, rc = cnt[2 * k:4 * k:2], cnt[2 * k + 1:4 * k:2]
            right_wins = rm > lm
            mx[k:2 * k] = np.where(right_wins, rm, lm)
            cnt[k:2 * k] = np.where(
                right_wins, rc, np.where(lm > rm, lc, lc + rc)
            )
            feas[k:2 * k] = feas[2 * k:4 * k:2] + feas[2 * k + 1:4 * k:2]
        self._mx, self._cnt, self._feas = mx, cnt, feas

    @property
    def feasible_count(self) -> int:
        return int(self._feas[1])

    @property
    def max_value(self) -> int:
        return int(self._mx[1])

    @property
    def tie_count(self) -> int:
        """Feasible leaves attaining the root max (0 when none are)."""
        return int(self._cnt[1])

    def argmax_first(self) -> int:
        """Leftmost leaf index attaining the root max — bit-identical to
        ``np.argmax`` over the masked column."""
        mx = self._mx
        m = mx[1]
        i = 1
        size = self._size
        while i < size:
            i *= 2
            if mx[i] != m:
                i += 1
        return i - size

    def select_tie(self, r: int) -> int:
        """Index of the r-th (0-based, ascending) feasible leaf attaining
        the root max — ``np.flatnonzero(ties)[r]`` without the scan."""
        mx, cnt = self._mx, self._cnt
        m = mx[1]
        i = 1
        size = self._size
        while i < size:
            i *= 2
            c = cnt[i] if mx[i] == m else 0
            if r >= c:
                r -= int(c)
                i += 1
        return i - size

    def update(self, i: int, value: int, feasible: bool) -> None:
        """Point update of leaf ``i`` (O(log n)) — the drip fold's only
        column maintenance: re-mask the bound node, leave n-1 alone."""
        mx, cnt, feas = self._mx, self._cnt, self._feas
        j = self._size + int(i)
        f = 1 if feasible else 0
        mx[j] = value if feasible else _SEG_MIN
        cnt[j] = f
        feas[j] = f
        j //= 2
        while j >= 1:
            l, r = 2 * j, 2 * j + 1
            lm, rm = mx[l], mx[r]
            if lm > rm:
                mx[j], cnt[j] = lm, cnt[l]
            elif rm > lm:
                mx[j], cnt[j] = rm, cnt[r]
            else:
                mx[j], cnt[j] = lm, cnt[l] + cnt[r]
            feas[j] = feas[l] + feas[r]
            j //= 2
