"""Device-resident multi-gang batch kernel: water-filling for K gangs.

``BatchScheduler.schedule_gang`` solves one gang per call — a full
``refresh()`` + O(N) ``_prepare`` + one solver invocation each time.
This module is the gang twin of ``scorer.drip_batch``: one jitted
program takes the version-cached gang columns (raw Dynamic score,
schedulable mask, fit free matrix — ``framework.drip.GangColumns``)
plus a *window* of K heterogeneous gangs (per-gang pod count, request
row, per-class score offsets) and runs

    for each gang k (sequentially, ``lax.scan``):
        cap      = copies of vec_k fitting in the free carry
        counts   = water-filling split (waterline search + prefix take)
        free    -= counts · vec_k                  # the fold
        emit (counts, unassigned, waterline)

so later gangs in the window see earlier gangs' capacity consumption
exactly like a sequential ``schedule_gang(bind=True)`` loop, and the
host gets all K verdicts in ONE device-to-host transfer (a packed
``[K, Npad+2]`` int32 array). The solver math is ``gang_assign_host``'s
bit for bit — same int32 clipping, same level table, same node-order
prefix split — with the dense waterline scan replaced by a fixed-trip
binary search over the monotone ``totals(L) >= P`` predicate (totals is
non-increasing in L, so the max satisfying level is the same level the
dense argmax finds; the oracle/host parity suite pins this).

Columns are cached device-side by ``(identity, col_epoch)`` through
``parallel.sharded.DeviceColumnCache``: an O(dirty) dynamic patch
scatters only the journal's dirty rows, and the free fold carry stays
resident across windows under the drip path's ``mark_synced`` host
fold-replay discipline (exact int64 subtraction on both sides, so
device == host bit-for-bit).

``gang_window_host`` is the numpy twin of the whole window — the parity
reference for the kernel AND the execution engine for tie policies the
in-program prefix split can't express (fragmentation-aware and seeded
splits reorder the waterline take on host via ``waterline_take``).
"""

from __future__ import annotations

import time
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import enable_x64

from ..constants import MAX_NODE_SCORE
from ..fit.tracker import UNBOUNDED, copy_counts_rows
from .drip_batch import _MIN_K_BUCKET, _bucket, _bucket_nodes, _pad
from .topk import GangResult, gang_assign_host, hot_penalty_steps

__all__ = ["GangBatchKernel", "gang_window_host"]

_I32_MAX = 2**31 - 1


def _tie_order_for(tie_policy, tie_rng, capacity, n):
    """Per-gang ``tie_order`` closure for ``gang_assign_host``.

    - ``"fragmentation"``: waterline tokens go to the nodes that would
      strand the least copy-capacity if drained (ascending stranded
      count, node index breaking exact ties) — see
      ``topology.batched.stranded_copies``.
    - ``"seeded"``: a seeded random permutation; one ``rng.random(n)``
      draw per gang regardless of window size, so RNG consumption is
      identical however the queue is windowed.
    """
    if tie_policy is None:
        return None
    idx = np.arange(n)
    if tie_policy == "fragmentation":
        from ..topology.batched import stranded_copies

        cap = capacity
        if cap is None:
            cap = np.full((n,), _I32_MAX, dtype=np.int64)

        def order(exact, upper, l_star, _cap=cap):
            return np.lexsort((idx, stranded_copies(_cap, upper, exact)))

        return order
    if tie_policy == "seeded":
        if tie_rng is None:
            raise ValueError("tie_policy='seeded' needs tie_rng")

        def order(exact, upper, l_star):
            return np.lexsort((idx, tie_rng.random(n)))

        return order
    raise ValueError(f"unknown tie_policy: {tie_policy!r}")


def gang_window_host(
    scores,
    schedulable,
    bounded,
    free,
    gangs,
    hv_counts: Sequence[int],
    dynamic_weight: int = 1,
    max_offset: int = 0,
    tie_policy=None,
    tie_rng=None,
    fold: bool = True,
) -> tuple[list[GangResult], np.ndarray | None]:
    """Numpy twin of one kernel window: solve each gang in ``gangs``
    (an iterable of ``(num_pods, request_vec, offsets-or-None)``)
    against an evolving free-matrix copy, exactly the scan's carry
    semantics. Returns ``(results, free_after)`` — the caller's arrays
    are never written. ``fold=False`` solves every gang against the
    SAME initial capacity (the ``bind=False`` preview semantics: with
    nothing bound, sequential ``schedule_gang`` calls see no capacity
    evolution either)."""
    free_c = None if free is None else np.array(free, dtype=np.int64)
    n = len(np.asarray(scores))
    results: list[GangResult] = []
    for num_pods, vec, offs in gangs:
        cap = None
        if free_c is not None and bounded is not None:
            cap = copy_counts_rows(free_c, bounded, np.asarray(vec, np.int64))
        r = gang_assign_host(
            scores,
            schedulable,
            int(num_pods),
            hv_counts,
            capacity=cap,
            offsets=offs,
            dynamic_weight=dynamic_weight,
            max_offset=max_offset,
            tie_order=_tie_order_for(tie_policy, tie_rng, cap, n),
        )
        if fold and free_c is not None:
            free_c -= (
                np.asarray(r.counts, np.int64)[:, None]
                * np.asarray(vec, np.int64)[None, :]
            )
        results.append(r)
    return results, free_c


class GangBatchKernel:
    """Host wrapper: bucketing, device column placement, fold-carry reuse.

    One instance per gang engine (single scheduling loop, like
    ``DripBatchKernel``). Static over (hotValue table, dynamic weight,
    max offset); jitted per (node bucket, window bucket, class bucket)
    shape. The gang columns are cached device-side keyed on
    ``GangColumns.col_epoch`` with journal-driven row scatters; the
    ``free`` carry advances in-program and is reusable only while the
    host replays the identical folds (``mark_synced``)."""

    def __init__(
        self,
        hv_counts: Sequence[int],
        dynamic_weight: int = 1,
        max_offset: int = 0,
        device=None,
    ):
        from ..parallel.sharded import DeviceColumnCache

        if dynamic_weight < 1:
            raise ValueError("dynamic_weight must be >= 1")
        if max_offset < 0:
            raise ValueError("max_offset must be >= 0")
        self._g_host = hot_penalty_steps(hv_counts)  # [11] np.int64
        self._weight = int(dynamic_weight)
        self._max_offset = int(max_offset)
        self._n_levels = MAX_NODE_SCORE * self._weight + self._max_offset + 2
        # fixed-trip binary search covers [0, n_levels-1]
        self._search_trips = int(self._n_levels).bit_length()
        self._cols = DeviceColumnCache(device)
        self._free_dev = None  # device fold carry [npad, 4]
        self._free_src = None  # host free array the carry mirrors
        self._free_synced = False
        self.dispatches = 0
        self.free_uploads = 0
        self.last_kernel_seconds = 0.0
        self._jit = jax.jit(self._window_impl)

    def mark_synced(self, host_free) -> None:
        """Host applied exactly the kernel's folds — carry is reusable."""
        self._free_src = host_free
        self._free_synced = True

    def mark_desynced(self) -> None:
        self._free_synced = False
        self._free_dev = None
        self._free_src = None

    def _g_lookup(self, xq):
        """g[xq] via an unrolled select chain (same rationale as
        ``GangScheduler._g_lookup``: a tiny-table gather is
        pathologically slow on TPU; 11 fused selects are free)."""
        out = jnp.asarray(int(self._g_host[10]), jnp.int32)
        out = jnp.broadcast_to(out, xq.shape)
        for x in range(9, -1, -1):
            out = jnp.where(xq <= x, jnp.int32(int(self._g_host[x])), out)
        return out

    def _a_table(self, s, off, k_cap, lv):
        """A_n(L): tokens of node n valued >= level L — the prior-free
        specialization of ``GangScheduler._a_table`` (the window resets
        the hot staircase per gang, exactly like sequential
        ``schedule_gang`` calls)."""
        qnum = lv - off
        w = self._weight
        q = (qnum + (w - 1)) // w
        xq = jnp.clip((s - q) // 10, 0, 10)
        unlocked = jnp.where(
            (q <= MAX_NODE_SCORE) & (s >= q), self._g_lookup(xq), 0
        )
        unlocked = jnp.where(qnum <= 0, k_cap, unlocked)
        return jnp.minimum(k_cap, unlocked)

    def _window_impl(
        self, s, schedulable, bounded, free, vecs, offs, class_id,
        num_pods, active, n_clip,
    ):
        n_levels = self._n_levels

        def step(free, xs):
            cid, p, act = xs
            vec = vecs[cid]  # [4] int64
            off = jnp.clip(offs[cid], 0, self._max_offset)  # [N] int32
            # capacity from the fold carry: free_copy_counts math
            # (clip >= 0, per-dim floor-div, min across requested dims,
            # UNBOUNDED where nothing is requested or reported)
            q = jnp.where(vec > 0, vec, 1)
            per = jnp.where(
                vec[None, :] > 0,
                jnp.clip(free, 0, None) // q[None, :],
                jnp.int64(UNBOUNDED),
            )
            cap = jnp.minimum(per.min(axis=1), jnp.int64(UNBOUNDED))
            cap = jnp.where(bounded, cap, jnp.int64(UNBOUNDED))
            # gang_assign_host's exact clips, int32 domain from here on
            cap = jnp.clip(cap, 0, _I32_MAX).astype(jnp.int32)
            k_cap = jnp.where(schedulable, cap, 0)
            k_cap = jnp.minimum(k_cap, jnp.maximum(p, 0))
            k_cap = jnp.minimum(k_cap, n_clip)

            def totals(lv):
                return self._a_table(s, off, k_cap, lv).sum(dtype=jnp.int32)

            # totals(0) == sum(k_cap): level 0 is never above any offset
            t0 = k_cap.sum(dtype=jnp.int32)

            # binary search the monotone predicate totals(L) >= p for
            # its max satisfying level (totals is non-increasing in L,
            # so this is the dense grid's argmax — O(N log L) instead
            # of the O(N·L) level table per scan step)
            def probe(_, lohi):
                lo, hi = lohi
                mid = (lo + hi + 1) // 2
                m = totals(mid) >= p
                return jnp.where(m, mid, lo), jnp.where(m, hi, mid - 1)

            lo, _hi = jax.lax.fori_loop(
                0, self._search_trips, probe,
                (jnp.int32(0), jnp.int32(n_levels - 1)),
            )
            l_star = jnp.where(t0 >= p, lo, jnp.int32(-1))

            def full_capacity(_):
                return k_cap, p - t0, jnp.asarray(-1, jnp.int32)

            def waterline(l_star):
                upper = jnp.where(
                    l_star + 1 >= n_levels,
                    0,
                    self._a_table(s, off, k_cap, l_star + 1),
                )
                at_or_above = self._a_table(s, off, k_cap, l_star)
                exact = at_or_above - upper
                remainder = p - jnp.sum(upper, dtype=jnp.int32)
                prefix = jnp.cumsum(exact, dtype=jnp.int32) - exact
                take = jnp.clip(remainder - prefix, 0, exact)
                return upper + take, jnp.asarray(0, jnp.int32), l_star

            counts, unassigned, wl = jax.lax.cond(
                l_star < 0, full_capacity, waterline, l_star
            )
            counts = jnp.where(act, counts, 0)
            unassigned = jnp.where(act, unassigned, 0)
            free = free - counts[:, None].astype(jnp.int64) * vec[None, :]
            out = jnp.concatenate([counts, jnp.stack([unassigned, wl])])
            return free, out

        free, outs = jax.lax.scan(step, free, (class_id, num_pods, active))
        return outs, free

    def dispatch(
        self,
        score: np.ndarray,
        schedulable: np.ndarray,
        bounded: np.ndarray | None,
        free: np.ndarray | None,
        vecs: np.ndarray,
        offsets,
        class_id,
        num_pods,
        col_version: int = 0,
        col_delta=None,
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Run one K-gang window; returns ``(counts, unassigned,
        waterline)`` — int32 ``[K, N]`` / ``[K]`` / ``[K]`` — from one
        D2H transfer. ``vecs`` is the ``[C, 4]`` class request matrix,
        ``offsets`` a length-C list of per-class int32 offset rows (or
        None for all-zero), ``class_id``/``num_pods`` length-K per-gang
        arrays. Pure w.r.t. the host columns; the device fold carry
        advances and is kept for reuse. ``col_version``/``col_delta``
        follow ``DripBatchKernel.dispatch``'s epoch-scatter contract."""
        n = int(score.shape[0])
        k = int(len(class_id))
        c = int(vecs.shape[0])
        npad = _bucket_nodes(n)
        kpad = _bucket(k, _MIN_K_BUCKET)
        cpad = _bucket(c, 2)
        t0 = time.perf_counter()

        def delta_for(col, arr):
            if col_delta is None:
                return None
            held = self._cols.held_version(col, arr)
            if held is None or held == col_version:
                return None
            return col_delta(held, col_version)

        with enable_x64():
            s_d = self._cols.put(
                "gang:score", score, version=col_version,
                prepare=lambda a: _pad(a.astype(np.int32), npad, 0),
                delta_rows=delta_for("gang:score", score),
                row_prepare=lambda v: v.astype(np.int32),
            )
            sched_d = self._cols.put(
                "gang:schedulable", schedulable, version=col_version,
                prepare=lambda a: _pad(a, npad, False),
                delta_rows=delta_for("gang:schedulable", schedulable),
            )
            if bounded is None or free is None:
                bounded = np.zeros((n,), dtype=bool)
                free = np.zeros((n, 4), dtype=np.int64)
            bnd_d = self._cols.put(
                "gang:bounded", bounded,
                prepare=lambda a: _pad(a, npad, False),
            )
            free_d = self._free_dev
            if (
                not self._free_synced
                or free_d is None
                or self._free_src is not free
                or free_d.shape[0] != npad
            ):
                free_d = jax.device_put(_pad(free, npad, 0))
                self._free_src = free
                self.free_uploads += 1
            if offsets is None:
                offs_d = jnp.zeros((cpad, npad), jnp.int32)
            else:
                rows = [
                    self._cols.put(
                        f"gang:offs:{i}", row,
                        prepare=(
                            lambda a: _pad(a.astype(np.int32), npad, 0)
                        ),
                    )
                    for i, row in enumerate(offsets)
                ]
                rows.extend(
                    jnp.zeros((npad,), jnp.int32)
                    for _ in range(cpad - len(rows))
                )
                offs_d = jnp.stack(rows)
            vecs_p = _pad(np.ascontiguousarray(vecs, dtype=np.int64), cpad, 0)
            cid_p = _pad(np.asarray(class_id, dtype=np.int32), kpad, 0)
            pods_p = np.minimum(
                np.asarray(num_pods, dtype=np.int64), _I32_MAX
            ).astype(np.int32)
            pods_p = _pad(pods_p, kpad, 0)
            active = np.zeros((kpad,), dtype=bool)
            active[:k] = True
            outs, free_out = self._jit(
                s_d, sched_d, bnd_d, free_d, jnp.asarray(vecs_p), offs_d,
                cid_p, pods_p, active, np.int32(_I32_MAX // max(n, 1)),
            )
            outs = np.asarray(outs)  # the single D2H transfer
        self._free_dev = free_out
        self._free_synced = True  # provisional; caller desyncs on reject
        self.last_kernel_seconds = time.perf_counter() - t0
        self.dispatches += 1
        return outs[:k, :n], outs[:k, npad], outs[:k, npad + 1]
