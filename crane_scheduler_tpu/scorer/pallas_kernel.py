"""Fused Pallas TPU kernel for the Dynamic filter+score hot op.

One VMEM pass computes both verdicts for a block of nodes: the load
matrix lives transposed ``[M_pad, N]`` so the (tiny, static) metric axis
sits on sublanes and the node axis streams along lanes; every predicate
and priority entry is unrolled at trace time from the compiled policy
(thresholds/weights/windows are kernel constants), so the whole scoring
function — staleness masks, fail-open logic, ordered weighted
accumulation, Go-style truncation, hot-value penalty, clamp — is a single
fused VPU loop with no intermediate HBM traffic.

This is the float32 fast path only (the float64 parity mode stays on the
XLA scorer); like ``BatchedScorer`` float32 mode it expects timestamps
rebased to ``now`` (now = 0). Correctness is tested against
``BatchedScorer(float32)`` in interpret mode on CPU and compiled on TPU.

Layout notes (pallas_guide.md): float32 min tile is (8, 128), so M pads
to a multiple of 8 and node blocks are multiples of 128; int32 outputs
are materialized as an (8, BN) block (row 0 is the payload) to respect
output tiling.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from ..constants import (
    HOT_VALUE_ACTIVE_PERIOD_SECONDS,
    MAX_NODE_SCORE,
    MIN_NODE_SCORE,
)
from ..policy.compile import PolicyTensors

_MIN_I32 = np.int32(-(2**31))
_LIMIT_F32 = np.float32(2.0**31)


def _go_trunc_i32(q):
    ok = jnp.isfinite(q) & (q > -_LIMIT_F32) & (q < _LIMIT_F32)
    safe = jnp.where(ok, jnp.trunc(q), jnp.float32(0.0))
    return jnp.where(ok, safe.astype(jnp.int32), jnp.int32(_MIN_I32))


def _make_kernel(tensors: PolicyTensors):
    pred = [
        (int(tensors.pred_idx[p]), float(tensors.pred_threshold[p]), float(tensors.pred_active[p]))
        for p in range(len(tensors.pred_idx))
    ]
    prio = [
        (int(tensors.prio_idx[k]), float(tensors.prio_weight[k]), float(tensors.prio_active[k]))
        for k in range(len(tensors.prio_idx))
    ]
    weight_sum = float(tensors.weight_sum)
    has_prio = len(prio) > 0

    def kernel(values_ref, ts_ref, hot_ref, hot_ts_ref, valid_ref, sched_ref, score_ref):
        # refs: values/ts [M_pad, BN]; hot/hot_ts/valid [8, BN]; outputs [8, BN]
        # All scalars below are explicitly typed: under x64 a bare python
        # int/float becomes a weak 64-bit constant and Mosaic's
        # convert-element-type lowering recurses forever on it.
        zero = jnp.float32(0.0)
        izero = jnp.int32(0)

        over = None
        for idx, threshold, active in pred:
            if active <= 0.0:  # entry disabled: skipped before any read
                continue
            u = values_ref[idx, :]
            t = ts_ref[idx, :]
            ok = (zero < t + jnp.float32(active)) & ~(u < zero)
            if threshold != 0.0:  # zero threshold disables the entry
                o = ok & (u > jnp.float32(threshold))
                over = o if over is None else (over | o)
        n_lanes = values_ref.shape[1]
        if over is None:
            over = jnp.zeros((n_lanes,), dtype=jnp.bool_)

        if has_prio:
            acc = jnp.zeros((n_lanes,), dtype=jnp.float32)
            for idx, weight, active in prio:
                if active > 0.0:
                    u = values_ref[idx, :]
                    t = ts_ref[idx, :]
                    ok = (zero < t + jnp.float32(active)) & ~(u < zero)
                    contrib = (jnp.float32(1.0) - u) * jnp.float32(weight) * jnp.float32(MAX_NODE_SCORE)
                    acc = acc + jnp.where(ok, contrib, zero)
                # inactive entries contribute 0 (weight is in weight_sum)
            if weight_sum == 0.0:
                q = jnp.where(acc == zero, jnp.float32(jnp.nan), jnp.sign(acc) * jnp.float32(jnp.inf))
            else:
                q = acc / jnp.float32(weight_sum)
            base = _go_trunc_i32(q)
        else:
            base = jnp.zeros((n_lanes,), dtype=jnp.int32)

        hot = hot_ref[0, :]
        hot_t = hot_ts_ref[0, :]
        hot_ok = (zero < hot_t + jnp.float32(HOT_VALUE_ACTIVE_PERIOD_SECONDS)) & ~(hot < zero)
        hv = jnp.where(hot_ok, hot, zero)
        penalty = _go_trunc_i32(hv * jnp.float32(10.0))
        score = jnp.clip(
            base - penalty, jnp.int32(MIN_NODE_SCORE), jnp.int32(MAX_NODE_SCORE)
        )

        valid = valid_ref[0, :] != izero
        score = jnp.where(valid, score, izero)
        sched = (~over) & valid

        # broadcast payload across the 8 sublanes of the output tile
        sched_ref[:, :] = jnp.broadcast_to(
            sched.astype(jnp.int32)[None, :], sched_ref.shape
        )
        score_ref[:, :] = jnp.broadcast_to(score[None, :], score_ref.shape)

    return kernel


class PallasScorer:
    """Drop-in float32 scorer backed by the fused Pallas kernel.

    Same call convention as ``BatchedScorer`` (epoch timestamps in, the
    wrapper rebases them around ``now``); requires the node axis padded
    to a multiple of ``block_nodes`` (snapshots already pad to 2048).
    """

    def __init__(self, tensors: PolicyTensors, block_nodes: int = 2048, interpret: bool = False):
        self.tensors = tensors
        self.block = block_nodes
        self.interpret = interpret
        self._kernel = _make_kernel(tensors)
        self._m_pad = max(8, math.ceil(max(tensors.num_metrics, 1) / 8) * 8)
        self._jit = jax.jit(functools.partial(self._run))

    def _run(self, values_t, ts_t, hot, hot_ts, valid):
        m_pad, n = values_t.shape
        bn = min(self.block, n)
        grid = (n // bn,)
        # typed zero: a bare python 0 becomes an i64 index under x64 and
        # Mosaic rejects the mixed-type index tuple
        _z = lambda: jnp.asarray(0, jnp.int32)  # noqa: E731
        row_specs = pl.BlockSpec((m_pad, bn), lambda i: (_z(), i))
        vec_specs = pl.BlockSpec((8, bn), lambda i: (_z(), i))
        out = pl.pallas_call(
            self._kernel,
            grid=grid,
            in_specs=[row_specs, row_specs, vec_specs, vec_specs, vec_specs],
            out_specs=[vec_specs, vec_specs],
            out_shape=[
                jax.ShapeDtypeStruct((8, n), jnp.int32),
                jax.ShapeDtypeStruct((8, n), jnp.int32),
            ],
            interpret=self.interpret,
        )(values_t, ts_t, hot, hot_ts, valid)
        return out[0][0, :] != 0, out[1][0, :]

    def __call__(self, values, ts, hot_value, hot_ts, node_valid, now):
        from .batched import ScoreResult

        now = float(now)
        n, m = np.asarray(values).shape
        if n % 128 != 0:
            raise ValueError(f"node axis must pad to a multiple of 128, got {n}")
        values_t = np.full((self._m_pad, n), np.nan, dtype=np.float32)
        values_t[:m, :] = np.asarray(values, dtype=np.float32).T
        ts_rel = np.asarray(ts, dtype=np.float64) - now
        ts_t = np.full((self._m_pad, n), -np.inf, dtype=np.float32)
        ts_t[:m, :] = ts_rel.T
        hot = np.zeros((8, n), dtype=np.float32)
        hot[0, :] = np.asarray(hot_value, dtype=np.float32)
        hts = np.full((8, n), -np.inf, dtype=np.float32)
        hts[0, :] = np.asarray(hot_ts, dtype=np.float64) - now
        valid = np.zeros((8, n), dtype=np.int32)
        valid[0, :] = np.asarray(node_valid).astype(np.int32)
        schedulable, scores = self._jit(
            jnp.asarray(values_t),
            jnp.asarray(ts_t),
            jnp.asarray(hot),
            jnp.asarray(hts),
            jnp.asarray(valid),
        )
        return ScoreResult(schedulable, scores)

    def prepare(self, snapshot, now: float):
        """Pre-transpose a snapshot once (device-resident inputs for
        repeated calls); returns args for ``run_prepared``."""
        now = float(now)
        n, m = snapshot.values.shape
        values_t = np.full((self._m_pad, n), np.nan, dtype=np.float32)
        values_t[:m, :] = np.asarray(snapshot.values, dtype=np.float32).T
        ts_t = np.full((self._m_pad, n), -np.inf, dtype=np.float32)
        ts_t[:m, :] = (np.asarray(snapshot.ts, dtype=np.float64) - now).T
        hot = np.zeros((8, n), dtype=np.float32)
        hot[0, :] = np.asarray(snapshot.hot_value, dtype=np.float32)
        hts = np.full((8, n), -np.inf, dtype=np.float32)
        hts[0, :] = np.asarray(snapshot.hot_ts, dtype=np.float64) - now
        valid = np.zeros((8, n), dtype=np.int32)
        valid[0, :] = np.asarray(snapshot.node_valid).astype(np.int32)
        return tuple(jnp.asarray(a) for a in (values_t, ts_t, hot, hts, valid))

    def run_prepared(self, prepared):
        from .batched import ScoreResult

        schedulable, scores = self._jit(*prepared)
        return ScoreResult(schedulable, scores)
