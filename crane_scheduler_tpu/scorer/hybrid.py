"""Hybrid scorer: float32 speed, float64 placement parity.

The float32 fast path can disagree with the Go semantics only where a
value sits within float32 error of a decision boundary:

- a usage within ~2^-24 of a predicate threshold (filter flip),
- a score quotient within accumulated-rounding error of an integer
  (trunc flip),
- a hot value within error of a multiple of 0.1 (penalty flip).

Those cases are detected by a HOST-side risk scan at snapshot-refresh
time (``risk_mask_f64`` below — numpy over the store columns; a
device-emitted mask was prototyped in round 3 and measured slower than
the host scan once the column-replay refresh landed, see
ROADMAP.md round 3). Risky rows — typically a tiny fraction — are
re-scored exactly in float64 numpy on the host (``score_rows_f64``, the
same IEEE-double operation sequence as the Go code and the oracle, with
no dependency on jax x64), and their verdicts ride the prepared
snapshot as override vectors the device step substitutes. The result is
bit-parity everywhere at f32 throughput.

Tolerances are deliberately loose (1e-4 absolute on comparisons, 1e-3 on
truncation distance for a ≤16-term accumulation of O(100) magnitudes —
orders of magnitude above the true f32 error bounds), trading a few
extra host re-scores for a safety margin.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax.numpy as jnp
import numpy as np

from ..constants import (
    HOT_VALUE_ACTIVE_PERIOD_SECONDS,
    MAX_NODE_SCORE,
    MIN_NODE_SCORE,
)
from ..policy.compile import PolicyTensors
from .batched import BatchedScorer, ScoreResult

_CMP_TOL = 1e-4  # |usage - threshold| risk window
_TRUNC_TOL = 1e-3  # distance-to-integer risk window for quotients
_GO_MIN_I64 = -(2**63)


def _trunc_f64(q: np.ndarray) -> np.ndarray:
    """Vectorized Go int64(float64) with the amd64 indefinite."""
    out = np.full(q.shape, _GO_MIN_I64, dtype=np.int64)
    ok = np.isfinite(q) & (q > -(2.0**63)) & (q < 2.0**63)
    out[ok] = np.trunc(q[ok]).astype(np.int64)
    return out


def score_rows_f64(
    values: np.ndarray,
    ts: np.ndarray,
    hot_value: np.ndarray,
    hot_ts: np.ndarray,
    now: float,
    tensors: PolicyTensors,
) -> tuple[np.ndarray, np.ndarray]:
    """Exact float64 verdicts for a row subset (IEEE-double, same op
    order as stats.go; bit-identical to the oracle)."""
    n = values.shape[0]
    # filter
    schedulable = np.ones((n,), dtype=bool)
    for p in range(len(tensors.pred_idx)):
        active = tensors.pred_active[p]
        if active <= 0:
            continue
        col = tensors.pred_idx[p]
        threshold = tensors.pred_threshold[p]
        if threshold == 0:
            continue
        u = values[:, col]
        fresh = now < ts[:, col] + active
        with np.errstate(invalid="ignore"):
            ok = fresh & ~(u < 0)
            over = ok & (u > threshold)
        schedulable &= ~over
    # score
    if len(tensors.prio_idx) == 0:
        base = np.zeros((n,), dtype=np.int64)
    else:
        acc = np.zeros((n,), dtype=np.float64)
        for k in range(len(tensors.prio_idx)):
            col = tensors.prio_idx[k]
            active = tensors.prio_active[k]
            u = values[:, col]
            fresh = now < ts[:, col] + active
            with np.errstate(invalid="ignore"):
                ok = (active > 0) & fresh & ~(u < 0)
            contrib = (1.0 - u) * tensors.prio_weight[k] * float(MAX_NODE_SCORE)
            acc = acc + np.where(ok, contrib, 0.0)
        if tensors.weight_sum == 0.0:
            with np.errstate(invalid="ignore"):
                q = np.where(acc == 0.0, np.nan, np.sign(acc) * np.inf)
                q = np.where(np.isnan(acc), np.nan, q)
        else:
            q = acc / tensors.weight_sum
        base = _trunc_f64(q)
    hot_fresh = now < hot_ts + HOT_VALUE_ACTIVE_PERIOD_SECONDS
    with np.errstate(invalid="ignore"):
        hot_ok = hot_fresh & ~(hot_value < 0)
    hv = np.where(hot_ok, hot_value, 0.0)
    penalty = _trunc_f64(hv * 10.0)
    score = (base - penalty).astype(np.int64)  # wraps like Go int64
    score = np.clip(score, MIN_NODE_SCORE, MAX_NODE_SCORE)
    return schedulable, score.astype(np.int32)


@dataclass
class HybridResult:
    schedulable: Any
    scores: Any
    rescored: int  # rows that took the f64 path


def risk_mask_f64(
    tensors: PolicyTensors, values, ts, hot_value, hot_ts, now,
    rebase_age: float = 0.0,
    return_margin: bool = False,
) -> np.ndarray:
    """Host-side exact risk detection (vectorized numpy float64).

    A node is risky when an f32 evaluation *could* flip a decision:
    the exact f64 quantity sits within the f32 rounding band of a
    boundary. Exactly-on-boundary counts as risky too (an f32
    accumulation can land microscopically on the other side), but a
    hot value that is a clean integer or a usage far from its
    threshold is provably safe — which is what keeps the rescore
    fraction tiny on real annotator data.

    ``rebase_age``: |now - epoch| of the device arrays when timestamps
    were rebased at an *earlier* prepare time (parallel.sharded keeps a
    cached snapshot resident and re-scores it at later wall times). The
    device's f32 freshness test then computes fl32(ts-epoch) and
    fl32(now-epoch), whose rounding grows with the age — widen the
    staleness tolerance accordingly or boundary flips go unflagged.

    ``return_margin``: also return a per-row float64 ``margin`` — a
    conservative lower bound on how far ``now`` must move before ANY
    now-dependent bit in this row's scan output (freshness flips,
    staleness-band membership, and therefore also the row's exact f64
    verdict) can change. The incremental rescan skips rows whose clock
    movement stays well inside their margin; every quantity that depends
    on ``now`` does so through a freshness comparison (flip at an
    expiry) or a band test (flip at ``|expiry - now| == tol``), and
    ``tol`` itself drifts with slope <= ~3e-6 per second of ``now``
    (1e-6 from |ts-now|, 2e-6 through ``rebase_age``) — so the margin
    consumer's 2x safety factor (``_MARGIN_SAFETY``) strictly covers the
    drift.
    """
    t = tensors
    n = values.shape[0]
    risk = np.zeros((n,), dtype=bool)
    margin = np.full((n,), np.inf) if return_margin else None

    def fold_margin(expiry, tol, gate):
        # distance from `now` to this term's nearest now-boundary: the
        # freshness flip (at expiry) or the band edges (|e - now| == tol)
        d = np.abs(expiry - now)
        m = np.where(gate, np.minimum(d, np.abs(d - tol)), np.inf)
        if m.ndim == 2:
            m = m.min(axis=1)
        np.minimum(margin, m, out=margin)

    # eps32 ~ 1.2e-7 per rounding; ts-epoch and now-epoch each carry one.
    # 1e-6 per second of age gives ~4x margin over the two roundings.
    age_tol = 1e-6 * 2.0 * abs(float(rebase_age))

    def sign_flip(u):
        # The f32 downcast can flush a tiny negative (e.g. -1e-310) to
        # -0.0, flipping the `u < 0` validity test between the f64 and
        # f32 paths — whole w*100 contributions appear/vanish, far from
        # any truncation boundary. Flag any row where the sign test
        # itself disagrees across precisions.
        return (u < 0) != (u.astype(np.float32) < 0)

    def stale_tol(tstamp, active):
        # The f32 freshness error scales with the operand magnitudes
        # (fl32(ts-now) + fl32(active) carries ~eps32*(|ts-now|+active)
        # of rounding), so an absolute tolerance under-flags long
        # windows (>~2h). eps32 ~ 1.2e-7; 1e-6 gives ~4x margin over
        # the two roundings involved. A missing timestamp (-inf) is
        # exactly stale in both precisions — no risk, tol 0 (a naive
        # formula would yield tol=inf and flag every sparse node,
        # forcing the whole cluster onto the slow f64 path).
        with np.errstate(invalid="ignore"):
            tol = 1e-3 + 1e-6 * (np.abs(tstamp - now) + np.abs(active)) + age_tol
            return np.where(np.isfinite(tstamp), tol, 0.0)

    with np.errstate(invalid="ignore"):
        if len(t.pred_idx):
            u = values[:, t.pred_idx]
            expiry = ts[:, t.pred_idx] + t.pred_active
            fresh = now < expiry
            near = np.abs(u - t.pred_threshold) <= _CMP_TOL
            risk |= np.any(fresh & near & (t.pred_active > 0), axis=1)
            risk |= np.any(sign_flip(u) & fresh & (t.pred_active > 0), axis=1)
            tol = stale_tol(ts[:, t.pred_idx], t.pred_active)
            risk |= np.any(
                (np.abs(expiry - now) <= tol) & (t.pred_active > 0), axis=1
            )
            if return_margin:
                fold_margin(expiry, tol, t.pred_active > 0)
        if len(t.prio_idx):
            u = values[:, t.prio_idx]
            expiry = ts[:, t.prio_idx] + t.prio_active
            fresh = now < expiry
            tol = stale_tol(ts[:, t.prio_idx], t.prio_active)
            if return_margin:
                # fold even when weight_sum == 0: the exact f64 score of
                # a rescued row still depends on these freshness bits
                fold_margin(expiry, tol, t.prio_active > 0)
            if t.weight_sum != 0.0:
                valid = fresh & ~(u < 0) & (t.prio_active > 0)
                risk |= np.any(
                    sign_flip(u) & fresh & (t.prio_active > 0), axis=1
                )
                risk |= np.any(
                    (np.abs(expiry - now) <= tol) & (t.prio_active > 0),
                    axis=1,
                )
                contrib = (1.0 - u) * t.prio_weight * float(MAX_NODE_SCORE)
                masked = np.where(valid, contrib, 0.0)
                acc = masked.sum(axis=1)
                q = acc / t.weight_sum
                finite = np.isfinite(q)
                dist = np.abs(q - np.round(q))
                # f32 accumulation error is bounded by K*eps32 times the
                # magnitude of the partial sums; 1e-5 gives ~25x margin.
                abs_sum = np.abs(masked).sum(axis=1)
                trunc_tol = _TRUNC_TOL * 0.1 + 1e-5 * abs_sum / abs(t.weight_sum)
                risk |= finite & (dist <= trunc_tol)
                risk |= ~finite  # NaN/Inf: let f64 decide the indefinite
        hot_expiry = hot_ts + HOT_VALUE_ACTIVE_PERIOD_SECONDS
        hot_tol = stale_tol(hot_ts, HOT_VALUE_ACTIVE_PERIOD_SECONDS)
        risk |= np.abs(hot_expiry - now) <= hot_tol
        if return_margin:
            fold_margin(hot_expiry, hot_tol, True)
        hot_fresh = now < hot_expiry
        hv = np.where(hot_fresh & ~(hot_value < 0), hot_value, 0.0)
        hp = hv * 10.0
        dist = np.abs(hp - np.round(hp))
        # a clean multiple of 10 (integral hot value) converts to f32
        # exactly and truncates identically: safe. Near-misses aren't.
        risk |= np.isfinite(hp) & (dist > 0) & (dist <= _CMP_TOL * 10)
        risk |= ~np.isfinite(hp)
    if return_margin:
        return risk, margin
    return risk


def compute_overrides(
    tensors: PolicyTensors, values, ts, hot_value, hot_ts, node_valid, now,
    rebase_age: float = 0.0,
):
    """Per-node f64 rescue vectors for the hybrid device step.

    Returns ``(ovr_mask, ovr_sched, ovr_score, n_rescored)``: boolean mask
    of rows whose f32 verdict is at risk of diverging from the Go/f64
    semantics at this ``now``, plus their exact f64 verdicts. The device
    step substitutes these rows, making the f32 fast path bit-identical
    to the f64 oracle everywhere (ref: pkg/plugins/dynamic/stats.go:114-138
    for the semantics being preserved).
    """
    now_f = float(now)
    values64 = np.asarray(values, dtype=np.float64)
    ts64 = np.asarray(ts, dtype=np.float64)
    hot64 = np.asarray(hot_value, dtype=np.float64)
    hot_ts64 = np.asarray(hot_ts, dtype=np.float64)
    valid = np.asarray(node_valid, dtype=bool)
    n = values64.shape[0]
    risk = risk_mask_f64(
        tensors, values64, ts64, hot64, hot_ts64, now_f, rebase_age=rebase_age
    )
    risky = np.nonzero(risk & valid)[0]
    ovr_mask = np.zeros((n,), dtype=bool)
    ovr_sched = np.zeros((n,), dtype=bool)
    ovr_score = np.zeros((n,), dtype=np.int32)
    if len(risky):
        sched64, score64 = score_rows_f64(
            values64[risky], ts64[risky], hot64[risky], hot_ts64[risky],
            now_f, tensors,
        )
        ovr_mask[risky] = True
        ovr_sched[risky] = sched64
        ovr_score[risky] = score64
    return ovr_mask, ovr_sched, ovr_score, len(risky)


# incremental rescan: a cached row is reused only while the clock stays
# within HALF its measured distance-to-boundary — the band tolerances
# drift with `now` at slope <= ~3e-6, so 2x strictly dominates and the
# reused bits are provably identical to a full scan at the new time.
_MARGIN_SAFETY = 0.5


@dataclass
class OverrideCache:
    """Host-side state for the incremental hybrid override refresh.

    Each row's cached scan output (risk bit + f64 rescue verdicts) is
    valid relative to its OWN reference time: rows rescanned at
    different ticks coexist, and a row is reused only while
    ``|now - now_ref| < _MARGIN_SAFETY * margin`` (see
    ``risk_mask_f64(return_margin=True)``) and its inputs are clean.
    """

    mask: np.ndarray  # [N] bool — row carries f64 rescue verdicts
    sched: np.ndarray  # [N] bool
    score: np.ndarray  # [N] int32
    margin: np.ndarray  # [N] f64 distance-to-boundary at now_ref
    now_ref: np.ndarray  # [N] f64 scan time per row
    valid: np.ndarray  # [N] bool node_valid the cache was built for


def compute_overrides_incremental(
    tensors: PolicyTensors, values, ts, hot_value, hot_ts, node_valid, now,
    cache: OverrideCache | None = None,
    dirty_rows=None,
    rebase_age: float = 0.0,
):
    """Incremental twin of ``compute_overrides``.

    Returns ``(ovr_mask, ovr_sched, ovr_score, changed_rows, cache,
    scanned)``: the full override vectors, the row indices whose cached
    entries were recomputed (``None`` after a full scan — everything may
    have changed), the refreshed cache, and the number of rows scanned.

    With a ``cache`` from an earlier call over the SAME array identity
    chain, only rows whose inputs changed (``dirty_rows``) or whose
    clock moved past their margin are rescanned; the rest reuse bits
    that are provably identical to a full ``risk_mask_f64`` +
    ``score_rows_f64`` pass at this ``now``. The returned cache is a
    fresh copy-on-write object — snapshots holding the old cache stay
    self-consistent.
    """
    now_f = float(now)
    values64 = np.asarray(values, dtype=np.float64)
    ts64 = np.asarray(ts, dtype=np.float64)
    hot64 = np.asarray(hot_value, dtype=np.float64)
    hot_ts64 = np.asarray(hot_ts, dtype=np.float64)
    valid = np.asarray(node_valid, dtype=bool)
    n = values64.shape[0]
    if (
        cache is None
        or cache.mask.shape[0] != n
        or not np.array_equal(cache.valid, valid)
    ):
        risk, margin = risk_mask_f64(
            tensors, values64, ts64, hot64, hot_ts64, now_f,
            rebase_age=rebase_age, return_margin=True,
        )
        ovr_mask = np.zeros((n,), dtype=bool)
        ovr_sched = np.zeros((n,), dtype=bool)
        ovr_score = np.zeros((n,), dtype=np.int32)
        risky = np.flatnonzero(risk & valid)
        if risky.size:
            sched64, score64 = score_rows_f64(
                values64[risky], ts64[risky], hot64[risky],
                hot_ts64[risky], now_f, tensors,
            )
            ovr_mask[risky] = True
            ovr_sched[risky] = sched64
            ovr_score[risky] = score64
        cache = OverrideCache(
            mask=ovr_mask,
            sched=ovr_sched,
            score=ovr_score,
            margin=margin,
            now_ref=np.full((n,), now_f),
            valid=valid.copy(),
        )
        return ovr_mask, ovr_sched, ovr_score, None, cache, n

    need = np.abs(now_f - cache.now_ref) >= _MARGIN_SAFETY * cache.margin
    if dirty_rows is not None and len(dirty_rows):
        need[np.asarray(dirty_rows, dtype=np.int64)] = True
    need &= valid
    rows = np.flatnonzero(need)
    if rows.size == 0:
        return cache.mask, cache.sched, cache.score, rows, cache, 0
    risk_r, margin_r = risk_mask_f64(
        tensors, values64[rows], ts64[rows], hot64[rows], hot_ts64[rows],
        now_f, rebase_age=rebase_age, return_margin=True,
    )
    mask_r = np.zeros((rows.size,), dtype=bool)
    sched_r = np.zeros((rows.size,), dtype=bool)
    score_r = np.zeros((rows.size,), dtype=np.int32)
    rr = np.flatnonzero(risk_r)
    if rr.size:
        sub = rows[rr]
        sched64, score64 = score_rows_f64(
            values64[sub], ts64[sub], hot64[sub], hot_ts64[sub], now_f,
            tensors,
        )
        mask_r[rr] = True
        sched_r[rr] = sched64
        score_r[rr] = score64
    # copy-on-write: earlier snapshots keep their own consistent cache
    cache = OverrideCache(
        mask=cache.mask.copy(),
        sched=cache.sched.copy(),
        score=cache.score.copy(),
        margin=cache.margin.copy(),
        now_ref=cache.now_ref.copy(),
        valid=cache.valid,
    )
    cache.mask[rows] = mask_r
    cache.sched[rows] = sched_r
    cache.score[rows] = score_r
    cache.margin[rows] = margin_r
    cache.now_ref[rows] = now_f
    return cache.mask, cache.sched, cache.score, rows, cache, int(rows.size)


class HybridScorer:
    """f32 batched pass + risk mask + exact f64 host re-score."""

    def __init__(self, tensors: PolicyTensors):
        self.tensors = tensors
        self._f32 = BatchedScorer(tensors, dtype=jnp.float32)

    def _risk_mask_f64(self, values, ts, hot_value, hot_ts, now) -> np.ndarray:
        return risk_mask_f64(self.tensors, values, ts, hot_value, hot_ts, now)

    def __call__(self, values, ts, hot_value, hot_ts, node_valid, now) -> HybridResult:
        now_f = float(now)
        values64 = np.asarray(values, dtype=np.float64)
        ts64 = np.asarray(ts, dtype=np.float64)
        hot64 = np.asarray(hot_value, dtype=np.float64)
        hot_ts64 = np.asarray(hot_ts, dtype=np.float64)
        # BatchedScorer's f32 mode owns the rebase/downcast invariants.
        f32 = self._f32(values64, ts64, hot64, hot_ts64, node_valid, now_f)
        schedulable = np.asarray(f32.schedulable)
        scores = np.asarray(f32.scores)
        risk = self._risk_mask_f64(values64, ts64, hot64, hot_ts64, now_f)
        risky = np.nonzero(risk & np.asarray(node_valid))[0]
        if len(risky):
            sched64, score64 = score_rows_f64(
                values64[risky], ts64[risky], hot64[risky], hot_ts64[risky],
                now_f, self.tensors,
            )
            schedulable = schedulable.copy()
            scores = scores.copy()
            schedulable[risky] = sched64 & np.asarray(node_valid)[risky]
            scores[risky] = np.where(np.asarray(node_valid)[risky], score64, 0)
        return HybridResult(schedulable, scores, rescored=len(risky))
