"""Device-resident drip batch kernel: mask + argmax + fold for K pods.

The columnar drip path (PR 10) reduced ``schedule_one`` to a host-side
mask AND + ``np.argmax`` — but still one Python round-trip per pod. This
module moves the whole per-pod loop device-side: one jitted program
takes the cluster columns plus a *queue* of K heterogeneous pending pods
(padded/bucketed request vectors, per-pod active flags) and runs

    for each pod k (sequentially, ``lax.scan``):
        fit_fail = bounded & any(vec_k > 0 & free < vec_k)
        mask     = schedulable & ~fit_fail
        best     = argmax(where(mask, weighted, INT64_MIN))
        free[best] -= vec_k                       # the fold
        emit (best, feasible_count, tie_count)

so later pods in the window see earlier folds exactly like the
sequential host loop, and the host gets all K verdicts in ONE
device-to-host transfer (a packed ``[K, 3]`` int64 array). The kernel is
*pure*: the host columns stay authoritative and untouched until the
scheduler accepts the window, which is what makes the optimistic
tie-break replay (see ``framework.scheduler.Scheduler.schedule_queue``)
free — per-pod ``tie_count`` comes back with the placements, and any
window containing a real tie under a seeded RNG is simply re-run through
the per-pod columnar path, consuming the RNG bit-identically.

Shapes are bucketed (nodes and window size each round up to a power of
two) so the jit cache stays small, and the fold carry can stay
device-resident across windows: after a fully-accepted window the host
applies the same integer folds to its own ``free`` copy, so the device
carry equals the host column exactly and the next dispatch skips the
``[N, 4]`` upload.

int64 is mandatory (memory bytes exceed int32) but the process-wide
``jax_enable_x64`` default stays untouched: every trace/call runs inside
the scoped ``jax.experimental.enable_x64`` context.
"""

from __future__ import annotations

import threading
import time
from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import enable_x64

__all__ = ["DripBatchKernel", "drip_batch_dispatch"]

_I64_MIN = np.int64(np.iinfo(np.int64).min)

# XLA's host-platform collectives rendezvous through one per-process
# participant table: two shard_map programs launched concurrently from
# different threads (the shard plane runs one scheduler per thread)
# interleave their all_gather participants across run ids and deadlock.
# Sharded dispatches therefore serialize process-wide — held through
# the output sync so the program has fully retired before the next one
# launches. Device parallelism is intra-program (across shards);
# schedulers still overlap on the host side.
_COLLECTIVE_LOCK = threading.Lock()

# shape buckets: small node counts round up to pow2 >= 256; past 4096
# they round to the next multiple of 4096 instead (pow2 would pad a 50k
# cluster to 65536 — 31% wasted bandwidth in every scan step — while
# 4096-multiples cap waste at ~8% and the jit cache at 16 entries per
# 64k nodes). Windows round to pow2 >= 8.
_MIN_N_BUCKET = 256
_N_BUCKET_STEP = 4096
_MIN_K_BUCKET = 8


def _bucket(n: int, floor: int) -> int:
    m = max(int(n), floor)
    return 1 << (m - 1).bit_length()


def _bucket_nodes(n: int) -> int:
    if n <= _N_BUCKET_STEP:
        return _bucket(n, _MIN_N_BUCKET)
    return -(-int(n) // _N_BUCKET_STEP) * _N_BUCKET_STEP


def _pad(arr: np.ndarray, npad: int, fill) -> np.ndarray:
    if arr.shape[0] == npad:
        return arr
    out = np.full((npad,) + arr.shape[1:], fill, dtype=arr.dtype)
    out[: arr.shape[0]] = arr
    return out


from functools import partial


@partial(jax.jit, static_argnames=("want_ties",))
def _drip_batch(schedulable, weighted, bounded, free, vecs, active,
                want_ties=True):
    """One dispatch window. Padded nodes are ``schedulable=False`` (never
    selected); padded pods are ``active=False`` (never fold — their
    emitted rows are garbage the host slices off). ``want_ties`` is
    static: without a seeded tie-break RNG the per-pod tie count is
    never read, so the unseeded program drops that whole O(n) reduction
    per scan step and reports a constant 1."""

    def step(free, xs):
        vec, act = xs
        fit_fail = bounded & ((vec > 0) & (free < vec)).any(axis=1)
        mask = schedulable & ~fit_fail
        w = jnp.where(mask, weighted, _I64_MIN)
        best = jnp.argmax(w)  # first maximum, like np.argmax
        feasible = jnp.sum(mask, dtype=jnp.int64)
        if want_ties:
            ties = jnp.sum(mask & (weighted == w[best]), dtype=jnp.int64)
        else:
            ties = jnp.ones((), dtype=jnp.int64)
        # fold only for real, feasible pods; computing the scatter-add
        # unconditionally with a zeroed delta keeps the trace branch-free
        delta = jnp.where(act & (feasible > 0), vec, jnp.zeros_like(vec))
        free = free.at[best].add(-delta)
        out = jnp.stack(
            [jnp.where(feasible > 0, best, -1).astype(jnp.int64),
             feasible, ties]
        )
        return free, out

    free, outs = jax.lax.scan(step, free, (vecs, active))
    return outs, free


@lru_cache(maxsize=8)
def _sharded_drip_fn(mesh, want_ties: bool):
    """Shard-parallel twin of ``_drip_batch`` over a 1-D placement mesh.

    Columns arrive tiled along the node axis (equal per-device tiles —
    the wrapper rounds the pad up to a shard multiple). Each scan step
    computes a LOCAL first-max ``(value, global_index)`` pair, one
    ``all_gather`` collects the S candidate pairs, and ``argmax`` over
    the gathered values picks the winner: argmax's first-maximum rule
    applied to shard-ordered candidates selects the lowest shard among
    value ties, and within a shard the local argmax already took the
    lowest local row — so the global winner is exactly the lowest
    global index holding the maximum, bit-identical to ``np.argmax``
    and to the single-device program. The fold lands only on the
    winning shard (``delta`` is zeroed elsewhere), so the sharded fold
    carry advances tile-locally with no cross-shard writes; feasible
    and tie counts are one fused ``psum``.
    """
    from jax.experimental.shard_map import shard_map

    from ..parallel.mesh import NODE_AXIS

    P = jax.sharding.PartitionSpec
    node1 = P(NODE_AXIS)
    node2 = P(NODE_AXIS, None)
    rep = P()

    def body(schedulable, weighted, bounded, free, vecs, active):
        nloc = schedulable.shape[0]
        sid = jax.lax.axis_index(NODE_AXIS).astype(jnp.int64)

        def step(free, xs):
            vec, act = xs
            fit_fail = bounded & ((vec > 0) & (free < vec)).any(axis=1)
            mask = schedulable & ~fit_fail
            w = jnp.where(mask, weighted, _I64_MIN)
            lbest = jnp.argmax(w)  # first maximum within the tile
            pair = jnp.stack(
                [w[lbest], (sid * nloc + lbest).astype(jnp.int64)]
            )
            pairs = jax.lax.all_gather(pair, NODE_AXIS)  # [S, 2]
            win = jnp.argmax(pairs[:, 0])  # lowest shard among ties
            gval = pairs[win, 0]
            gbest = pairs[win, 1]
            feas_local = jnp.sum(mask, dtype=jnp.int64)
            if want_ties:
                ties_local = jnp.sum(
                    mask & (weighted == gval), dtype=jnp.int64
                )
                sums = jax.lax.psum(
                    jnp.stack([feas_local, ties_local]), NODE_AXIS
                )
                feasible, ties = sums[0], sums[1]
            else:
                feasible = jax.lax.psum(feas_local, NODE_AXIS)
                ties = jnp.ones((), dtype=jnp.int64)
            mine = win.astype(jnp.int64) == sid
            delta = jnp.where(
                act & (feasible > 0) & mine, vec, jnp.zeros_like(vec)
            )
            free = free.at[lbest].add(-delta)
            out = jnp.stack(
                [jnp.where(feasible > 0, gbest, -1).astype(jnp.int64),
                 feasible, ties]
            )
            return free, out

        free, outs = jax.lax.scan(step, free, (vecs, active))
        return outs, free

    fn = shard_map(
        body, mesh=mesh,
        in_specs=(node1, node1, node1, node2, rep, rep),
        out_specs=(rep, node2),
        check_rep=False,  # outs are psum/all_gather products: replicated
    )
    return jax.jit(fn)


class DripBatchKernel:
    """Host wrapper: bucketing, device column placement, fold-carry reuse.

    One instance per ``Scheduler`` (single scheduling loop, like
    ``DripColumns``). The dynamic/fit columns are cached device-side by
    identity (``parallel.sharded.DeviceColumnCache`` — rebuilds replace
    host arrays, so identity is the version). The ``free`` carry is the
    only column the kernel itself advances: ``mark_synced`` tells the
    wrapper the host applied the very same folds (exact int64
    subtraction, so device == host bit-for-bit) and the carry may be
    reused; anything else — replay, partial bind, column drop — calls
    ``mark_desynced`` and the next dispatch re-uploads from the host.
    """

    def __init__(self, device=None, mesh=None):
        from ..parallel.sharded import DeviceColumnCache

        self._cols = DeviceColumnCache(device)
        self._free_dev = None  # device fold carry [npad, 4]
        self._free_src = None  # host free array the carry mirrors
        self._free_synced = False
        self.dispatches = 0
        self.free_uploads = 0
        self.last_kernel_seconds = 0.0
        # shard-parallel mode (doc/sharding.md): a 1-D placement mesh
        # tiles the columns along the node axis and dispatches the
        # shard_map program instead; a 1-device mesh (or None) runs the
        # single-device program unchanged
        self._mesh = None
        self.repartitions = 0
        if mesh is not None:
            self.repartition(mesh)

    @property
    def mesh(self):
        return self._mesh

    def _partition_token(self):
        mesh = self._mesh
        if mesh is None:
            return ("single",)
        return (
            tuple(int(d.id) for d in mesh.devices.flat),
            tuple(mesh.axis_names),
            tuple(int(s) for s in mesh.devices.shape),
        )

    def repartition(self, mesh) -> bool:
        """Point the kernel at a (possibly resized) placement mesh.
        Any change to the device set or shard layout drops every cached
        device column AND desyncs the fold carry — a resize must never
        replay folds onto a carry tiled for the old partitioning.
        Returns True when the partitioning actually changed."""
        self._mesh = mesh
        changed = self._cols.set_partition(self._partition_token())
        if changed:
            self.mark_desynced()
            self.repartitions += 1
        return changed

    def mark_synced(self, host_free) -> None:
        """Host applied exactly the kernel's folds — carry is reusable."""
        self._free_src = host_free
        self._free_synced = True

    def mark_desynced(self) -> None:
        self._free_synced = False
        self._free_dev = None
        self._free_src = None

    def dispatch(
        self,
        schedulable: np.ndarray,
        weighted: np.ndarray,
        bounded: np.ndarray | None,
        free: np.ndarray | None,
        vecs: np.ndarray,
        want_ties: bool = True,
        col_version: int = 0,
        col_delta=None,
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Run one window; returns ``(chosen, feasible, ties)`` int64[K]
        (chosen = -1 where no feasible node; ties is a constant 1 when
        ``want_ties`` is False). Pure w.r.t. the host columns; the
        device fold carry advances and is kept for reuse.

        ``col_version`` stamps the dynamic columns' build epoch
        (``DripColumns.col_epoch``): O(dirty) refreshes patch the host
        arrays IN PLACE, so identity alone no longer keys freshness —
        callers on the dirty path MUST pass it. ``col_delta(held,
        current)`` (``DripColumns.dirty_rows_between``) then turns a
        version miss into a device-side row scatter instead of a full
        column re-upload; returning None falls back to the upload."""
        n = int(schedulable.shape[0])
        k = int(vecs.shape[0])
        npad = _bucket_nodes(n)
        kpad = _bucket(k, _MIN_K_BUCKET)
        no_fit = bounded is None or free is None
        mesh = self._mesh
        sharded = mesh is not None and int(mesh.devices.size) > 1
        col_dev = free_dev_target = None
        if sharded:
            from ..parallel.mesh import node_sharding, round_up_to_shards

            npad = round_up_to_shards(npad, mesh)  # equal tiles
            col_dev = node_sharding(mesh, 1)
            free_dev_target = node_sharding(mesh, 2)
        if self._cols.set_partition(self._partition_token()):
            self.mark_desynced()
            self.repartitions += 1
        t0 = time.perf_counter()

        def delta_for(col, arr):
            if col_delta is None or sharded:
                return None  # mesh tiles re-place; scatter is 1-device only
            held = self._cols.held_version(col, arr)
            if held is None or held == col_version:
                return None
            return col_delta(held, col_version)

        with enable_x64():
            sched_d = self._cols.put(
                "schedulable", schedulable, version=col_version,
                prepare=lambda a: _pad(a, npad, False),
                device=col_dev,
                delta_rows=delta_for("schedulable", schedulable),
            )
            w_d = self._cols.put(
                "weighted", weighted, version=col_version,
                prepare=lambda a: _pad(a.astype(np.int64), npad, _I64_MIN),
                device=col_dev,
                delta_rows=delta_for("weighted", weighted),
                row_prepare=lambda v: v.astype(np.int64),
            )
            if no_fit:
                # tracker-less plugin set: fit never fails
                bounded = np.zeros((n,), dtype=bool)
                free = np.zeros((n, 4), dtype=np.int64)
            bnd_d = self._cols.put(
                "bounded", bounded, prepare=lambda a: _pad(a, npad, False),
                device=col_dev,
            )
            free_d = self._free_dev
            if (
                not self._free_synced
                or free_d is None
                or self._free_src is not free
                or free_d.shape[0] != npad
            ):
                free_d = jax.device_put(_pad(free, npad, 0), free_dev_target)
                self._free_src = free
                self.free_uploads += 1
            vecs_p = _pad(np.ascontiguousarray(vecs, dtype=np.int64), kpad, 0)
            active = np.zeros((kpad,), dtype=bool)
            active[:k] = True
            if sharded:
                fn = _sharded_drip_fn(mesh, bool(want_ties))
                with _COLLECTIVE_LOCK:
                    outs, free_out = fn(
                        sched_d, w_d, bnd_d, free_d, vecs_p, active
                    )
                    # sync INSIDE the lock: dispatch is async, and the
                    # collective table must drain before the next launch
                    outs = np.asarray(outs)
            else:
                outs, free_out = _drip_batch(
                    sched_d, w_d, bnd_d, free_d, vecs_p, active,
                    want_ties=want_ties,
                )
                outs = np.asarray(outs)  # the single D2H transfer
        self._free_dev = free_out
        self._free_synced = True  # provisional; caller desyncs on reject
        self.last_kernel_seconds = time.perf_counter() - t0
        self.dispatches += 1
        return outs[:k, 0], outs[:k, 1], outs[:k, 2]


def drip_batch_dispatch(schedulable, weighted, bounded, free, vecs):
    """One-shot functional entry (bench/tests): no carry reuse."""
    kern = DripBatchKernel()
    return kern.dispatch(schedulable, weighted, bounded, free, vecs)
