"""Scalar reference scorer — the bit-exact parity oracle.

A pure-Python float64 transliteration of the Dynamic plugin's semantics
(ref: pkg/plugins/dynamic/stats.go, plugins.go). Every quirk is preserved
deliberately, because the batched TPU scorer is validated bit-for-bit
against this module:

- **fail-open**: any usage-read error (missing key, malformed value, stale
  or unparseable timestamp, negative value) means "not overloaded" for
  Filter (ref: stats.go:96-99) and a 0 contribution for Score.
- **zero threshold disables** a predicate entry (ref: stats.go:102-105).
- **weight counted on error**: a priority entry whose usage can't be read
  still adds its weight to the denominator (ref: stats.go:122-137 — the
  error branch does not skip ``weight += ``).
- **Go int truncation** toward zero for ``int(score/weight)`` and
  ``int(hotValue*10)`` (ref: stats.go:135, plugins.go:91).
- **hot value** read from the ``node_hot_value`` annotation with a fixed 5m
  validity window (ref: stats.go:152-166).
- priority entries whose metric has no (nonzero-period) syncPolicy entry
  score 0 with weight counted (ref: stats.go:80-84, 140-150).

All functions take an explicit ``now`` (epoch seconds) so behavior is a
pure function of (annotations, policy, now).
"""

from __future__ import annotations

import math

from ..loadstore.codec import go_parse_float
from ..policy.types import PolicySpec, PredicatePolicy, PriorityPolicy, SyncPolicy
from ..utils.score import go_trunc, normalize_score
from ..utils.timeutil import parse_local_time

from ..constants import (
    EXTRA_ACTIVE_PERIOD_SECONDS as EXTRA_ACTIVE_PERIOD,
    HOT_VALUE_ACTIVE_PERIOD_SECONDS as DEFAULT_HOT_VALUE_ACTIVE_PERIOD,
    MAX_NODE_SCORE,
    MIN_NODE_SCORE,
    NODE_HOT_VALUE_KEY as NODE_HOT_VALUE,
)


class UsageError(Exception):
    """A usage annotation could not be read (any fail-open condition)."""


def in_active_period(update_time_str: str, active_duration: float, now: float) -> bool:
    """ref: stats.go:30-49 — strict ``now < ts + activeDuration``."""
    ts = parse_local_time(update_time_str)
    if ts is None:
        return False
    return now < ts + active_duration


def get_resource_usage(
    anno: dict[str, str], key: str, active_duration: float, now: float
) -> float:
    """ref: stats.go:51-76. Raises UsageError on any invalid condition."""
    raw = anno.get(key)
    if raw is None:
        raise UsageError(f"key[{key}] not found")
    parts = raw.split(",")
    if len(parts) != 2:
        raise UsageError(f"illegal value: {raw}")
    if not in_active_period(parts[1], active_duration, now):
        raise UsageError(f"timestamp[{raw}] is expired")
    value = go_parse_float(parts[0])
    if value is None:
        raise UsageError(f"failed to parse float[{parts[0]}]")
    if value < 0:  # NaN compares False, i.e. NaN passes — as in Go
        raise UsageError(f"illegal value: {raw}")
    return value


def get_active_duration(sync_period: tuple[SyncPolicy, ...], name: str) -> float:
    """First matching nonzero-period entry + 5m; 0.0 means "no valid entry"
    (ref: stats.go:140-150 — the Go version returns (0, err); callers treat
    err and 0 identically)."""
    for sp in sync_period:
        if sp.name == name and sp.period_seconds != 0:
            return sp.period_seconds + EXTRA_ACTIVE_PERIOD
    return 0.0


def is_overload(
    anno: dict[str, str],
    predicate: PredicatePolicy,
    active_duration: float,
    now: float,
) -> bool:
    """ref: stats.go:94-112."""
    try:
        usage = get_resource_usage(anno, predicate.name, active_duration, now)
    except UsageError:
        return False  # fail-open
    if predicate.max_limit_percent == 0:
        return False  # zero threshold disables this entry
    return usage > predicate.max_limit_percent  # NaN > t is False


def get_score(
    anno: dict[str, str], priority: PriorityPolicy, spec: PolicySpec, now: float
) -> float:
    """ref: stats.go:78-92. Raises UsageError when the entry contributes 0."""
    active_duration = get_active_duration(spec.sync_period, priority.name)
    if active_duration == 0:
        raise UsageError(f"no active duration for resource[{priority.name}]")
    usage = get_resource_usage(anno, priority.name, active_duration, now)
    return (1.0 - usage) * priority.weight * float(MAX_NODE_SCORE)


def get_node_score(anno: dict[str, str], spec: PolicySpec, now: float) -> int:
    """ref: stats.go:114-138."""
    if len(spec.priority) == 0:
        return 0
    score = 0.0
    weight = 0.0
    for priority in spec.priority:
        try:
            priority_score = get_score(anno, priority, spec, now)
        except UsageError:
            priority_score = 0.0
        weight += priority.weight
        score += priority_score
    if weight == 0.0:
        # Go float division: 0/0 and NaN/0 -> NaN, x/0 -> ±Inf; all
        # truncate to int64-min on amd64 (see go_trunc).
        if score == 0.0 or math.isnan(score):
            quotient = math.nan
        else:
            quotient = math.copysign(math.inf, score)
    else:
        quotient = score / weight
    return go_trunc(quotient)


def get_node_hot_value(anno: dict[str, str] | None, now: float) -> float:
    """ref: stats.go:152-166."""
    if anno is None:
        return 0.0
    try:
        return get_resource_usage(anno, NODE_HOT_VALUE, DEFAULT_HOT_VALUE_ACTIVE_PERIOD, now)
    except UsageError:
        return 0.0


def filter_node(
    anno: dict[str, str] | None,
    spec: PolicySpec,
    now: float,
    is_daemonset_pod: bool = False,
) -> tuple[bool, str]:
    """Dynamic Filter: returns (schedulable, failing_metric_name)
    (ref: plugins.go:39-69)."""
    if is_daemonset_pod:
        return True, ""
    if anno is None:
        anno = {}
    for predicate in spec.predicate:
        active_duration = get_active_duration(spec.sync_period, predicate.name)
        if active_duration == 0:
            continue  # ref: plugins.go:57-61
        if is_overload(anno, predicate, active_duration, now):
            return False, predicate.name
    return True, ""


def score_node(anno: dict[str, str] | None, spec: PolicySpec, now: float) -> int:
    """Dynamic Score: base score minus hot-value penalty, clamped to
    [0, 100] (ref: plugins.go:73-98)."""
    if anno is None:
        anno = {}
    score = get_node_score(anno, spec, now)
    hot_value = get_node_hot_value(anno, now)
    score = score - go_trunc(hot_value * 10)
    # Go ints are 64-bit two's complement; the subtraction above can wrap
    # when the degenerate zero-weight-sum path yields int64-min.
    score = ((score + 2**63) % 2**64) - 2**63
    return normalize_score(score, MAX_NODE_SCORE, MIN_NODE_SCORE)
