"""Cluster-wide Filter/Score columns for the drip fast path.

The per-pod ("drip") scheduler needs the same verdicts the scalar
oracle produces node-by-node, but as whole-cluster numpy columns it can
cache across pods: a feasibility mask, the failing-predicate index each
infeasible node would report, and the Dynamic score. The score/filter
math is ``hybrid.score_rows_f64`` — the IEEE-double operation sequence
already validated bit-identical to ``scorer.oracle`` — so the only new
logic here is first-failing-predicate tracking, which the scalar
``filter_node`` reports as the failure message's metric name
(ref: plugins.go:39-69 — the scan returns on the FIRST overloaded
predicate in policy order).
"""

from __future__ import annotations

import numpy as np

from ..policy.compile import PolicyTensors
from .hybrid import score_rows_f64


def drip_filter_score_columns(
    tensors: PolicyTensors,
    values: np.ndarray,
    ts: np.ndarray,
    hot_value: np.ndarray,
    hot_ts: np.ndarray,
    now: float,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """``(schedulable[N] bool, fail_entry[N] int32, score[N] int32)``.

    ``fail_entry`` is the index into ``tensors.pred_idx`` of the first
    overloaded predicate entry per node (-1 when the node passes) —
    enough to reconstruct the scalar Filter's failure message lazily
    without re-walking annotations.
    """
    n = values.shape[0]
    fail_entry = np.full((n,), -1, dtype=np.int32)
    for p in range(len(tensors.pred_idx)):
        active = tensors.pred_active[p]
        if active <= 0:
            continue  # entry skipped (ref: plugins.go:57-61)
        threshold = tensors.pred_threshold[p]
        if threshold == 0:
            continue  # zero threshold disables (ref: stats.go:102-105)
        col = tensors.pred_idx[p]
        u = values[:, col]
        fresh = now < ts[:, col] + active
        with np.errstate(invalid="ignore"):
            # fail-open: stale/missing/negative never overloads; NaN
            # passes both comparisons exactly as in the oracle
            over = fresh & ~(u < 0) & (u > threshold)
        first = over & (fail_entry < 0)
        if first.any():
            fail_entry[first] = p
    schedulable, score = score_rows_f64(
        values, ts, hot_value, hot_ts, float(now), tensors
    )
    return schedulable, fail_entry, score


def fail_metric_name(tensors: PolicyTensors, entry: int) -> str:
    """Metric name the scalar Filter reports for ``fail_entry`` value."""
    return tensors.metric_names[int(tensors.pred_idx[int(entry)])]


def fail_metric_names(tensors: PolicyTensors) -> list[str]:
    """All ``fail_entry -> metric name`` resolutions at once — the
    vectorized ``reason_counts`` path does one table build per policy
    instead of a per-node ``fail_metric_name`` call."""
    return [
        tensors.metric_names[int(col)] for col in tensors.pred_idx
    ]
