"""Fused Pallas TPU kernel for the gang solver's level-table totals.

The water-filling gang solver's dominant cost is ``totals[L] = Σ_n
A_n(L)`` over the [n_levels, N] token table (see ``topk.GangScheduler``,
ref semantics: pkg/plugins/dynamic/plugins.go:89-91 applied in-batch).
The XLA path materializes that table through HBM; this kernel streams
node blocks through VMEM instead — per block it builds the (L, BN) table
in registers/VMEM, reduces over the lane (node) axis, and accumulates
the per-level partial totals into a single resident output across the
sequential TPU grid. The hotValue penalty staircase g, the combined
weight, and the level count are kernel constants unrolled at trace time
(the g lookup is the same 11-way select chain the XLA path uses — a
dynamic gather of a tiny table is pathological on TPU).

Waterline selection and the node-order prefix split stay on the XLA path
(O(n_levels) + O(N) elementwise — nothing left to fuse); results are
bit-identical to ``GangScheduler`` and the sequential oracle, tested in
interpret mode on CPU and compiled on TPU.

**Measured outcome (v5e, 50k nodes, 100k pods): XLA wins.** The fused
XLA totals run ~0.04ms/step vs ~0.12ms for this kernel (combined mode
wider) — XLA already streams the level table through fusion without an
HBM round-trip, exactly as the pallas guide warns ("don't hand-schedule
what the compiler already does"). The kernel is kept as a parity-tested
alternative backend (guards against future XLA fusion regressions and
exercises the Mosaic int-op quirks documented below), NOT as a default:
``GangScheduler`` remains the production solver everywhere. A
pallas_call is also opaque to GSPMD partitioning, so the mesh-sharded
``ShardedScheduleStep`` could never use it.
"""

from __future__ import annotations

import math
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from ..constants import MAX_NODE_SCORE
from .topk import GangScheduler

_LANE = 128  # f32/i32 lane tile; node blocks are multiples of this


class PallasGangScheduler(GangScheduler):
    """``GangScheduler`` with the O(n_levels · N) totals fused in Pallas.

    Same constructor and ``__call__`` contract (scores, schedulable,
    num_pods, capacity, offsets, prior); only ``_totals`` differs. The
    node axis is padded to a lane multiple inside the jitted step with
    zero-capacity lanes, which contribute no tokens.
    """

    def __init__(
        self,
        hv_counts: Sequence[int],
        dynamic_weight: int = 1,
        max_offset: int = 0,
        interpret: bool = False,
    ):
        self.interpret = interpret
        # (L, BN) int32 temporaries must fit VMEM comfortably: cap each
        # at ~2MB so plain mode (L=104) blocks 2048 lanes and combined
        # mode (L~504) drops to 1024.
        super().__init__(hv_counts, dynamic_weight, max_offset)
        self._n_levels_pad = max(8, math.ceil(self._n_levels / 8) * 8)
        budget_lanes = (1 << 21) // (4 * self._n_levels_pad)
        self._bn = int(max(_LANE, min(2048, budget_lanes // _LANE * _LANE)))
        self._kernel = self._make_kernel()

    def _make_kernel(self):
        w = int(self._weight)
        n_levels = int(self._n_levels)
        l_pad = int(self._n_levels_pad)
        g = [int(v) for v in self._g_host]  # 11 static table entries

        # Every scalar below is explicitly typed: under x64 a bare python
        # int/float becomes a weak int64/f64 constant, and Mosaic's
        # convert-element-type lowering recurses forever on 64-bit types.
        def i32(v):
            return jnp.asarray(v, jnp.int32)

        def floordiv_pos(d, c):
            """Exact ``d // c`` for small non-negative int32 ``d`` and a
            static positive int ``c``. Mosaic cannot lower integer
            floordiv (and under x64 the jnp implementation routes through
            64-bit), so divide in f32: (d + 0.5)/c sits strictly between
            d//c and d//c + 1 at distance >= 0.5/c from either integer —
            far beyond f32 rounding error for d < 2^20 — so floor is
            exact."""
            q = jnp.floor(
                (d.astype(jnp.float32) + jnp.float32(0.5)) / jnp.float32(c)
            )
            return q.astype(jnp.int32)

        def kernel(s_ref, offs_ref, cap_ref, pri_ref, out_ref):
            i = pl.program_id(0)
            bn = s_ref.shape[1]
            s = s_ref[0, :][None, :]  # (1, BN) int32
            offs = offs_ref[0, :][None, :]
            cap = cap_ref[0, :][None, :]
            pri = pri_ref[0, :][None, :]
            zero = i32(0)

            lv = jax.lax.broadcasted_iota(jnp.int32, (l_pad, bn), 0)
            qnum = lv - offs
            # q only matters where qnum > 0 (else the cap override wins),
            # so a non-negative clamp keeps floordiv_pos's domain valid
            q = (
                floordiv_pos(jnp.maximum(qnum, zero) + i32(w - 1), w)
                if w != 1
                else qnum
            )
            xq = jnp.clip(floordiv_pos(jnp.maximum(s - q, zero), 10), zero, i32(10))
            unlocked = jnp.full((l_pad, bn), g[10], dtype=jnp.int32)
            for x in range(9, -1, -1):  # 11-way select chain (see topk)
                unlocked = jnp.where(xq <= i32(x), i32(g[x]), unlocked)
            unlocked = jnp.where(
                (q <= i32(MAX_NODE_SCORE)) & (s >= q), unlocked, zero
            )
            unlocked = jnp.maximum(unlocked - pri, zero)
            unlocked = jnp.where(qnum <= zero, cap, unlocked)
            a = jnp.minimum(cap, unlocked)
            a = jnp.where(lv < i32(n_levels), a, zero)  # padded levels: none
            # dtype pinned: under x64 an unconstrained sum accumulates
            # int64, which Mosaic cannot lower
            part = a.sum(axis=1, dtype=jnp.int32)  # (L_pad,)

            @pl.when(i == 0)
            def _init():
                out_ref[...] = jnp.zeros_like(out_ref)

            # TPU grids run sequentially, so accumulating into the same
            # resident output block across steps is well-defined.
            out_ref[...] += jnp.broadcast_to(part[:, None], out_ref.shape)

        return kernel

    def _totals(self, s, offs, k_cap, pri):
        n = s.shape[0]
        bn = self._bn if n >= self._bn else max(_LANE, math.ceil(n / _LANE) * _LANE)
        n_pad = math.ceil(n / bn) * bn

        def row(vec, fill):
            padded = jnp.pad(vec, (0, n_pad - n), constant_values=fill)
            return jnp.broadcast_to(padded[None, :], (8, n_pad))

        l_pad = self._n_levels_pad
        # index maps return typed zeros: under x64 a bare python 0 turns
        # into an i64 index and Mosaic rejects the mixed-type index tuple
        _z = lambda: jnp.asarray(0, jnp.int32)  # noqa: E731
        vec_spec = pl.BlockSpec((8, bn), lambda i: (_z(), i))
        out_spec = pl.BlockSpec((l_pad, _LANE), lambda i: (_z(), _z()))
        out = pl.pallas_call(
            self._kernel,
            grid=(n_pad // bn,),
            in_specs=[vec_spec, vec_spec, vec_spec, vec_spec],
            out_specs=out_spec,
            out_shape=jax.ShapeDtypeStruct((l_pad, _LANE), jnp.int32),
            interpret=self.interpret,
        )(
            row(s, 0),
            row(offs, 0),
            row(k_cap, 0),  # zero-capacity pad lanes contribute nothing
            row(pri, 0),
        )
        return out[: self._n_levels, 0]
