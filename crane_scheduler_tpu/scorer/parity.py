"""Shared bit-for-bit parity gate against the f64/Go semantics.

The BASELINE north star requires device placements "matching in-process
Score() placements bit-for-bit" (ref semantics:
/root/reference/pkg/plugins/dynamic/stats.go:114-138). This module is the
ONE place that comparison lives: bench.py and bench_suite.py both gate on
it, so the masking and capacity conventions cannot drift apart.
tests/test_hybrid_sharded.py deliberately keeps its own independent
re-implementation — a parity gate verified by a circular copy of itself
would prove nothing.
"""

from __future__ import annotations

import numpy as np

from .hybrid import score_rows_f64
from .topk import gang_assign_host


class ParityError(AssertionError):
    """Device results diverged from the exact f64/Go host semantics."""


def f64_verdicts(values, ts, hot_value, hot_ts, node_valid, now, tensors):
    """Exact f64 filter verdicts + scores with invalid rows masked the
    way the device step masks them (unschedulable, score 0)."""
    sched64, score64 = score_rows_f64(values, ts, hot_value, hot_ts, now, tensors)
    node_valid = np.asarray(node_valid, bool)
    return sched64 & node_valid, np.where(node_valid, score64, 0)


def check_placement_parity(
    *,
    values,
    ts,
    hot_value,
    hot_ts,
    node_valid,
    now,
    tensors,
    schedulable,
    scores,
    counts,
    num_pods,
    capacity=None,
    unassigned=None,
    offsets=None,
    dynamic_weight: int = 1,
    max_offset: int = 0,
    prior=None,
):
    """Raise ``ParityError`` unless the device verdicts, scores, and
    per-node placement counts equal the exact f64 scoring + host
    water-filling on the same inputs. ``offsets``/``dynamic_weight``/
    ``max_offset``/``prior`` must mirror the gang parameters the device
    step solved with (combined-score mode); the defaults are the plain
    Dynamic-score domain. Returns the oracle
    ``(sched64, score64, gang_result)`` for further inspection."""
    sched64, score64 = f64_verdicts(
        values, ts, hot_value, hot_ts, node_valid, now, tensors
    )
    if not (np.asarray(schedulable, bool) == sched64).all():
        raise ParityError("device filter verdicts != f64 oracle")
    dev_scores = np.asarray(scores)
    if not (dev_scores == score64).all():
        raise ParityError(f"{int((dev_scores != score64).sum())} device scores != f64 oracle")
    want = gang_assign_host(
        score64, sched64, num_pods, tensors.hv_count, capacity=capacity,
        offsets=offsets, dynamic_weight=dynamic_weight,
        max_offset=max_offset, prior=prior,
    )
    if not (np.asarray(counts) == np.asarray(want.counts)).all():
        raise ParityError("device placements != f64 water-filling")
    if unassigned is not None and int(unassigned) != int(want.unassigned):
        raise ParityError("device unassigned count != f64 water-filling")
    return sched64, score64, want
