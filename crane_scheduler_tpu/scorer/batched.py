"""Batched TPU scorer: the whole cluster in one fused tensor expression.

Replaces the reference's per-node scalar loops
(ref: pkg/plugins/dynamic/stats.go:94-138 inside the kube-scheduler's
per-node Filter/Score callbacks) with a single vectorized evaluation over
the node-by-metric load matrix:

    filter:  any_p [ valid(n,p) & thresh_p != 0 & usage(n,p) > thresh_p ]
    score:   clip( trunc(Σ_k s_k / Σ_k w_k) - trunc(hot*10), 0, 100 )
    s_k   =  valid(n,k) ? (1 - usage(n,k)) * w_k * 100 : 0
    valid =  fresh(now < ts + window) & ¬(value < 0) & window > 0

Bit-exactness rules honored (validated against ``scorer.oracle``):

- priority contributions accumulate **in policy list order** via an
  explicit chain of adds (float addition is not associative; XLA preserves
  explicit ordering);
- Go ``int(float64)`` truncation toward zero, with NaN/±Inf and
  out-of-int64-range mapping to int64-min (amd64 ``CVTTSD2SI``), and int64
  two's-complement wraparound on the hot-penalty subtraction;
- NaN usage propagates through the score sum like Go (a node annotated
  "NaN,<fresh ts>" truncates to int64-min and clamps to 0);
- fail-open everywhere: staleness/missing/negative reads score 0 with the
  weight still counted, and never mark a node overloaded.

``dtype=float64`` (requires jax_enable_x64) is the parity mode;
``dtype=float32`` is the TPU fast path (scores may differ by ±1 at exact
truncation boundaries — filtering differs only when usage values sit
within float32 epsilon of a threshold).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..constants import (
    HOT_VALUE_ACTIVE_PERIOD_SECONDS,
    MAX_NODE_SCORE,
    MIN_NODE_SCORE,
)
from ..policy.compile import PolicyTensors


@dataclass
class ScoreResult:
    schedulable: Any  # [N] bool — Filter verdict (True = passes)
    scores: Any  # [N] int32 in [0, 100] — Score verdict

    def __iter__(self):
        yield self.schedulable
        yield self.scores


def _go_trunc_to_int(q, int_dtype):
    """Vectorized Go int(floatExpr): trunc toward zero; NaN/Inf/overflow
    -> integer-indefinite (min int)."""
    info = jnp.iinfo(int_dtype)
    limit = jnp.asarray(2.0 ** (info.bits - 1), dtype=q.dtype)
    ok = jnp.isfinite(q) & (q > -limit) & (q < limit)
    safe = jnp.where(ok, jnp.trunc(q), 0.0)
    return jnp.where(ok, safe.astype(int_dtype), info.min)


def _ordered_sum(columns):
    """Sum a list of [N] arrays with a left-to-right addition chain."""
    if not columns:
        return None
    acc = columns[0]
    for c in columns[1:]:
        acc = acc + c
    return acc


class BatchedScorer:
    """Jitted filter+score over a load-store snapshot.

    Usage::

        scorer = BatchedScorer(compile_policy(policy))
        result = scorer(snap.values, snap.ts, snap.hot_value, snap.hot_ts,
                        snap.node_valid, now)
    """

    def __init__(self, tensors: PolicyTensors, dtype=jnp.float64):
        self.tensors = tensors
        self.dtype = jnp.dtype(dtype)
        if self.dtype == jnp.dtype(jnp.float64) and not jax.config.jax_enable_x64:
            raise ValueError(
                "float64 parity mode requires jax_enable_x64 "
                "(set JAX_ENABLE_X64=1 or jax.config.update)"
            )
        self.int_dtype = jnp.int64 if self.dtype == jnp.dtype(jnp.float64) else jnp.int32
        t = tensors
        # Policy constants stay HOST-side (numpy): numpy values captured by
        # a traced function lower to inline HLO literals. Closed-over
        # jax.Arrays instead become runtime buffer parameters, and on the
        # axon TPU runtime executing any program with captured device
        # constants degrades every later dispatch in the process from
        # ~30us to ~70ms (measured; the poisoning persists even after the
        # executable is dropped). numpy rounding to the compute dtype here
        # is identical to the former jnp.asarray conversion.
        npdtype = np.float64 if self.dtype == jnp.dtype(jnp.float64) else np.float32
        f = lambda a: np.asarray(a, dtype=npdtype)
        self._pred_threshold = f(t.pred_threshold)
        self._pred_active = f(t.pred_active)
        self._prio_weight = f(t.prio_weight)
        self._prio_active = f(t.prio_active)
        self._weight_sum = float(t.weight_sum)
        self._jit = jax.jit(self._score_impl)

    def __call__(self, values, ts, hot_value, hot_ts, node_valid, now) -> ScoreResult:
        if self.dtype != jnp.dtype(jnp.float64):
            # Rebase timestamps around `now` before the downcast: epoch
            # seconds (~1.7e9) have ~2-minute granularity in float32, which
            # would corrupt staleness windows. (ts - now) is exact in
            # float64 (Sterbenz) and small enough to survive float32.
            ts = np.asarray(ts, dtype=np.float64) - float(now)
            hot_ts = np.asarray(hot_ts, dtype=np.float64) - float(now)
            now = 0.0
        out = self._jit(
            jnp.asarray(values, dtype=self.dtype),
            jnp.asarray(ts, dtype=self.dtype),
            jnp.asarray(hot_value, dtype=self.dtype),
            jnp.asarray(hot_ts, dtype=self.dtype),
            jnp.asarray(node_valid, dtype=jnp.bool_),
            jnp.asarray(now, dtype=self.dtype),
        )
        return ScoreResult(*out)

    # The pure function (also used by the sharded path via shard_map).
    def _score_impl(self, values, ts, hot_value, hot_ts, node_valid, now):
        schedulable = self.filter_mask(values, ts, now) & node_valid
        scores = self.score_values(values, ts, hot_value, hot_ts, now)
        scores = jnp.where(node_valid, scores, 0)
        return schedulable, scores

    def filter_mask(self, values, ts, now):
        """True = node passes every predicate (ref: plugins.go:39-69).

        Columns are selected with *static* indices (the policy's metric
        map is compile-time data): a dynamic-index gather along the minor
        [N, M] axis costs ~70ms at 50k nodes on TPU, while static slices
        fuse into the elementwise work for free.
        """
        n = values.shape[0]
        if len(self.tensors.pred_idx) == 0:
            return jnp.ones((n,), dtype=jnp.bool_)
        over_any = None
        for p in range(len(self.tensors.pred_idx)):
            col = int(self.tensors.pred_idx[p])
            usage = values[:, col]  # [N]
            fresh = now < ts[:, col] + self._pred_active[p]  # -inf ts never fresh
            valid = fresh & ~(usage < 0) & (self._pred_active[p] > 0)
            over = (
                valid
                & (self._pred_threshold[p] != 0)
                & (usage > self._pred_threshold[p])
            )
            over_any = over if over_any is None else (over_any | over)
        return ~over_any

    def score_values(self, values, ts, hot_value, hot_ts, now):
        """[0,100] int scores (ref: plugins.go:73-98, stats.go:114-138)."""
        n = values.shape[0]
        izero = jnp.zeros((n,), dtype=self.int_dtype)
        if len(self.tensors.prio_idx) == 0:
            base = izero  # ref: stats.go:116-120 — no priorities => score 0
        else:
            # Static column slices (see filter_mask) + in-order
            # accumulation: Go adds entry scores left to right.
            zero = jnp.asarray(0.0, self.dtype)
            per_entry = []
            for k in range(len(self.tensors.prio_idx)):
                col = int(self.tensors.prio_idx[k])
                usage = values[:, col]  # [N]
                fresh = now < ts[:, col] + self._prio_active[k]
                valid = fresh & ~(usage < 0) & (self._prio_active[k] > 0)
                # Go rounds twice: fl(fl((1-u)*w) * 100). The barrier stops
                # XLA from constant-folding w*100 into one multiply, which
                # flips scores at exact truncation boundaries.
                partial = jax.lax.optimization_barrier(
                    (1.0 - usage) * self._prio_weight[k]
                )
                contrib = partial * float(MAX_NODE_SCORE)
                per_entry.append(jnp.where(valid, contrib, zero))
            score_sum = _ordered_sum(per_entry)
            if self._weight_sum == 0.0:
                quotient = jnp.where(
                    score_sum == 0.0,
                    jnp.asarray(jnp.nan, self.dtype),
                    jnp.sign(score_sum) * jnp.asarray(jnp.inf, self.dtype),
                )
            else:
                quotient = score_sum / jnp.asarray(self._weight_sum, self.dtype)
            base = _go_trunc_to_int(quotient, self.int_dtype)

        hot_fresh = now < hot_ts + jnp.asarray(
            HOT_VALUE_ACTIVE_PERIOD_SECONDS, self.dtype
        )
        hot_ok = hot_fresh & ~(hot_value < 0)
        hv = jnp.where(hot_ok, hot_value, jnp.asarray(0.0, self.dtype))
        penalty = _go_trunc_to_int(hv * 10.0, self.int_dtype)
        # int64 subtraction wraps two's-complement, matching Go.
        score = base - penalty
        score = jnp.clip(score, MIN_NODE_SCORE, MAX_NODE_SCORE)
        return score.astype(jnp.int32)
