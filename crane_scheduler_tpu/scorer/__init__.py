from .oracle import (
    UsageError,
    get_resource_usage,
    get_active_duration,
    is_overload,
    get_node_score,
    get_node_hot_value,
    filter_node,
    score_node,
)
from .batched import BatchedScorer, ScoreResult

__all__ = [
    "UsageError",
    "get_resource_usage",
    "get_active_duration",
    "is_overload",
    "get_node_score",
    "get_node_hot_value",
    "filter_node",
    "score_node",
    "BatchedScorer",
    "ScoreResult",
]
