from .oracle import (
    UsageError,
    get_resource_usage,
    get_active_duration,
    is_overload,
    get_node_score,
    get_node_hot_value,
    filter_node,
    score_node,
)
from .batched import BatchedScorer, ScoreResult
from .hybrid import (
    HybridScorer,
    OverrideCache,
    compute_overrides,
    compute_overrides_incremental,
    score_rows_f64,
)
from .topk import GangScheduler, gang_assign_host, gang_assign_oracle

__all__ = [
    "HybridScorer",
    "OverrideCache",
    "compute_overrides",
    "compute_overrides_incremental",
    "score_rows_f64",
    "GangScheduler",
    "gang_assign_host",
    "gang_assign_oracle",
    "UsageError",
    "get_resource_usage",
    "get_active_duration",
    "is_overload",
    "get_node_score",
    "get_node_hot_value",
    "filter_node",
    "score_node",
    "BatchedScorer",
    "ScoreResult",
]
