"""Incremental columnar allocatable/requested accounting.

ref: k8s.io/kubernetes/pkg/scheduler/framework/plugins/noderesources —
the fit math (effective pod request = max(sum of containers, max over
init containers) + overhead; insufficient when request exceeds
allocatable minus requested) — computed over numpy columns maintained
off the ``ClusterState`` mirror instead of per-NodeInfo structs.

Incrementality rides the mirror's existing change journal: requested
sums are version-gated on ``pod_version`` and, when the journal window
still covers the interval, only the nodes named by
``pod_changes_since`` are recounted; a journal overrun (watch storm)
falls back to a from-scratch recount. Allocatable columns are gated on
``node_version`` with a per-node identity check so the annotator's
sweep (which bumps ``node_version`` without touching allocatable) costs
one ``is`` comparison per node, not a quantity reparse.

Nodes that never reported ``status.allocatable`` (the sim's synthetic
nodes, sparse fixtures) are UNBOUNDED — the fit layer fails open, so
wiring it into an existing cluster changes no placement until kubelets
actually report capacity.
"""

from __future__ import annotations

import threading
from typing import Any, Iterable, Mapping

import numpy as np

from ..cluster.state import Pod
from ..framework.types import (
    CPU,
    EPHEMERAL_STORAGE,
    MEMORY,
    PODS,
    Resource,
)
from ..utils.quantity import to_milli, to_value

# Capacity sentinel for "no limit" — matches the gang solver's historical
# default so min(solver_default, fit_rows) is an identity on unreported
# nodes and plain-path parity is preserved bit-for-bit.
UNBOUNDED = 1 << 30

# Columnar dim order: [milli_cpu, memory, ephemeral_storage, pods].
_N_DIMS = 4
_DIM_CPU, _DIM_MEM, _DIM_EPH, _DIM_PODS = range(_N_DIMS)
_DIM_NAMES = (CPU, MEMORY, EPHEMERAL_STORAGE, PODS)


def _max_into(acc: Resource, other: Resource) -> None:
    """Element-wise max of ``other`` into ``acc`` (init-container rule)."""
    acc.milli_cpu = max(acc.milli_cpu, other.milli_cpu)
    acc.memory = max(acc.memory, other.memory)
    acc.ephemeral_storage = max(acc.ephemeral_storage, other.ephemeral_storage)
    for k, v in other.scalar_resources.items():
        if v > acc.scalar_resources.get(k, 0):
            acc.scalar_resources[k] = v


def pod_fit_request(pod: Pod) -> Resource:
    """Effective scheduling request, kube semantics: per-resource
    max(sum of container requests, max over init-container requests),
    plus pod overhead. Missing requests default to 0."""
    r = Resource()
    for c in pod.containers:
        r.add(c.resources.requests)
    for c in getattr(pod, "init_containers", ()):
        one = Resource()
        one.add(c.resources.requests)
        _max_into(r, one)
    overhead = getattr(pod, "overhead", None)
    if overhead:
        r.add(overhead)
    return r


def request_vec(r: Resource) -> np.ndarray:
    """Dense int64 request row in columnar dim order."""
    vec = np.zeros((_N_DIMS,), dtype=np.int64)
    vec[_DIM_CPU] = r.milli_cpu
    vec[_DIM_MEM] = r.memory
    vec[_DIM_EPH] = r.ephemeral_storage
    vec[_DIM_PODS] = 1  # every pod consumes one slot
    return vec


_request_vec = request_vec


def copy_counts_rows(
    free: np.ndarray, bounded: np.ndarray, vec: np.ndarray
) -> np.ndarray:
    """``free_copy_counts`` math over an EXPLICIT free matrix: how many
    request rows ``vec`` fit in each row of ``free`` (int64[N,4]),
    unbounded rows (``~bounded``) coming back UNBOUNDED. This is the
    capacity derivation the gang window's host fold twin and the device
    kernel both mirror — it must stay bit-identical to the tracker's
    own ``free_copy_counts`` over the same rows (minus the scalar-
    resources walk, which columnar callers route to the fallback)."""
    counts = np.full((free.shape[0],), UNBOUNDED, dtype=np.int64)
    clipped = np.clip(free, 0, None)
    for d in range(_N_DIMS):
        if vec[d] > 0:
            np.minimum(counts, clipped[:, d] // vec[d], out=counts)
    counts[~np.asarray(bounded, bool)] = UNBOUNDED
    return counts


def row_fail_reason(free_row, vec) -> str:
    """First failing dimension of a bounded free row against ``vec``,
    in NodeResourcesFit's check order and wording (pods slot first,
    then cpu/memory/ephemeral-storage). Empty string means it fits."""
    if free_row[_DIM_PODS] < vec[_DIM_PODS]:
        return "Too many pods"
    for d in (_DIM_CPU, _DIM_MEM, _DIM_EPH):
        if vec[d] > 0 and vec[d] > free_row[d]:
            return f"Insufficient {_DIM_NAMES[d]}"
    return ""


def rows_fail_codes(free: np.ndarray, vec: np.ndarray) -> np.ndarray:
    """Vectorized ``row_fail_reason``: int8[N] of first-failing dims
    (-1 = fits) in the same check order — pods slot first, then
    cpu/memory/ephemeral-storage. One pass over the free matrix instead
    of a Python loop per row; ``fail_code_reason`` maps codes back to
    the exact scalar wording."""
    codes = np.full((free.shape[0],), -1, dtype=np.int8)
    # reverse priority order, later writes win
    for d in (_DIM_EPH, _DIM_MEM, _DIM_CPU):
        if vec[d] > 0:
            codes[vec[d] > free[:, d]] = d
    codes[free[:, _DIM_PODS] < vec[_DIM_PODS]] = _DIM_PODS
    return codes


def fail_code_reason(code: int) -> str:
    """``row_fail_reason`` wording for a ``rows_fail_codes`` entry."""
    if code == _DIM_PODS:
        return "Too many pods"
    return f"Insufficient {_DIM_NAMES[code]}"


def request_matrix(requests) -> np.ndarray:
    """Stacked ``request_vec`` rows, int64[K, 4] — the drip batch
    kernel's per-window pod queue."""
    reqs = list(requests)
    mat = np.zeros((len(reqs), _N_DIMS), dtype=np.int64)
    for i, r in enumerate(reqs):
        mat[i] = request_vec(r)
    return mat


class FitTracker:
    """Columnar free-allocatable accounting over a cluster mirror.

    Thread-safe; ``refresh()`` is cheap when nothing changed (two
    version reads) and incremental when the mirror's change journal
    covers the interval. All read methods operate on the columns built
    by the last ``refresh()`` — callers refresh once per cycle, not per
    lookup.
    """

    def __init__(self, cluster, telemetry=None):
        self._cluster = cluster
        self._lock = threading.Lock()
        self._node_ver = -1
        self._pod_ver = -1
        self._names: list[str] = []
        self._index: dict[str, int] = {}
        self._has_alloc = np.zeros((0,), dtype=bool)
        self._alloc = np.zeros((0, _N_DIMS), dtype=np.int64)
        self._req = np.zeros((0, _N_DIMS), dtype=np.int64)
        # rare paths, keyed by node name; only nodes that have any
        self._alloc_maps: dict[str, Mapping[str, Any]] = {}
        self._scalar_alloc: dict[str, dict[str, int]] = {}
        self._scalar_req: dict[str, dict[str, int]] = {}
        self._full_recounts = 0
        self._incremental_recounts = 0
        self._node_patches = 0  # journal-driven O(dirty) node refreshes
        self._req_dirty = True  # requested columns not yet counted
        # bumps only when capacity state actually moved (membership or
        # an allocatable row) — annotation patches bump node_version
        # without touching it, so free_matrix consumers can skip the
        # O(n) aligned copy entirely
        self.alloc_version = 0
        # name->row gathers cached per (names list identity, index
        # epoch): the drip column cache, the gang solver's capacity rows
        # and the descheduler's landing mask all re-pass the SAME list
        # object every call, so steady state is pure fancy indexing
        self._index_ver = 0
        self._aligned: list[tuple] = []  # (names_ref, index_ver, rows, known)
        self.mask_builds = 0  # aligned-gather rebuilds (regression gate)
        self._telemetry = telemetry
        if telemetry is not None:
            reg = telemetry.registry
            self._m_refresh = reg.counter(
                "crane_fit_refresh_total",
                "Fit-tracker requested-column refreshes by kind.",
                ("kind",),
            )
            self._m_nodes = reg.gauge(
                "crane_fit_tracked_nodes",
                "Nodes with reported allocatable under fit accounting.",
            )
            self._m_dirty_rows = reg.counter(
                "crane_dirty_rows_total",
                "Rows patched via the dirty-name journal instead of a "
                "full identity sweep, by consumer",
                ("consumer",),
            )

    # -- refresh -----------------------------------------------------------

    def refresh(self) -> None:
        """Bring the columns up to date with the mirror (version-gated)."""
        with self._lock:
            nv = self._cluster.node_version
            pv = self._cluster.pod_version
            if nv != self._node_ver:
                dirty = None
                if self._names and self._node_ver >= 0:
                    fn = getattr(self._cluster, "dirty_nodes_since", None)
                    if fn is not None:
                        dirty = fn(self._node_ver)
                if dirty is not None and not dirty[1]:
                    # journal-covered, membership unchanged: identity-
                    # check only the dirty names instead of every node
                    self._patch_nodes_locked(dirty[0])
                else:
                    self._rebuild_nodes_locked()
                self._node_ver = nv
            if not self._has_alloc.any():
                # nothing bounded: requested sums can't matter, so skip
                # the recount — a capacity-free cluster (the sim, parity
                # fixtures) pays two version reads per refresh, nothing
                # more. Mark the columns dirty for when allocatable
                # first appears.
                self._req_dirty = True
                self._pod_ver = pv
                return
            if pv == self._pod_ver and not self._req_dirty:
                return
            changed: Iterable[str] | None
            if self._req_dirty:
                changed = None
            else:
                changed = self._cluster.pod_changes_since(self._pod_ver)
            if changed is None:
                self._recount_all_locked()
                self._full_recounts += 1
                if self._telemetry is not None:
                    self._m_refresh.labels(kind="full").inc()
            else:
                for name in changed:
                    i = self._index.get(name)
                    if i is not None:
                        self._recount_node_locked(name, i)
                self._incremental_recounts += 1
                if self._telemetry is not None:
                    self._m_refresh.labels(kind="incremental").inc()
            self._req_dirty = False
            self._pod_ver = pv

    def _patch_nodes_locked(self, touched) -> None:
        """O(dirty) twin of ``_rebuild_nodes_locked``: membership is
        unchanged, so only the journal's dirty names can have a new
        allocatable object."""
        if not touched:
            return
        index = self._index
        get_node = self._cluster.get_node
        changed = 0
        for name in touched:
            i = index.get(name)
            if i is None:
                continue  # another shard's write (global journal)
            node = get_node(name)
            if node is None:
                continue
            if self._apply_alloc_locked(name, i, node):
                changed += 1
        self._node_patches += 1
        if self._telemetry is not None:
            self._m_dirty_rows.labels(consumer="fit").inc(len(touched))
        if changed:
            self.alloc_version += 1
            if self._telemetry is not None:
                self._m_nodes.set(int(self._has_alloc.sum()))

    def _apply_alloc_locked(self, name: str, i: int, node) -> bool:
        """Identity-gated allocatable row update for one node; returns
        True when the row actually changed."""
        amap = getattr(node, "allocatable", None) or None
        prev = self._alloc_maps.get(name)
        if amap is prev:
            return False  # annotation fold kept the same allocatable object
        if amap is None:
            self._alloc_maps.pop(name, None)
            self._scalar_alloc.pop(name, None)
            self._has_alloc[i] = False
            return True
        self._alloc_maps[name] = amap
        row = self._alloc[i]
        row[:] = 0
        # kubelet always reports "pods"; a fixture that omits it
        # means "don't model pod count" — fail open on that dim only
        row[_DIM_PODS] = UNBOUNDED
        scalars: dict[str, int] = {}
        for key, quantity in amap.items():
            if key == CPU:
                row[_DIM_CPU] = to_milli(quantity)
            elif key == MEMORY:
                row[_DIM_MEM] = to_value(quantity)
            elif key == EPHEMERAL_STORAGE:
                row[_DIM_EPH] = to_value(quantity)
            elif key == PODS:
                row[_DIM_PODS] = to_value(quantity)
            else:
                scalars[key] = to_value(quantity)
        if scalars:
            self._scalar_alloc[name] = scalars
        else:
            self._scalar_alloc.pop(name, None)
        self._has_alloc[i] = True
        return True

    def _rebuild_nodes_locked(self) -> None:
        nodes = self._cluster.list_nodes()
        names = [n.name for n in nodes]
        if names != self._names:
            # membership changed: rebuild index and realign requested rows
            old_index, old_req = self._index, self._req
            old_scalar_req = self._scalar_req
            self._names = names
            self._index = {name: i for i, name in enumerate(names)}
            self._has_alloc = np.zeros((len(names),), dtype=bool)
            self._alloc = np.zeros((len(names), _N_DIMS), dtype=np.int64)
            req = np.zeros((len(names), _N_DIMS), dtype=np.int64)
            stale = []
            for i, name in enumerate(names):
                j = old_index.get(name)
                if j is None:
                    stale.append((name, i))
                else:
                    req[i] = old_req[j]
            self._req = req
            self._scalar_req = {
                k: v for k, v in old_scalar_req.items() if k in self._index
            }
            self._alloc_maps = {}
            self._index_ver += 1
            self._aligned.clear()
            self.alloc_version += 1  # membership moved the capacity rows
            if not self._req_dirty:
                for name, i in stale:
                    self._recount_node_locked(name, i)
        changed = 0
        for i, node in enumerate(nodes):
            if self._apply_alloc_locked(node.name, i, node):
                changed += 1
        if changed:
            self.alloc_version += 1
        if self._telemetry is not None:
            self._m_nodes.set(int(self._has_alloc.sum()))

    def _recount_node_locked(self, name: str, i: int) -> None:
        row = np.zeros((_N_DIMS,), dtype=np.int64)
        scalars: dict[str, int] = {}
        for pod in self._cluster.list_pods(name):
            r = pod_fit_request(pod)
            row[_DIM_CPU] += r.milli_cpu
            row[_DIM_MEM] += r.memory
            row[_DIM_EPH] += r.ephemeral_storage
            row[_DIM_PODS] += 1
            for k, v in r.scalar_resources.items():
                scalars[k] = scalars.get(k, 0) + v
        self._req[i] = row
        if scalars:
            self._scalar_req[name] = scalars
        else:
            self._scalar_req.pop(name, None)

    def _recount_all_locked(self) -> None:
        self._req[:] = 0
        self._scalar_req = {}
        index = self._index
        req = self._req
        scalar_req = self._scalar_req
        for pod in self._cluster.list_pods():
            node_name = pod.node_name
            i = index.get(node_name) if node_name else None
            if i is None:
                continue
            r = pod_fit_request(pod)
            row = req[i]
            row[_DIM_CPU] += r.milli_cpu
            row[_DIM_MEM] += r.memory
            row[_DIM_EPH] += r.ephemeral_storage
            row[_DIM_PODS] += 1
            if r.scalar_resources:
                dst = scalar_req.setdefault(node_name, {})
                for k, v in r.scalar_resources.items():
                    dst[k] = dst.get(k, 0) + v

    # -- reads -------------------------------------------------------------

    def fits(self, pod: Pod, node_name: str, request: Resource | None = None):
        """Does ``pod`` fit in the node's current free allocatable?
        Returns ``(ok, reason)`` — reason mirrors NodeResourcesFit's
        ("Too many pods" / "Insufficient <resource>"). Unknown nodes and
        nodes without reported allocatable fail open."""
        if request is None:
            request = pod_fit_request(pod)
        with self._lock:
            i = self._index.get(node_name)
            if i is None or not self._has_alloc[i]:
                return True, ""
            vec = request_vec(request)
            reason = row_fail_reason(self._alloc[i] - self._req[i], vec)
            if reason:
                return False, reason
            if request.scalar_resources:
                salloc = self._scalar_alloc.get(node_name) or {}
                sused = self._scalar_req.get(node_name) or {}
                for k, v in request.scalar_resources.items():
                    if v > 0 and v > salloc.get(k, 0) - sused.get(k, 0):
                        return False, f"Insufficient {k}"
            return True, ""

    def _rows_for_locked(self, names) -> tuple[np.ndarray, np.ndarray]:
        """``(rows[N], known[N])`` aligning ``names`` with the tracker's
        columns, cached by list identity + index epoch (a caller that
        re-passes the same list object pays the O(N) dict-get gather
        once, not per call)."""
        for ent in self._aligned:
            if ent[0] is names and ent[1] == self._index_ver:
                return ent[2], ent[3]
        index = self._index
        n = len(names)
        rows = np.fromiter(
            (index.get(nm, -1) for nm in names), dtype=np.int64, count=n
        )
        known = rows >= 0
        self.mask_builds += 1
        self._aligned.append((names, self._index_ver, rows, known))
        if len(self._aligned) > 8:
            del self._aligned[0]
        return rows, known

    def fits_mask(self, names, request: Resource) -> np.ndarray:
        """Vectorized ``fits`` verdict over ``names`` — bit-identical
        per node, one broadcast instead of a per-node Python walk.
        Unknown/unreported nodes fail open (True)."""
        with self._lock:
            n = len(names)
            ok = np.ones((n,), dtype=bool)
            if not self._names or n == 0:
                return ok
            rows, known = self._rows_for_locked(names)
            bounded = np.zeros((n,), dtype=bool)
            bounded[known] = self._has_alloc[rows[known]]
            bidx = np.flatnonzero(bounded)
            if not bidx.size:
                return ok
            vec = request_vec(request)
            br = rows[bidx]
            free = self._alloc[br] - self._req[br]
            fail = ((vec > 0) & (free < vec)).any(axis=1)
            if request.scalar_resources:
                # rare path: per-name dict walk, mirroring fits()
                for j, i in enumerate(bidx):
                    if fail[j]:
                        continue
                    nm = names[i]
                    salloc = self._scalar_alloc.get(nm) or {}
                    sused = self._scalar_req.get(nm) or {}
                    for k, v in request.scalar_resources.items():
                        if v > 0 and v > salloc.get(k, 0) - sused.get(k, 0):
                            fail[j] = True
                            break
            ok[bidx] = ~fail
            return ok

    def free_matrix(self, names) -> tuple[np.ndarray, np.ndarray]:
        """Aligned ``(bounded[N] bool, free[N,4] int64)`` COPIES for a
        column cache: callers may fold their own binds into ``free``
        (subtract a request row) without touching tracker state.
        Unknown/unreported rows come back unbounded (False, zeros)."""
        with self._lock:
            n = len(names)
            bounded = np.zeros((n,), dtype=bool)
            free = np.zeros((n, _N_DIMS), dtype=np.int64)
            if not self._names or n == 0:
                return bounded, free
            rows, known = self._rows_for_locked(names)
            kr = rows[known]
            bounded[known] = self._has_alloc[kr]
            free[known] = self._alloc[kr] - self._req[kr]
            return bounded, free

    def free_copy_counts(
        self, names: list, request: Resource
    ) -> np.ndarray:
        """How many copies of ``request`` fit on each node, vectorized
        and aligned with ``names`` — the gang solver's capacity row.
        Unreported/unknown nodes are UNBOUNDED; results clip to
        [0, UNBOUNDED]."""
        with self._lock:
            n = len(names)
            out = np.full((n,), UNBOUNDED, dtype=np.int64)
            if not self._names:
                return out
            rows, known = self._rows_for_locked(names)
            if not known.any():
                return out
            r = rows[known]
            bounded = self._has_alloc[r]
            if not bounded.any():
                return out
            free = self._alloc[r] - self._req[r]
            np.clip(free, 0, None, out=free)
            vec = _request_vec(request)
            counts = np.full((len(r),), UNBOUNDED, dtype=np.int64)
            for d in range(_N_DIMS):
                if vec[d] > 0:
                    np.minimum(counts, free[:, d] // vec[d], out=counts)
            if request.scalar_resources:
                # rare path: walk only nodes that reported scalars
                for j, nm_i in enumerate(r):
                    nm = self._names[nm_i]
                    salloc = self._scalar_alloc.get(nm) or {}
                    sused = self._scalar_req.get(nm) or {}
                    for k, v in request.scalar_resources.items():
                        if v > 0:
                            cap = max(0, salloc.get(k, 0) - sused.get(k, 0)) // v
                            if cap < counts[j]:
                                counts[j] = cap
            counts[~bounded] = UNBOUNDED
            out[known] = counts
            return out

    def free_for(self, node_name: str) -> dict | None:
        """Introspection: free amounts per dim, or None when the node is
        unknown or reports no allocatable (unbounded)."""
        with self._lock:
            i = self._index.get(node_name)
            if i is None or not self._has_alloc[i]:
                return None
            free = self._alloc[i] - self._req[i]
            return {
                CPU: int(free[_DIM_CPU]),
                MEMORY: int(free[_DIM_MEM]),
                EPHEMERAL_STORAGE: int(free[_DIM_EPH]),
                PODS: int(free[_DIM_PODS]),
            }

    def stats(self) -> dict:
        with self._lock:
            return {
                "tracked_nodes": len(self._names),
                "bounded_nodes": int(self._has_alloc.sum()),
                "full_recounts": self._full_recounts,
                "incremental_recounts": self._incremental_recounts,
                "node_patches": self._node_patches,
                "alloc_version": self.alloc_version,
                "mask_builds": self.mask_builds,
            }
