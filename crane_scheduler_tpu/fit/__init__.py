"""Vectorized resource-fit layer.

Columnar allocatable/requested accounting maintained incrementally off
the ``ClusterState`` mirror, exposed two ways:

- ``ResourceFitPlugin`` — a framework Filter predicate with stock
  NodeResourcesFit semantics (closes the over-commit gap in drip mode);
- ``FitTracker.free_copy_counts`` — per-node capacity rows feeding the
  gang solver in place of its ``1 << 30`` default.
"""

from .tracker import (
    UNBOUNDED,
    FitTracker,
    copy_counts_rows,
    pod_fit_request,
    request_vec,
    row_fail_reason,
)
from .plugin import PLUGIN_NAME, ResourceFitPlugin

__all__ = [
    "UNBOUNDED",
    "FitTracker",
    "copy_counts_rows",
    "pod_fit_request",
    "request_vec",
    "row_fail_reason",
    "ResourceFitPlugin",
    "PLUGIN_NAME",
]
