"""NodeResourcesFit-equivalent framework Filter.

ref: pkg/scheduler/framework/plugins/noderesources/fit.go — the stock
allocatable-capacity predicate the rebuilt framework lacked: without
it, drip mode happily binds onto a node with zero free CPU. No
daemonset bypass (stock has none); zero-request pods pass trivially on
every node that still has a pod slot.
"""

from __future__ import annotations

from ..cluster.state import Pod
from ..framework.types import CycleState, NodeInfo, Status
from .tracker import FitTracker, pod_fit_request

PLUGIN_NAME = "NodeResourcesFit"

_STATE_KEY = "fit/pod-request"


class ResourceFitPlugin:
    def __init__(self, tracker: FitTracker):
        self.tracker = tracker

    @property
    def name(self) -> str:
        return PLUGIN_NAME

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        if node_info.node is None:
            return Status.error("node not found")
        # compute the effective request once per cycle, not per node
        try:
            request = state.read(_STATE_KEY)
        except KeyError:
            self.tracker.refresh()
            request = pod_fit_request(pod)
            state.write(_STATE_KEY, request)
        ok, reason = self.tracker.fits(pod, node_info.node.name, request)
        if not ok:
            return Status.unschedulable(
                f"Node {node_info.node.name} fit failure: {reason}"
            )
        return Status.success()
