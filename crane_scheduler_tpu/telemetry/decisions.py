"""Sampled per-decision traces with bounded memory.

Answers "why did pod X land on node Y" after the fact (the question the
reference can only answer by replaying logs): each recorded decision
carries the filter verdict reasons, the top-k candidate scores, the
chosen node, and the staleness of the annotations the verdict consulted.
Gavel (arXiv:2008.09213) and Tesserae (arXiv:2508.04953) both lean on
exactly this per-decision visibility to validate policy behavior at
scale.

Memory is bounded two ways: a sampling stride (record every Nth
decision — the drip path is per pod, the batch path per burst) and a
fixed-capacity ring buffer (oldest evicted). Served by the scoring
sidecar's ``/debug/decisions`` endpoint.
"""

from __future__ import annotations

import threading
import time
from collections import deque


class DecisionTraceBuffer:
    def __init__(
        self,
        capacity: int = 512,
        sample_every: int = 1,
        clock=time.time,
    ):
        if capacity < 1 or sample_every < 1:
            raise ValueError("capacity and sample_every must be >= 1")
        self._buf: deque = deque(maxlen=int(capacity))
        self._lock = threading.Lock()
        self._clock = clock
        self.capacity = int(capacity)
        self.sample_every = int(sample_every)
        self.seen = 0  # decisions offered
        self.recorded = 0  # decisions kept (before ring eviction)

    def record(
        self,
        pod: str = "",
        node: str | None = None,
        reason: str = "",
        feasible: int = 0,
        top_scores=(),
        staleness_seconds: float = -1.0,
        source: str = "",
        **extra,
    ) -> bool:
        """Offer one decision; returns True when it was kept. The
        sampled-out fast path is one counter bump — callers may offer
        every decision unconditionally."""
        # GIL-serialized counter; a rare racy undercount only shifts
        # which decision the stride keeps, never unbounded memory
        self.seen += 1
        if (self.seen - 1) % self.sample_every:
            return False
        return self._append(
            pod, node, reason, feasible, top_scores, staleness_seconds,
            source, extra,
        )

    def offer(self, build) -> bool:
        """Like ``record`` but lazily: ``build()`` (returning ``record``'s
        kwargs) only runs when the sampling stride keeps the entry — the
        sampled-out fast path never pays for top-k extraction."""
        self.seen += 1
        if (self.seen - 1) % self.sample_every:
            return False
        kw = dict(build())
        extra = {
            k: kw.pop(k)
            for k in list(kw)
            if k not in (
                "pod", "node", "reason", "feasible", "top_scores",
                "staleness_seconds", "source",
            )
        }
        return self._append(
            kw.get("pod", ""),
            kw.get("node"),
            kw.get("reason", ""),
            kw.get("feasible", 0),
            kw.get("top_scores", ()),
            kw.get("staleness_seconds", -1.0),
            kw.get("source", ""),
            extra,
        )

    def _append(
        self, pod, node, reason, feasible, top_scores, staleness_seconds,
        source, extra,
    ) -> bool:
        entry = {
            "ts": self._clock(),
            "pod": pod,
            "node": node,
            "reason": reason,
            "feasible": int(feasible),
            "top_scores": [[str(n), int(s)] for n, s in top_scores],
            "staleness_seconds": round(float(staleness_seconds), 6),
            "source": source,
        }
        if extra:
            entry.update(extra)
        with self._lock:
            self.recorded += 1
            entry["seq"] = self.recorded  # flight-recorder drain cursor
            self._buf.append(entry)
        return True

    def drain_since(self, cursor: int) -> tuple[list[dict], int]:
        """Entries recorded after ``cursor`` (a seq from a prior call)
        plus the new cursor — the flight recorder's incremental pull."""
        with self._lock:
            new_cursor = self.recorded
            picked = [e for e in self._buf if e["seq"] > cursor]
        return picked, new_cursor

    def snapshot(self, limit: int | None = None) -> list[dict]:
        """Most recent decisions, oldest first; ``limit`` keeps the
        newest N."""
        with self._lock:
            entries = list(self._buf)
        if limit is not None and limit >= 0:
            entries = entries[-limit:]
        return entries

    def stats(self) -> dict:
        with self._lock:
            buffered = len(self._buf)
        return {
            "seen": self.seen,
            "recorded": self.recorded,
            "buffered": buffered,
            "capacity": self.capacity,
            "sample_every": self.sample_every,
        }
