"""Thread-safe metrics registry with Prometheus text exposition.

SURVEY §5: the reference exports *no* metrics — its only latency
visibility is log lines. This registry is the one measurement surface
every subsystem (scheduler, parallel, annotator, cluster, service)
writes into: Counter / Gauge / log-bucketed Histogram primitives with
labels, rendered in the Prometheus text exposition format (``# HELP`` /
``# TYPE``, ``_bucket``/``_sum``/``_count`` with cumulative ``le``
buckets) that real scrapers consume.

Design points:

- stdlib-only, no prometheus_client dependency (the container must not
  grow deps);
- get-or-create families (``registry.counter(...)`` twice returns the
  same object) so instrumented modules don't coordinate construction;
- per-child locks on the write path — hot-path cost is one lock and one
  float add, cheap enough that the bench's pipelined p99 budget (<3%
  overhead) holds;
- deterministic rendering (families and children sorted) so exposition
  output is golden-file testable.
"""

from __future__ import annotations

import math
import re
import threading
import time

_METRIC_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
# suffixes the histogram renderer owns; bare families must not collide
_RESERVED_SUFFIXES = ("_bucket", "_sum", "_count")


def log_buckets(start: float, factor: float, count: int) -> tuple[float, ...]:
    """Log-spaced histogram bounds: ``start * factor**i`` for i < count
    (the +Inf bucket is implicit)."""
    if start <= 0 or factor <= 1 or count < 1:
        raise ValueError("need start > 0, factor > 1, count >= 1")
    return tuple(start * factor**i for i in range(count))


# default latency buckets: 50us .. ~26s in x2 steps — wide enough for
# both a sub-ms device dispatch and a multi-second cold refresh
DEFAULT_LATENCY_BUCKETS = log_buckets(5e-5, 2.0, 20)


def format_value(v: float) -> str:
    """Exposition float rendering: integers without the trailing ``.0``
    (Go-style), ``+Inf``/``-Inf``/``NaN`` spelled the Prometheus way."""
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if math.isnan(v):
        return "NaN"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _escape_label_value(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _escape_help(v: str) -> str:
    return v.replace("\\", "\\\\").replace("\n", "\\n")


class _Child:
    """One labeled series; subclasses own the sample math."""

    __slots__ = ("_lock",)

    def __init__(self):
        self._lock = threading.Lock()


class CounterChild(_Child):
    __slots__ = ("_value",)

    def __init__(self):
        super().__init__()
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters can only increase")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class GaugeChild(_Child):
    __slots__ = ("_value",)

    def __init__(self):
        super().__init__()
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class HistogramChild(_Child):
    __slots__ = ("_bounds", "_counts", "_sum", "_total", "_exemplars")

    def __init__(self, bounds: tuple[float, ...]):
        super().__init__()
        self._bounds = bounds
        self._counts = [0] * len(bounds)
        self._sum = 0.0
        self._total = 0
        # OpenMetrics exemplars: bucket index -> (labels, value, unix ts);
        # index len(bounds) is the +Inf bucket. Lazy — the common
        # observe() path never allocates it.
        self._exemplars: dict | None = None

    def observe(self, value: float, exemplar: dict | None = None) -> None:
        with self._lock:
            self._sum += value
            self._total += 1
            idx = len(self._bounds)
            # linear scan beats bisect below ~30 bounds (no call overhead)
            for i, b in enumerate(self._bounds):
                if value <= b:
                    self._counts[i] += 1
                    idx = i
                    break
            if exemplar is not None:
                if self._exemplars is None:
                    self._exemplars = {}
                self._exemplars[idx] = (dict(exemplar), value, time.time())

    def snapshot(self) -> tuple[list[int], float, int]:
        """(per-bucket counts, sum, total count) — non-cumulative."""
        with self._lock:
            return list(self._counts), self._sum, self._total

    def snapshot_exemplars(self) -> dict:
        """Latest exemplar per bucket index (may be empty)."""
        with self._lock:
            return dict(self._exemplars) if self._exemplars else {}


class _Family:
    """One named metric family; children keyed on label-value tuples."""

    kind = ""

    def __init__(self, name: str, help: str, labelnames: tuple[str, ...]):
        if not _METRIC_NAME_RE.match(name):
            raise ValueError(f"invalid metric name: {name!r}")
        for ln in labelnames:
            if not _LABEL_NAME_RE.match(ln):
                raise ValueError(f"invalid label name: {ln!r}")
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._children: dict[tuple[str, ...], _Child] = {}
        self._lock = threading.Lock()

    def _new_child(self) -> _Child:
        raise NotImplementedError

    def labels(self, *values, **kv) -> _Child:
        if kv:
            if values:
                raise ValueError("pass label values positionally OR by name")
            values = tuple(str(kv[ln]) for ln in self.labelnames)
        else:
            values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, got {values}"
            )
        with self._lock:
            child = self._children.get(values)
            if child is None:
                child = self._children[values] = self._new_child()
            return child

    def _default(self) -> _Child:
        return self.labels()

    def children(self) -> list[tuple[tuple[str, ...], _Child]]:
        with self._lock:
            return sorted(self._children.items())

    def _label_str(self, values: tuple[str, ...], extra: str = "") -> str:
        parts = [
            f'{ln}="{_escape_label_value(lv)}"'
            for ln, lv in zip(self.labelnames, values)
        ]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""


class Counter(_Family):
    kind = "counter"

    def _new_child(self):
        return CounterChild()

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    @property
    def value(self) -> float:
        return self._default().value

    def render_into(self, out: list[str], openmetrics: bool = False) -> None:
        for values, child in self.children():
            out.append(
                f"{self.name}{self._label_str(values)} "
                f"{format_value(child.value)}"
            )


class Gauge(_Family):
    kind = "gauge"

    def _new_child(self):
        return GaugeChild()

    def set(self, value: float) -> None:
        self._default().set(value)

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default().dec(amount)

    @property
    def value(self) -> float:
        return self._default().value

    render_into = Counter.render_into


class Histogram(_Family):
    kind = "histogram"

    def __init__(self, name, help, labelnames, buckets=None):
        super().__init__(name, help, labelnames)
        bounds = tuple(float(b) for b in (buckets or DEFAULT_LATENCY_BUCKETS))
        if list(bounds) != sorted(set(bounds)):
            raise ValueError("histogram buckets must be strictly increasing")
        self.buckets = bounds

    def _new_child(self):
        return HistogramChild(self.buckets)

    def observe(self, value: float, exemplar: dict | None = None) -> None:
        self._default().observe(value, exemplar=exemplar)

    def time(self):
        """Context manager observing the block's wall seconds."""
        return _HistogramTimer(self._default())

    @staticmethod
    def _exemplar_str(ex) -> str:
        """OpenMetrics exemplar tail: `` # {labels} value timestamp``."""
        labels, value, ts = ex
        inner = ",".join(
            f'{k}="{_escape_label_value(str(v))}"'
            for k, v in sorted(labels.items())
        )
        return f" # {{{inner}}} {format_value(value)} {ts:.3f}"

    def render_into(self, out: list[str], openmetrics: bool = False) -> None:
        for values, child in self.children():
            counts, total_sum, total = child.snapshot()
            exemplars = child.snapshot_exemplars() if openmetrics else {}
            running = 0
            for i, (bound, c) in enumerate(zip(self.buckets, counts)):
                running += c
                le = f'le="{format_value(bound)}"'
                tail = ""
                if i in exemplars:
                    tail = self._exemplar_str(exemplars[i])
                out.append(
                    f"{self.name}_bucket{self._label_str(values, le)} "
                    f"{running}{tail}"
                )
            inf_label = 'le="+Inf"'
            inf_tail = ""
            if len(self.buckets) in exemplars:
                inf_tail = self._exemplar_str(exemplars[len(self.buckets)])
            out.append(
                f"{self.name}_bucket{self._label_str(values, inf_label)} "
                f"{total}{inf_tail}"
            )
            out.append(
                f"{self.name}_sum{self._label_str(values)} "
                f"{format_value(total_sum)}"
            )
            out.append(f"{self.name}_count{self._label_str(values)} {total}")


class _HistogramTimer:
    __slots__ = ("_child", "_start")

    def __init__(self, child: HistogramChild):
        self._child = child

    def __enter__(self):
        import time

        self._start = time.perf_counter()
        return self

    def __exit__(self, *exc):
        import time

        self._child.observe(time.perf_counter() - self._start)
        return False


class MetricsRegistry:
    """Named families, get-or-create, rendered deterministically."""

    def __init__(self):
        self._families: dict[str, _Family] = {}
        self._lock = threading.Lock()

    def _get_or_create(self, cls, name, help, labelnames, **kw) -> _Family:
        labelnames = tuple(labelnames)
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if not isinstance(fam, cls) or fam.labelnames != labelnames:
                    raise ValueError(
                        f"metric {name!r} already registered with a "
                        f"different type or label set"
                    )
                return fam
            for fname in self._families:
                # a histogram's rendered suffixes must not collide with
                # an existing bare family (and vice versa)
                for suffix in _RESERVED_SUFFIXES:
                    if cls is Histogram and fname == name + suffix:
                        raise ValueError(
                            f"histogram {name!r} collides with {fname!r}"
                        )
                    if (
                        isinstance(self._families[fname], Histogram)
                        and name == fname + suffix
                    ):
                        raise ValueError(
                            f"metric {name!r} collides with histogram "
                            f"{fname!r}"
                        )
            fam = self._families[name] = cls(name, help, labelnames, **kw)
            return fam

    def counter(self, name: str, help: str = "", labelnames=()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "", labelnames=()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(
        self, name: str, help: str = "", labelnames=(), buckets=None
    ) -> Histogram:
        return self._get_or_create(
            Histogram, name, help, labelnames, buckets=buckets
        )

    def families(self) -> list[_Family]:
        with self._lock:
            return [self._families[k] for k in sorted(self._families)]

    def render(self, openmetrics: bool = False) -> str:
        """Prometheus text exposition (version 0.0.4), or the OpenMetrics
        variant (``openmetrics=True``): histogram buckets carry their
        latest exemplar and the payload ends with ``# EOF``."""
        out: list[str] = []
        for fam in self.families():
            if fam.help:
                out.append(f"# HELP {fam.name} {_escape_help(fam.help)}")
            out.append(f"# TYPE {fam.name} {fam.kind}")
            fam.render_into(out, openmetrics=openmetrics)
        if openmetrics:
            out.append("# EOF")
        return "\n".join(out) + "\n" if out else ""

    def snapshot(self) -> dict:
        """Flat ``{series: value}`` view (bench/JSON artifacts); histogram
        families contribute ``_sum``/``_count`` only."""
        flat: dict[str, float] = {}
        for fam in self.families():
            for values, child in fam.children():
                series = fam.name + fam._label_str(values)
                if isinstance(child, HistogramChild):
                    _, s, n = child.snapshot()
                    flat[fam.name + "_sum" + fam._label_str(values)] = s
                    flat[fam.name + "_count" + fam._label_str(values)] = n
                else:
                    flat[series] = child.value
        return flat
