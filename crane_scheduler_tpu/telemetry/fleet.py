"""Fleet observability plane (ISSUE 17): federation, SLOs, anomalies.

The crane fleet is N cooperating processes (annotator, sharded
schedulers, scoring primary, serving replicas, router, descheduler)
that talk only through the apiserver — and, until this module, each
exposed an isolated ``/metrics``. Nobody could answer "is the *fleet*
meeting its placement SLO" without hand-stitching ten scrapes. Three
layers, one module:

- **MetricsFederator** — scrapes every fleet process's ``/metrics``
  with the strict expfmt parser, merges the families into one union
  under injected ``role``/``process`` labels, and re-exposes it on
  ``/fleet/metrics``. Merge semantics: counter-family samples (and
  histogram ``_bucket``/``_sum``/``_count``, which are counters too)
  are reset-adjusted via per-series monotonicity tracking, so a
  restarted replica never produces a negative rate downstream; gauges
  are last-scraped-wins; a family whose declared TYPE conflicts across
  processes is **quarantined** — removed from the union, counted in
  ``crane_fleet_quarantined_families`` and listed in ``status()``,
  never dropped silently.

- **SLOEngine** — multi-window burn rates (5m/1h fast, 6h/3d slow by
  default) over good/bad event counts derived from the federated
  families: placement e2e latency (PR 9 histograms), serving goodput
  vs shed ratio (PR 13), replication lag vs budget (PR 15), shard
  conflict rate (PR 14), and fleet scrape availability. Per-objective
  alert state machines (ok -> warning -> page, hysteresis on clear)
  exported as ``crane_slo_burn_rate{objective,window}``,
  ``crane_slo_budget_remaining{objective}`` and
  ``crane_slo_alert_state{objective}``, served as JSON at ``/v1/slo``.
  The engine is driven by an injected clock: seeded tests and bench
  config 20 tick it deterministically.

- **Anomaly detectors** — breaker flapping (transition rate over a
  sliding tick window), degraded-mode dwell (consecutive seconds with
  ``crane_degraded_mode`` raised anywhere in the fleet), and
  replication-lag trend (EWMA of the lag plus an EWMA'd slope over the
  injected clock). Exported as ``crane_fleet_anomaly{kind}`` and
  listed in the ``/v1/slo`` payload.

``FleetPlane`` bundles the three behind one ``tick()`` plus an
optional wall-clock pump thread, and is what ``service.http`` wires
behind ``/fleet/metrics`` and ``/v1/slo``. Stdlib-only, no sockets in
the core: the fetch function is injected (tests pass canned text, the
plane passes an HTTP fetcher).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from .expfmt import ExpositionError, parse_exposition
from .registry import MetricsRegistry, format_value

# ---------------------------------------------------------------------------
# process identity (satellite: crane_build_info)
# ---------------------------------------------------------------------------

_role_lock = threading.Lock()
_process_role = "unknown"


def set_process_role(role: str) -> None:
    """Record this process's fleet role (scorer, scheduler, annotator,
    descheduler, replica, router, sim...). Read back by the /debug
    envelopes and by ``register_build_info``."""
    global _process_role
    with _role_lock:
        _process_role = str(role)


def process_role() -> str:
    with _role_lock:
        return _process_role


def register_build_info(registry: MetricsRegistry, role: str,
                        version: str | None = None, *,
                        set_role: bool = True):
    """Register the ``crane_build_info{role,version}`` identity gauge
    every CLI entrypoint exports, so federated scrapes and crane-top
    can label processes without out-of-band config. Also records the
    role process-globally unless ``set_role=False`` (in-process
    replicas/routers riding inside another role's process). Returns the
    gauge child (value pinned 1)."""
    if version is None:
        from .. import __version__ as version
    if set_role:
        set_process_role(role)
    gauge = registry.gauge(
        "crane_build_info",
        "Process identity: constant 1, labeled with fleet role and "
        "build version",
        labelnames=("role", "version"),
    )
    child = gauge.labels(role=role, version=version)
    child.set(1)
    return child


# ---------------------------------------------------------------------------
# federation
# ---------------------------------------------------------------------------


@dataclass
class ScrapeTarget:
    """One fleet process's scrape endpoint. ``role=None`` means "learn
    it from the process's own crane_build_info gauge" (falling back to
    the target name)."""

    name: str
    host: str = "127.0.0.1"
    port: int = 0
    path: str = "/metrics"
    role: str | None = None
    # tests / in-process targets: fetch() -> exposition text overrides
    # the HTTP scrape entirely
    fetch: object | None = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}{self.path}"


# labels the federator owns on every sample it re-exposes
_META_LABELS = ("role", "process")


def _http_fetch(target: ScrapeTarget, timeout_s: float) -> str:
    from http.client import HTTPConnection

    conn = HTTPConnection(target.host, target.port, timeout=timeout_s)
    try:
        conn.request(
            "GET", target.path,
            headers={"Accept": "text/plain;version=0.0.4"},
        )
        resp = conn.getresponse()
        body = resp.read()
        if resp.status != 200:
            raise OSError(f"{target.url}: HTTP {resp.status}")
        return body.decode("utf-8", "replace")
    finally:
        conn.close()


class _SeriesState:
    """Reset-adjusted cumulative value for one counter-kind series."""

    __slots__ = ("last_raw", "offset", "resets")

    def __init__(self):
        self.last_raw = 0.0
        self.offset = 0.0
        self.resets = 0

    def update(self, raw: float) -> float:
        if raw < self.last_raw:
            # the process restarted (or the family was re-created):
            # fold the pre-reset total into the offset so the adjusted
            # series stays monotone and rates never go negative
            self.offset += self.last_raw
            self.resets += 1
        self.last_raw = raw
        return self.offset + raw


class MetricsFederator:
    """Scrape + merge + re-expose. All methods are safe to call from
    one pump thread plus any number of render/aggregate readers."""

    def __init__(
        self,
        targets=(),
        *,
        timeout_s: float = 5.0,
        registry: MetricsRegistry | None = None,
    ):
        self.targets: list[ScrapeTarget] = list(targets)
        self.timeout_s = float(timeout_s)
        self._lock = threading.Lock()
        # family -> {"type", "help"}
        self._families: dict[str, dict] = {}
        # family -> {(name, labels): value} (labels include role/process)
        self._values: dict[str, dict] = {}
        # counter adjustment state: (family, name, labels) -> _SeriesState
        self._series: dict[tuple, _SeriesState] = {}
        # family -> reason, counted and listed, never silent
        self.quarantined: dict[str, str] = {}
        self._last_outcome: dict[str, str] = {}
        self._scrapes = {"ok": 0, "error": 0, "invalid": 0}
        self._last_scrape_s = 0.0
        self._roles: dict[str, str] = {}
        # optional self-metrics in a host registry (the primary's)
        self._m_scrapes = self._m_quarantined = self._m_duration = None
        self._m_targets = None
        if registry is not None:
            self._m_scrapes = registry.counter(
                "crane_fleet_scrapes_total",
                "Federated scrape attempts by process and outcome",
                labelnames=("process", "outcome"),
            )
            self._m_quarantined = registry.gauge(
                "crane_fleet_quarantined_families",
                "Families excluded from /fleet/metrics because their "
                "declared TYPE conflicts across processes",
            )
            self._m_duration = registry.gauge(
                "crane_fleet_scrape_seconds",
                "Wall seconds the last full federation pass took",
            )
            self._m_targets = registry.gauge(
                "crane_fleet_targets", "Configured scrape targets"
            )
            self._m_targets.set(len(self.targets))

    def add_target(self, target: ScrapeTarget) -> None:
        with self._lock:
            self.targets.append(target)
            if self._m_targets is not None:
                self._m_targets.set(len(self.targets))

    # -- scraping -----------------------------------------------------------

    def scrape_once(self) -> dict:
        """One federation pass over every target. Returns a summary:
        ``{"ok": [...], "failed": {name: reason}, "quarantined": [...]}``.
        A target that fails to scrape or strict-parse keeps its previous
        samples (stale beats absent for cumulative series) but is
        reported failed — the availability objective counts it bad."""
        t0 = time.perf_counter()
        ok: list[str] = []
        failed: dict[str, str] = {}
        for target in list(self.targets):
            try:
                if target.fetch is not None:
                    text = target.fetch()
                else:
                    text = _http_fetch(target, self.timeout_s)
            except Exception as exc:
                failed[target.name] = f"scrape: {type(exc).__name__}"
                self._record_outcome(target.name, "error")
                continue
            try:
                families = parse_exposition(text)
            except ExpositionError as exc:
                failed[target.name] = f"parse: {exc}"
                self._record_outcome(target.name, "invalid")
                continue
            self._merge(target, families)
            ok.append(target.name)
            self._record_outcome(target.name, "ok")
        with self._lock:
            self._last_scrape_s = time.perf_counter() - t0
            if self._m_duration is not None:
                self._m_duration.set(self._last_scrape_s)
            if self._m_quarantined is not None:
                self._m_quarantined.set(len(self.quarantined))
        return {
            "ok": ok,
            "failed": failed,
            "quarantined": sorted(self.quarantined),
        }

    def _record_outcome(self, name: str, outcome: str) -> None:
        with self._lock:
            self._last_outcome[name] = outcome
            self._scrapes[outcome if outcome in self._scrapes else "error"] \
                = self._scrapes.get(outcome, 0) + 1
        if self._m_scrapes is not None:
            self._m_scrapes.labels(process=name, outcome=outcome).inc()

    def _merge(self, target: ScrapeTarget, families: dict) -> None:
        role = target.role
        if role is None:
            # satellite: learn the role from the process's own
            # crane_build_info gauge; fall back to the target name
            info = families.get("crane_build_info")
            if info:
                for _, labels, value in info["samples"]:
                    if value:
                        role = dict(labels).get("role")
                        break
            role = role or target.name
        with self._lock:
            self._roles[target.name] = role
            for fam, doc in families.items():
                kind = doc["type"]
                known = self._families.get(fam)
                if fam in self.quarantined:
                    continue
                if known is None:
                    self._families[fam] = {"type": kind, "help": doc["help"]}
                    self._values[fam] = {}
                elif known["type"] != kind:
                    # conflicting declared types: quarantine the whole
                    # family (both sides) — counted, listed, never silent
                    self.quarantined[fam] = (
                        f"type conflict: {known['type']} vs {kind} "
                        f"(from {target.name})"
                    )
                    self._values.pop(fam, None)
                    continue
                counterish = kind in ("counter", "histogram", "summary")
                out = self._values[fam]
                # drop this process's previous samples for the family:
                # a label set that disappears upstream must not linger
                stale = [
                    key for key in out
                    if dict(key[1]).get("process") == target.name
                ]
                for key in stale:
                    del out[key]
                for name, labels, value in doc["samples"]:
                    merged = tuple(
                        lv for lv in labels if lv[0] not in _META_LABELS
                    ) + (("role", role), ("process", target.name))
                    if counterish:
                        skey = (fam, name, merged)
                        state = self._series.get(skey)
                        if state is None:
                            state = self._series[skey] = _SeriesState()
                        value = state.update(value)
                    out[(name, merged)] = value

    # -- re-exposure --------------------------------------------------------

    def render(self) -> str:
        """The union exposition: every process's families under
        ``role``/``process`` labels, deterministically ordered, valid
        under the strict parser (histogram series keep their buckets
        numerically le-sorted with ``_sum``/``_count`` trailing — the
        order ``_validate_histograms`` requires)."""
        with self._lock:
            out: list[str] = []
            for fam in sorted(self._families):
                if fam in self.quarantined:
                    continue
                meta = self._families[fam]
                if meta["help"]:
                    out.append(f"# HELP {fam} {meta['help']}")
                out.append(f"# TYPE {fam} {meta['type']}")
                values = self._values.get(fam, {})
                if meta["type"] == "histogram":
                    out.extend(self._render_histogram_locked(fam, values))
                    continue
                for (name, labels), value in sorted(values.items()):
                    out.append(
                        f"{name}{_render_labels(labels)} "
                        f"{format_value(value)}"
                    )
            return "\n".join(out) + "\n" if out else ""

    @staticmethod
    def _render_histogram_locked(fam: str, values: dict) -> list[str]:
        # group by the non-le label set, emit numerically-sorted
        # buckets then _sum then _count per group
        groups: dict[tuple, dict] = {}
        for (name, labels), value in values.items():
            base = tuple(lv for lv in labels if lv[0] != "le")
            entry = groups.setdefault(
                base, {"buckets": [], "sum": None, "count": None}
            )
            if name == fam + "_bucket":
                le = dict(labels).get("le", "+Inf")
                bound = float("inf") if le in ("+Inf", "Inf") else float(le)
                entry["buckets"].append((bound, le, value))
            elif name == fam + "_sum":
                entry["sum"] = value
            elif name == fam + "_count":
                entry["count"] = value
        out = []
        for base in sorted(groups):
            entry = groups[base]
            for _, le, value in sorted(
                entry["buckets"], key=lambda b: b[0]
            ):
                labels = base + (("le", le),)
                out.append(
                    f"{fam}_bucket{_render_labels(labels)} "
                    f"{format_value(value)}"
                )
            if entry["sum"] is not None:
                out.append(
                    f"{fam}_sum{_render_labels(base)} "
                    f"{format_value(entry['sum'])}"
                )
            if entry["count"] is not None:
                out.append(
                    f"{fam}_count{_render_labels(base)} "
                    f"{format_value(entry['count'])}"
                )
        return out

    # -- aggregate readers (the SLO engine's diet) --------------------------

    def counter_total(self, name: str, **label_filter) -> float:
        """Sum of a counter-kind sample's reset-adjusted values across
        the fleet, optionally filtered by label equality. ``name`` may
        be a plain counter family (``crane_shard_binds_total``) or a
        histogram child (``crane_service_request_seconds_count``, whose
        family is the suffix-stripped base name)."""
        candidates = [name]
        for suffix in ("_count", "_sum", "_bucket"):
            if name.endswith(suffix):
                candidates.append(name[: -len(suffix)])
        with self._lock:
            total = 0.0
            for fam in candidates:
                values = self._values.get(fam)
                if values is None:
                    continue
                for (sname, labels), value in values.items():
                    if sname != name:
                        continue
                    if _matches(labels, label_filter):
                        total += value
                break
            return total

    def histogram_agg(self, family: str, **label_filter):
        """Bucket-wise merge of a histogram family across processes:
        ``(sorted [(le, cumulative_count)], sum, count)`` — the
        fleet-level distribution the latency SLO burns against. Returns
        None when no process exposes the family yet."""
        with self._lock:
            if self._families.get(family, {}).get("type") != "histogram":
                return None
            buckets: dict[float, float] = {}
            total_sum = 0.0
            total_count = 0.0
            seen = False
            for (name, labels), value in self._values.get(family, {}).items():
                if not _matches(labels, label_filter):
                    continue
                if name == family + "_bucket":
                    le = dict(labels).get("le")
                    if le is None:
                        continue
                    bound = float("inf") if le in ("+Inf", "Inf") else float(le)
                    buckets[bound] = buckets.get(bound, 0.0) + value
                    seen = True
                elif name == family + "_sum":
                    total_sum += value
                elif name == family + "_count":
                    total_count += value
            if not seen:
                return None
            return sorted(buckets.items()), total_sum, total_count

    def gauge_values(self, family: str, **label_filter) -> list[tuple[dict, float]]:
        """Every (labels-dict, value) sample of a gauge family."""
        with self._lock:
            out = []
            for (name, labels), value in self._values.get(family, {}).items():
                if name == family and _matches(labels, label_filter):
                    out.append((dict(labels), value))
            return out

    def availability(self) -> tuple[int, int]:
        """(targets whose last scrape succeeded, configured targets)."""
        with self._lock:
            ok = sum(
                1 for t in self.targets
                if self._last_outcome.get(t.name) == "ok"
            )
            return ok, len(self.targets)

    def reset_count(self) -> int:
        with self._lock:
            return sum(s.resets for s in self._series.values())

    def status(self) -> dict:
        with self._lock:
            return {
                "targets": [
                    {
                        "name": t.name,
                        "role": self._roles.get(t.name, t.role),
                        "url": None if t.fetch is not None else t.url,
                        "lastOutcome": self._last_outcome.get(t.name),
                    }
                    for t in self.targets
                ],
                "scrapes": dict(self._scrapes),
                "families": len(self._families) - len(self.quarantined),
                "quarantined": dict(self.quarantined),
                "counterResets": sum(
                    s.resets for s in self._series.values()
                ),
                "lastScrapeSeconds": self._last_scrape_s,
            }


def _render_labels(labels: tuple) -> str:
    if not labels:
        return ""
    parts = []
    for k, v in labels:
        v = str(v).replace("\\", "\\\\").replace('"', '\\"')
        v = v.replace("\n", "\\n")
        parts.append(f'{k}="{v}"')
    return "{" + ",".join(parts) + "}"


def _matches(labels: tuple, label_filter: dict) -> bool:
    if not label_filter:
        return True
    have = dict(labels)
    return all(have.get(k) == v for k, v in label_filter.items())


# ---------------------------------------------------------------------------
# SLO engine
# ---------------------------------------------------------------------------

ALERT_OK, ALERT_WARNING, ALERT_PAGE = 0, 1, 2
_ALERT_NAMES = {ALERT_OK: "ok", ALERT_WARNING: "warning", ALERT_PAGE: "page"}

# the classic multi-window pairs (seconds): both windows of a pair must
# burn hot before the state machine moves, so a blip can't page
DEFAULT_FAST_WINDOWS = (300.0, 3600.0)      # 5m / 1h
DEFAULT_SLOW_WINDOWS = (21600.0, 259200.0)  # 6h / 3d


@dataclass
class SLOObjective:
    """One objective: a ``sample()`` closure returning cumulative
    ``(good, bad)`` event counts (monotone; the engine differences them
    over windows), the objective fraction, and alert thresholds."""

    name: str
    sample: object
    objective: float = 0.999
    warn_burn: float = 2.0
    page_burn: float = 14.4
    # hysteresis: this many consecutive ticks below clear_ratio * the
    # threshold before the state steps DOWN one level
    clear_ticks: int = 3
    clear_ratio: float = 0.5
    description: str = ""


class _ObjectiveState:
    __slots__ = ("history", "state", "clear_streak", "transitions")

    def __init__(self):
        self.history: list[tuple[float, float, float]] = []  # (t, good, bad)
        self.state = ALERT_OK
        self.clear_streak = 0
        self.transitions: list[dict] = []


class SLOEngine:
    """Burn-rate computation + alert state machines over federated
    counts. Fully deterministic: every time-dependent step goes through
    ``tick(now)`` with an injected ``now``."""

    def __init__(
        self,
        federator: MetricsFederator,
        objectives=None,
        *,
        registry: MetricsRegistry | None = None,
        fast_windows=DEFAULT_FAST_WINDOWS,
        slow_windows=DEFAULT_SLOW_WINDOWS,
        placement_target_s: float = 5.0,
        lag_budget_versions: int = 8,
    ):
        self.federator = federator
        self.fast_windows = tuple(float(w) for w in fast_windows)
        self.slow_windows = tuple(float(w) for w in slow_windows)
        self.placement_target_s = float(placement_target_s)
        self.lag_budget_versions = int(lag_budget_versions)
        self.objectives: list[SLOObjective] = (
            list(objectives) if objectives is not None
            else self._default_objectives()
        )
        self._states = {o.name: _ObjectiveState() for o in self.objectives}
        self._tick = 0
        self._last_now: float | None = None
        self._lock = threading.Lock()
        self._m_burn = self._m_budget = self._m_state = None
        if registry is not None:
            self._m_burn = registry.gauge(
                "crane_slo_burn_rate",
                "Error-budget burn rate per objective and window "
                "(1.0 = consuming the budget exactly)",
                labelnames=("objective", "window"),
            )
            self._m_budget = registry.gauge(
                "crane_slo_budget_remaining",
                "Fraction of the error budget left over the longest "
                "window (negative = overspent)",
                labelnames=("objective",),
            )
            self._m_state = registry.gauge(
                "crane_slo_alert_state",
                "Alert state per objective (0 ok, 1 warning, 2 page)",
                labelnames=("objective",),
            )

    # -- default objective set ---------------------------------------------

    def _default_objectives(self) -> list[SLOObjective]:
        fed = self.federator
        target = self.placement_target_s
        lag_budget = self.lag_budget_versions

        def placement():
            agg = fed.histogram_agg("crane_placement_e2e_seconds")
            if agg is None:
                return 0.0, 0.0
            buckets, _, count = agg
            good = 0.0
            for le, cum in buckets:
                if le <= target:
                    good = cum  # cumulative: the largest qualifying bound
            return good, max(0.0, count - good)

        def goodput():
            served = fed.counter_total("crane_service_request_seconds_count")
            shed = fed.counter_total("crane_service_shed_total")
            return served, shed

        # replication lag and availability are gauge/target-state
        # derived: each tick contributes one good-or-bad event per
        # replica / target, so the burn windows see a rate
        lag_events = {"good": 0.0, "bad": 0.0}

        def replication():
            for family in ("crane_replica_lag_versions",
                           "crane_router_replica_lag_versions"):
                samples = fed.gauge_values(family)
                if samples:
                    for _, lag in samples:
                        if lag > lag_budget:
                            lag_events["bad"] += 1
                        else:
                            lag_events["good"] += 1
                    break
            return lag_events["good"], lag_events["bad"]

        def shards():
            conflicts = fed.counter_total("crane_shard_conflicts_total")
            binds = fed.counter_total("crane_shard_binds_total")
            return binds, conflicts

        avail_events = {"good": 0.0, "bad": 0.0}

        def availability():
            ok, total = fed.availability()
            avail_events["good"] += ok
            avail_events["bad"] += total - ok
            return avail_events["good"], avail_events["bad"]

        return [
            SLOObjective(
                "placement_latency", placement, objective=0.99,
                description=f"pod e2e placement <= {target:g}s "
                            "(crane_placement_e2e_seconds)",
            ),
            SLOObjective(
                "serving_goodput", goodput, objective=0.999,
                description="served vs shed requests "
                            "(crane_service_shed_total)",
            ),
            SLOObjective(
                "replication_lag", replication, objective=0.99,
                description=f"replica lag <= {lag_budget} versions "
                            "per probe tick",
            ),
            SLOObjective(
                "shard_conflicts", shards, objective=0.95,
                description="optimistic shard binds vs conflicts "
                            "(crane_shard_conflicts_total)",
            ),
            SLOObjective(
                "scrape_availability", availability, objective=0.99,
                warn_burn=1.0, page_burn=10.0,
                description="fleet processes answering their scrape",
            ),
        ]

    # -- the tick -----------------------------------------------------------

    def tick(self, now: float | None = None) -> dict:
        """Sample every objective, recompute burns, advance the alert
        state machines. Returns the same payload ``status()`` serves."""
        if now is None:
            now = time.time()
        with self._lock:
            self._tick += 1
            self._last_now = float(now)
            horizon = max(self.slow_windows) if self.slow_windows else 0.0
            for obj in self.objectives:
                st = self._states[obj.name]
                good, bad = obj.sample()
                st.history.append((float(now), float(good), float(bad)))
                # bound memory: one sample older than the horizon is
                # kept as the window anchor
                cutoff = float(now) - horizon
                while len(st.history) > 2 and st.history[1][0] <= cutoff:
                    st.history.pop(0)
                self._advance(obj, st)
            return self._status_locked()

    def _burn_over(self, st: _ObjectiveState, window: float,
                   objective: float) -> float | None:
        """bad-fraction over ``window`` divided by the error budget;
        None while the window has no events yet."""
        if not st.history:
            return None
        now, good_now, bad_now = st.history[-1]
        anchor = st.history[0]
        for sample in st.history:
            if sample[0] >= now - window:
                break
            anchor = sample
        d_good = good_now - anchor[1]
        d_bad = bad_now - anchor[2]
        total = d_good + d_bad
        if total <= 0:
            return None
        budget = max(1e-9, 1.0 - objective)
        return (d_bad / total) / budget

    def _advance(self, obj: SLOObjective, st: _ObjectiveState) -> None:
        fast = [
            self._burn_over(st, w, obj.objective) for w in self.fast_windows
        ]
        slow = [
            self._burn_over(st, w, obj.objective) for w in self.slow_windows
        ]

        def hot(burns, threshold):
            return (
                bool(burns)
                and all(b is not None and b > threshold for b in burns)
            )

        target_state = st.state
        if hot(fast, obj.page_burn):
            target_state = ALERT_PAGE
        elif hot(fast, obj.warn_burn) or hot(slow, obj.warn_burn):
            target_state = max(st.state, ALERT_WARNING) \
                if st.state == ALERT_PAGE else ALERT_WARNING
        if target_state > st.state:
            self._transition(obj, st, target_state)
            st.clear_streak = 0
            return
        # hysteresis on clear: step DOWN one level only after
        # clear_ticks consecutive quiet ticks
        if st.state > ALERT_OK:
            threshold = (
                obj.page_burn if st.state == ALERT_PAGE else obj.warn_burn
            )
            quiet = all(
                b is None or b < threshold * obj.clear_ratio for b in fast
            )
            if quiet:
                st.clear_streak += 1
                if st.clear_streak >= obj.clear_ticks:
                    self._transition(obj, st, st.state - 1)
                    st.clear_streak = 0
            else:
                st.clear_streak = 0

    def _transition(self, obj: SLOObjective, st: _ObjectiveState,
                    to: int) -> None:
        st.transitions.append({
            "objective": obj.name,
            "from": _ALERT_NAMES[st.state],
            "to": _ALERT_NAMES[to],
            "tick": self._tick,
            "at": self._last_now,
        })
        st.state = to

    # -- export -------------------------------------------------------------

    def _window_name(self, seconds: float) -> str:
        if seconds % 3600 == 0:
            return f"{int(seconds // 3600)}h"
        if seconds % 60 == 0:
            return f"{int(seconds // 60)}m"
        return f"{seconds:g}s"

    def _status_locked(self) -> dict:
        objectives = {}
        for obj in self.objectives:
            st = self._states[obj.name]
            burns = {}
            for w in self.fast_windows + self.slow_windows:
                burns[self._window_name(w)] = self._burn_over(
                    st, w, obj.objective
                )
            longest = max(self.slow_windows) if self.slow_windows else None
            budget_remaining = None
            if longest is not None:
                burn = self._burn_over(st, longest, obj.objective)
                if burn is not None:
                    budget_remaining = 1.0 - burn
            objectives[obj.name] = {
                "objective": obj.objective,
                "description": obj.description,
                "state": _ALERT_NAMES[st.state],
                "burnRates": burns,
                "budgetRemaining": budget_remaining,
                "transitions": list(st.transitions),
            }
            if self._m_state is not None:
                self._m_state.labels(objective=obj.name).set(st.state)
                for wname, burn in burns.items():
                    if burn is not None:
                        self._m_burn.labels(
                            objective=obj.name, window=wname
                        ).set(burn)
                if budget_remaining is not None:
                    self._m_budget.labels(objective=obj.name).set(
                        budget_remaining
                    )
        return {
            "tick": self._tick,
            "now": self._last_now,
            "fastWindows": [self._window_name(w) for w in self.fast_windows],
            "slowWindows": [self._window_name(w) for w in self.slow_windows],
            "objectives": objectives,
        }

    def status(self) -> dict:
        with self._lock:
            return self._status_locked()

    def alert_state(self, objective: str) -> str:
        with self._lock:
            return _ALERT_NAMES[self._states[objective].state]

    def timeline(self) -> list[tuple[str, str, str]]:
        """The deterministic transition sequence — ``(objective, from,
        to)`` in occurrence order, timestamps stripped. Bench config 20
        compares this across same-seed runs."""
        with self._lock:
            events = []
            for obj in self.objectives:
                for tr in self._states[obj.name].transitions:
                    events.append(
                        (tr["tick"], tr["objective"], tr["from"], tr["to"])
                    )
            events.sort()
            return [(o, f, t) for _, o, f, t in events]


# ---------------------------------------------------------------------------
# anomaly detectors
# ---------------------------------------------------------------------------


class TrendDetector:
    """EWMA level + EWMA slope over an injected clock. Anomalous after
    ``min_ticks`` consecutive ticks with the smoothed slope above
    ``slope_per_s`` — the replication-lag trend detector ("lag is not
    just high, it is *growing*")."""

    def __init__(self, *, alpha: float = 0.3, slope_per_s: float = 1.0,
                 min_ticks: int = 3):
        self.alpha = float(alpha)
        self.slope_per_s = float(slope_per_s)
        self.min_ticks = int(min_ticks)
        self.level: float | None = None
        self.slope = 0.0
        self._last: tuple[float, float] | None = None
        self.streak = 0
        self.anomalous = False

    def update(self, now: float, value: float) -> bool:
        if self.level is None:
            self.level = value
        else:
            self.level += self.alpha * (value - self.level)
        if self._last is not None:
            dt = now - self._last[0]
            if dt > 0:
                inst = (value - self._last[1]) / dt
                self.slope += self.alpha * (inst - self.slope)
        self._last = (now, value)
        if self.slope > self.slope_per_s:
            self.streak += 1
        else:
            self.streak = 0
        self.anomalous = self.streak >= self.min_ticks
        return self.anomalous


class FlapDetector:
    """Transition-rate window over a cumulative transitions counter:
    anomalous when more than ``max_flaps`` transitions land inside
    ``window_s`` — the breaker-flapping detector."""

    def __init__(self, *, window_s: float = 60.0, max_flaps: int = 4):
        self.window_s = float(window_s)
        self.max_flaps = int(max_flaps)
        self._events: list[tuple[float, float]] = []  # (t, cumulative)
        self.anomalous = False
        self.flaps_in_window = 0.0

    def update(self, now: float, cumulative: float) -> bool:
        self._events.append((now, cumulative))
        while (
            len(self._events) > 2
            and self._events[1][0] <= now - self.window_s
        ):
            self._events.pop(0)
        anchor = self._events[0]
        for ev in self._events:
            if ev[0] >= now - self.window_s:
                break
            anchor = ev
        self.flaps_in_window = max(0.0, cumulative - anchor[1])
        self.anomalous = self.flaps_in_window > self.max_flaps
        return self.anomalous


class DwellDetector:
    """Consecutive-seconds-in-state accumulator: anomalous once the
    fleet has dwelt in the raised state longer than ``max_dwell_s`` —
    degraded mode is designed to be transient; an hour of it is an
    incident even if no single tick looks alarming."""

    def __init__(self, *, max_dwell_s: float = 300.0):
        self.max_dwell_s = float(max_dwell_s)
        self._raised_at: float | None = None
        self.dwell_s = 0.0
        self.anomalous = False

    def update(self, now: float, raised: bool) -> bool:
        if not raised:
            self._raised_at = None
            self.dwell_s = 0.0
        else:
            if self._raised_at is None:
                self._raised_at = now
            self.dwell_s = now - self._raised_at
        self.anomalous = self.dwell_s > self.max_dwell_s
        return self.anomalous


class FleetAnomalies:
    """The fleet's detector set, fed from federated families each
    ``tick(now)``; exported as ``crane_fleet_anomaly{kind}``."""

    KINDS = ("breaker_flapping", "degraded_dwell", "replication_lag_trend")

    def __init__(
        self,
        federator: MetricsFederator,
        *,
        registry: MetricsRegistry | None = None,
        breaker_window_s: float = 60.0,
        breaker_max_flaps: int = 4,
        degraded_max_dwell_s: float = 300.0,
        lag_slope_per_s: float = 1.0,
        lag_min_ticks: int = 3,
    ):
        self.federator = federator
        self.flap = FlapDetector(
            window_s=breaker_window_s, max_flaps=breaker_max_flaps
        )
        self.dwell = DwellDetector(max_dwell_s=degraded_max_dwell_s)
        self.trend = TrendDetector(
            slope_per_s=lag_slope_per_s, min_ticks=lag_min_ticks
        )
        self._m_anomaly = None
        if registry is not None:
            self._m_anomaly = registry.gauge(
                "crane_fleet_anomaly",
                "Fleet anomaly detectors (1 = firing)",
                labelnames=("kind",),
            )

    def tick(self, now: float | None = None) -> dict:
        if now is None:
            now = time.time()
        fed = self.federator
        transitions = fed.counter_total("crane_breaker_transitions_total")
        self.flap.update(now, transitions)
        degraded = any(
            v > 0 for _, v in fed.gauge_values("crane_degraded_mode")
        )
        self.dwell.update(now, degraded)
        lags = [
            v for _, v in fed.gauge_values("crane_replica_lag_versions")
        ] or [
            v for _, v in fed.gauge_values("crane_router_replica_lag_versions")
        ]
        self.trend.update(now, max(lags) if lags else 0.0)
        return self.status()

    def status(self) -> dict:
        out = {
            "breaker_flapping": {
                "firing": self.flap.anomalous,
                "flapsInWindow": self.flap.flaps_in_window,
                "windowSeconds": self.flap.window_s,
            },
            "degraded_dwell": {
                "firing": self.dwell.anomalous,
                "dwellSeconds": self.dwell.dwell_s,
                "maxDwellSeconds": self.dwell.max_dwell_s,
            },
            "replication_lag_trend": {
                "firing": self.trend.anomalous,
                "ewmaLag": self.trend.level,
                "ewmaSlopePerS": self.trend.slope,
            },
        }
        if self._m_anomaly is not None:
            for kind in self.KINDS:
                self._m_anomaly.labels(kind=kind).set(
                    1 if out[kind]["firing"] else 0
                )
        return out


# ---------------------------------------------------------------------------
# the plane
# ---------------------------------------------------------------------------


class FleetPlane:
    """Federator + SLO engine + anomaly detectors behind one ``tick()``,
    plus an optional wall-clock pump thread (``interval_s``, 1 Hz by
    default). ``registry`` is the HOST process's registry — the plane's
    own health (scrape outcomes, quarantines, burn rates, alert states,
    anomalies) lands there so the primary's plain ``/metrics`` carries
    the fleet verdict too. ``local_registry`` adds an in-process target
    (no socket) rendering that registry under ``local_role``."""

    def __init__(
        self,
        targets=(),
        *,
        registry: MetricsRegistry | None = None,
        local_registry: MetricsRegistry | None = None,
        local_role: str | None = None,
        local_name: str = "self",
        interval_s: float = 1.0,
        clock=time.time,
        slo_kwargs: dict | None = None,
        anomaly_kwargs: dict | None = None,
    ):
        self.interval_s = float(interval_s)
        self.clock = clock
        self.federator = MetricsFederator(targets, registry=registry)
        if local_registry is not None:
            self.federator.add_target(ScrapeTarget(
                name=local_name,
                role=local_role or process_role(),
                fetch=local_registry.render,
            ))
        self.slo = SLOEngine(
            self.federator, registry=registry, **(slo_kwargs or {})
        )
        self.anomalies = FleetAnomalies(
            self.federator, registry=registry, **(anomaly_kwargs or {})
        )
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def tick(self, now: float | None = None) -> dict:
        """One full pass: scrape -> burn -> detect. Deterministic when
        ``now`` is supplied and the targets' fetchers are injected."""
        if now is None:
            now = self.clock()
        scrape = self.federator.scrape_once()
        slo = self.slo.tick(now)
        anomalies = self.anomalies.tick(now)
        return {"scrape": scrape, "slo": slo, "anomalies": anomalies}

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._pump, name="crane-fleet-pump", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _pump(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:  # pragma: no cover - the pump must survive
                pass

    # -- the HTTP surfaces (service.http wires these) -----------------------

    def render_metrics(self) -> str:
        return self.federator.render()

    def slo_status(self) -> dict:
        return {
            "role": process_role(),
            "slo": self.slo.status(),
            "anomalies": self.anomalies.status(),
            "federation": self.federator.status(),
        }


def parse_scrape_flag(spec: str) -> list[ScrapeTarget]:
    """Parse the ``--fleet-scrape`` CLI flag: a comma list of
    ``[role@]host:port[/path]`` entries (``scheduler@127.0.0.1:8090``).
    Names are derived ``role-N`` / ``target-N`` by position."""
    targets = []
    for i, entry in enumerate(x.strip() for x in spec.split(",")):
        if not entry:
            continue
        role = None
        if "@" in entry:
            role, _, entry = entry.partition("@")
        path = "/metrics"
        hostport = entry
        slash = entry.find("/")
        if slash >= 0:
            hostport, path = entry[:slash], entry[slash:]
        host, _, port = hostport.rpartition(":")
        targets.append(ScrapeTarget(
            name=f"{role or 'target'}-{i}",
            host=host or "127.0.0.1",
            port=int(port),
            path=path,
            role=role,
        ))
    return targets
