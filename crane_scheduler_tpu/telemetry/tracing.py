"""W3C trace-context propagation for the placement pipeline.

One placement crosses four processes — annotator sync stamps the
annotations, the scheduler ingests and scores them, the kube client
POSTs the binding, the watch stream confirms it. A trace ID minted at
pod first-seen (``lifecycle.PodLifecycleTracker.seen``) rides the
``traceparent`` header (https://www.w3.org/TR/trace-context/) across
the HTTP hops and a thread-local ``TraceContext`` within a process, so
every ``SpanRecorder`` span recorded under ``use(ctx)`` is stamped with
the trace and parented to the enclosing span.

Stdlib-only; ID generation is one random 128/64-bit base per process
plus a counter — no per-span ``os.urandom`` syscall on the hot path.
"""

from __future__ import annotations

import contextlib
import itertools
import os
import threading

_TRACEPARENT_LEN = 55  # "00-" + 32 + "-" + 16 + "-" + 2 + separators
_HEX = set("0123456789abcdef")

# per-process random bases; the counter keeps successive IDs distinct
# without a syscall per span
_trace_base = int.from_bytes(os.urandom(16), "big") | 1
_span_base = int.from_bytes(os.urandom(8), "big") | 1
_counter = itertools.count(1)


def new_trace_id() -> str:
    """32 lowercase hex chars, never all-zero."""
    return f"{(_trace_base + (next(_counter) << 64)) & ((1 << 128) - 1) or 1:032x}"


def new_span_id() -> str:
    """16 lowercase hex chars, never all-zero."""
    return f"{(_span_base + next(_counter)) & ((1 << 64) - 1) or 1:016x}"


class TraceContext:
    """An active (trace_id, span_id) pair — the parent for new spans."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id

    def child(self) -> "TraceContext":
        return TraceContext(self.trace_id, new_span_id())

    def __repr__(self):
        return f"TraceContext({self.trace_id!r}, {self.span_id!r})"

    def __eq__(self, other):
        return (
            isinstance(other, TraceContext)
            and self.trace_id == other.trace_id
            and self.span_id == other.span_id
        )


def new_context() -> TraceContext:
    return TraceContext(new_trace_id(), new_span_id())


def format_traceparent(ctx: TraceContext) -> str:
    """``00-<trace-id>-<parent-id>-01`` (sampled flag always set: the
    lifecycle tracker already decided this pod is tracked)."""
    return f"00-{ctx.trace_id}-{ctx.span_id}-01"


def _hexfield(s: str, width: int) -> bool:
    return len(s) == width and set(s) <= _HEX and set(s) != {"0"}


def parse_traceparent(value) -> TraceContext | None:
    """Strict W3C parse; returns None on anything malformed (a bad
    header must never break request handling). Future versions (> 00)
    are accepted as long as the first four fields are well-formed, per
    spec section 4.3."""
    if not value or not isinstance(value, str):
        return None
    parts = value.strip().split("-")
    if len(parts) < 4:
        return None
    version, trace_id, span_id, flags = parts[0], parts[1], parts[2], parts[3]
    if len(version) != 2 or set(version) - _HEX or version == "ff":
        return None
    if version == "00" and len(parts) != 4:
        return None
    if not _hexfield(trace_id, 32) or not _hexfield(span_id, 16):
        return None
    if len(flags) != 2 or set(flags) - _HEX:
        return None
    return TraceContext(trace_id, span_id)


_tls = threading.local()


def current() -> TraceContext | None:
    """The thread's active context (None when untraced — the disabled
    hot path is one ``getattr``)."""
    return getattr(_tls, "ctx", None)


@contextlib.contextmanager
def use(ctx: TraceContext | None):
    """Install ``ctx`` as the thread's active context for the block;
    ``use(None)`` is a no-op passthrough (keeps call sites branch-free)."""
    if ctx is None:
        yield None
        return
    prev = getattr(_tls, "ctx", None)
    _tls.ctx = ctx
    try:
        yield ctx
    finally:
        _tls.ctx = prev
