"""Unified telemetry: metrics registry + span recorder + decision traces.

One coherent measurement layer threaded through scheduler, parallel,
annotator, cluster, and service (SURVEY §5: the reference exports no
metrics at all). Three surfaces, one bundle:

- ``MetricsRegistry`` — Counter/Gauge/log-bucketed Histogram with real
  Prometheus text exposition (``/metrics``);
- ``SpanRecorder`` — pipelined-loop stage spans exported as Chrome
  trace-event JSON (Perfetto / ``chrome://tracing``), alongside the
  ``jax_trace`` device-level hook;
- ``DecisionTraceBuffer`` — sampled per-decision traces
  (``/debug/decisions``), bounded memory.

Instrumented modules accept ``telemetry=`` and fall back to the
process-global instance (``active()``), which is None unless enabled —
so the disabled hot path costs one attribute check. Enable explicitly
with ``telemetry.enable()`` or by setting ``CRANE_TELEMETRY=1`` in the
environment before first use.
"""

from __future__ import annotations

import contextlib
import os
import threading

from . import tracing
from .decisions import DecisionTraceBuffer
from .lifecycle import FlightRecorder, PodLifecycleTracker, slo_report
from .registry import (
    DEFAULT_LATENCY_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    log_buckets,
)
from .fleet import (
    FleetAnomalies,
    FleetPlane,
    MetricsFederator,
    ScrapeTarget,
    SLOEngine,
    process_role,
    register_build_info,
    set_process_role,
)
from .spans import SpanRecorder

__all__ = [
    "Telemetry",
    "FleetPlane",
    "MetricsFederator",
    "SLOEngine",
    "FleetAnomalies",
    "ScrapeTarget",
    "register_build_info",
    "set_process_role",
    "process_role",
    "MetricsRegistry",
    "SpanRecorder",
    "DecisionTraceBuffer",
    "PodLifecycleTracker",
    "FlightRecorder",
    "slo_report",
    "tracing",
    "Counter",
    "Gauge",
    "Histogram",
    "log_buckets",
    "DEFAULT_LATENCY_BUCKETS",
    "enable",
    "disable",
    "active",
    "maybe_span",
]


class Telemetry:
    """The bundle instrumented modules share."""

    def __init__(
        self,
        registry: MetricsRegistry | None = None,
        spans: SpanRecorder | None = None,
        decisions: DecisionTraceBuffer | None = None,
        lifecycle: PodLifecycleTracker | None = None,
        span_capacity: int = 16384,
        decision_capacity: int = 512,
        decision_sample_every: int = 1,
        lifecycle_capacity: int = 8192,
        flight_dir: str | None = None,
        flight_fsync: bool | None = None,
    ):
        self.registry = registry if registry is not None else MetricsRegistry()
        self.spans = (
            spans if spans is not None else SpanRecorder(capacity=span_capacity)
        )
        self.decisions = (
            decisions
            if decisions is not None
            else DecisionTraceBuffer(
                capacity=decision_capacity,
                sample_every=decision_sample_every,
            )
        )
        if flight_dir is None:
            flight_dir = os.environ.get("CRANE_FLIGHT_DIR") or None
        if flight_fsync is None:
            env = os.environ.get("CRANE_FLIGHT_FSYNC", "").strip().lower()
            flight_fsync = bool(env) and env not in ("0", "false", "no")
        self.flight = (
            FlightRecorder(flight_dir, fsync=flight_fsync)
            if flight_dir else None
        )
        self.lifecycle = (
            lifecycle
            if lifecycle is not None
            else PodLifecycleTracker(
                registry=self.registry,
                spans=self.spans,
                capacity=lifecycle_capacity,
                flight=self.flight,
            )
        )
        # incremental flight-drain cursors (flush_flight)
        self._span_cursor = 0
        self._decision_cursor = 0
        self._flush_lock = threading.Lock()
        if self.flight is not None:
            # stream spans to disk without explicit wiring: the CLIs
            # never pump flush_flight themselves, and a crash is exactly
            # when the tail matters
            import atexit

            self._flight_stop = threading.Event()
            threading.Thread(
                target=self._flight_pump,
                name="crane-flight-flush",
                daemon=True,
            ).start()
            atexit.register(self.flush_flight)

    def render_prometheus(self, openmetrics: bool = False) -> str:
        return self.registry.render(openmetrics=openmetrics)

    def export_chrome_trace(self) -> dict:
        return self.spans.export_chrome_trace()

    def flush_flight(self) -> dict:
        """Drain spans + decision traces recorded since the last call
        into the flight recorder (lifecycle records stream on completion
        already). A flight-enabled bundle also pumps this from a daemon
        thread every second, plus once at interpreter exit. Returns
        written counts; no-op without a flight dir."""
        if self.flight is None:
            return {"spans": 0, "decisions": 0}
        with self._flush_lock:
            spans, self._span_cursor = self.spans.drain_since(
                self._span_cursor
            )
            decisions, self._decision_cursor = self.decisions.drain_since(
                self._decision_cursor
            )
            return {
                "spans": self.flight.write_many("span", spans),
                "decisions": self.flight.write_many("decision", decisions),
            }

    def _flight_pump(self) -> None:
        while not self._flight_stop.wait(1.0):
            try:
                self.flush_flight()
            except Exception:
                pass


_active: Telemetry | None = None
_lock = threading.Lock()


def enable(telemetry: Telemetry | None = None) -> Telemetry:
    """Install (and return) the process-global telemetry instance."""
    global _active
    with _lock:
        if telemetry is not None:
            _active = telemetry
        elif _active is None:
            _active = Telemetry()
        return _active


def disable() -> None:
    global _active
    with _lock:
        _active = None


def active() -> Telemetry | None:
    """The process-global instance, or None when disabled. Honors
    ``CRANE_TELEMETRY=1`` (any non-empty value but ``0``/``false``)."""
    if _active is None:
        env = os.environ.get("CRANE_TELEMETRY", "").strip().lower()
        if env and env not in ("0", "false", "no"):
            return enable()
    return _active


_NULL_CTX = contextlib.nullcontext()


def maybe_span(telemetry: Telemetry | None, name: str, **args):
    """``telemetry.spans.span(...)`` when enabled, a shared no-op context
    otherwise — the hot-path gating idiom."""
    if telemetry is None:
        return _NULL_CTX
    return telemetry.spans.span(name, **args)


def flush_on_signal(telemetry: Telemetry, signum=None) -> None:
    """Install a SIGTERM handler that drains the flight recorder before
    the process dies. atexit only fires on orderly interpreter exit;
    SIGTERM's default action skips it entirely, so the last second of
    spans from an orderly kill was lost. Chains any previously-installed
    handler, and re-raises with the default disposition when there was
    none so exit status still reports the signal. Main-thread only (the
    CLIs qualify)."""
    import signal as _signal

    signum = _signal.SIGTERM if signum is None else signum
    prev = _signal.getsignal(signum)

    def _handler(num, frame):
        try:
            telemetry.flush_flight()
        except Exception:
            pass
        if callable(prev):
            prev(num, frame)
        elif prev == _signal.SIG_DFL:
            _signal.signal(num, _signal.SIG_DFL)
            os.kill(os.getpid(), num)

    _signal.signal(signum, _handler)


__all__.append("flush_on_signal")
