"""Unified telemetry: metrics registry + span recorder + decision traces.

One coherent measurement layer threaded through scheduler, parallel,
annotator, cluster, and service (SURVEY §5: the reference exports no
metrics at all). Three surfaces, one bundle:

- ``MetricsRegistry`` — Counter/Gauge/log-bucketed Histogram with real
  Prometheus text exposition (``/metrics``);
- ``SpanRecorder`` — pipelined-loop stage spans exported as Chrome
  trace-event JSON (Perfetto / ``chrome://tracing``), alongside the
  ``jax_trace`` device-level hook;
- ``DecisionTraceBuffer`` — sampled per-decision traces
  (``/debug/decisions``), bounded memory.

Instrumented modules accept ``telemetry=`` and fall back to the
process-global instance (``active()``), which is None unless enabled —
so the disabled hot path costs one attribute check. Enable explicitly
with ``telemetry.enable()`` or by setting ``CRANE_TELEMETRY=1`` in the
environment before first use.
"""

from __future__ import annotations

import contextlib
import os
import threading

from .decisions import DecisionTraceBuffer
from .registry import (
    DEFAULT_LATENCY_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    log_buckets,
)
from .spans import SpanRecorder

__all__ = [
    "Telemetry",
    "MetricsRegistry",
    "SpanRecorder",
    "DecisionTraceBuffer",
    "Counter",
    "Gauge",
    "Histogram",
    "log_buckets",
    "DEFAULT_LATENCY_BUCKETS",
    "enable",
    "disable",
    "active",
    "maybe_span",
]


class Telemetry:
    """The bundle instrumented modules share."""

    def __init__(
        self,
        registry: MetricsRegistry | None = None,
        spans: SpanRecorder | None = None,
        decisions: DecisionTraceBuffer | None = None,
        span_capacity: int = 16384,
        decision_capacity: int = 512,
        decision_sample_every: int = 1,
    ):
        self.registry = registry if registry is not None else MetricsRegistry()
        self.spans = (
            spans if spans is not None else SpanRecorder(capacity=span_capacity)
        )
        self.decisions = (
            decisions
            if decisions is not None
            else DecisionTraceBuffer(
                capacity=decision_capacity,
                sample_every=decision_sample_every,
            )
        )

    def render_prometheus(self) -> str:
        return self.registry.render()

    def export_chrome_trace(self) -> dict:
        return self.spans.export_chrome_trace()


_active: Telemetry | None = None
_lock = threading.Lock()


def enable(telemetry: Telemetry | None = None) -> Telemetry:
    """Install (and return) the process-global telemetry instance."""
    global _active
    with _lock:
        if telemetry is not None:
            _active = telemetry
        elif _active is None:
            _active = Telemetry()
        return _active


def disable() -> None:
    global _active
    with _lock:
        _active = None


def active() -> Telemetry | None:
    """The process-global instance, or None when disabled. Honors
    ``CRANE_TELEMETRY=1`` (any non-empty value but ``0``/``false``)."""
    if _active is None:
        env = os.environ.get("CRANE_TELEMETRY", "").strip().lower()
        if env and env not in ("0", "false", "no"):
            return enable()
    return _active


_NULL_CTX = contextlib.nullcontext()


def maybe_span(telemetry: Telemetry | None, name: str, **args):
    """``telemetry.spans.span(...)`` when enabled, a shared no-op context
    otherwise — the hot-path gating idiom."""
    if telemetry is None:
        return _NULL_CTX
    return telemetry.spans.span(name, **args)
