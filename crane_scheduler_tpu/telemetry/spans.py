"""Span recorder: pipeline stage timing -> Chrome trace-event JSON.

SURVEY §5: the reference's only latency visibility is log lines timing
each sync. ``jax_trace`` (utils/profiling.py) covers *device*-level
analysis; this recorder covers the *host* pipeline — the stages of the
pipelined scheduling loop (ingest, risk rescan, H2D, dispatch, async
D2H drain, bind flush, the overlap-refresh worker) land in a bounded
ring buffer and export as Chrome trace-event JSON, viewable in Perfetto
(https://ui.perfetto.dev) or ``chrome://tracing`` next to the JAX
profiler's own traces.

Tracks default to the recording thread's name, so the overlap-refresh
worker's spans land on their own track and visibly overlap the
scheduling thread's cycles — exactly the picture "why did cycle N's p99
spike" needs.

Trace propagation (ISSUE 9): when a ``tracing.TraceContext`` is active
(thread-local, or passed as ``ctx=``), spans are stamped with
``trace_id``/``span_id``/``parent_id`` and nested ``span()`` blocks
parent correctly. The export adds Perfetto flow events chaining spans
that share a trace ID — the visual thread stitching annotator sync →
ingest → dispatch → bind flush across tracks. Untraced spans pay one
thread-local ``getattr`` and carry no trace fields.
"""

from __future__ import annotations

import contextlib
import json
import os
import tempfile
import threading
import time
from collections import deque

from . import tracing

# ring entries: (ts_us, dur_us, name, track, args|None,
#                trace_id|None, span_id|None, parent_id|None, seq)
_UNTRACED = (None, None, None)


class SpanRecorder:
    """Bounded ring buffer of completed spans (oldest evicted first)."""

    def __init__(self, capacity: int = 16384, clock=time.perf_counter):
        self._clock = clock
        self._epoch = clock()
        self._buf: deque = deque(maxlen=int(capacity))
        self._lock = threading.Lock()
        self.recorded = 0  # total ever recorded (evictions included)
        self._seq = 0  # monotone id for flight-recorder drain cursors

    @contextlib.contextmanager
    def span(self, name: str, track: str | None = None, ctx=None, **args):
        """Record the wrapped block as one complete ('X') span. ``track``
        defaults to the current thread's name. When a trace context is
        active (``ctx=`` or thread-local), the span becomes its child and
        is itself the parent of spans recorded inside the block."""
        parent = ctx if ctx is not None else tracing.current()
        start = self._clock()
        if parent is None:
            try:
                yield
            finally:
                self.record(name, start, self._clock(), track=track, args=args)
            return
        child = parent.child()
        try:
            with tracing.use(child):
                yield
        finally:
            self.record(
                name, start, self._clock(), track=track, args=args,
                trace_id=parent.trace_id, span_id=child.span_id,
                parent_id=parent.span_id,
            )

    def record(
        self,
        name: str,
        start: float,
        end: float,
        track: str | None = None,
        args: dict | None = None,
        ctx=None,
        trace_id: str | None = None,
        span_id: str | None = None,
        parent_id: str | None = None,
    ) -> None:
        """Record a span from explicit ``clock()`` readings (for callers
        that only learn the span's metadata after it finished). Trace
        fields come from ``trace_id``/``span_id``/``parent_id`` when
        given, else from ``ctx`` or the thread-local context."""
        if track is None:
            track = threading.current_thread().name
        if trace_id is None:
            parent = ctx if ctx is not None else tracing.current()
            if parent is not None:
                trace_id = parent.trace_id
                span_id = tracing.new_span_id()
                parent_id = parent.span_id
        ts_us = (start - self._epoch) * 1e6
        dur_us = max(0.0, (end - start) * 1e6)
        with self._lock:
            self.recorded += 1
            self._seq += 1
            self._buf.append(
                (ts_us, dur_us, name, track, args or None,
                 trace_id, span_id, parent_id, self._seq)
            )

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()

    def export_chrome_trace(self) -> dict:
        """Chrome trace-event JSON object (``{"traceEvents": [...]}``):
        one ``ph: "X"`` complete event per span plus ``thread_name``
        metadata per track, events sorted by timestamp. Traced spans
        carry trace_id/span_id/parent_id in ``args`` and are linked by
        Perfetto flow events (``ph: s/t/f``) per trace ID."""
        with self._lock:
            # key=s[:2]: entries end in dicts — a (ts, dur, name, track)
            # tie must not fall through to comparing args
            spans = sorted(self._buf, key=lambda s: s[:2])
        tids: dict[str, int] = {}
        events: list[dict] = []
        flows: dict[str, list[tuple[float, int, str]]] = {}
        for ts_us, dur_us, name, track, args, trace_id, span_id, parent_id, _ in spans:
            tid = tids.get(track)
            if tid is None:
                tid = tids[track] = len(tids) + 1
            event = {
                "name": name,
                "ph": "X",
                "pid": 1,
                "tid": tid,
                "ts": round(ts_us, 3),
                "dur": round(dur_us, 3),
            }
            if trace_id is not None:
                targs = dict(args) if args else {}
                targs["trace_id"] = trace_id
                targs["span_id"] = span_id
                if parent_id is not None:
                    targs["parent_id"] = parent_id
                event["args"] = targs
                flows.setdefault(trace_id, []).append(
                    (round(ts_us, 3), tid, name)
                )
            elif args:
                event["args"] = args
            events.append(event)
        flow_events: list[dict] = []
        for trace_id, hops in flows.items():
            if len(hops) < 2:
                continue  # a flow needs at least two ends
            # 52-bit id fits a JS number; stable per trace
            fid = int(trace_id[:13], 16)
            for i, (ts, tid, name) in enumerate(hops):
                ph = "s" if i == 0 else ("f" if i == len(hops) - 1 else "t")
                ev = {
                    "name": "trace", "cat": "trace", "ph": ph, "id": fid,
                    "pid": 1, "tid": tid, "ts": ts,
                }
                if ph == "f":
                    ev["bp"] = "e"
                flow_events.append(ev)
        meta = [
            {
                "name": "thread_name",
                "ph": "M",
                "pid": 1,
                "tid": tid,
                "args": {"name": track},
            }
            for track, tid in tids.items()
        ]
        return {
            "traceEvents": meta + events + flow_events,
            "displayTimeUnit": "ms",
        }

    def drain_since(self, cursor: int) -> tuple[list[dict], int]:
        """Spans recorded after ``cursor`` (a seq from a prior call) as
        JSON-able dicts, plus the new cursor — the flight recorder's
        incremental pull. Ring evictions may drop spans between pulls;
        what remains is still ordered."""
        with self._lock:
            new_cursor = self._seq
            picked = [s for s in self._buf if s[8] > cursor]
        out = []
        for ts_us, dur_us, name, track, args, trace_id, span_id, parent_id, seq in picked:
            d = {
                "seq": seq,
                "ts_us": round(ts_us, 3),
                "dur_us": round(dur_us, 3),
                "name": name,
                "track": track,
            }
            if args:
                d["args"] = args
            if trace_id is not None:
                d["trace_id"] = trace_id
                d["span_id"] = span_id
                if parent_id is not None:
                    d["parent_id"] = parent_id
            out.append(d)
        return out, new_cursor

    def dump(self, path: str) -> int:
        """Write the Chrome trace to ``path`` atomically (temp file +
        ``os.replace`` — a crash mid-dump never leaves torn JSON);
        returns the span count."""
        trace = self.export_chrome_trace()
        d = os.path.dirname(os.path.abspath(path))
        fd, tmp = tempfile.mkstemp(dir=d, prefix=".spans-", suffix=".tmp")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(trace, f)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return sum(1 for e in trace["traceEvents"] if e["ph"] == "X")
