"""Span recorder: pipeline stage timing -> Chrome trace-event JSON.

SURVEY §5: the reference's only latency visibility is log lines timing
each sync. ``jax_trace`` (utils/profiling.py) covers *device*-level
analysis; this recorder covers the *host* pipeline — the stages of the
pipelined scheduling loop (ingest, risk rescan, H2D, dispatch, async
D2H drain, bind flush, the overlap-refresh worker) land in a bounded
ring buffer and export as Chrome trace-event JSON, viewable in Perfetto
(https://ui.perfetto.dev) or ``chrome://tracing`` next to the JAX
profiler's own traces.

Tracks default to the recording thread's name, so the overlap-refresh
worker's spans land on their own track and visibly overlap the
scheduling thread's cycles — exactly the picture "why did cycle N's p99
spike" needs.
"""

from __future__ import annotations

import contextlib
import json
import threading
import time
from collections import deque


class SpanRecorder:
    """Bounded ring buffer of completed spans (oldest evicted first)."""

    def __init__(self, capacity: int = 16384, clock=time.perf_counter):
        self._clock = clock
        self._epoch = clock()
        self._buf: deque = deque(maxlen=int(capacity))
        self._lock = threading.Lock()
        self.recorded = 0  # total ever recorded (evictions included)

    @contextlib.contextmanager
    def span(self, name: str, track: str | None = None, **args):
        """Record the wrapped block as one complete ('X') span. ``track``
        defaults to the current thread's name."""
        start = self._clock()
        try:
            yield
        finally:
            self.record(name, start, self._clock(), track=track, args=args)

    def record(
        self,
        name: str,
        start: float,
        end: float,
        track: str | None = None,
        args: dict | None = None,
    ) -> None:
        """Record a span from explicit ``clock()`` readings (for callers
        that only learn the span's metadata after it finished)."""
        if track is None:
            track = threading.current_thread().name
        ts_us = (start - self._epoch) * 1e6
        dur_us = max(0.0, (end - start) * 1e6)
        with self._lock:
            self.recorded += 1
            self._buf.append((ts_us, dur_us, name, track, args or None))

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()

    def export_chrome_trace(self) -> dict:
        """Chrome trace-event JSON object (``{"traceEvents": [...]}``):
        one ``ph: "X"`` complete event per span plus ``thread_name``
        metadata per track, events sorted by timestamp."""
        with self._lock:
            spans = sorted(self._buf)
        tids: dict[str, int] = {}
        events: list[dict] = []
        for ts_us, dur_us, name, track, args in spans:
            tid = tids.get(track)
            if tid is None:
                tid = tids[track] = len(tids) + 1
            event = {
                "name": name,
                "ph": "X",
                "pid": 1,
                "tid": tid,
                "ts": round(ts_us, 3),
                "dur": round(dur_us, 3),
            }
            if args:
                event["args"] = args
            events.append(event)
        meta = [
            {
                "name": "thread_name",
                "ph": "M",
                "pid": 1,
                "tid": tid,
                "args": {"name": track},
            }
            for track, tid in tids.items()
        ]
        return {"traceEvents": meta + events, "displayTimeUnit": "ms"}

    def dump(self, path: str) -> int:
        """Write the Chrome trace to ``path``; returns the span count."""
        trace = self.export_chrome_trace()
        with open(path, "w") as f:
            json.dump(trace, f)
        return sum(1 for e in trace["traceEvents"] if e["ph"] == "X")
