"""Pod-lifecycle tracking: first-seen -> bind-confirmed, end to end.

The decoupled annotator -> scheduler -> service -> descheduler pipeline
(SURVEY §1) had no answer to "how long from pod-seen to bind-confirmed"
— each process only timed its own stages. This module owns the bounded
per-pod state machine that stitches them:

- ``PodLifecycleTracker`` — per-pod records walking ``seen ->
  filtered -> scored -> bind_post -> watch_confirm`` (plus ``evicted``
  for the descheduler loop; a re-placed pod keeps its trace ID and
  bumps ``attempt``). Stage timestamps come off the existing hooks
  (mirror ingest, dispatch, bind flush, watch apply), both wall-clock
  and monotonic. Completion observes
  ``crane_placement_stage_seconds{stage}`` and the
  ``crane_placement_e2e_seconds`` headline (with a trace-ID exemplar),
  emits per-stage spans into the process ``SpanRecorder`` under the
  pod's trace, and pushes the finished record to a bounded ring —
  joinable to decision traces by pod key and timestamp.
- ``FlightRecorder`` — a crash-safe on-disk JSONL ring (size-capped
  segments, oldest deleted) of lifecycle records + spans + decisions;
  ``tools/crane_trace.py`` replays it for ``explain``/``slo``.
- ``slo_report`` — p50/p99 per stage and e2e compliance / burn rate
  against a target, computed from raw records so the CLI and bench can
  cross-check the histogram.

Memory is bounded three ways: a live-record cap (oldest dropped), a
completed ring, and ``batch_sample`` — the batch/burst paths track only
a prefix sample of each dispatch (100k-pod cycles must not pay O(pods);
the PR 2 rule keeps bench overhead < 3%).

Watch events may outrun POST acks (the stub — and a busy apiserver —
can deliver the confirming watch before the writer thread marks the
POST done): stages store absolute timestamps, so ``watch_confirm``
arriving before ``bind_post`` is recorded as-is and the record
finalizes once both are present, with negative stage deltas clamped
to zero.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import OrderedDict, deque

from . import tracing

STAGES = ("seen", "filtered", "scored", "bind_post", "watch_confirm")

_JSON_SEP = (",", ":")


class PodLifecycleTracker:
    """Bounded per-pod placement state machine. All methods are
    thread-safe and cheap on untracked keys (one dict miss)."""

    def __init__(
        self,
        registry=None,
        spans=None,
        capacity: int = 8192,
        completed_capacity: int = 2048,
        batch_sample: int = 16,
        clock=time.time,
        mono=time.perf_counter,
        flight=None,
    ):
        self._registry = registry
        self._spans = spans
        self._clock = clock
        self._mono = mono
        self.capacity = int(capacity)
        self.batch_sample = int(batch_sample)
        self.flight = flight
        self._lock = threading.Lock()
        self._live: OrderedDict[str, dict] = OrderedDict()
        self._completed: deque = deque(maxlen=int(completed_capacity))
        # evicted pods keep their trace across re-placement attempts
        self._evicted_traces: OrderedDict[str, tuple[str, int]] = OrderedDict()
        # (trace, attempt) of each finalized record — an eviction landing
        # AFTER the placement finalized still continues the pod's trace
        self._last_traces: OrderedDict[str, tuple[str, int]] = OrderedDict()
        self._m_stage = None
        self._m_e2e = None
        self._stage_children: dict = {}  # labeled-child cache (finalize)
        self.tracked_total = 0
        self.confirmed_total = 0
        self.evicted_total = 0
        self.dropped_total = 0

    # -- metrics (lazy: don't pollute exposition until a pod completes) --

    def ensure_metrics(self) -> None:
        if self._m_e2e is not None or self._registry is None:
            return
        self._m_stage = self._registry.histogram(
            "crane_placement_stage_seconds",
            "Per-stage placement latency (delta to the previous stage)",
            labelnames=("stage",),
        )
        self._m_e2e = self._registry.histogram(
            "crane_placement_e2e_seconds",
            "Pod first-seen to watch-confirmed placement latency",
        )
        # finalize runs per confirmed pod; skip the labels() lookup there
        self._stage_children = {
            s: self._m_stage.labels(stage=s) for s in STAGES[1:]
        }

    # -- state machine ---------------------------------------------------

    def _new_record(self, key: str, source: str, now: float, m: float) -> dict:
        trace_id = tracing.new_trace_id()
        attempt = 1
        prior = self._evicted_traces.pop(key, None)
        if prior is not None:
            trace_id, attempt = prior[0], prior[1] + 1
        rec = {
            "pod": key,
            "trace_id": trace_id,
            "root_span": tracing.new_span_id(),
            "attempt": attempt,
            "source": source,
            "node": None,
            "anno_ts": None,
            "cycle_trace": None,
            "stages": {"seen": now},
            "mono": {"seen": m},
            "evicted": False,
            "done": False,
        }
        if len(self._live) >= self.capacity:
            _, dropped = self._live.popitem(last=False)
            self.dropped_total += 1
            self._completed.append(dropped)
        self._live[key] = rec
        self.tracked_total += 1
        return rec

    def seen(self, key: str, source: str = "drip"):
        """Start (or resume) tracking; returns the pod's root
        ``TraceContext``. Idempotent on a live record."""
        now, m = self._clock(), self._mono()
        with self._lock:
            rec = self._live.get(key)
            if rec is None:
                rec = self._new_record(key, source, now, m)
            return tracing.TraceContext(rec["trace_id"], rec["root_span"])

    def seen_batch(self, keys, source: str = "batch") -> list[str]:
        """Track a prefix sample of a dispatch batch; returns the tracked
        subset — later stages iterate only this, keeping the batch path
        O(batch_sample), not O(pods)."""
        sample = keys[: self.batch_sample]
        if not sample:
            return []
        now, m = self._clock(), self._mono()
        tracked = []
        with self._lock:
            for key in sample:
                if key not in self._live:
                    self._new_record(key, source, now, m)
                tracked.append(key)
        return tracked

    def _stage_locked(self, rec, stage, now, m, node=None):
        stages = rec["stages"]
        if node is not None:
            rec["node"] = node
        if stage in stages:
            return  # idempotent: repeated watch applies re-confirm
        prev_m = rec["mono"].get(self._prev_present(rec, stage))
        stages[stage] = now
        rec["mono"][stage] = m
        if self._spans is not None:
            self._spans.record(
                f"lifecycle:{stage}",
                prev_m if prev_m is not None else m,
                m,
                track="lifecycle",
                args={"pod": rec["pod"], "attempt": rec["attempt"]},
                trace_id=rec["trace_id"],
                span_id=tracing.new_span_id(),
                parent_id=rec["root_span"],
            )

    @staticmethod
    def _prev_present(rec, stage):
        try:
            i = STAGES.index(stage)
        except ValueError:
            return "seen"
        for s in reversed(STAGES[:i]):
            if s in rec["mono"]:
                return s
        return "seen"

    def stage(self, key: str, stage: str, node: str | None = None,
              cycle_trace: str | None = None,
              anno_ts: float | None = None) -> bool:
        """Mark ``stage`` reached for a tracked pod (no-op on untracked
        keys). Finalizes the record once both ``bind_post`` and
        ``watch_confirm`` are present, in either order."""
        with self._lock:
            rec = self._live.get(key)
            if rec is None:
                return False
            now, m = self._clock(), self._mono()
            self._stage_locked(rec, stage, now, m, node=node)
            if cycle_trace is not None:
                rec["cycle_trace"] = cycle_trace
            if anno_ts is not None:
                rec["anno_ts"] = anno_ts
            if "bind_post" in rec["stages"] and "watch_confirm" in rec["stages"]:
                self._finalize_locked(key, rec)
            return True

    def stage_batch(self, keys, stage: str, cycle_trace=None, anno_ts=None):
        """One clock read for a whole tracked subset (the drain-side
        hook of the pipelined loops)."""
        if not keys:
            return
        now, m = self._clock(), self._mono()
        with self._lock:
            for key in keys:
                rec = self._live.get(key)
                if rec is None:
                    continue
                self._stage_locked(rec, stage, now, m)
                if cycle_trace is not None:
                    rec["cycle_trace"] = cycle_trace
                if anno_ts is not None:
                    rec["anno_ts"] = anno_ts

    def posted_batch(self, pairs):
        """Mark ``bind_post`` for ``(key, node)`` pairs — the bind-flush
        hook (background thread on the pipelined path)."""
        pairs = list(pairs)
        if not pairs:
            return
        now, m = self._clock(), self._mono()
        with self._lock:
            for key, node in pairs:
                rec = self._live.get(key)
                if rec is None:
                    continue
                self._stage_locked(rec, "bind_post", now, m, node=node)
                if "watch_confirm" in rec["stages"]:
                    self._finalize_locked(key, rec)

    def posted(self, key: str, node: str | None = None) -> bool:
        return self.stage(key, "bind_post", node=node)

    def confirmed_batch(self, pairs):
        """Mark ``watch_confirm`` for ``(key, node)`` pairs — the
        coalesced watch-apply hook (one lock + one clock read per event
        batch; untracked keys cost one dict miss each)."""
        pairs = list(pairs)
        if not pairs:
            return
        now, m = self._clock(), self._mono()
        with self._lock:
            for key, node in pairs:
                rec = self._live.get(key)
                if rec is None:
                    continue
                self._stage_locked(rec, "watch_confirm", now, m, node=node)
                if "bind_post" in rec["stages"]:
                    self._finalize_locked(key, rec)

    def confirmed(self, key: str, node: str | None = None) -> bool:
        """The watch stream confirmed the pod landed on ``node`` — the
        e2e endpoint. Tolerates arriving before the POST ack."""
        return self.stage(key, "watch_confirm", node=node)

    def rearm(self, key: str, trace_id: str, attempt: int = 1) -> None:
        """Restart reconciliation hook: a pod whose bind intent was lost
        in a crash re-enters scheduling on the SAME trace id at
        ``attempt + 1`` — its next ``seen()`` continues the story the
        dead process started."""
        with self._lock:
            self._evicted_traces[key] = (trace_id, int(attempt))
            while len(self._evicted_traces) > self.capacity:
                self._evicted_traces.popitem(last=False)

    def evicted(self, key: str, reason: str = "") -> None:
        """Descheduler hook: finalize the current attempt as evicted and
        remember the trace so a re-placement continues it."""
        now, m = self._clock(), self._mono()
        with self._lock:
            rec = self._live.get(key)
            if rec is None:
                # an eviction-only process (standalone descheduler) still
                # gets a record for its flight recorder; if this process
                # placed the pod earlier the finalized record's trace
                # continues
                rec = self._new_record(key, "evict", now, m)
                prior = self._last_traces.get(key)
                if prior is not None:
                    rec["trace_id"], rec["attempt"] = prior
            self._stage_locked(rec, "evicted", now, m)
            rec["evicted"] = True
            if reason:
                rec["evict_reason"] = reason
            self.evicted_total += 1
            self._evicted_traces[key] = (rec["trace_id"], rec["attempt"])
            while len(self._evicted_traces) > self.capacity:
                self._evicted_traces.popitem(last=False)
            self._finalize_locked(key, rec)

    def _finalize_locked(self, key: str, rec: dict) -> None:
        self._live.pop(key, None)
        rec["done"] = True
        mono = rec["mono"]
        if not rec["evicted"]:
            self.confirmed_total += 1
            self.ensure_metrics()
            if self._m_e2e is not None:
                prev = mono["seen"]
                children = self._stage_children
                for s in STAGES[1:]:
                    t = mono.get(s)
                    if t is None:
                        continue
                    children[s].observe(max(0.0, t - prev))
                    prev = t
                e2e = max(0.0, mono.get("watch_confirm", prev) - mono["seen"])
                self._m_e2e.observe(
                    e2e, exemplar={"trace_id": rec["trace_id"]}
                )
        self._completed.append(rec)
        self._last_traces[key] = (rec["trace_id"], rec["attempt"])
        while len(self._last_traces) > self.capacity:
            self._last_traces.popitem(last=False)
        if self.flight is not None:
            self.flight.write("lifecycle", rec)

    # -- read side -------------------------------------------------------

    def traceparent(self, key: str) -> str | None:
        """The W3C header value for a live pod's root context (stamped on
        its bind/evict POSTs by the kube client)."""
        with self._lock:
            rec = self._live.get(key)
            if rec is None:
                return None
            return tracing.format_traceparent(
                tracing.TraceContext(rec["trace_id"], rec["root_span"])
            )

    def traceparent_batch(self, keys) -> dict:
        """``{key: traceparent}`` for the tracked subset of ``keys`` —
        one lock acquisition for a whole POST batch."""
        out = {}
        with self._lock:
            live = self._live
            for key in keys:
                rec = live.get(key)
                if rec is not None:
                    out[key] = (
                        f"00-{rec['trace_id']}-{rec['root_span']}-01"
                    )
        return out

    def records(self, limit: int | None = None) -> list[dict]:
        """Completed records, oldest first."""
        with self._lock:
            out = list(self._completed)
        if limit is not None and limit >= 0:
            out = out[-limit:]
        return out

    def live_count(self) -> int:
        with self._lock:
            return len(self._live)

    def stats(self) -> dict:
        with self._lock:
            live, completed = len(self._live), len(self._completed)
        return {
            "live": live,
            "completed": completed,
            "tracked_total": self.tracked_total,
            "confirmed_total": self.confirmed_total,
            "evicted_total": self.evicted_total,
            "dropped_total": self.dropped_total,
            "capacity": self.capacity,
            "batch_sample": self.batch_sample,
        }

    def snapshot(self, limit: int | None = None) -> dict:
        """JSON-able view for ``/debug/lifecycle``."""
        return {"stats": self.stats(), "records": self.records(limit=limit)}


class FlightRecorder:
    """Crash-safe bounded JSONL ring on disk.

    Records append to ``flight-<n>.jsonl`` segments; a segment passing
    ``max_segment_bytes`` rotates to the next index and the oldest
    segment beyond ``max_segments`` is deleted. Every record is one
    ``write()`` of a full line followed by a flush, and the reader skips
    unparseable lines — a crash can lose at most the torn tail, never
    corrupt the ring. ``fsync=True`` additionally fsyncs each line so
    the tail survives power loss, not just process death — the intent
    journal's durability mode (``--flight-fsync``)."""

    def __init__(self, directory: str, max_segment_bytes: int = 4 << 20,
                 max_segments: int = 8, fsync: bool = False):
        self.directory = directory
        self.max_segment_bytes = int(max_segment_bytes)
        self.max_segments = int(max_segments)
        self.fsync = bool(fsync)
        os.makedirs(directory, exist_ok=True)
        self._lock = threading.Lock()
        indices = self._segment_indices()
        self._index = indices[-1] if indices else 1
        self._file = open(self._segment_path(self._index), "a")
        self._size = self._file.tell()

    def _segment_path(self, index: int) -> str:
        return os.path.join(self.directory, f"flight-{index:06d}.jsonl")

    def _segment_indices(self) -> list[int]:
        out = []
        for name in os.listdir(self.directory):
            if name.startswith("flight-") and name.endswith(".jsonl"):
                try:
                    out.append(int(name[len("flight-"):-len(".jsonl")]))
                except ValueError:
                    continue
        return sorted(out)

    def write(self, kind: str, obj: dict) -> None:
        line = json.dumps(
            {"kind": kind, **obj}, separators=_JSON_SEP, default=str
        )
        with self._lock:
            self._file.write(line + "\n")
            self._file.flush()
            if self.fsync:
                os.fsync(self._file.fileno())
            self._size += len(line) + 1
            if self._size >= self.max_segment_bytes:
                self._rotate_locked()

    def write_many(self, kind: str, objs) -> int:
        n = 0
        for obj in objs:
            self.write(kind, obj)
            n += 1
        return n

    def _rotate_locked(self) -> None:
        self._file.close()
        self._index += 1
        self._file = open(self._segment_path(self._index), "a")
        self._size = 0
        indices = self._segment_indices()
        while len(indices) > self.max_segments:
            oldest = indices.pop(0)
            try:
                os.unlink(self._segment_path(oldest))
            except OSError:
                pass

    def close(self) -> None:
        with self._lock:
            try:
                self._file.close()
            except OSError:
                pass

    @staticmethod
    def read(directory: str):
        """Yield records from all segments, oldest first, skipping torn
        or foreign lines."""
        if not os.path.isdir(directory):
            return
        names = sorted(
            n for n in os.listdir(directory)
            if n.startswith("flight-") and n.endswith(".jsonl")
        )
        for name in names:
            try:
                with open(os.path.join(directory, name)) as f:
                    for line in f:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            obj = json.loads(line)
                        except ValueError:
                            continue  # torn tail from a crash
                        if isinstance(obj, dict):
                            yield obj
            except OSError:
                continue


# -- SLO math ------------------------------------------------------------


def percentile(values, q: float) -> float:
    """Nearest-rank percentile on a sequence (0 < q <= 1)."""
    vals = sorted(values)
    if not vals:
        return float("nan")
    idx = max(0, min(len(vals) - 1, int(round(q * len(vals) + 0.5)) - 1))
    return vals[idx]


def stage_durations(rec: dict) -> dict:
    """Per-stage deltas (seconds, monotonic, clamped >= 0) for one
    record — later stages may have landed out of order."""
    mono = rec.get("mono") or {}
    out = {}
    prev = mono.get("seen")
    if prev is None:
        return out
    for s in STAGES[1:]:
        t = mono.get(s)
        if t is None:
            continue
        out[s] = max(0.0, t - prev)
        prev = t
    if "watch_confirm" in mono:
        out["e2e"] = max(0.0, mono["watch_confirm"] - mono["seen"])
    return out


def slo_report(records, target_seconds: float | None = None,
               objective: float = 0.99) -> dict:
    """p50/p99 per stage + e2e compliance/burn-rate from raw lifecycle
    records. ``burn_rate`` is (observed error rate) / (error budget):
    1.0 means exactly consuming the budget, > 1 means burning it."""
    stages: dict[str, list[float]] = {}
    e2e: list[float] = []
    confirmed = evicted = 0
    for rec in records:
        if rec.get("evicted"):
            evicted += 1
            continue
        durs = stage_durations(rec)
        if "e2e" in durs:
            confirmed += 1
            e2e.append(durs.pop("e2e"))
        for s, d in durs.items():
            stages.setdefault(s, []).append(d)
    report = {
        "confirmed": confirmed,
        "evicted": evicted,
        "stages": {
            s: {
                "count": len(v),
                "p50": percentile(v, 0.50),
                "p99": percentile(v, 0.99),
            }
            for s, v in sorted(stages.items())
        },
        "e2e": {
            "count": len(e2e),
            "p50": percentile(e2e, 0.50) if e2e else None,
            "p99": percentile(e2e, 0.99) if e2e else None,
            "sum": sum(e2e),
        },
    }
    if target_seconds is not None and e2e:
        good = sum(1 for v in e2e if v <= target_seconds)
        compliance = good / len(e2e)
        budget = 1.0 - objective
        report["slo"] = {
            "target_seconds": target_seconds,
            "objective": objective,
            "compliance": compliance,
            "burn_rate": (
                (1.0 - compliance) / budget if budget > 0 else float("inf")
            ),
        }
    return report
