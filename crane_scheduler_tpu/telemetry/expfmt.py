"""Strict Prometheus text-exposition (0.0.4) parser.

Used by the ``make metrics-smoke`` gate and the golden exposition tests:
a ``/metrics`` payload that any real scraper could choke on must fail
CI, not page an operator later. Deliberately stricter than Prometheus'
own lenient parser:

- every line must be a comment, blank, or well-formed sample;
- ``# TYPE`` must precede the family's samples and appear at most once;
- sample names must belong to a declared family (histograms own their
  ``_bucket``/``_sum``/``_count`` suffixes);
- duplicate series (same name + label set) are rejected;
- histogram buckets must be cumulative, carry parseable ``le`` bounds in
  increasing order, and end with ``le="+Inf"`` equal to ``_count``;
- counter values must be finite and non-negative;
- the payload must end with a newline.
"""

from __future__ import annotations

import math
import re

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
_TYPES = ("counter", "gauge", "histogram", "summary", "untyped")


class ExpositionError(ValueError):
    """A strict-format violation, with the offending line number."""

    def __init__(self, lineno: int, message: str):
        super().__init__(f"line {lineno}: {message}")
        self.lineno = lineno


def _parse_value(lineno: int, raw: str) -> float:
    if raw in ("+Inf", "Inf"):
        return math.inf
    if raw == "-Inf":
        return -math.inf
    if raw == "NaN":
        return math.nan
    try:
        return float(raw)
    except ValueError:
        raise ExpositionError(lineno, f"unparseable value {raw!r}") from None


def _parse_labels(lineno: int, raw: str) -> tuple[tuple[str, str], ...]:
    """Parse the inside of a ``{...}`` label block with escape handling."""
    labels: list[tuple[str, str]] = []
    i, n = 0, len(raw)
    while i < n:
        j = i
        while j < n and raw[j] not in "=":
            j += 1
        if j >= n:
            raise ExpositionError(lineno, f"label without '=': {raw[i:]!r}")
        name = raw[i:j].strip()
        if not _LABEL_RE.match(name):
            raise ExpositionError(lineno, f"invalid label name {name!r}")
        i = j + 1
        if i >= n or raw[i] != '"':
            raise ExpositionError(lineno, f"label {name!r} value not quoted")
        i += 1
        out: list[str] = []
        while True:
            if i >= n:
                raise ExpositionError(lineno, f"unterminated value for {name!r}")
            ch = raw[i]
            if ch == "\\":
                if i + 1 >= n:
                    raise ExpositionError(lineno, "dangling escape")
                nxt = raw[i + 1]
                if nxt == "n":
                    out.append("\n")
                elif nxt in ("\\", '"'):
                    out.append(nxt)
                else:
                    raise ExpositionError(lineno, f"bad escape \\{nxt}")
                i += 2
            elif ch == '"':
                i += 1
                break
            else:
                out.append(ch)
                i += 1
        labels.append((name, "".join(out)))
        if i < n:
            if raw[i] != ",":
                raise ExpositionError(
                    lineno, f"expected ',' between labels, got {raw[i]!r}"
                )
            i += 1
    return tuple(labels)


def _label_block_end(lineno: int, raw: str) -> int:
    """Index of the ``}`` closing the label block ``raw`` starts with,
    honoring quoted values and escapes — ``rfind`` would grab a brace
    from an exemplar tail (or a quoted value) further right."""
    in_quote = False
    i, n = 1, len(raw)
    while i < n:
        ch = raw[i]
        if in_quote:
            if ch == "\\":
                i += 1
            elif ch == '"':
                in_quote = False
        elif ch == '"':
            in_quote = True
        elif ch == "}":
            return i
        i += 1
    raise ExpositionError(lineno, "unterminated label block")


def _parse_exemplar(
    lineno: int, raw: str
) -> tuple[tuple, float, float | None]:
    """Parse an OpenMetrics exemplar tail ``{labels} value [timestamp]``
    (the part after `` # ``); returns (labels, value, timestamp)."""
    raw = raw.strip()
    if not raw.startswith("{"):
        raise ExpositionError(lineno, f"exemplar must start with '{{': {raw!r}")
    end = _label_block_end(lineno, raw)
    labels = _parse_labels(lineno, raw[1:end])
    fields = raw[end + 1:].split()
    if len(fields) not in (1, 2):
        raise ExpositionError(lineno, f"malformed exemplar tail: {raw!r}")
    value = _parse_value(lineno, fields[0])
    if not math.isfinite(value):
        raise ExpositionError(lineno, f"exemplar value not finite: {value}")
    ts = None
    if len(fields) == 2:
        ts = _parse_value(lineno, fields[1])
        if not math.isfinite(ts):
            raise ExpositionError(lineno, "exemplar timestamp not finite")
    return labels, value, ts


_HIST_SUFFIXES = ("_bucket", "_sum", "_count")
_SUMMARY_SUFFIXES = ("_sum", "_count")


def _family_of(name: str, types: dict) -> str | None:
    """Resolve a sample name to its declared family (suffix-aware)."""
    if name in types:
        return name
    for suffix in _HIST_SUFFIXES:
        if name.endswith(suffix):
            base = name[: -len(suffix)]
            kind = types.get(base)
            if kind == "histogram":
                return base
            if kind == "summary" and suffix in _SUMMARY_SUFFIXES:
                return base
    return None


def parse_exposition(text: str) -> dict:
    """Parse + validate; returns ``{family: {"type", "help", "samples"}}``
    where samples are ``(name, labels-tuple, value)``. Raises
    ``ExpositionError`` on any strict-format violation."""
    if text and not text.endswith("\n"):
        raise ExpositionError(text.count("\n") + 1, "payload must end with \\n")
    types: dict[str, str] = {}
    helps: dict[str, str] = {}
    samples: dict[str, list] = {}
    exemplars: dict[str, list] = {}
    seen_series: set[tuple] = set()
    families_with_samples: set[str] = set()

    for lineno, line in enumerate(text.split("\n"), start=1):
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 2 and parts[1] in ("TYPE", "HELP"):
                if len(parts) < 3 or not _NAME_RE.match(parts[2]):
                    raise ExpositionError(lineno, f"malformed {parts[1]} line")
                fname = parts[2]
                if parts[1] == "TYPE":
                    kind = parts[3].strip() if len(parts) > 3 else ""
                    if kind not in _TYPES:
                        raise ExpositionError(lineno, f"unknown type {kind!r}")
                    if fname in types:
                        raise ExpositionError(lineno, f"duplicate TYPE {fname}")
                    if fname in families_with_samples:
                        raise ExpositionError(
                            lineno, f"TYPE {fname} after its samples"
                        )
                    types[fname] = kind
                else:
                    helps[fname] = parts[3] if len(parts) > 3 else ""
            continue  # other comments are allowed
        # sample line: name[{labels}] value [timestamp]
        m = re.match(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{)?", line)
        if not m:
            raise ExpositionError(lineno, f"malformed sample: {line!r}")
        name = m.group(1)
        rest = line[len(name):]
        labels: tuple = ()
        if rest.startswith("{"):
            end = _label_block_end(lineno, rest)
            labels = _parse_labels(lineno, rest[1:end])
            rest = rest[end + 1:]
        exemplar = None
        if " # " in rest:
            # OpenMetrics exemplar: `` # {labels} value [timestamp]``
            rest, _, ex_raw = rest.partition(" # ")
            exemplar = _parse_exemplar(lineno, ex_raw)
        fields = rest.split()
        if len(fields) not in (1, 2):
            raise ExpositionError(lineno, f"malformed sample tail: {rest!r}")
        value = _parse_value(lineno, fields[0])
        family = _family_of(name, types)
        if family is None:
            raise ExpositionError(
                lineno, f"sample {name!r} has no preceding TYPE declaration"
            )
        series_key = (name, labels)
        if series_key in seen_series:
            raise ExpositionError(lineno, f"duplicate series {series_key!r}")
        seen_series.add(series_key)
        families_with_samples.add(family)
        if types[family] == "counter" and not (
            value >= 0 and math.isfinite(value)
        ):
            raise ExpositionError(
                lineno, f"counter {name} has invalid value {value}"
            )
        if exemplar is not None:
            if types[family] != "histogram" or not name.endswith("_bucket"):
                raise ExpositionError(
                    lineno, f"exemplar on non-bucket sample {name!r}"
                )
            exemplars.setdefault(family, []).append(
                (name, labels) + exemplar
            )
        samples.setdefault(family, []).append((name, labels, value))

    _validate_histograms(types, samples)
    return {
        fam: {
            "type": kind,
            "help": helps.get(fam, ""),
            "samples": samples.get(fam, []),
            "exemplars": exemplars.get(fam, []),
        }
        for fam, kind in types.items()
    }


def _validate_histograms(types: dict, samples: dict) -> None:
    for fam, kind in types.items():
        if kind != "histogram":
            continue
        # group by the non-le label set
        by_series: dict[tuple, dict] = {}
        for name, labels, value in samples.get(fam, []):
            base_labels = tuple(lv for lv in labels if lv[0] != "le")
            entry = by_series.setdefault(
                base_labels, {"buckets": [], "sum": None, "count": None}
            )
            if name == fam + "_bucket":
                le = dict(labels).get("le")
                if le is None:
                    raise ExpositionError(0, f"{fam}_bucket missing le label")
                entry["buckets"].append((_parse_value(0, le), value))
            elif name == fam + "_sum":
                entry["sum"] = value
            elif name == fam + "_count":
                entry["count"] = value
        for base_labels, entry in by_series.items():
            buckets = entry["buckets"]
            if not buckets or entry["sum"] is None or entry["count"] is None:
                raise ExpositionError(
                    0, f"{fam}{dict(base_labels)}: incomplete histogram"
                )
            bounds = [b for b, _ in buckets]
            if bounds != sorted(bounds):
                raise ExpositionError(
                    0, f"{fam}{dict(base_labels)}: le bounds not sorted"
                )
            counts = [c for _, c in buckets]
            if counts != sorted(counts):
                raise ExpositionError(
                    0, f"{fam}{dict(base_labels)}: buckets not cumulative"
                )
            if not math.isinf(bounds[-1]):
                raise ExpositionError(
                    0, f"{fam}{dict(base_labels)}: missing le=\"+Inf\" bucket"
                )
            if counts[-1] != entry["count"]:
                raise ExpositionError(
                    0,
                    f"{fam}{dict(base_labels)}: +Inf bucket != _count "
                    f"({counts[-1]} vs {entry['count']})",
                )
