"""Shared reference constants — single source of truth.

The oracle and the batched scorer must agree on these by construction
(the parity tests assume it), so every module imports from here.
"""

MAX_NODE_SCORE = 100  # ref: k8s framework.MaxNodeScore
MIN_NODE_SCORE = 0  # ref: k8s framework.MinNodeScore

# ref: pkg/plugins/dynamic/stats.go:18-27
NODE_HOT_VALUE_KEY = "node_hot_value"
EXTRA_ACTIVE_PERIOD_SECONDS = 300.0
HOT_VALUE_ACTIVE_PERIOD_SECONDS = 300.0

# ref: pkg/controller/annotator/node.go:24-27
DEFAULT_BACKOFF_SECONDS = 10.0
MAX_BACKOFF_SECONDS = 360.0

# ref: cmd/controller/app/options/options.go:38-58
DEFAULT_BINDING_HEAP_SIZE = 1024
DEFAULT_CONCURRENT_SYNCS = 1
