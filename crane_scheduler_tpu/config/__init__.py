from .types import (
    DynamicArgs,
    NodeResourceTopologyMatchArgs,
    PluginWeight,
    SchedulerConfiguration,
    SchedulerProfile,
)
from .scheme import load_scheduler_config, ConfigDecodeError
from .factory import build_scheduler_from_config

__all__ = [
    "DynamicArgs",
    "NodeResourceTopologyMatchArgs",
    "PluginWeight",
    "SchedulerConfiguration",
    "SchedulerProfile",
    "load_scheduler_config",
    "ConfigDecodeError",
    "build_scheduler_from_config",
]
