"""Scheduler assembly from configuration.

Equivalent of ``cmd/scheduler/main.go:20-23``: the stock scheduler with
the crane plugins registered via ``app.WithPlugin`` — here, a Scheduler
built from a decoded SchedulerConfiguration, wiring DynamicPlugin and
TopologyMatch with their decoded args and score weights.
"""

from __future__ import annotations

from ..cluster.state import ClusterState
from ..framework.scheduler import Scheduler
from ..plugins.dynamic import DynamicPlugin
from ..policy.v1alpha1 import load_policy_from_file
from ..topology.plugin import TopologyMatch
from .types import DynamicArgs, NodeResourceTopologyMatchArgs, SchedulerConfiguration


def build_scheduler_from_config(
    cluster: ClusterState,
    config: SchedulerConfiguration,
    nrt_lister=None,
    clock=None,
    policy=None,
    tie_break_seed=None,
    mesh=None,
) -> Scheduler:
    """Build a Scheduler for the first profile.

    ``policy`` overrides reading DynamicArgs.policy_config_path from disk
    (useful in tests/sim); ``nrt_lister`` is required when the NRT plugin
    is enabled. ``tie_break_seed`` opts into the stock framework's
    random-among-ties host selection (seeded; default off = lowest
    snapshot index, deterministic). ``mesh`` shards the drip batch
    kernel over a placement mesh (doc/sharding.md).
    """
    import time

    if not config.profiles:
        raise ValueError("scheduler configuration has no profiles")
    profile = config.profiles[0]
    sched = Scheduler(cluster, clock=clock or time.time,
                      tie_break_seed=tie_break_seed, mesh=mesh)

    weights = {pw.name: pw.weight for pw in profile.score_enabled}
    enabled = set(profile.filter_enabled) | set(weights)

    # allocatable-fit predicate: always on, like the stock scheduler's
    # default-enabled NodeResourcesFit. Fails open on nodes that never
    # reported status.allocatable, so config-less sims are unchanged.
    from ..fit import FitTracker, ResourceFitPlugin

    sched.register(ResourceFitPlugin(FitTracker(cluster)))

    if "Dynamic" in enabled:
        args = profile.plugin_config.get("Dynamic", DynamicArgs())
        if policy is None:
            policy = load_policy_from_file(args.policy_config_path)
        plugin = DynamicPlugin(policy, clock=clock or time.time)
        sched.register(plugin, weight=weights.get("Dynamic", 1))

    if "NodeResourceTopologyMatch" in enabled:
        if nrt_lister is None:
            raise ValueError("NodeResourceTopologyMatch enabled but no NRT lister")
        args = profile.plugin_config.get(
            "NodeResourceTopologyMatch", NodeResourceTopologyMatchArgs()
        )
        plugin = TopologyMatch(
            nrt_lister,
            cluster=cluster,
            topology_aware_resources=frozenset(args.topology_aware_resources),
        )
        # the reference starts the assumed-pod cleaner with the cache
        # (ref: cache.go:111-117); tests drive cleanup(now) directly
        plugin.cache.start_cleaner()
        sched.register(plugin, weight=weights.get("NodeResourceTopologyMatch", 1))

    return sched
