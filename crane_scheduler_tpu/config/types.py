"""Plugin-args configuration API.

Equivalent of the reference's kube-scheduler plugin-args machinery
(ref: pkg/plugins/apis/config): internal ``DynamicArgs`` /
``NodeResourceTopologyMatchArgs`` types (types.go:10-23) decoded from a
scheduler-configuration document's ``pluginConfig`` section, with
versioned defaulting:

- v1beta2: plain string ``policyConfigPath`` defaulting to
  ``/etc/kubernetes/dynamic-scheduler-policy.yaml``; topology-aware
  resources default ["cpu"] (ref: v1beta2/defaults.go:4-19)
- v1beta3: pointer field with pointer defaulting — absent means default,
  empty string stays empty (ref: v1beta3/types.go:13, defaults.go:8-12)
"""

from __future__ import annotations

from dataclasses import dataclass, field

DEFAULT_DYNAMIC_POLICY_CONFIG_PATH = "/etc/kubernetes/dynamic-scheduler-policy.yaml"
DEFAULT_TOPOLOGY_AWARE_RESOURCES = ("cpu",)


@dataclass(frozen=True)
class DynamicArgs:
    """ref: config/types.go:10-16."""

    policy_config_path: str = DEFAULT_DYNAMIC_POLICY_CONFIG_PATH


@dataclass(frozen=True)
class NodeResourceTopologyMatchArgs:
    """ref: config/types.go:18-23."""

    topology_aware_resources: tuple[str, ...] = DEFAULT_TOPOLOGY_AWARE_RESOURCES


@dataclass(frozen=True)
class PluginWeight:
    name: str
    weight: int = 1


@dataclass(frozen=True)
class SchedulerProfile:
    """One scheduler profile: enabled plugins per extension point plus
    decoded plugin args (the subset of KubeSchedulerConfiguration the
    crane plugins use; ref: deploy/manifests/*/scheduler-config.yaml)."""

    scheduler_name: str = "default-scheduler"
    filter_enabled: tuple[str, ...] = ()
    score_enabled: tuple[PluginWeight, ...] = ()
    # other extension points follow the plugin's own declaration
    plugin_config: dict = field(default_factory=dict)  # plugin name -> args


@dataclass(frozen=True)
class SchedulerConfiguration:
    api_version: str = "kubescheduler.config.k8s.io/v1beta2"
    profiles: tuple[SchedulerProfile, ...] = ()
