"""Versioned decode of scheduler-configuration documents.

Strict decoding of the ``KubeSchedulerConfiguration``-shaped YAML the
reference ships (ref: deploy/manifests/dynamic/scheduler-config.yaml,
deploy/manifests/noderesourcetopology/scheduler-config.yaml), supporting
both args versions registered by the reference scheme
(ref: pkg/plugins/apis/config/scheme/scheme.go:14-31):

- ``kubescheduler.config.k8s.io/v1beta2``: ``policyConfigPath`` is a
  plain string; absent => default path (v1beta2/defaults.go).
- ``kubescheduler.config.k8s.io/v1beta3``: pointer defaulting — an absent
  field gets the default, an explicitly empty string is preserved
  (v1beta3/defaults.go:8-12).

Only the fields the crane plugins consume are modeled; unknown plugin
args names are rejected (the reference's scheme would fail decoding too).
"""

from __future__ import annotations

from typing import Any, Mapping

import yaml

from .types import (
    DEFAULT_DYNAMIC_POLICY_CONFIG_PATH,
    DEFAULT_TOPOLOGY_AWARE_RESOURCES,
    DynamicArgs,
    NodeResourceTopologyMatchArgs,
    PluginWeight,
    SchedulerConfiguration,
    SchedulerProfile,
)

SUPPORTED_VERSIONS = (
    "kubescheduler.config.k8s.io/v1beta2",
    "kubescheduler.config.k8s.io/v1beta3",
)

DYNAMIC_ARGS_KIND = "DynamicArgs"
NRT_ARGS_KIND = "NodeResourceTopologyMatchArgs"


class ConfigDecodeError(ValueError):
    pass


def _require_mapping(obj: Any, where: str) -> Mapping:
    if not isinstance(obj, Mapping):
        raise ConfigDecodeError(f"{where}: expected a mapping, got {type(obj).__name__}")
    return obj


def _decode_dynamic_args(doc: Mapping, version: str) -> DynamicArgs:
    unknown = set(doc) - {"apiVersion", "kind", "policyConfigPath"}
    if unknown:
        raise ConfigDecodeError(f"DynamicArgs: unknown field(s) {sorted(unknown)}")
    if version.endswith("v1beta3"):
        # pointer defaulting: absent -> default; empty string preserved
        if "policyConfigPath" in doc:
            path = doc["policyConfigPath"]
            if path is None:
                path = DEFAULT_DYNAMIC_POLICY_CONFIG_PATH
        else:
            path = DEFAULT_DYNAMIC_POLICY_CONFIG_PATH
    else:
        path = doc.get("policyConfigPath") or DEFAULT_DYNAMIC_POLICY_CONFIG_PATH
    if not isinstance(path, str):
        raise ConfigDecodeError(f"DynamicArgs.policyConfigPath: expected string, got {path!r}")
    return DynamicArgs(policy_config_path=path)


def _decode_nrt_args(doc: Mapping) -> NodeResourceTopologyMatchArgs:
    unknown = set(doc) - {"apiVersion", "kind", "topologyAwareResources"}
    if unknown:
        raise ConfigDecodeError(
            f"NodeResourceTopologyMatchArgs: unknown field(s) {sorted(unknown)}"
        )
    resources = doc.get("topologyAwareResources")
    if resources is None:
        resources = list(DEFAULT_TOPOLOGY_AWARE_RESOURCES)
    if not isinstance(resources, list) or not all(isinstance(r, str) for r in resources):
        raise ConfigDecodeError(
            f"topologyAwareResources: expected string list, got {resources!r}"
        )
    return NodeResourceTopologyMatchArgs(topology_aware_resources=tuple(resources))


def _decode_plugin_set(doc: Mapping, point: str) -> tuple:
    section = _require_mapping(doc.get(point, {}) or {}, f"plugins.{point}")
    enabled = section.get("enabled") or []
    out = []
    for i, item in enumerate(enabled):
        item = _require_mapping(item, f"plugins.{point}.enabled[{i}]")
        name = item.get("name")
        if not isinstance(name, str) or not name:
            raise ConfigDecodeError(f"plugins.{point}.enabled[{i}]: missing name")
        weight = item.get("weight", 1)
        if not isinstance(weight, int):
            raise ConfigDecodeError(f"plugins.{point}.enabled[{i}]: bad weight {weight!r}")
        out.append(PluginWeight(name=name, weight=weight))
    return tuple(out)


def load_scheduler_config(data: str | bytes) -> SchedulerConfiguration:
    try:
        doc = yaml.safe_load(data)
    except yaml.YAMLError as e:
        raise ConfigDecodeError(f"invalid YAML: {e}") from e
    doc = _require_mapping(doc, "document")
    version = doc.get("apiVersion")
    if version not in SUPPORTED_VERSIONS:
        raise ConfigDecodeError(
            f"unsupported apiVersion {version!r}, want one of {SUPPORTED_VERSIONS}"
        )
    if doc.get("kind") != "KubeSchedulerConfiguration":
        raise ConfigDecodeError(f"unsupported kind {doc.get('kind')!r}")

    profiles = []
    for i, profile_doc in enumerate(doc.get("profiles") or []):
        profile_doc = _require_mapping(profile_doc, f"profiles[{i}]")
        plugins_doc = _require_mapping(profile_doc.get("plugins", {}) or {}, "plugins")
        filter_enabled = tuple(
            pw.name for pw in _decode_plugin_set(plugins_doc, "filter")
        )
        score_enabled = _decode_plugin_set(plugins_doc, "score")
        # the NRT plugin registers 5 extension points from one entry
        for point in ("preFilter", "reserve", "preBind"):
            _decode_plugin_set(plugins_doc, point)  # validated, implied by plugin

        plugin_config: dict[str, object] = {}
        for j, pc in enumerate(profile_doc.get("pluginConfig") or []):
            pc = _require_mapping(pc, f"profiles[{i}].pluginConfig[{j}]")
            name = pc.get("name")
            args_doc = _require_mapping(pc.get("args", {}) or {}, "args")
            if name == "Dynamic":
                plugin_config[name] = _decode_dynamic_args(args_doc, version)
            elif name == "NodeResourceTopologyMatch":
                plugin_config[name] = _decode_nrt_args(args_doc)
            else:
                raise ConfigDecodeError(f"unknown pluginConfig name {name!r}")
        # defaulting: enabled plugins without explicit args get defaults
        # (the reference's defaulter runs for every registered type)
        mentioned = {pw.name for pw in score_enabled} | set(filter_enabled)
        if "Dynamic" in mentioned and "Dynamic" not in plugin_config:
            plugin_config["Dynamic"] = DynamicArgs()
        if (
            "NodeResourceTopologyMatch" in mentioned
            and "NodeResourceTopologyMatch" not in plugin_config
        ):
            plugin_config["NodeResourceTopologyMatch"] = NodeResourceTopologyMatchArgs()

        profiles.append(
            SchedulerProfile(
                scheduler_name=profile_doc.get("schedulerName", "default-scheduler"),
                filter_enabled=filter_enabled,
                score_enabled=score_enabled,
                plugin_config=plugin_config,
            )
        )

    return SchedulerConfiguration(api_version=version, profiles=tuple(profiles))


def load_scheduler_config_from_file(path: str) -> SchedulerConfiguration:
    with open(path, "rb") as f:
        return load_scheduler_config(f.read())
