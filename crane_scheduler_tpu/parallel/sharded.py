"""The full scheduling step, sharded over a device mesh (GSPMD/pjit).

One jitted function runs the complete batch cycle for a pod burst:

    load matrix [N, M] (node-sharded)
      -> filter mask + scores            (elementwise per shard, no comms)
      -> gang water-filling              (102-level token counts per shard;
                                          XLA inserts psum for level totals
                                          and an all-gather/scan for the
                                          node-index prefix sum over ICI)
      -> per-node assignment counts [N]  (node-sharded)

This is the idiomatic pjit shape: annotate input/output shardings on a
``Mesh`` and let the compiler place collectives (instead of translating
the reference's Go worker pools into explicit message passing). The math
is identical to ``scorer.BatchedScorer`` + ``scorer.topk.GangScheduler``,
which are validated bit-for-bit against the scalar oracles; this module
only changes *where* it runs.
"""

from __future__ import annotations

import functools
import time
from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from ..policy.compile import PolicyTensors
from ..scorer.batched import BatchedScorer
from ..telemetry import Telemetry, maybe_span

# compact packed layout (single source of truth for pack AND unpack):
# per-node uint32 = counts(COMPACT_COUNT_BITS) | score | schedulable(msb).
# COMPACT_MAX_PODS bounds the burst size the counts field can carry
# (per-node counts never exceed the burst's pod total).
COMPACT_COUNT_BITS = 18
COMPACT_MAX_PODS = 1 << COMPACT_COUNT_BITS
_COMPACT_COUNT_MASK = COMPACT_MAX_PODS - 1
_COMPACT_SCORE_MASK = (1 << (31 - COMPACT_COUNT_BITS)) - 1
from ..scorer.topk import GangScheduler

# Rebased (non-f64) snapshots must not age past this: the f32 rounding
# window of `now - epoch` grows with age. with_overrides re-rebases past
# it, and BatchScheduler._prepare forces a full prepare — both sides
# must share the threshold.
EPOCH_REBASE_SECONDS = 6 * 3600.0
from .mesh import node_sharding, replicated_sharding


@dataclass
class PreparedSnapshot:
    """Device-resident, sharded scoring inputs.

    In float32 mode timestamps are stored rebased to ``epoch`` (epoch
    seconds don't survive a float32 downcast); ``now`` holds the rebased
    scheduling time. A cached snapshot can be re-scored at a later wall
    time by passing ``now`` to the step call — the upload is not redone.
    """

    values: Any  # [N, M] dtype, node-sharded
    ts: Any  # [N, M] dtype, node-sharded (possibly rebased)
    hot_value: Any  # [N]
    hot_ts: Any  # [N] (possibly rebased)
    node_valid: Any  # [N] bool
    now: Any  # scalar dtype (rebased: wall now - epoch)
    capacity: Any  # [N] int64
    offsets: Any = None  # [N] int32 combined-score offsets (see scorer.topk)
    epoch: float = 0.0  # host-side rebase origin (0 in float64 mode)
    # hybrid-mode f64 rescue vectors (None when the step is not hybrid):
    # rows whose f32 verdict could diverge from Go/f64 semantics carry
    # their exact f64 verdicts, substituted on device (scorer.hybrid).
    ovr_mask: Any = None  # [N] bool
    ovr_sched: Any = None  # [N] bool
    ovr_score: Any = None  # [N] int32
    ovr_now: float | None = None  # wall-clock the overrides were computed at
    # host-side incremental-rescan state (scorer.hybrid.OverrideCache):
    # per-row cached risk bits/verdicts + validity margins, so an
    # override refresh rescans O(dirty + boundary-band) rows instead of
    # the full store (None for non-hybrid steps)
    ovr_cache: Any = None
    ovr_rescan_rows: int = 0  # rows rescanned by the last override refresh


@dataclass
class ShardedStepResult:
    schedulable: Any  # [N] bool, node-sharded
    scores: Any  # [N] int32, node-sharded
    counts: Any  # [N] int32, node-sharded — pods assigned per node
    unassigned: Any  # scalar int64, replicated
    waterline: Any  # scalar int64, replicated


class ShardedScheduleStep:
    """score + gang-assign, jitted with node-axis shardings on ``mesh``."""

    def __init__(
        self,
        tensors: PolicyTensors,
        mesh: Mesh,
        dtype=jnp.float32,
        dynamic_weight: int = 1,
        max_offset: int = 0,
        hybrid: bool = False,
        telemetry: Telemetry | None = None,
    ):
        """``hybrid=True`` (f32 dtype only): every prepared snapshot
        carries host-computed f64 rescue rows (scorer.hybrid) that the
        device step substitutes, giving bit-for-bit Go/f64 placement
        parity at f32 throughput.

        ``telemetry``: optional span recording for the H2D upload and
        risk-rescan stages (None = zero-cost no-op)."""
        self.mesh = mesh
        self.tensors = tensors
        self.telemetry = telemetry
        self.hybrid = bool(hybrid) and jnp.dtype(dtype) != jnp.dtype(jnp.float64)
        self.scorer = BatchedScorer(tensors, dtype=dtype)
        self.gang = GangScheduler(
            tensors.hv_count, dynamic_weight=dynamic_weight, max_offset=max_offset
        )
        row = node_sharding(mesh, 2)
        vec = node_sharding(mesh, 1)
        rep = replicated_sharding(mesh)
        self._row, self._vec, self._rep = row, vec, rep
        in_vecs = (row, row, vec, vec, vec, rep, vec, vec)
        if self.hybrid:
            in_vecs = in_vecs + (vec, vec, vec)
        self._jit = jax.jit(
            self._step,
            in_shardings=(in_vecs, rep),
            out_shardings=(vec, vec, vec, rep, rep),
        )
        # Packed variant: one int32 output so the host needs exactly one
        # device->host fetch per scheduling cycle (each fetch costs a full
        # runtime round-trip; five of them dominated the batch path).
        self._jit_packed = jax.jit(
            self._step_packed,
            in_shardings=(in_vecs, rep),
            out_shardings=rep,
        )
        self._jit_packed_compact = jax.jit(
            self._step_packed_compact,
            in_shardings=(in_vecs, rep),
            out_shardings=rep,
        )

    def _step(self, prepared, num_pods):
        if self.hybrid:
            (values, ts, hot_value, hot_ts, node_valid, now, capacity, offsets,
             ovr_mask, ovr_sched, ovr_score) = prepared
        else:
            values, ts, hot_value, hot_ts, node_valid, now, capacity, offsets = (
                prepared
            )
        schedulable, scores = self.scorer._score_impl(
            values, ts, hot_value, hot_ts, node_valid, now
        )
        if self.hybrid:
            schedulable = jnp.where(ovr_mask, ovr_sched & node_valid, schedulable)
            scores = jnp.where(ovr_mask & node_valid, ovr_score, scores)
        counts, unassigned, waterline = self.gang._assign_impl(
            scores, schedulable, num_pods, capacity, offsets,
            jnp.zeros_like(capacity),
        )
        return schedulable, scores, counts, unassigned, waterline

    def _step_packed(self, prepared, num_pods):
        """[3N+2] int32: schedulable | scores | counts | unassigned, level."""
        schedulable, scores, counts, unassigned, waterline = self._step(
            prepared, num_pods
        )
        return jnp.concatenate(
            [
                schedulable.astype(jnp.int32),
                scores.astype(jnp.int32),
                counts.astype(jnp.int32),
                jnp.stack(
                    [unassigned.astype(jnp.int32), waterline.astype(jnp.int32)]
                ),
            ]
        )

    def _step_packed_compact(self, prepared, num_pods):
        """[N+2] uint32: per node ``counts(bits 0-17) | score(18-30) |
        schedulable(31)``; tail ``[unassigned, bitcast(waterline)]``.
        Sound while counts <= num_pods < 2^18 (``packed`` enforces) and
        scores are in [0, 8191] — the scorer clamps to [0, 100]
        (oracle.py trunc-clamp; hybrid rescue rows substitute oracle
        scores with the same range)."""
        schedulable, scores, counts, unassigned, waterline = self._step(
            prepared, num_pods
        )
        body = (
            counts.astype(jnp.uint32)
            | (scores.astype(jnp.uint32) << COMPACT_COUNT_BITS)
            | (schedulable.astype(jnp.uint32) << 31)
        )
        tail = jnp.stack([
            unassigned.astype(jnp.uint32),
            jax.lax.bitcast_convert_type(
                waterline.astype(jnp.int32), jnp.uint32
            ),
        ])
        return jnp.concatenate([body, tail])

    def prepare(
        self, snapshot, now: float, capacity=None, offsets=None
    ) -> PreparedSnapshot:
        """Upload a store snapshot with node-axis shardings.

        Host -> device transfer happens here, once per refresh; the jitted
        step then reruns against the resident arrays for any pod batch.
        All scoring inputs ship in ONE batched ``device_put`` (a remote
        runtime pays a full round trip per transfer call — the previous
        per-array puts serialized most of the 50k-node cold refresh), and
        the hybrid risk scan runs on host WHILE that async transfer is in
        flight, so the scan is no longer on the upload's critical path.
        """
        with maybe_span(self.telemetry, "h2d_prepare", n=int(snapshot.n_nodes)):
            return self._prepare_impl(snapshot, now, capacity, offsets)

    def _prepare_impl(self, snapshot, now, capacity, offsets):
        np_dtype = jnp.dtype(self.scorer.dtype)
        ts = np.asarray(snapshot.ts, np.float64)
        hot_ts = np.asarray(snapshot.hot_ts, np.float64)
        now_value = float(now)
        epoch = 0.0
        if np_dtype != jnp.dtype(jnp.float64):
            epoch = now_value  # exact in f64; deltas small enough for f32
            ts = ts - epoch
            hot_ts = hot_ts - epoch
            now_value = 0.0
        n = ts.shape[0]
        if capacity is None:
            capacity = np.full((n,), 1 << 30, dtype=np.int64)
        if offsets is None:
            offsets = np.zeros((n,), dtype=np.int32)
        host = (
            np.ascontiguousarray(np.asarray(snapshot.values), dtype=np_dtype),
            np.ascontiguousarray(ts, dtype=np_dtype),
            np.ascontiguousarray(np.asarray(snapshot.hot_value), dtype=np_dtype),
            np.ascontiguousarray(hot_ts, dtype=np_dtype),
            np.ascontiguousarray(np.asarray(snapshot.node_valid), dtype=bool),
            np.ascontiguousarray(np.asarray(capacity, dtype=np.int64)),
            np.ascontiguousarray(np.asarray(offsets, dtype=np.int32)),
        )
        values_d, ts_d, hot_d, hot_ts_d, valid_d, cap_d, off_d = jax.device_put(
            host,
            (self._row, self._row, self._vec, self._vec, self._vec,
             self._vec, self._vec),
        )
        ovr = {}
        if self.hybrid:
            ovr = self._override_vectors(snapshot, float(now))
        return PreparedSnapshot(
            values=values_d,
            ts=ts_d,
            hot_value=hot_d,
            hot_ts=hot_ts_d,
            node_valid=valid_d,
            now=jnp.asarray(now_value, self.scorer.dtype),
            capacity=cap_d,
            offsets=off_d,
            epoch=epoch,
            **ovr,
        )

    def _override_vectors(
        self, snapshot, now: float, rebase_age: float = 0.0,
        cache=None, dirty_rows=None,
    ) -> dict:
        """Compute + device-put the hybrid f64 rescue vectors for
        ``(snapshot, now)``. With ``cache`` (an OverrideCache from an
        earlier call), only dirty/boundary-band rows rescan — but this
        path always re-uploads the full [N] vectors; ``with_overrides``
        owns the cheaper device-side scatter."""
        from ..scorer.hybrid import compute_overrides_incremental

        ovr_mask, ovr_sched, ovr_score, _, new_cache, scanned = (
            compute_overrides_incremental(
                self.tensors,
                snapshot.values,
                snapshot.ts,
                snapshot.hot_value,
                snapshot.hot_ts,
                snapshot.node_valid,
                now,
                cache=cache,
                dirty_rows=dirty_rows,
                rebase_age=rebase_age,
            )
        )
        mask_d, sched_d, score_d = jax.device_put(
            (
                np.ascontiguousarray(ovr_mask),
                np.ascontiguousarray(ovr_sched),
                np.ascontiguousarray(ovr_score, dtype=np.int32),
            ),
            (self._vec, self._vec, self._vec),
        )
        return {
            "ovr_mask": mask_d,
            "ovr_sched": sched_d,
            "ovr_score": score_d,
            "ovr_now": now,
            "ovr_cache": new_cache,
            "ovr_rescan_rows": scanned,
        }

    def with_overrides(
        self, prepared: PreparedSnapshot, snapshot, now: float,
        force: bool = False, dirty_rows=None,
    ) -> PreparedSnapshot:
        """Refresh the hybrid rescue vectors for a new wall time against
        the same (cached) snapshot. No-op for non-hybrid steps or (unless
        ``force``) when the overrides are already current for ``now``.

        With the snapshot's incremental cache (``ovr_cache``), only rows
        whose inputs changed (``dirty_rows`` — pass the store's delta
        rows; ``force`` with ``dirty_rows=None`` means unknown dirt and
        falls back to a full rescan) or whose cached verdict can flip
        with the clock (staleness-boundary band) are rescanned, and the
        refreshed rows SCATTER into the resident device vectors — the
        common annotator tick costs O(dirty) host work and a tiny upload
        (zero when nothing changed) instead of an O(N·M) rescan plus
        three [N] uploads.

        The f32 rounding of the rebased timestamps grows with
        ``now - epoch`` (the cached snapshot's age); the risk scan widens
        its tolerance to match, and past ~6h the whole snapshot is
        re-prepared with a fresh epoch to keep the rescue fraction small.
        """
        tel = self.telemetry
        if tel is None or not self.hybrid or (
            not force and prepared.ovr_now == float(now)
        ):
            return self._with_overrides_impl(
                prepared, snapshot, now, force, dirty_rows
            )
        t0 = time.perf_counter()
        out = self._with_overrides_impl(
            prepared, snapshot, now, force, dirty_rows
        )
        tel.spans.record(
            "risk_rescan", t0, time.perf_counter(),
            args={"rows": int(out.ovr_rescan_rows)},
        )
        return out

    def _with_overrides_impl(
        self, prepared: PreparedSnapshot, snapshot, now: float,
        force: bool = False, dirty_rows=None,
    ) -> PreparedSnapshot:
        import dataclasses

        if not self.hybrid or (not force and prepared.ovr_now == float(now)):
            return prepared
        age = abs(float(now) - prepared.epoch)
        if age > EPOCH_REBASE_SECONDS:  # hybrid is always non-f64 (see __init__)
            # re-rebase the resident matrices around the current time
            # (capacity/offsets are age-independent; carry them over)
            dtype = self.scorer.dtype
            ts = np.asarray(snapshot.ts, np.float64) - float(now)
            hot_ts = np.asarray(snapshot.hot_ts, np.float64) - float(now)
            return dataclasses.replace(
                prepared,
                ts=jax.device_put(jnp.asarray(ts, dtype), self._row),
                hot_ts=jax.device_put(jnp.asarray(hot_ts, dtype), self._vec),
                now=jnp.asarray(0.0, dtype),
                epoch=float(now),
                **self._override_vectors(snapshot, float(now), rebase_age=0.0),
            )
        cache = prepared.ovr_cache if prepared.ovr_mask is not None else None
        if force and dirty_rows is None:
            cache = None  # unknown mutations: a full rescan is required
        if cache is None:
            return dataclasses.replace(
                prepared,
                **self._override_vectors(snapshot, float(now), rebase_age=age),
            )
        from ..scorer.hybrid import compute_overrides_incremental

        mask, sched, score, changed, new_cache, scanned = (
            compute_overrides_incremental(
                self.tensors,
                snapshot.values,
                snapshot.ts,
                snapshot.hot_value,
                snapshot.hot_ts,
                snapshot.node_valid,
                float(now),
                cache=cache,
                dirty_rows=dirty_rows,
                rebase_age=age,
            )
        )
        if changed is None:
            # cache was rebuilt from scratch: full [N] re-upload
            mask_d, sched_d, score_d = jax.device_put(
                (mask, sched, np.ascontiguousarray(score, dtype=np.int32)),
                (self._vec, self._vec, self._vec),
            )
            return dataclasses.replace(
                prepared, ovr_mask=mask_d, ovr_sched=sched_d,
                ovr_score=score_d, ovr_now=float(now),
                ovr_cache=new_cache, ovr_rescan_rows=scanned,
            )
        if changed.size == 0:
            # nothing to change on device: zero host scan, zero upload
            return dataclasses.replace(
                prepared, ovr_now=float(now), ovr_cache=new_cache,
                ovr_rescan_rows=0,
            )
        import math as _math

        k = changed.size
        kpad = 1 << max(0, _math.ceil(_math.log2(k)))
        npad = int(prepared.capacity.shape[0])
        idx = np.full((kpad,), npad, dtype=np.int32)  # pad rows drop
        idx[:k] = changed
        m_rows = np.zeros((kpad,), dtype=bool)
        m_rows[:k] = mask[changed]
        s_rows = np.zeros((kpad,), dtype=bool)
        s_rows[:k] = sched[changed]
        sc_rows = np.zeros((kpad,), dtype=np.int32)
        sc_rows[:k] = score[changed]
        mask_d, sched_d, score_d = self._jit_ovr_scatter(
            prepared.ovr_mask, prepared.ovr_sched, prepared.ovr_score,
            jnp.asarray(idx), jnp.asarray(m_rows), jnp.asarray(s_rows),
            jnp.asarray(sc_rows),
        )
        return dataclasses.replace(
            prepared, ovr_mask=mask_d, ovr_sched=sched_d, ovr_score=score_d,
            ovr_now=float(now), ovr_cache=new_cache, ovr_rescan_rows=scanned,
        )

    @functools.cached_property
    def _jit_ovr_scatter(self):
        def scatter(mask, sched, score, idx, m_rows, s_rows, sc_rows):
            # mode="drop": the kpad padding indices point past the array
            return (
                mask.at[idx].set(m_rows, mode="drop"),
                sched.at[idx].set(s_rows, mode="drop"),
                score.at[idx].set(sc_rows, mode="drop"),
            )

        return jax.jit(
            scatter,
            in_shardings=(
                self._vec, self._vec, self._vec,
                self._rep, self._rep, self._rep, self._rep,
            ),
            out_shardings=(self._vec, self._vec, self._vec),
        )

    def apply_delta(
        self,
        prepared: PreparedSnapshot,
        rows,
        values_rows,
        ts_rows,
        hot_rows,
        hot_ts_rows,
    ) -> PreparedSnapshot:
        """Scatter changed rows into the resident device arrays instead
        of re-uploading full matrices (the annotator touches a handful of
        rows per tick; full prepare is O(N·M) H2D). Timestamps rebase to
        the prepared snapshot's existing epoch, so the result is
        bit-identical to a full ``prepare`` of the updated store at the
        same epoch. Row counts pad to power-of-two buckets (out-of-range
        indices drop) so jit variants stay few. Hybrid callers must
        refresh their override vectors afterwards — the rescue rows
        derive from the data that just changed."""
        k = len(rows)
        if k == 0:
            return prepared
        with maybe_span(self.telemetry, "h2d_delta", rows=int(k)):
            return self._apply_delta_impl(
                prepared, rows, values_rows, ts_rows, hot_rows, hot_ts_rows
            )

    def _apply_delta_impl(
        self, prepared, rows, values_rows, ts_rows, hot_rows, hot_ts_rows
    ):
        import dataclasses
        import math as _math

        k = len(rows)
        dtype = self.scorer.dtype
        kpad = 1 << max(0, _math.ceil(_math.log2(k)))
        npad = int(prepared.capacity.shape[0])
        idx = np.full((kpad,), npad, dtype=np.int32)  # pad rows drop
        idx[:k] = np.asarray(rows, np.int64)
        m = self.tensors.num_metrics

        def pad(a, fill, shape):
            out = np.full(shape, fill, dtype=np.float64)
            out[:k] = a
            return out

        ts_rows = np.asarray(ts_rows, np.float64) - prepared.epoch
        hot_ts_rows = np.asarray(hot_ts_rows, np.float64) - prepared.epoch
        values2, ts2, hot2, hot_ts2 = self._jit_delta(
            prepared.values,
            prepared.ts,
            prepared.hot_value,
            prepared.hot_ts,
            jnp.asarray(idx),
            jnp.asarray(pad(values_rows, np.nan, (kpad, m)), dtype),
            jnp.asarray(pad(ts_rows, -np.inf, (kpad, m)), dtype),
            jnp.asarray(pad(hot_rows, np.nan, (kpad,)), dtype),
            jnp.asarray(pad(hot_ts_rows, -np.inf, (kpad,)), dtype),
        )
        return dataclasses.replace(
            prepared, values=values2, ts=ts2, hot_value=hot2, hot_ts=hot_ts2
        )

    def apply_columns(self, prepared: PreparedSnapshot, entries, n: int):
        """Replay a store column-write log (``NodeLoadStore.
        column_delta_since``) against the resident device arrays.

        The annotator's bulk sweep writes whole columns — one [N] value
        vector per metric with one shared timestamp — so a cycle's
        refresh uploads ~[N] floats per touched column instead of the
        full [N, M] matrices (the tunnel H2D of full matrices dominated
        the 50k-node refresh). Timestamps rebase to the prepared epoch;
        uniform ts columns upload as a scalar. Bit-identical scoring
        results to a full ``prepare`` of the updated store at the same
        epoch (pad rows may carry a fresher ts under a uniform-ts column
        set; they are node_valid=False and never score).
        """
        with maybe_span(
            self.telemetry, "h2d_columns", entries=int(len(entries))
        ):
            return self._apply_columns_impl(prepared, entries, n)

    def _apply_columns_impl(self, prepared: PreparedSnapshot, entries, n: int):
        import dataclasses
        import math as _math

        dtype = self.scorer.dtype
        npad = int(prepared.capacity.shape[0])
        values, ts = prepared.values, prepared.ts
        hot, hot_ts = prepared.hot_value, prepared.hot_ts
        for col, ids, v, t, hv, ht in entries:
            full = len(ids) == n and np.array_equal(
                ids, np.arange(n, dtype=ids.dtype)
            )
            if col is not None:
                t64 = np.asarray(t, np.float64) - prepared.epoch
                if full:
                    v_pad = np.full((npad,), np.nan)
                    v_pad[:n] = v
                    if t64.size and np.all(t64 == t64[0]):
                        values, ts = self._jit_col_set_uniform(
                            values, ts, jnp.asarray(int(col)),
                            jnp.asarray(v_pad, dtype),
                            jnp.asarray(t64[0], dtype),
                        )
                    else:
                        t_pad = np.full((npad,), -np.inf)
                        t_pad[:n] = t64
                        values, ts = self._jit_col_set(
                            values, ts, jnp.asarray(int(col)),
                            jnp.asarray(v_pad, dtype),
                            jnp.asarray(t_pad, dtype),
                        )
                else:
                    k = len(ids)
                    kpad = 1 << max(0, _math.ceil(_math.log2(max(k, 1))))
                    idx = np.full((kpad,), npad, dtype=np.int32)
                    idx[:k] = ids
                    v_rows = np.full((kpad,), np.nan)
                    v_rows[:k] = v
                    t_rows = np.full((kpad,), -np.inf)
                    t_rows[:k] = t64
                    values, ts = self._jit_col_scatter(
                        values, ts, jnp.asarray(idx), jnp.asarray(int(col)),
                        jnp.asarray(v_rows, dtype), jnp.asarray(t_rows, dtype),
                    )
            if hv is not None:
                ht64 = np.asarray(ht, np.float64) - prepared.epoch
                k = len(ids)
                if full:
                    h_pad = np.full((npad,), np.nan)
                    h_pad[:k] = hv
                    ht_pad = np.full((npad,), -np.inf)
                    ht_pad[:k] = ht64
                    hot = jax.device_put(jnp.asarray(h_pad, dtype), self._vec)
                    hot_ts = jax.device_put(jnp.asarray(ht_pad, dtype), self._vec)
                else:
                    kpad = 1 << max(0, _math.ceil(_math.log2(max(k, 1))))
                    idx = np.full((kpad,), npad, dtype=np.int32)
                    idx[:k] = ids
                    h_rows = np.full((kpad,), np.nan)
                    h_rows[:k] = hv
                    ht_rows = np.full((kpad,), -np.inf)
                    ht_rows[:k] = ht64
                    hot, hot_ts = self._jit_hot_scatter(
                        hot, hot_ts, jnp.asarray(idx),
                        jnp.asarray(h_rows, dtype), jnp.asarray(ht_rows, dtype),
                    )
        return dataclasses.replace(
            prepared, values=values, ts=ts, hot_value=hot, hot_ts=hot_ts
        )

    @functools.cached_property
    def _jit_col_set(self):
        def set_col(values, ts, col, v_pad, t_pad):
            npad = values.shape[0]
            values = jax.lax.dynamic_update_slice(
                values, v_pad.reshape(npad, 1), (0, col)
            )
            ts = jax.lax.dynamic_update_slice(ts, t_pad.reshape(npad, 1), (0, col))
            return values, ts

        return jax.jit(
            set_col,
            in_shardings=(self._row, self._row, self._rep, self._vec, self._vec),
            out_shardings=(self._row, self._row),
        )

    @functools.cached_property
    def _jit_col_set_uniform(self):
        def set_col(values, ts, col, v_pad, t_scalar):
            npad = values.shape[0]
            values = jax.lax.dynamic_update_slice(
                values, v_pad.reshape(npad, 1), (0, col)
            )
            ts = jax.lax.dynamic_update_slice(
                ts, jnp.full((npad, 1), t_scalar, ts.dtype), (0, col)
            )
            return values, ts

        return jax.jit(
            set_col,
            in_shardings=(
                self._row, self._row, self._rep, self._vec, self._rep,
            ),
            out_shardings=(self._row, self._row),
        )

    @functools.cached_property
    def _jit_col_scatter(self):
        def scatter(values, ts, idx, col, v_rows, t_rows):
            return (
                values.at[idx, col].set(v_rows, mode="drop"),
                ts.at[idx, col].set(t_rows, mode="drop"),
            )

        return jax.jit(
            scatter,
            in_shardings=(
                self._row, self._row, self._rep, self._rep, self._rep, self._rep,
            ),
            out_shardings=(self._row, self._row),
        )

    @functools.cached_property
    def _jit_hot_scatter(self):
        def scatter(hot, hot_ts, idx, h_rows, ht_rows):
            return (
                hot.at[idx].set(h_rows, mode="drop"),
                hot_ts.at[idx].set(ht_rows, mode="drop"),
            )

        return jax.jit(
            scatter,
            in_shardings=(self._vec, self._vec, self._rep, self._rep, self._rep),
            out_shardings=(self._vec, self._vec),
        )

    @functools.cached_property
    def _jit_delta(self):
        def scatter(values, ts, hot, hot_ts, idx, v_rows, t_rows, h_rows, ht_rows):
            # mode="drop": the kpad padding indices point past the array
            return (
                values.at[idx].set(v_rows, mode="drop"),
                ts.at[idx].set(t_rows, mode="drop"),
                hot.at[idx].set(h_rows, mode="drop"),
                hot_ts.at[idx].set(ht_rows, mode="drop"),
            )

        return jax.jit(
            scatter,
            in_shardings=(
                self._row, self._row, self._vec, self._vec,
                self._rep, self._rep, self._rep, self._rep, self._rep,
            ),
            out_shardings=(self._row, self._row, self._vec, self._vec),
        )

    def with_vectors(
        self, prepared: PreparedSnapshot, capacity=None, offsets=None
    ) -> PreparedSnapshot:
        """Clone a prepared snapshot with new per-node gang vectors,
        reusing the resident load matrices (uploads only [N]-sized data —
        the per-gang-request path)."""
        import dataclasses

        changes = {}
        if capacity is not None:
            capacity = np.minimum(np.asarray(capacity, np.int64), 2**31 - 1)
            changes["capacity"] = jax.device_put(jnp.asarray(capacity), self._vec)
        if offsets is not None:
            changes["offsets"] = jax.device_put(
                jnp.asarray(offsets, jnp.int32), self._vec
            )
        return dataclasses.replace(prepared, **changes) if changes else prepared

    def _args(self, prepared: PreparedSnapshot, num_pods, now):
        now_arr = (
            prepared.now
            if now is None
            else jnp.asarray(float(now) - prepared.epoch, self.scorer.dtype)
        )
        vecs = (
            prepared.values,
            prepared.ts,
            prepared.hot_value,
            prepared.hot_ts,
            prepared.node_valid,
            now_arr,
            prepared.capacity,
            prepared.offsets,
        )
        if self.hybrid:
            if prepared.ovr_mask is None:
                raise ValueError(
                    "hybrid step requires a snapshot prepared with overrides "
                    "(use prepare()/with_overrides of a hybrid step)"
                )
            if now is not None and prepared.ovr_now != float(now):
                raise ValueError(
                    "hybrid overrides are stale for this `now`; call "
                    "with_overrides(prepared, snapshot, now) first"
                )
            vecs = vecs + (prepared.ovr_mask, prepared.ovr_sched, prepared.ovr_score)
        return vecs, jnp.asarray(num_pods)

    def __call__(
        self, prepared: PreparedSnapshot, num_pods, now: float | None = None
    ) -> ShardedStepResult:
        out = self._jit(*self._args(prepared, num_pods, now))
        return ShardedStepResult(*out)

    def packed(self, prepared: PreparedSnapshot, num_pods, now: float | None = None):
        """One-fetch variant. Bursts below ``COMPACT_MAX_PODS`` use the
        compact [N+2] uint32 layout (1/3 the tunnel bytes of the wide
        [3N+2] int32 — ~60ms/fetch at 50k nodes over a ~7MB/s tunnel —
        at the same single round-trip); larger bursts fall back to the
        wide layout. ``unpack`` discriminates by dtype."""
        args = self._args(prepared, num_pods, now)
        if num_pods < COMPACT_MAX_PODS:
            return self._jit_packed_compact(*args)
        return self._jit_packed(*args)

    @staticmethod
    def unpack(packed_host: np.ndarray, n: int):
        """Split a fetched packed result into host-side step outputs
        (wide int32 or compact uint32 — see ``_step_packed_compact``)."""
        if packed_host.dtype == np.uint32:
            body = packed_host[:n]
            counts = (body & _COMPACT_COUNT_MASK).astype(np.int32)
            scores = (
                (body >> COMPACT_COUNT_BITS) & _COMPACT_SCORE_MASK
            ).astype(np.int32)
            schedulable = (body >> 31).astype(bool)
            unassigned = int(packed_host[-2])
            waterline = int(packed_host[-2:].view(np.int32)[1])
            return schedulable, scores, counts, unassigned, waterline
        npad = (packed_host.shape[0] - 2) // 3
        schedulable = packed_host[:n].astype(bool)
        scores = packed_host[npad : npad + n]
        counts = packed_host[2 * npad : 2 * npad + n]
        unassigned = int(packed_host[3 * npad])
        waterline = int(packed_host[3 * npad + 1])
        return schedulable, scores, counts, unassigned, waterline


class DeviceColumnCache:
    """Identity-keyed device mirrors of host numpy columns.

    The drip batch kernel (``scorer.drip_batch``) re-dispatches against
    the same cluster columns for many windows in a row; re-uploading
    50k-node columns per window would cost more than the kernel. Column
    rebuilds always REPLACE the host arrays (``framework.drip`` never
    resizes in place), so object identity plus an optional caller
    version is a sound cache key. The slot pins the host array, so an
    ``id()`` can never be recycled while its key is live.

    ``prepare`` (e.g. pad-to-bucket) runs only on upload, never on a
    hit.
    """

    def __init__(self, device=None):
        self._device = device
        self._slots: dict[str, tuple] = {}
        self.uploads = 0
        self.scatters = 0  # delta-row device patches (avoided uploads)
        # mesh-repartition fence: cached device arrays are placed for
        # ONE partitioning (device set + shard spec). set_partition()
        # drops everything when that changes — a resized mesh must
        # never serve columns (or let a kernel replay a fold carry)
        # laid out for the old partitioning.
        self._partition_token = None
        self.repartitions = 0

    def set_partition(self, token) -> bool:
        """Declare the current partitioning (any hashable/equatable
        token — e.g. ``(tuple(mesh.devices.flat), mesh.axis_names)``).
        Returns True (and drops every slot) when it changed."""
        if token == self._partition_token:
            return False
        changed = self._partition_token is not None
        self._partition_token = token
        if changed:
            self._slots.clear()
            self.repartitions += 1
        return changed

    def held_version(self, name: str, arr):
        """The version the cached slot for ``name`` holds, or None when
        the slot is absent or keyed to a different array object — the
        input for ``DripColumns.dirty_rows_between`` when building a
        ``delta_rows`` scatter."""
        slot = self._slots.get(name)
        if slot is None:
            return None
        key = slot[0]
        if key[0] != id(arr) or key[1] != arr.shape:
            return None
        return key[2]

    def put(self, name: str, arr, version=0, prepare=None, device=None,
            delta_rows=None, row_prepare=None):
        """Device array for ``arr``, uploading only when the
        ``(identity, shape, version)`` key changed since the last call.
        ``device`` overrides the cache-wide placement for this column
        (e.g. a ``NamedSharding`` for mesh-sharded columns).

        ``delta_rows`` (int array) declares that the held slot differs
        from ``arr`` ONLY at those rows (same array object, patched in
        place between the held version and ``version`` — see
        ``DripColumns.dirty_rows_between``): the device copy is patched
        with one scatter instead of a full re-upload, so a 1-node
        annotation write at 1M nodes moves a handful of rows over PCIe
        rather than the whole column. ``row_prepare`` is the elementwise
        (dtype) half of ``prepare`` applied to the scattered rows;
        shape-changing prepares (pad-to-bucket) keep working because
        padding sits past every row index. Mesh-sharded placements
        (``device=``) skip the scatter and re-upload."""
        key = (id(arr), arr.shape, version)
        slot = self._slots.get(name)
        if slot is not None and slot[0] == key:
            return slot[1]
        if (
            delta_rows is not None
            and device is None
            and slot is not None
            and slot[0][0] == id(arr)
            and slot[0][1] == arr.shape
        ):
            if len(delta_rows) == 0:
                dev = slot[1]
            else:
                vals = arr[delta_rows]
                if row_prepare is not None:
                    vals = row_prepare(vals)
                dev = slot[1].at[jnp.asarray(delta_rows)].set(
                    jnp.asarray(vals))
            self._slots[name] = (key, dev, arr)
            self.scatters += 1
            return dev
        host = arr if prepare is None else prepare(arr)
        dev = jax.device_put(host, device if device is not None else self._device)
        self._slots[name] = (key, dev, arr)
        self.uploads += 1
        return dev

    def drop(self, name: str | None = None) -> None:
        if name is None:
            self._slots.clear()
        else:
            self._slots.pop(name, None)
