"""The full scheduling step, sharded over a device mesh (GSPMD/pjit).

One jitted function runs the complete batch cycle for a pod burst:

    load matrix [N, M] (node-sharded)
      -> filter mask + scores            (elementwise per shard, no comms)
      -> gang water-filling              (102-level token counts per shard;
                                          XLA inserts psum for level totals
                                          and an all-gather/scan for the
                                          node-index prefix sum over ICI)
      -> per-node assignment counts [N]  (node-sharded)

This is the idiomatic pjit shape: annotate input/output shardings on a
``Mesh`` and let the compiler place collectives (instead of translating
the reference's Go worker pools into explicit message passing). The math
is identical to ``scorer.BatchedScorer`` + ``scorer.topk.GangScheduler``,
which are validated bit-for-bit against the scalar oracles; this module
only changes *where* it runs.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from ..policy.compile import PolicyTensors
from ..scorer.batched import BatchedScorer
from ..scorer.topk import GangScheduler
from .mesh import node_sharding, replicated_sharding


@dataclass
class PreparedSnapshot:
    """Device-resident, sharded scoring inputs.

    In float32 mode timestamps are stored rebased to ``now`` (epoch
    seconds don't survive a float32 downcast) and ``now`` is 0.
    """

    values: Any  # [N, M] dtype, node-sharded
    ts: Any  # [N, M] dtype, node-sharded (possibly rebased)
    hot_value: Any  # [N]
    hot_ts: Any  # [N] (possibly rebased)
    node_valid: Any  # [N] bool
    now: Any  # scalar dtype
    capacity: Any  # [N] int64


@dataclass
class ShardedStepResult:
    schedulable: Any  # [N] bool, node-sharded
    scores: Any  # [N] int32, node-sharded
    counts: Any  # [N] int32, node-sharded — pods assigned per node
    unassigned: Any  # scalar int64, replicated
    waterline: Any  # scalar int64, replicated


class ShardedScheduleStep:
    """score + gang-assign, jitted with node-axis shardings on ``mesh``."""

    def __init__(self, tensors: PolicyTensors, mesh: Mesh, dtype=jnp.float32):
        self.mesh = mesh
        self.scorer = BatchedScorer(tensors, dtype=dtype)
        self.gang = GangScheduler(tensors.hv_count)
        row = node_sharding(mesh, 2)
        vec = node_sharding(mesh, 1)
        rep = replicated_sharding(mesh)
        self._row, self._vec, self._rep = row, vec, rep
        self._jit = jax.jit(
            self._step,
            in_shardings=((row, row, vec, vec, vec, rep, vec), rep),
            out_shardings=(vec, vec, vec, rep, rep),
        )

    def _step(self, prepared, num_pods):
        values, ts, hot_value, hot_ts, node_valid, now, capacity = prepared
        schedulable, scores = self.scorer._score_impl(
            values, ts, hot_value, hot_ts, node_valid, now
        )
        counts, unassigned, waterline = self.gang._assign_impl(
            scores, schedulable, num_pods, capacity
        )
        return schedulable, scores, counts, unassigned, waterline

    def prepare(self, snapshot, now: float, capacity=None) -> PreparedSnapshot:
        """Upload a store snapshot with node-axis shardings.

        Host -> device transfer happens here, once per refresh; the jitted
        step then reruns against the resident arrays for any pod batch.
        """
        dtype = self.scorer.dtype
        ts = np.asarray(snapshot.ts, np.float64)
        hot_ts = np.asarray(snapshot.hot_ts, np.float64)
        now_value = float(now)
        if dtype != jnp.dtype(jnp.float64):
            ts = ts - now_value  # exact in f64; small enough for f32
            hot_ts = hot_ts - now_value
            now_value = 0.0
        n = ts.shape[0]
        if capacity is None:
            capacity = np.full((n,), 1 << 30, dtype=np.int64)
        return PreparedSnapshot(
            values=jax.device_put(jnp.asarray(snapshot.values, dtype), self._row),
            ts=jax.device_put(jnp.asarray(ts, dtype), self._row),
            hot_value=jax.device_put(jnp.asarray(snapshot.hot_value, dtype), self._vec),
            hot_ts=jax.device_put(jnp.asarray(hot_ts, dtype), self._vec),
            node_valid=jax.device_put(
                jnp.asarray(snapshot.node_valid, jnp.bool_), self._vec
            ),
            now=jnp.asarray(now_value, dtype),
            capacity=jax.device_put(jnp.asarray(capacity), self._vec),
        )

    def __call__(self, prepared: PreparedSnapshot, num_pods) -> ShardedStepResult:
        out = self._jit(
            (
                prepared.values,
                prepared.ts,
                prepared.hot_value,
                prepared.hot_ts,
                prepared.node_valid,
                prepared.now,
                prepared.capacity,
            ),
            jnp.asarray(num_pods),
        )
        return ShardedStepResult(*out)
