"""Device-mesh helpers: the node axis is the scaling axis.

The reference scales by sharding node/metric work items across Go worker
pools (ref: pkg/controller/annotator/node.go:148-177); here the analogous
axis — the cluster's node dimension — shards across TPU devices on a 1-D
``jax.sharding.Mesh``. Scoring is elementwise over nodes (no cross-node
dependencies), and gang water-filling needs only small cross-shard
reductions/scans ([102]-level totals and one prefix sum), which XLA lowers
to psum/all-gather over ICI.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

NODE_AXIS = "nodes"


def make_node_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """1-D mesh over the node axis using the first ``n_devices`` devices."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        if len(devices) < n_devices:
            raise ValueError(
                f"requested {n_devices} devices, only {len(devices)} available"
            )
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (NODE_AXIS,))


def node_sharding(mesh: Mesh, ndim: int = 1) -> NamedSharding:
    """Shard dim 0 (the node axis); later dims (metrics) replicated."""
    spec = PartitionSpec(NODE_AXIS, *([None] * (ndim - 1)))
    return NamedSharding(mesh, spec)


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


# -- placement mesh (sharded drip plane, doc/sharding.md) -------------------

# The drip batch kernel's shard axis carries the same name as the node
# axis: columns are node-major, and the placement mesh is just the node
# mesh under a role-specific constructor so callers (scheduler CLI,
# bench, smoke) can ask for "the placement mesh" without caring that it
# is 1-D over nodes today.
PLACEMENT_MESH_NAME = "placement"


def make_placement_mesh(n_shards: int | None = None, devices=None) -> Mesh:
    """Named 1-D placement mesh: the drip columns shard along
    ``NODE_AXIS`` across ``n_shards`` devices (default: all local
    devices). A 1-device mesh is valid and degrades the sharded kernel
    to the single-device program."""
    return make_node_mesh(n_shards, devices)


def mesh_shape(mesh: Mesh) -> dict:
    """Self-describing mesh metadata for bench/smoke result blobs."""
    return {
        "axes": dict(zip(mesh.axis_names, mesh.devices.shape)),
        "devices": int(mesh.devices.size),
    }


def round_up_to_shards(n: int, mesh: Mesh) -> int:
    """Smallest multiple of the mesh's node-axis size >= ``n`` (sharded
    arrays need equal per-device tiles)."""
    s = int(mesh.devices.size)
    return -(-int(n) // s) * s
