"""Multi-host scheduling: the node axis sharded across hosts over DCN.

The reference has no in-process distributed backend — coordination rides
the kube-apiserver and leader election (SURVEY §2.10/§5). The TPU-native
equivalent for clusters past one host's HBM/compute is multi-controller
SPMD: every host runs the same jitted step over a global ``Mesh`` of all
devices; XLA places the gang solver's small cross-shard reductions
([L]-level totals psum, node-order prefix sum) on ICI within a host and
DCN across hosts. No hand-written collectives — the same
``ShardedScheduleStep`` program runs unmodified; only array construction
changes (host-local shards -> global arrays).

Deployment shape: each host's annotator syncs the node shard it owns
(``partition_nodes``) into a local ``NodeLoadStore``; scoring assembles
the global load matrix with ``prepare_from_local_shard``. The packed
step result is replicated, so every host sees the full verdict vector
and binds its own nodes' pods.

Driven in tests by a real two-process CPU run (coordinator over
localhost TCP — the DCN stand-in) asserting bit-identical results
against the single-process step.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec

from .mesh import NODE_AXIS


def initialize(
    coordinator_address: str,
    num_processes: int,
    process_id: int,
    local_device_ids=None,
) -> None:
    """``jax.distributed.initialize`` wrapper (idempotent per process).

    Call before any device use. On TPU pods the three arguments are
    normally auto-detected from the environment and may be ``None``; we
    keep them explicit so CPU/DCN dry-runs and tests can drive it.
    """
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
    )


def global_node_mesh() -> Mesh:
    """1-D mesh over ALL global devices (every process must build the
    identical mesh — standard multi-controller contract)."""
    return Mesh(np.array(jax.devices()), (NODE_AXIS,))


def partition_nodes(names, num_processes: int, process_id: int):
    """Contiguous node-name shard owned by ``process_id``.

    Deterministic given identical name order on every host (the
    annotator sorts); the global array assembles shards in process
    order, so global row i maps back to the same node everywhere.
    """
    names = list(names)
    n = len(names)
    base, rem = divmod(n, num_processes)
    start = process_id * base + min(process_id, rem)
    end = start + base + (1 if process_id < rem else 0)
    return names[start:end]


def host_local_to_global(local: np.ndarray, mesh: Mesh, sharded_dim0: bool = True):
    """Assemble per-host shards into one global jax.Array.

    With ``sharded_dim0`` the hosts' dim-0 shards concatenate in process
    order along the node axis; otherwise the input must be identical on
    every host (replicated)."""
    from jax.experimental import multihost_utils

    spec = (
        PartitionSpec(NODE_AXIS, *([None] * (local.ndim - 1)))
        if sharded_dim0
        else PartitionSpec()
    )
    return multihost_utils.host_local_array_to_global_array(local, mesh, spec)


def prepare_from_local_shard(
    step, snapshot, now: float, capacity=None, offsets=None
):
    """Multi-host twin of ``ShardedScheduleStep.prepare``: ``snapshot``
    holds only THIS host's node shard; the returned PreparedSnapshot
    wraps global arrays spanning every host's shard.

    The local shard length must be equal across hosts (pad each host's
    store snapshot to the same bucket multiple).

    For a hybrid step (f32 with f64 rescue rows — scorer.hybrid), each
    host computes the exact f64 rescue vectors for ITS shard only and
    they assemble globally like every other node-axis vector, so
    multi-host f32 placements keep bit-for-bit Go/f64 parity without any
    host ever seeing the full load matrix.
    """
    import jax.numpy as jnp

    from .sharded import PreparedSnapshot

    dtype = step.scorer.dtype
    ts = np.asarray(snapshot.ts, np.float64)
    hot_ts = np.asarray(snapshot.hot_ts, np.float64)
    now_value = float(now)
    epoch = 0.0
    if dtype != jnp.dtype(jnp.float64):
        epoch = now_value
        ts = ts - epoch
        hot_ts = hot_ts - epoch
        now_value = 0.0
    n = ts.shape[0]
    if capacity is None:
        capacity = np.full((n,), 1 << 30, dtype=np.int64)
    if offsets is None:
        offsets = np.zeros((n,), dtype=np.int32)
    mesh = step.mesh
    np_dtype = np.dtype(dtype)
    ovr = {}
    if getattr(step, "hybrid", False):
        from ..scorer.hybrid import compute_overrides

        ovr_mask, ovr_sched, ovr_score, _ = compute_overrides(
            step.tensors,
            snapshot.values,
            snapshot.ts,
            snapshot.hot_value,
            snapshot.hot_ts,
            snapshot.node_valid,
            float(now),
        )
        ovr = {
            "ovr_mask": host_local_to_global(np.asarray(ovr_mask, bool), mesh),
            "ovr_sched": host_local_to_global(np.asarray(ovr_sched, bool), mesh),
            "ovr_score": host_local_to_global(
                np.asarray(ovr_score, np.int32), mesh
            ),
            "ovr_now": float(now),
        }
    return PreparedSnapshot(
        values=host_local_to_global(
            np.asarray(snapshot.values, np_dtype), mesh
        ),
        ts=host_local_to_global(np.asarray(ts, np_dtype), mesh),
        hot_value=host_local_to_global(
            np.asarray(snapshot.hot_value, np_dtype), mesh
        ),
        hot_ts=host_local_to_global(np.asarray(hot_ts, np_dtype), mesh),
        node_valid=host_local_to_global(
            np.asarray(snapshot.node_valid, bool), mesh
        ),
        now=jnp.asarray(now_value, dtype),
        capacity=host_local_to_global(np.asarray(capacity, np.int64), mesh),
        offsets=host_local_to_global(np.asarray(offsets, np.int32), mesh),
        epoch=epoch,
        **ovr,
    )
