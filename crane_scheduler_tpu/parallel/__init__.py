from .mesh import make_node_mesh, node_sharding, replicated_sharding
from .sharded import ShardedScheduleStep

__all__ = [
    "make_node_mesh",
    "node_sharding",
    "replicated_sharding",
    "ShardedScheduleStep",
]
