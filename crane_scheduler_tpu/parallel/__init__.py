from .distributed import (
    global_node_mesh,
    host_local_to_global,
    initialize,
    partition_nodes,
    prepare_from_local_shard,
)
from .mesh import (
    make_node_mesh,
    make_placement_mesh,
    mesh_shape,
    node_sharding,
    replicated_sharding,
    round_up_to_shards,
)
from .sharded import ShardedScheduleStep

__all__ = [
    "global_node_mesh",
    "host_local_to_global",
    "initialize",
    "make_node_mesh",
    "make_placement_mesh",
    "mesh_shape",
    "node_sharding",
    "partition_nodes",
    "prepare_from_local_shard",
    "replicated_sharding",
    "round_up_to_shards",
    "ShardedScheduleStep",
]
