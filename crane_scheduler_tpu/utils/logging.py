"""Leveled ``[crane]``-prefixed logging.

The reference logs its hot paths through klog verbosity levels with a
``[crane]`` message prefix (ref: pkg/plugins/dynamic/plugins.go:59,64 —
``klog.V(4).Infof("[crane] ...")``): a default run is QUIET, and
per-cycle diagnostics only appear when the operator raises verbosity.
This module is that convention for the rebuild: ``vlog(level, msg)``
prints ``[crane] msg`` to stderr iff the process verbosity is >= level.

Levels follow the klog habit loosely:
  1 — per-sweep / lifecycle summaries (one line per annotator sync,
      per bind flush window)
  2 — per-cycle scheduling summaries (one line per batch/burst cycle)
  3 — per-pod decisions (drip mode; the plugins.go:59,64 analogue)

Verbosity comes from ``-v``-style CLI flags (``set_verbosity``) or the
``CRANE_VERBOSITY`` env var; the default is 0 (silent). The check is a
plain int compare so a disabled vlog costs nothing measurable on the
hot path.
"""

from __future__ import annotations

import os
import sys

_level = 0
try:
    _level = int(os.environ.get("CRANE_VERBOSITY", "0") or 0)
except ValueError:
    _level = 0


def set_verbosity(level: int) -> None:
    """Set the process verbosity (CLI ``-v`` flags land here)."""
    global _level
    _level = int(level)


def verbosity() -> int:
    return _level


def vlog(level: int, msg: str) -> None:
    """Print ``[crane] msg`` to stderr iff verbosity >= ``level``."""
    if _level >= level:
        print(f"[crane] {msg}", file=sys.stderr, flush=True)
