"""Score arithmetic helpers matching Go integer semantics."""

from __future__ import annotations

import math

_GO_MIN_INT64 = -(2**63)


def go_trunc(x: float) -> int:
    """Go ``int(floatExpr)``: truncation toward zero.

    Non-finite and out-of-int64-range inputs are mapped to Go/amd64's
    "integer indefinite" (min int64) — the observable behavior of
    ``CVTTSD2SI`` for NaN/±Inf/overflow — so downstream clamping matches
    the reference on degenerate paths (ref: pkg/plugins/dynamic/stats.go:135).
    """
    if math.isnan(x) or math.isinf(x):
        return _GO_MIN_INT64
    t = math.trunc(x)
    if t < _GO_MIN_INT64 or t >= 2**63:
        return _GO_MIN_INT64
    return t


def normalize_score(value: int, max_score: int = 100, min_score: int = 0) -> int:
    """Clamp to [min, max] (ref: pkg/utils/utils.go:58-68)."""
    if value < min_score:
        return min_score
    if value > max_score:
        return max_score
    return value
