"""System-namespace resolution (ref: pkg/utils/utils.go:47-55).

The reference reads the ``CRANE_SYSTEM_NAMESPACE`` environment variable
(consumed at cmd/controller/app/options/options.go:52 for the leader-
election lease namespace) and falls back to ``crane-system`` when the
variable is unset or empty.
"""

from __future__ import annotations

import os

DEFAULT_SYSTEM_NAMESPACE = "crane-system"
SYSTEM_NAMESPACE_ENV = "CRANE_SYSTEM_NAMESPACE"


def system_namespace(default: str = DEFAULT_SYSTEM_NAMESPACE) -> str:
    """The namespace system objects (the leader-election Lease) live in:
    ``$CRANE_SYSTEM_NAMESPACE`` when set and non-empty, else
    ``crane-system`` — exactly the reference's GetSystemNamespace."""
    ns = os.environ.get(SYSTEM_NAMESPACE_ENV, "")
    return ns if ns != "" else default
