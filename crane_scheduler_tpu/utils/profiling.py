"""Tracing/profiling hooks.

SURVEY §5: the reference has no tracing — its only latency visibility is
log lines timing each sync. Here:

- ``phase_timer``: lightweight wall-clock phase timing with counters
  (always available, no deps);
- ``jax_trace``: wraps a block in a JAX profiler trace (viewable with
  TensorBoard / xprof) for device-level analysis of the scorer;
- ``chrome_trace``: dumps a telemetry ``SpanRecorder``'s host-side
  pipeline spans as Chrome trace-event JSON at block exit — the host
  twin of ``jax_trace``, viewable in Perfetto / ``chrome://tracing``
  (see crane_scheduler_tpu.telemetry).
"""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict


class PhaseTimer:
    """Accumulates per-phase wall time and counts."""

    def __init__(self):
        self.seconds: dict[str, float] = defaultdict(float)
        self.counts: dict[str, int] = defaultdict(int)

    @contextlib.contextmanager
    def phase(self, name: str):
        start = time.perf_counter()
        try:
            yield
        finally:
            self.seconds[name] += time.perf_counter() - start
            self.counts[name] += 1

    def report(self) -> dict:
        return {
            name: {
                "total_ms": round(self.seconds[name] * 1e3, 3),
                "count": self.counts[name],
                "mean_ms": round(self.seconds[name] * 1e3 / max(self.counts[name], 1), 3),
            }
            for name in sorted(self.seconds)
        }


@contextlib.contextmanager
def jax_trace(log_dir: str | None):
    """JAX profiler trace when ``log_dir`` is set; no-op otherwise."""
    if not log_dir:
        yield
        return
    import jax

    with jax.profiler.trace(log_dir):
        yield


@contextlib.contextmanager
def chrome_trace(recorder, path: str | None):
    """Write ``recorder``'s spans (telemetry.SpanRecorder) as a Chrome
    trace-event JSON file when the block exits; no-op when either side
    is unset. Pairs with ``jax_trace`` for host+device pictures of the
    same run."""
    try:
        yield
    finally:
        if recorder is not None and path:
            recorder.dump(path)
