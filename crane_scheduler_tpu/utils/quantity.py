"""Kubernetes resource-quantity parsing (the subset schedulers need).

Supports the k8s canonical forms: plain/decimal numbers ("2", "0.5"),
milli-suffix ("500m"), binary suffixes (Ki Mi Gi Ti Pi Ei) and decimal
suffixes (k M G T P E), and scientific notation ("1e3"). Values convert to
integer base units the way the reference's ``framework.Resource`` does:
CPU to millicores (rounded up), everything else to whole units
(bytes for memory), matching ``resource.Quantity.MilliValue``/``Value``.
"""

from __future__ import annotations

import functools
import math

_BINARY = {"Ki": 1024, "Mi": 1024**2, "Gi": 1024**3, "Ti": 1024**4, "Pi": 1024**5, "Ei": 1024**6}
_DECIMAL = {"k": 10**3, "M": 10**6, "G": 10**9, "T": 10**12, "P": 10**15, "E": 10**18}


class QuantityError(ValueError):
    pass


def parse_quantity(value) -> float:
    """Parse a quantity into a float of base units.

    Pure, so string parses memoize: a cluster sweep parses the same few
    quantity literals ("64000m", "256Gi", ...) per node per zone, and
    the suffix scan dominated NUMA wrapper-build profiles.
    """
    if isinstance(value, bool):
        raise QuantityError(f"invalid quantity {value!r}")
    if isinstance(value, (int, float)):
        return float(value)
    if not isinstance(value, str) or not value:
        raise QuantityError(f"invalid quantity {value!r}")
    return _parse_str(value)


@functools.lru_cache(maxsize=65536)
def _parse_str(value: str) -> float:
    s = value.strip()
    for suffix, mult in _BINARY.items():
        if s.endswith(suffix):
            return _number(s[: -len(suffix)]) * mult
    if s.endswith("m"):
        return _number(s[:-1]) / 1000.0
    for suffix, mult in _DECIMAL.items():
        if s.endswith(suffix):
            return _number(s[: -len(suffix)]) * mult
    return _number(s)


def _number(s: str) -> float:
    try:
        return float(s)
    except ValueError as e:
        raise QuantityError(f"invalid quantity number {s!r}") from e


def to_milli(value) -> int:
    """Quantity -> integer milli-units, rounding up like
    ``resource.Quantity.MilliValue`` (ceil for fractional nanos)."""
    return int(math.ceil(parse_quantity(value) * 1000 - 1e-9))


def to_value(value) -> int:
    """Quantity -> integer whole units, rounding up like
    ``resource.Quantity.Value``."""
    return int(math.ceil(parse_quantity(value) - 1e-9))
