"""Go-compatible duration parsing.

Policy files express sync periods and hot-value windows as Go duration
strings ("3m", "15m", "3h", "1.5h", "2h45m"); the reference decodes them with
``metav1.Duration`` / ``time.ParseDuration``. This module reproduces that
grammar so the same YAML policy documents decode identically
(ref: pkg/plugins/apis/policy/v1alpha1/types.go:14-39).
"""

from __future__ import annotations

_UNITS = {
    "ns": 1e-9,
    "us": 1e-6,
    "µs": 1e-6,
    "μs": 1e-6,
    "ms": 1e-3,
    "s": 1.0,
    "m": 60.0,
    "h": 3600.0,
}


class DurationError(ValueError):
    pass


def parse_go_duration(s: str) -> float:
    """Parse a Go duration string into seconds (float).

    Grammar per Go ``time.ParseDuration``: an optionally-signed sequence of
    decimal numbers each with optional fraction and a mandatory unit suffix,
    e.g. "300ms", "-1.5h", "2h45m". "0" (bare zero) is allowed.
    """
    if not isinstance(s, str):
        raise DurationError(f"duration must be a string, got {type(s)!r}")
    orig = s
    neg = False
    if s and s[0] in "+-":
        neg = s[0] == "-"
        s = s[1:]
    if s == "0":
        return 0.0
    if not s:
        raise DurationError(f"invalid duration {orig!r}")
    total = 0.0
    i = 0
    n = len(s)
    while i < n:
        start = i
        while i < n and (s[i].isdigit() or s[i] == "."):
            i += 1
        num = s[start:i]
        if not num or num == "." or num.count(".") > 1:
            raise DurationError(f"invalid duration {orig!r}")
        # unit: longest match first
        unit = None
        for u in ("ns", "us", "µs", "μs", "ms", "h", "m", "s"):
            if s.startswith(u, i):
                # bare "m" must not swallow the "m" of "ms"
                unit = u
                break
        if unit is None:
            raise DurationError(f"missing unit in duration {orig!r}")
        i += len(unit)
        total += float(num) * _UNITS[unit]
    return -total if neg else total


def format_go_duration(seconds: float) -> str:
    """Render seconds as a Go-style duration string (h/m/s granularity)."""
    if seconds == 0:
        return "0s"
    neg = seconds < 0
    seconds = abs(seconds)
    parts = []
    h = int(seconds // 3600)
    m = int((seconds % 3600) // 60)
    sec = seconds - h * 3600 - m * 60
    if h:
        parts.append(f"{h}h")
    if m:
        parts.append(f"{m}m")
    if sec:
        if sec == int(sec):
            parts.append(f"{int(sec)}s")
        else:
            parts.append(f"{sec}s")
    return ("-" if neg else "") + "".join(parts)
