"""Timestamp codec reproducing the reference's timezone quirk.

The reference renders annotation timestamps with Go layout
``2006-01-02T15:04:05Z`` **in local time** (env ``TZ``, default
``Asia/Shanghai``): the trailing ``Z`` is a literal character, not a UTC
marker (ref: pkg/utils/utils.go:10-45). The reader parses with
``time.ParseInLocation`` using the same location
(ref: pkg/plugins/dynamic/stats.go:36), so values round-trip — but only if
writer and reader agree on the zone. We reproduce this exactly: wire strings
look like UTC but are local, and we store epoch seconds internally.
"""

from __future__ import annotations

import functools
import os
import time as _time
from datetime import datetime, timezone
from zoneinfo import ZoneInfo

# Go layout "2006-01-02T15:04:05Z" with literal Z, rendered in local TZ.
TIME_FORMAT = "%Y-%m-%dT%H:%M:%SZ"
DEFAULT_TIMEZONE = "Asia/Shanghai"  # ref: pkg/utils/utils.go:12
# Timestamps shorter than this are rejected outright
# (ref: pkg/plugins/dynamic/stats.go:19-20,31-34).
MIN_TIMESTAMP_STR_LENGTH = 5


def _zone_for(zone_name: str) -> ZoneInfo:
    try:
        return ZoneInfo(zone_name)
    except Exception:
        return ZoneInfo(DEFAULT_TIMEZONE)


def get_location() -> ZoneInfo:
    """Resolve the annotation timezone from env ``TZ`` (ref: utils.go:36-45).

    Env is re-read on every call (tests flip ``TZ``); ZoneInfo itself
    caches per zone name, so this is a dict lookup in the steady state.
    """
    return _zone_for(os.environ.get("TZ") or DEFAULT_TIMEZONE)


def now_epoch() -> float:
    return _time.time()


@functools.lru_cache(maxsize=4096)
def _format_cached(whole_seconds: int, zone_key: str) -> str:
    # the zone must derive from the cache KEY, not a second env read — a
    # concurrent TZ flip between the caller's read and this body would
    # otherwise poison the cache under the wrong key
    dt = datetime.fromtimestamp(whole_seconds, tz=timezone.utc).astimezone(
        _zone_for(zone_key)
    )
    return dt.strftime(TIME_FORMAT)


def format_local_time(epoch_seconds: float | None = None) -> str:
    """Epoch seconds -> quirky local-time-with-literal-Z wire string.

    Cached per (whole second, zone): the wire format has second
    precision, and an annotator sync formats the same ``now`` for every
    node x metric — strftime dominated bulk-sync profiles before this.
    The sub-second remainder cannot change the output (strftime has no
    sub-second field in this layout), so truncating the cache key is
    exact.
    """
    if epoch_seconds is None:
        epoch_seconds = _time.time()
    zone_key = os.environ.get("TZ") or DEFAULT_TIMEZONE
    # int() truncates toward zero; fromtimestamp floors — keep exactness
    # for negative epochs by flooring explicitly
    whole = int(epoch_seconds // 1)
    return _format_cached(whole, zone_key)


@functools.lru_cache(maxsize=4096)
def _parse_cached(s: str, zone_key: str) -> float | None:
    try:
        naive = datetime.strptime(s, TIME_FORMAT)
    except ValueError:
        return None
    local = naive.replace(tzinfo=_zone_for(zone_key))  # key-derived zone
    return local.timestamp()


def parse_local_time(s: str) -> float | None:
    """Wire string -> epoch seconds, or None if invalid.

    Mirrors ``inActivePeriod``'s validity checks: too-short strings and
    layout mismatches are rejected (ref: stats.go:30-41). The string is
    interpreted in the configured location, matching
    ``time.ParseInLocation``. Cached per (string, zone): annotation
    sweeps parse the same handful of sync timestamps tens of thousands
    of times, and strptime dominated those profiles.
    """
    if not isinstance(s, str) or len(s) < MIN_TIMESTAMP_STR_LENGTH:
        return None
    return _parse_cached(s, os.environ.get("TZ") or DEFAULT_TIMEZONE)
