"""Timestamp codec reproducing the reference's timezone quirk.

The reference renders annotation timestamps with Go layout
``2006-01-02T15:04:05Z`` **in local time** (env ``TZ``, default
``Asia/Shanghai``): the trailing ``Z`` is a literal character, not a UTC
marker (ref: pkg/utils/utils.go:10-45). The reader parses with
``time.ParseInLocation`` using the same location
(ref: pkg/plugins/dynamic/stats.go:36), so values round-trip — but only if
writer and reader agree on the zone. We reproduce this exactly: wire strings
look like UTC but are local, and we store epoch seconds internally.
"""

from __future__ import annotations

import os
import time as _time
from datetime import datetime, timezone
from zoneinfo import ZoneInfo

# Go layout "2006-01-02T15:04:05Z" with literal Z, rendered in local TZ.
TIME_FORMAT = "%Y-%m-%dT%H:%M:%SZ"
DEFAULT_TIMEZONE = "Asia/Shanghai"  # ref: pkg/utils/utils.go:12
# Timestamps shorter than this are rejected outright
# (ref: pkg/plugins/dynamic/stats.go:19-20,31-34).
MIN_TIMESTAMP_STR_LENGTH = 5


def get_location() -> ZoneInfo:
    """Resolve the annotation timezone from env ``TZ`` (ref: utils.go:36-45)."""
    zone = os.environ.get("TZ") or DEFAULT_TIMEZONE
    try:
        return ZoneInfo(zone)
    except Exception:
        return ZoneInfo(DEFAULT_TIMEZONE)


def now_epoch() -> float:
    return _time.time()


def format_local_time(epoch_seconds: float | None = None) -> str:
    """Epoch seconds -> quirky local-time-with-literal-Z wire string."""
    if epoch_seconds is None:
        epoch_seconds = _time.time()
    dt = datetime.fromtimestamp(epoch_seconds, tz=timezone.utc).astimezone(get_location())
    return dt.strftime(TIME_FORMAT)


def parse_local_time(s: str) -> float | None:
    """Wire string -> epoch seconds, or None if invalid.

    Mirrors ``inActivePeriod``'s validity checks: too-short strings and
    layout mismatches are rejected (ref: stats.go:30-41). The string is
    interpreted in the configured location, matching
    ``time.ParseInLocation``.
    """
    if not isinstance(s, str) or len(s) < MIN_TIMESTAMP_STR_LENGTH:
        return None
    try:
        naive = datetime.strptime(s, TIME_FORMAT)
    except ValueError:
        return None
    local = naive.replace(tzinfo=get_location())
    return local.timestamp()
