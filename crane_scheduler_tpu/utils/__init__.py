from .duration import parse_go_duration, format_go_duration
from .timeutil import (
    TIME_FORMAT,
    DEFAULT_TIMEZONE,
    MIN_TIMESTAMP_STR_LENGTH,
    get_location,
    format_local_time,
    parse_local_time,
    now_epoch,
)
from .score import normalize_score, go_trunc
from .system import (
    DEFAULT_SYSTEM_NAMESPACE,
    SYSTEM_NAMESPACE_ENV,
    system_namespace,
)
from .logging import set_verbosity, verbosity, vlog

__all__ = [
    "set_verbosity",
    "verbosity",
    "vlog",
    "DEFAULT_SYSTEM_NAMESPACE",
    "SYSTEM_NAMESPACE_ENV",
    "system_namespace",
    "parse_go_duration",
    "format_go_duration",
    "TIME_FORMAT",
    "DEFAULT_TIMEZONE",
    "MIN_TIMESTAMP_STR_LENGTH",
    "get_location",
    "format_local_time",
    "parse_local_time",
    "now_epoch",
    "normalize_score",
    "go_trunc",
]
