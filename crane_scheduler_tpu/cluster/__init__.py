from .kube import KubeClusterClient
from .replication import (
    DeltaDecoder,
    DeltaPublisher,
    DeltaStreamClient,
    FrameError,
    ReplicaMirror,
    VersionGapError,
    encode_frame,
)
from .state import (
    Container,
    ResourceRequirements,
    Node,
    NodeAddress,
    Pod,
    Event,
    OwnerReference,
    ClusterState,
)

__all__ = [
    "Container",
    "ResourceRequirements",
    "Node",
    "NodeAddress",
    "Pod",
    "Event",
    "OwnerReference",
    "ClusterState",
    "DeltaDecoder",
    "DeltaPublisher",
    "DeltaStreamClient",
    "FrameError",
    "KubeClusterClient",
    "ReplicaMirror",
    "VersionGapError",
    "encode_frame",
]
