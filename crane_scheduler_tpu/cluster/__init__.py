from .kube import KubeClusterClient
from .state import (
    Container,
    ResourceRequirements,
    Node,
    NodeAddress,
    Pod,
    Event,
    OwnerReference,
    ClusterState,
)

__all__ = [
    "Container",
    "ResourceRequirements",
    "Node",
    "NodeAddress",
    "Pod",
    "Event",
    "OwnerReference",
    "ClusterState",
    "KubeClusterClient",
]
