"""Mirror delta-stream replication: the versioned-log substrate v1.

The blueprint paper's central idea — decouple the metrics-sync path
from the scheduling/serving hot path via versioned state hand-off —
lands here as wire-shipped state: a primary process that owns the
authoritative ``ClusterState`` publishes **version-keyed, named-key
deltas**, and any number of shared-nothing serving replicas ingest the
delta stream into their own mirror instead of each running a LIST+watch
against the apiserver (N replicas must not multiply apiserver read
load; doc/replication.md). The framing rides the PR 3 write-path
discipline: length-prefixed, checksummed frames that a torn tail can
never half-apply, and a per-consumer fence (the version cursor) that
makes resume exact.

Three pieces:

- ``encode_frame`` / ``DeltaDecoder`` — the wire format. One frame is
  ``MAGIC | u32 length | u32 crc32 | payload`` (payload = canonical
  JSON). The decoder buffers arbitrary kernel-torn byte arrivals and
  yields only complete, checksum-verified frames; a partial tail stays
  buffered (or is dropped with the connection), so a delta either
  applies whole or not at all.

- ``DeltaPublisher`` — diffs the authoritative cluster against its
  last-published shadow once per version window and ships ONE delta
  frame per window: ``{from, v, nodes: {name: annotations | null}}``
  (null = node deleted). Deltas are named-key (keyed by node name, the
  same key discipline as the store's named writes), so windows coalesce
  naturally: ten sweeps inside one window ship as one frame with each
  node's newest value. A bounded ring of recent frames lets a consumer
  resume from its fence; a consumer behind the ring floor gets a
  snapshot frame (``snap: true``) and continues live from there.

- ``ReplicaMirror`` / ``DeltaStreamClient`` — the consumer side. The
  mirror owns a private ``ClusterState`` and applies each frame as one
  transaction; ``applied_version`` is the fence. A frame whose ``from``
  does not equal the fence is a **version gap** (`VersionGapError`):
  the client drops the stream and reconnects with its cursor, which the
  publisher answers with ring replay or a snapshot — resume is always
  cursor-exact, never "hope the stream was contiguous".

Metrics (doc/observability.md): ``crane_replica_deltas_applied_total``,
``crane_replica_snapshots_total``, ``crane_replica_gaps_total``,
``crane_replica_lag_versions``, ``crane_replica_feed_connected``,
``crane_replication_published_version``, ``crane_replication_consumers``.
Stdlib + the in-repo cluster model only.
"""

from __future__ import annotations

import json
import socket
import struct
import threading
import time
import zlib
from collections import deque
from typing import Callable, Mapping

from .state import ClusterState, Node

FRAME_MAGIC = b"CRDL"
_HEADER = struct.Struct(">II")  # payload length, crc32(payload)
_MAX_FRAME_BYTES = 256 << 20  # a 1M-node snapshot fits well under this

FEED_PATH = "/v1/replication/feed"
FEED_CONTENT_TYPE = "application/x-crane-delta-stream"


class FrameError(Exception):
    """The byte stream is not a valid frame sequence (bad magic, crc
    mismatch, or an absurd length): the connection is poisoned and the
    consumer must resync by reconnecting from its cursor."""


class VersionGapError(Exception):
    """A delta's ``from`` fence does not match the mirror's cursor —
    applying it could tear the mirror. The consumer reconnects with its
    cursor; the publisher answers with replay or a snapshot."""

    def __init__(self, expected: int, got: int):
        super().__init__(
            f"delta stream gap: mirror fence {expected}, frame from {got}"
        )
        self.expected = expected
        self.got = got


def encode_frame(payload: dict) -> bytes:
    """One wire frame: canonical JSON behind a length + crc32 header.
    ``sort_keys`` keeps the encoding deterministic, so identical deltas
    are identical bytes (the byte-identity discipline end to end)."""
    body = json.dumps(payload, sort_keys=True, separators=(",", ":")).encode()
    return FRAME_MAGIC + _HEADER.pack(len(body), zlib.crc32(body)) + body


class DeltaDecoder:
    """Incremental frame parser over kernel-torn byte arrivals (the PR 4
    watch-stream discipline): bytes accumulate however they arrive, and
    ``feed`` yields every COMPLETE checksum-verified frame. A torn tail
    stays buffered until its remainder arrives or the connection dies —
    it can never be half-applied."""

    __slots__ = ("_buf",)

    def __init__(self):
        self._buf = bytearray()

    @property
    def pending_bytes(self) -> int:
        return len(self._buf)

    def feed(self, data: bytes) -> list[dict]:
        self._buf += data
        frames: list[dict] = []
        buf = self._buf
        head = len(FRAME_MAGIC) + _HEADER.size
        while len(buf) >= head:
            if buf[: len(FRAME_MAGIC)] != FRAME_MAGIC:
                raise FrameError("bad frame magic")
            length, crc = _HEADER.unpack_from(buf, len(FRAME_MAGIC))
            if length > _MAX_FRAME_BYTES:
                raise FrameError(f"frame length {length} over cap")
            total = head + length
            if len(buf) < total:
                break  # torn tail: wait for the rest
            body = bytes(buf[head:total])
            if zlib.crc32(body) != crc:
                raise FrameError("frame crc mismatch")
            del buf[:total]
            try:
                frames.append(json.loads(body))
            except ValueError as e:  # pragma: no cover - crc caught it
                raise FrameError(f"frame payload not JSON: {e}") from e
        return frames


class _Consumer:
    """One attached feed connection: a send callable plus its fence."""

    __slots__ = ("send", "fence", "name")

    def __init__(self, send: Callable[[bytes], bool], fence: int, name: str):
        self.send = send
        self.fence = fence
        self.name = name


class DeltaPublisher:
    """The primary-side delta source over an authoritative cluster.

    ``publish_window()`` is the one state-advancing step: diff the
    cluster against the published shadow, ship one frame to every
    attached consumer, retain the frame in the resume ring. It is safe
    to call from a timer thread (``start``) or directly (tests, bench —
    deterministic windows). Consumers attach via ``subscribe`` with
    their cursor; catch-up (ring replay or snapshot) happens inside the
    subscribe call, so a consumer is live-consistent the moment it is
    attached."""

    def __init__(
        self,
        cluster: ClusterState,
        *,
        window_s: float = 0.05,
        ring_frames: int = 128,
        telemetry=None,
    ):
        self.cluster = cluster
        self.window_s = float(window_s)
        self._lock = threading.Lock()
        # name -> annotations mapping of the last published state;
        # sweeps replace whole mapping objects, so the diff is an
        # identity check per node with an equality fallback
        self._shadow: dict[str, Mapping[str, str]] = {}
        self._published_version = -1
        self._ring: deque[tuple[int, int, bytes]] = deque(maxlen=ring_frames)
        self._consumers: list[_Consumer] = []
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self.stats = {"windows": 0, "frames_sent": 0, "snapshots_sent": 0}
        self._m_published = self._m_consumers = None
        if telemetry is not None:
            reg = telemetry.registry
            self._m_published = reg.gauge(
                "crane_replication_published_version",
                "Version fence of the last published delta window",
            )
            self._m_consumers = reg.gauge(
                "crane_replication_consumers",
                "Feed connections currently attached",
            )

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name="crane-delta-pub", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        with self._lock:
            consumers, self._consumers = self._consumers, []
        for c in consumers:
            try:
                c.send(b"")
            except Exception:
                pass

    def _run(self) -> None:
        while not self._stop.wait(self.window_s):
            try:
                self.publish_window()
            except Exception:  # pragma: no cover - keep the feed alive
                pass

    # -- publishing ---------------------------------------------------------

    @property
    def published_version(self) -> int:
        with self._lock:
            return self._published_version

    def _cluster_version(self) -> int:
        return self.cluster.node_version

    def publish_window(self) -> int:
        """Diff + ship one version window. Returns the number of changed
        names shipped (0 = quiet window, nothing sent — a quiet stream
        is normal and must not reset consumer liveness)."""
        with self._lock:
            nodes = self.cluster.list_nodes()
            version = self._cluster_version()
            shadow = self._shadow
            changed: dict[str, dict[str, str] | None] = {}
            seen = set()
            for node in nodes:
                name = node.name
                seen.add(name)
                prev = shadow.get(name)
                anno = node.annotations
                if prev is None or (prev is not anno and prev != anno):
                    changed[name] = dict(anno)
            for name in shadow.keys() - seen:
                changed[name] = None
            self.stats["windows"] += 1
            if not changed and self._published_version >= 0:
                return 0
            frame = encode_frame({
                "from": self._published_version,
                "v": version,
                "nodes": changed,
            })
            self._ring.append((self._published_version, version, frame))
            for name, anno in changed.items():
                if anno is None:
                    shadow.pop(name, None)
                else:
                    # keep the live node's mapping object so the next
                    # window's identity check short-circuits
                    shadow[name] = anno
            self._published_version = version
            consumers = list(self._consumers)
        if self._m_published is not None:
            self._m_published.set(version)
        dead: list[_Consumer] = []
        for c in consumers:
            if c.send(frame):
                c.fence = version
                self.stats["frames_sent"] += 1
            else:
                dead.append(c)
        if dead:
            with self._lock:
                for c in dead:
                    try:
                        self._consumers.remove(c)
                    except ValueError:
                        pass
            self._note_consumers()
        return len(changed)

    def _snapshot_frame_locked(self) -> bytes:
        return encode_frame({
            "from": -1,
            "v": self._published_version,
            "snap": True,
            "nodes": {n: dict(a) for n, a in self._shadow.items()},
        })

    def _note_consumers(self) -> None:
        if self._m_consumers is not None:
            with self._lock:
                n = len(self._consumers)
            self._m_consumers.set(n)

    # -- consumers ----------------------------------------------------------

    def subscribe(
        self, send: Callable[[bytes], bool], from_version: int,
        name: str = "",
    ) -> int:
        """Attach a consumer whose fence is ``from_version``. Catch-up
        is decided here, under the lock, so no window can slip between
        catch-up and live attachment: ring replay when the cursor is
        inside the retained ring, a snapshot frame otherwise. Returns
        the consumer's fence after catch-up."""
        with self._lock:
            current = self._published_version
            catchup: list[bytes] = []
            snapshot = False
            if from_version == current:
                fence = current
            elif from_version > current:
                # the consumer is AHEAD of us (publisher restart lost
                # the shadow): only a snapshot can make it consistent
                catchup = [self._snapshot_frame_locked()]
                fence = current
                self.stats["snapshots_sent"] += 1
            else:
                replay = [
                    (f, t, frame) for f, t, frame in self._ring
                    if f >= from_version
                ]
                if replay and replay[0][0] == from_version:
                    fence = from_version
                    for f, t, frame in replay:
                        if f == fence:
                            catchup.append(frame)
                            fence = t
                    snapshot = fence != current
                else:
                    snapshot = True
                if snapshot:
                    catchup = [self._snapshot_frame_locked()]
                    fence = current
                    self.stats["snapshots_sent"] += 1
            consumer = _Consumer(send, fence, name)
            self._consumers.append(consumer)
        for frame in catchup:
            if not consumer.send(frame):
                with self._lock:
                    try:
                        self._consumers.remove(consumer)
                    except ValueError:
                        pass
                break
            self.stats["frames_sent"] += 1
        self._note_consumers()
        return consumer.fence

    def unsubscribe(self, send: Callable[[bytes], bool]) -> None:
        with self._lock:
            self._consumers = [c for c in self._consumers if c.send is not send]
        self._note_consumers()

    @property
    def consumer_count(self) -> int:
        with self._lock:
            return len(self._consumers)

    # -- async front-end stream glue ---------------------------------------

    def stream_handler(self, method: str, target: str, headers):
        """``AsyncHTTPServer`` stream-route hook: claim GET requests on
        ``/v1/replication/feed`` as long-lived delta streams. Returns
        ``(status, content_type, attach)`` or None (not ours)."""
        path, _, query = target.partition("?")
        if method != "GET" or path != FEED_PATH:
            return None

        from urllib.parse import parse_qs

        try:
            raw = parse_qs(query).get("from", ["-1"])[0]
            cursor = int(raw)
        except (ValueError, TypeError):
            cursor = -1

        def attach(handle) -> None:
            self.subscribe(handle.send, cursor, name=f"fd{handle.fd}")

        return 200, FEED_CONTENT_TYPE, attach

    def status(self) -> dict:
        with self._lock:
            return {
                "publishedVersion": self._published_version,
                "consumers": len(self._consumers),
                "windows": self.stats["windows"],
                "framesSent": self.stats["frames_sent"],
                "snapshotsSent": self.stats["snapshots_sent"],
                "ringFrames": len(self._ring),
            }


class ReplicaMirror:
    """A replica's private cluster mirror fed exclusively by delta
    frames. ``applied_version`` is the per-consumer fence: every frame
    applies as one ``ClusterState`` transaction keyed by it, so a
    mirror is always AT a published version, never between two."""

    def __init__(self, telemetry=None):
        self.cluster = ClusterState()
        self._lock = threading.Lock()
        self._applied_version = -1
        self._published_hint = -1
        self.stats = {"deltas": 0, "snapshots": 0, "gaps": 0, "nodes": 0}
        self._m_applied = None
        if telemetry is not None:
            reg = telemetry.registry
            self._m_deltas = reg.counter(
                "crane_replica_deltas_applied_total",
                "Delta frames applied to the mirror",
            )
            self._m_snapshots = reg.counter(
                "crane_replica_snapshots_total",
                "Snapshot frames applied (restart / out-of-ring resume)",
            )
            self._m_gaps = reg.counter(
                "crane_replica_gaps_total",
                "Version gaps detected (frame fence != mirror cursor)",
            )
            self._m_lag = reg.gauge(
                "crane_replica_lag_versions",
                "Published version minus the mirror's applied version",
            )
            self._m_applied = reg.gauge(
                "crane_replica_applied_version",
                "The mirror's applied version fence",
            )

    @property
    def applied_version(self) -> int:
        with self._lock:
            return self._applied_version

    @property
    def published_hint(self) -> int:
        """The newest published version this mirror has SEEN (frames
        carry it); lag accounting against a live primary should prefer
        the primary's own status over this hint."""
        with self._lock:
            return self._published_hint

    @property
    def lag_versions(self) -> int:
        with self._lock:
            return max(0, self._published_hint - self._applied_version)

    def note_published(self, version: int) -> None:
        """Fold an externally learned published version into the lag
        hint (the feed client calls this per frame; a status prober may
        too)."""
        with self._lock:
            if version > self._published_hint:
                self._published_hint = version
        if self._m_applied is not None:
            self._m_lag.set(self.lag_versions)

    def apply_frame(self, frame: dict) -> int:
        """Apply one decoded frame as one mirror transaction. Returns
        the number of node rows touched. Raises ``VersionGapError``
        when the frame's fence does not match the cursor (the caller
        resyncs by reconnecting from the cursor)."""
        nodes = frame.get("nodes") or {}
        version = int(frame.get("v", -1))
        snap = bool(frame.get("snap"))
        with self._lock:
            if snap:
                self.cluster.replace_nodes(
                    Node(name=name, annotations=anno)
                    for name, anno in nodes.items()
                    if anno is not None
                )
                self.stats["snapshots"] += 1
            else:
                if int(frame.get("from", -2)) != self._applied_version:
                    self.stats["gaps"] += 1
                    if self._m_applied is not None:
                        self._m_gaps.inc()
                    raise VersionGapError(
                        self._applied_version, int(frame.get("from", -2))
                    )
                self.cluster.apply_node_changes(
                    ("DELETED", Node(name=name)) if anno is None
                    else ("MODIFIED", Node(name=name, annotations=anno))
                    for name, anno in nodes.items()
                )
                self.stats["deltas"] += 1
            self._applied_version = version
            if version > self._published_hint:
                self._published_hint = version
            self.stats["nodes"] += len(nodes)
        if self._m_applied is not None:
            if snap:
                self._m_snapshots.inc()
            else:
                self._m_deltas.inc()
            self._m_applied.set(version)
            self._m_lag.set(self.lag_versions)
        return len(nodes)

    def status(self) -> dict:
        with self._lock:
            return {
                "appliedVersion": self._applied_version,
                "publishedHint": self._published_hint,
                "lagVersions": max(
                    0, self._published_hint - self._applied_version
                ),
                "deltasApplied": self.stats["deltas"],
                "snapshotsApplied": self.stats["snapshots"],
                "gaps": self.stats["gaps"],
                "nodes": len(self.cluster.list_nodes()),
            }


class DeltaStreamClient:
    """The replica's feed connection: one background thread that keeps
    a ``GET /v1/replication/feed?from=<cursor>`` stream open against
    the primary, decodes frames, and applies them to the mirror.

    Resume discipline: ANY stream failure — socket death, torn tail,
    frame corruption, version gap — tears down the connection and
    reconnects with ``from=<applied_version>``; the publisher answers
    with ring replay or a snapshot. The mirror can therefore never be
    torn: frames apply whole, and the cursor only moves on a whole
    frame."""

    def __init__(
        self,
        host: str,
        port: int,
        mirror: ReplicaMirror,
        *,
        telemetry=None,
        reconnect_backoff_s: float = 0.05,
        max_backoff_s: float = 1.0,
        read_timeout_s: float = 2.0,
    ):
        self.host = host
        self.port = int(port)
        self.mirror = mirror
        self.backoff_s = float(reconnect_backoff_s)
        self.max_backoff_s = float(max_backoff_s)
        self.read_timeout_s = float(read_timeout_s)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._connected = threading.Event()
        self._applied_any = threading.Event()
        self.stats = {"connects": 0, "resumes": 0, "stream_errors": 0}
        self._m_connected = None
        if telemetry is not None:
            reg = telemetry.registry
            self._m_connected = reg.gauge(
                "crane_replica_feed_connected",
                "1 while the delta-stream connection is established",
            )
            self._m_resumes = reg.counter(
                "crane_replica_feed_resumes_total",
                "Feed reconnects carrying a non-initial cursor",
            )

    @property
    def connected(self) -> bool:
        return self._connected.is_set()

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name="crane-delta-feed", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def wait_caught_up(self, version: int, timeout_s: float = 10.0) -> bool:
        """Block until the mirror's fence reaches ``version``."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.mirror.applied_version >= version:
                return True
            time.sleep(0.005)
        return self.mirror.applied_version >= version

    def _run(self) -> None:
        backoff = self.backoff_s
        while not self._stop.is_set():
            try:
                self._stream_once()
                backoff = self.backoff_s  # clean teardown: reset
            except Exception:
                self.stats["stream_errors"] += 1
            if self._stop.is_set():
                break
            self._stop.wait(backoff)
            backoff = min(self.max_backoff_s, backoff * 2)

    def _stream_once(self) -> None:
        cursor = self.mirror.applied_version
        sock = socket.create_connection(
            (self.host, self.port), timeout=self.read_timeout_s
        )
        try:
            sock.settimeout(self.read_timeout_s)
            request = (
                f"GET {FEED_PATH}?from={cursor} HTTP/1.1\r\n"
                f"Host: {self.host}:{self.port}\r\n"
                "\r\n"
            ).encode("latin-1")
            sock.sendall(request)
            head = bytearray()
            while b"\r\n\r\n" not in head:
                chunk = sock.recv(4096)
                if not chunk:
                    raise ConnectionError("feed closed during head")
                head += chunk
                if len(head) > 64 * 1024:
                    raise FrameError("feed response head too large")
            head_bytes, _, rest = bytes(head).partition(b"\r\n\r\n")
            status_line = head_bytes.split(b"\r\n", 1)[0]
            if b" 200 " not in status_line + b" ":
                raise ConnectionError(
                    f"feed rejected: {status_line.decode('latin-1')!r}"
                )
            self.stats["connects"] += 1
            if cursor >= 0:
                self.stats["resumes"] += 1
                if self._m_connected is not None:
                    self._m_resumes.inc()
            self._connected.set()
            if self._m_connected is not None:
                self._m_connected.set(1)
            decoder = DeltaDecoder()
            data = rest
            while not self._stop.is_set():
                if data:
                    for frame in decoder.feed(data):
                        self.mirror.apply_frame(frame)
                        self._applied_any.set()
                try:
                    data = sock.recv(1 << 16)
                except socket.timeout:
                    data = b""
                    continue
                if not data:
                    raise ConnectionError("feed closed")
        finally:
            self._connected.clear()
            if self._m_connected is not None:
                self._m_connected.set(0)
            try:
                sock.close()
            except OSError:
                pass
