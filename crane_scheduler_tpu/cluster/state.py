"""In-memory cluster state: the framework's kube-apiserver stand-in.

The reference coordinates its two processes exclusively through the
Kubernetes API — the controller JSON-patches node annotations
(ref: pkg/controller/annotator/node.go:123-146) and watches ``Scheduled``
events (ref: cmd/controller/app/options/factory.go:25-33); the scheduler
plugin reads nodes from its informer snapshot. ``ClusterState`` models
exactly that surface: nodes with annotations and addresses, pods with owner
references and containers, a bounded event log with subscriber callbacks,
and thread-safe patch/bind operations that emit the same
"Successfully assigned <ns/pod> to <node>" events the reference parses.

In a real deployment this object is replaced by a k8s client hitting a live
apiserver; everything above it (annotator, scorer, framework) only sees
this interface.
"""

from __future__ import annotations

import bisect
import threading
import time
from collections import deque
from dataclasses import dataclass, field, replace
from types import MappingProxyType
from typing import Any, Callable, Mapping

import numpy as np

# Shared empty-mapping default for the optional resource fields below.
# A PLAIN default (not default_factory) makes it a class attribute, so
# instances built through the raw ``object.__new__`` fast paths (the
# native LIST decoder, burst materialization, bind_pods) that predate a
# field still resolve it — absent from the instance ``__dict__``, the
# lookup falls back here and reads as "not reported".
_EMPTY_MAP: Mapping[str, Any] = MappingProxyType({})


@dataclass(frozen=True)
class NodeAddress:
    type: str  # "InternalIP", "Hostname", ...
    address: str


@dataclass(frozen=True)
class Node:
    name: str
    annotations: Mapping[str, str] = field(default_factory=dict)
    labels: Mapping[str, str] = field(default_factory=dict)
    addresses: tuple[NodeAddress, ...] = ()
    # ``status.allocatable`` quantities (cpu/memory/pods/...), verbatim
    # wire strings. Empty = the node never reported allocatable — the
    # fit layer treats that as unknown (fail-open), NOT as zero.
    allocatable: Mapping[str, Any] = _EMPTY_MAP

    def internal_ip(self) -> str:
        """ref: node.go:179-187 — InternalIP, falling back to the name."""
        for addr in self.addresses:
            if addr.type == "InternalIP":
                return addr.address
        return self.name


@dataclass(frozen=True)
class OwnerReference:
    kind: str
    name: str = ""


@dataclass(frozen=True)
class ResourceRequirements:
    requests: Mapping[str, float] = field(default_factory=dict)
    limits: Mapping[str, float] = field(default_factory=dict)


@dataclass(frozen=True)
class Container:
    name: str
    resources: ResourceRequirements = field(default_factory=ResourceRequirements)


@dataclass(frozen=True)
class Pod:
    name: str
    namespace: str = "default"
    annotations: Mapping[str, str] = field(default_factory=dict)
    owner_references: tuple[OwnerReference, ...] = ()
    containers: tuple[Container, ...] = ()
    node_name: str = ""
    # ``spec.initContainers`` / ``spec.overhead`` — inputs to the kube
    # effective-request rule max(init, sum(containers)) + overhead
    init_containers: tuple[Container, ...] = ()
    overhead: Mapping[str, Any] = _EMPTY_MAP

    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    def is_daemonset_pod(self) -> bool:
        """ref: pkg/utils/utils.go:17-24."""
        return any(ref.kind == "DaemonSet" for ref in self.owner_references)


@dataclass(frozen=True)
class Event:
    namespace: str
    name: str
    type: str  # "Normal" | "Warning"
    reason: str
    message: str
    count: int = 1
    event_time: float = 0.0  # used when count == 0 (ref: event.go:131-137)
    last_timestamp: float = 0.0
    resource_version: int = 0


EventHandler = Callable[[Event], None]


class _PodBurst:
    """Columnar pod population: a burst of bare pods as rows, not objects.

    TPU-native counterpart of a 100k-pod arrival wave: names are a list,
    placements are one int32 column indexing a burst-local node table.
    Rows materialize into real ``Pod`` objects lazily (get/list/patch/
    delete), so every ClusterState read keeps its semantics while bind
    application and event feedback stay O(1) Python calls per burst.
    """

    __slots__ = (
        "namespace", "names", "node_ids", "table", "table_map", "dead",
    )

    def __init__(self, namespace: str, names: list):
        self.namespace = namespace
        self.names = names
        self.node_ids = np.full((len(names),), -1, dtype=np.int32)
        self.table: list[str] = []  # burst-local node intern table
        self.table_map: dict[str, int] = {}
        self.dead: set[int] = set()  # rows materialized out / deleted

    def materialize(self, row: int) -> Pod:
        node = self.table[self.node_ids[row]] if self.node_ids[row] >= 0 else ""
        pod = object.__new__(Pod)
        pod.__dict__.update(
            name=self.names[row],
            namespace=self.namespace,
            annotations={},
            owner_references=(),
            containers=(),
            node_name=node,
        )
        return pod


class _DirtyJournal:
    """Bounded (version, name, membership) journal keyed on a node
    fence. ``since(v)`` replays the tail: the set of names written
    after ``v`` plus a membership-changed flag, or ``None`` when the
    interval is not covered — a name-less bulk write (relist, columnar
    sweep) reset the floor, the deque overran its cap, or ``v``
    predates the journal. ``None`` costs the caller exactly ONE
    identity sweep; every covered interval is O(dirty)."""

    __slots__ = ("log", "floor", "overruns", "bulk_marks")

    def __init__(self, cap: int, floor: int = 0):
        self.log: deque[tuple[int, str, bool]] = deque(maxlen=cap)
        self.floor = floor  # versions < floor are NOT covered
        self.overruns = 0  # cap evictions (bounded-journal overflow)
        self.bulk_marks = 0  # name-less bulk writes (relist / sweep)

    def note(self, version: int, name: str, membership: bool = False) -> None:
        log = self.log
        if len(log) == log.maxlen:
            evicted = log[0][0]
            if evicted > self.floor:
                self.floor = evicted
            self.overruns += 1
        log.append((version, name, membership))

    def mark_bulk(self, version: int) -> None:
        if version > self.floor:
            self.floor = version
        self.bulk_marks += 1
        self.log.clear()

    def since(self, version: int):
        if version < self.floor:
            return None
        names: set[str] = set()
        membership = False
        for v, name, m in self.log:
            if v > version:
                names.add(name)
                if m:
                    membership = True
        return (names, membership)


class ClusterState:
    """Thread-safe cluster model with event subscription."""

    def __init__(self, max_events: int = 4096, dirty_journal_cap: int = 4096):
        self._lock = threading.RLock()
        self._nodes: dict[str, Node] = {}
        # Lazy annotation overlay: columnar patches append SEGMENTS —
        # (names, pos, {key: values}, dead) — holding the sweep's
        # column lists by reference, so a 50k-node x 7-key flush is
        # O(keys) bookkeeping instead of a Python loop copying 50k node
        # objects (that loop dominated 50k-node cycle profiles at
        # ~7us/node). ``pos`` is a name->row map cached per names-list
        # object (sweeps reuse the cluster's cached node table, so it
        # builds once per node-set epoch). Reads merge lazily: get_node
        # folds one node, list_nodes folds everything. Cross-style
        # writes (add_node, delete_node, single/bulk dict patches) mark
        # the name ``dead`` in every existing segment so a stale column
        # value can never shadow a newer authoritative write; segments
        # created later apply to the name again. Steady state is ONE
        # segment whose key->values entries are replaced every sweep;
        # a changing node set appends segments, capped by a full fold.
        self._anno_segments: list[
            tuple[list[str], dict[str, int], dict[str, list[str]], set]
        ] = []
        self._names_pos_cache: tuple[list[str], dict[str, int]] | None = None
        self._pods: dict[str, Pod] = {}
        # per-node bound-pod key index (insertion-ordered) so
        # list_pods(node) is O(pods on node), not O(all pods) — metric
        # streams and per-pod bind filters call it per node
        self._pods_by_node: dict[str, dict[str, None]] = {}
        self._events: deque[Event] = deque(maxlen=max_events)
        self._event_index: dict[str, Event] = {}
        self._event_handlers: list[EventHandler] = []
        self._batch_handlers: list[Callable[[list[Event]], None]] = []
        self._rv_next = 1  # next event resourceVersion
        self._sched_version = 0
        self._node_set_version = 0
        # bumps on NODE mutations only (membership or annotations) —
        # pod binds / event emission leave it alone. The kube client's
        # decoded-columns cache keys on this: a pod storm must not
        # invalidate node annotation columns that didn't change.
        self._node_version = 0
        # columnar pod bursts (see add_pod_burst)
        self._bursts: list[_PodBurst] = []
        self._burst_index: dict[str, tuple[_PodBurst, int]] | None = None
        # burst bound-pod counts, slot-interned and COLUMNAR: one
        # growable int64 array indexed by a cluster-wide name->slot map.
        # A 100k-pod bind folds in as one vectorized fancy-index add
        # (the per-name dict read-modify-write loop it replaces cost
        # ~25ms per 50k-node bind); dict readers (count_pods_all) get a
        # lazily rebuilt merged view cached on _count_version, and
        # vectorized readers use bound_counts_for.
        self._count_slot: dict[str, int] = {}
        self._slot_names: list[str] = []
        self._count_arr = np.zeros((0,), dtype=np.int64)
        self._count_version = 0
        self._count_dict_cache: tuple | None = None
        self._table_slots_cache: tuple | None = None
        self._gather_cache: tuple | None = None
        # pod-change journal: which NODES had bound-pod/membership
        # changes, per pod_version — lets NUMA-vector caches rebuild
        # O(changed nodes) instead of O(all nodes) per bind pass.
        # Annotation sweeps bump sched_version but NOT pod_version.
        # Columnar-burst binds are excluded by design: burst rows are
        # bare pods (no containers, no annotations), invisible to NUMA
        # accounting (helper.add_pod no-ops on them).
        self._pod_version = 0
        self._pod_change_log: deque[tuple[int, str]] = deque(maxlen=8192)
        self._pod_log_floor = 0  # oldest version NOT fully covered
        # batch handlers that also accept columnar delivery (parallel to
        # _batch_handlers; None = must materialize events for this one)
        self._batch_columnar: list[Callable | None] = []
        # per-shard watch fences (sharded placement plane): when a
        # shard layout is configured, every write additionally bumps
        # the fence of each shard that OBSERVES the touched node — a
        # bind or annotation patch in shard 0 must not invalidate shard
        # 1's drip columns. Writes without a node name (bulk sweeps,
        # relists, burst binds) conservatively bump every shard.
        self._shard_layout: tuple[int, float] | None = None  # (count, overlap)
        self._shard_sched: list[int] = []
        self._shard_pod: list[int] = []
        self._shard_node: list[int] = []
        self._shard_owner_cache: dict[str, tuple[int, ...]] = {}
        # dirty-name journals (O(dirty) refresh): every NAMED node write
        # appends (node_fence_after, name, membership?) to the global
        # journal and — when a shard layout is configured — to each
        # observing shard's journal; name-less bulk writes reset the
        # floor instead. Consumers (store ingest, FitTracker,
        # DripColumns, the device column cache) replay the tail to
        # patch only dirty rows; an uncovered interval costs exactly
        # one identity sweep (counted via overruns/bulk_marks).
        self._dirty_cap = int(dirty_journal_cap)
        self._dirty_global = _DirtyJournal(self._dirty_cap)
        self._shard_dirty: list[_DirtyJournal] = []
        # pluggable shard keyspace (None = static crc32 modulo): a
        # HashRing here makes ownership dynamic — reshard() migrates
        # only the moved names' rows (membership-dirty journal entries
        # on both the old and the new owner's journals).
        self._shard_keyspace = None
        # sorted (crc32, name) index over the node table, built lazily
        # for ring resharding: moved names are found by bisecting the
        # moved arcs instead of re-hashing the whole name set.
        self._crc_index: tuple[list[int], list[str]] | None = None

    @property
    def sched_version(self) -> int:
        """Monotonic mutation counter (an informer resourceVersion
        stand-in) over changes a scheduling snapshot can observe: node
        add/delete/annotation, bound-pod add/delete/annotation, and
        binds. Adding or annotating a pending (unbound) pod does NOT
        bump it, so drip scheduling (add pod, schedule, repeat) can
        reuse a cached snapshot."""
        with self._lock:
            return self._sched_version

    @property
    def pod_version(self) -> int:
        """Bumps on bound-pod set/placement/annotation changes and node
        membership — the inputs NUMA wrapper state derives from. Node
        ANNOTATION patches (the annotator's sweep) do not bump it."""
        with self._lock:
            return self._pod_version

    def _note_pod_change_locked(self, node_name: str) -> None:
        """Journal a NUMA-relevant change on ``node_name`` (caller holds
        the lock)."""
        self._pod_version += 1
        log = self._pod_change_log
        if len(log) == log.maxlen:
            self._pod_log_floor = log[0][0]
        log.append((self._pod_version, node_name))
        if self._shard_layout is not None:
            self._bump_shards_locked(node_name, pod=True)

    # -- per-shard watch fences (sharded placement plane) ------------------

    def configure_shards(
        self, count: int, overlap: float = 0.0, layout=None
    ) -> None:
        """Enable per-shard version fences for a ``count``-way node
        partition (``cluster.shards.shard_owners`` ownership, or a
        ``layout`` object — e.g. ``shards.HashRing`` — answering
        ``owners(name)``). Each shard's (sched, pod, node) counters
        start at the global values and from then on move only when a
        write touches a node that shard observes — the O(dirty)
        refresh gate for N concurrent drip schedulers. Reconfiguring
        resets the fences and the dirty journals."""
        from .shards import shard_owners  # noqa: F401  (validates import)

        if count < 1:
            raise ValueError(f"shard count must be >= 1, got {count}")
        if layout is not None and layout.count != count:
            raise ValueError(
                f"layout has {layout.count} shards, expected {count}"
            )
        with self._lock:
            self._shard_layout = (int(count), float(overlap))
            self._shard_keyspace = layout
            self._shard_sched = [self._sched_version] * count
            self._shard_pod = [self._pod_version] * count
            self._shard_node = [self._node_version] * count
            self._shard_owner_cache = {}
            # fresh journals: nothing before the current fence is covered
            self._shard_dirty = [
                _DirtyJournal(self._dirty_cap, floor=self._node_version)
                for _ in range(count)
            ]

    def shard_keyspace(self):
        """The pluggable keyspace object (``shards.HashRing``), or None
        when ownership is the static crc32 modulo."""
        with self._lock:
            return self._shard_keyspace

    def shard_layout(self) -> tuple[int, float] | None:
        with self._lock:
            return self._shard_layout

    def shard_versions(self, index: int) -> tuple[int, int, int]:
        """(sched, pod, node) fence for shard ``index``; falls back to
        the global counters when no layout is configured (a ShardView
        over an unconfigured mirror degrades to global invalidation)."""
        with self._lock:
            if self._shard_layout is None:
                return (self._sched_version, self._pod_version,
                        self._node_version)
            return (self._shard_sched[index], self._shard_pod[index],
                    self._shard_node[index])

    def _bump_shards_locked(
        self, name: str | None, pod: bool = False, node: bool = False,
        member: bool = False,
    ) -> None:
        layout = self._shard_layout
        if layout is None:
            return
        count, overlap = layout
        if name is None:
            owners: tuple[int, ...] | range = range(count)
        else:
            owners = self._shard_owner_cache.get(name)  # type: ignore[assignment]
            if owners is None:
                if self._shard_keyspace is not None:
                    owners = self._shard_keyspace.owners(name)
                else:
                    from .shards import shard_owners

                    owners = shard_owners(name, count, overlap)
                cache = self._shard_owner_cache
                if len(cache) > 2_000_000:  # churn backstop
                    cache.clear()
                cache[name] = owners
        for s in owners:
            self._shard_sched[s] += 1
            if pod:
                self._shard_pod[s] += 1
            if node:
                self._shard_node[s] += 1
                if name is None:
                    self._shard_dirty[s].mark_bulk(self._shard_node[s])
                else:
                    self._shard_dirty[s].note(
                        self._shard_node[s], name, member
                    )

    def pod_changes_since(self, version: int):
        """Node names with bound-pod changes after ``version``, or None
        when the journal no longer covers the interval (caller must do a
        full rebuild)."""
        with self._lock:
            if version < self._pod_log_floor:
                return None
            return {
                name for v, name in self._pod_change_log if v > version
            }

    def dirty_nodes_since(self, version: int, shard: int | None = None):
        """Replay the dirty-name journal: ``(names, membership_changed)``
        for node writes after node-fence ``version``, or None when the
        interval is not covered (bulk relist/sweep, journal overrun, or
        a pre-journal version) — the caller then does exactly one
        identity sweep. ``shard`` selects the per-shard journal (keyed
        on that shard's node fence) when a layout is configured."""
        with self._lock:
            if shard is not None and self._shard_layout is not None:
                return self._shard_dirty[shard].since(version)
            return self._dirty_global.since(version)

    def dirty_journal_stats(self) -> dict:
        """Aggregate journal health for telemetry: cap overruns,
        name-less bulk floor resets, and current/max depth."""
        with self._lock:
            js = [self._dirty_global] + list(self._shard_dirty)
            return {
                "overruns": sum(j.overruns for j in js),
                "bulk_marks": sum(j.bulk_marks for j in js),
                "depth": max(len(j.log) for j in js),
                "cap": self._dirty_cap,
            }

    def forget_dirty_names(self) -> None:
        """Drop dirty-name coverage exactly as a name-less bulk write
        (relist / columnar sweep) does: every journal's floor moves to
        its current fence, so the NEXT consumer refresh pays the one
        identity-sweep fallback. Bench/test hook for measuring that
        fallback against the O(dirty) path in the same process."""
        with self._lock:
            self._dirty_global.mark_bulk(self._node_version)
            for s, j in enumerate(self._shard_dirty):
                j.mark_bulk(self._shard_node[s])

    def _note_dirty_locked(
        self, version: int, name: str, member: bool = False
    ) -> None:
        self._dirty_global.note(version, name, member)

    # -- dynamic resharding (consistent-hash ring keyspace) ----------------

    def _ensure_crc_index_locked(self):
        """Sorted (crc32, name) parallel lists over the node table —
        built once (O(n log n)), then maintained incrementally by
        add/delete while a ring keyspace is active, so a reshard finds
        the names inside the moved arcs by bisecting instead of
        re-hashing every name."""
        idx = self._crc_index
        if idx is None:
            from .shards import name_point

            pairs = sorted(
                (name_point(name), name) for name in self._nodes
            )
            idx = ([p for p, _ in pairs], [n for _, n in pairs])
            self._crc_index = idx
        return idx

    def _crc_index_add_locked(self, name: str) -> None:
        idx = self._crc_index
        if idx is None:
            return
        from .shards import name_point

        point = name_point(name)
        crcs, names = idx
        i = bisect.bisect_left(crcs, point)
        # same-crc collisions: keep names sorted within the run so
        # add/remove agree on position
        while i < len(crcs) and crcs[i] == point and names[i] < name:
            i += 1
        if i < len(crcs) and crcs[i] == point and names[i] == name:
            return
        crcs.insert(i, point)
        names.insert(i, name)

    def _crc_index_remove_locked(self, name: str) -> None:
        idx = self._crc_index
        if idx is None:
            return
        from .shards import name_point

        point = name_point(name)
        crcs, names = idx
        i = bisect.bisect_left(crcs, point)
        while i < len(crcs) and crcs[i] == point:
            if names[i] == name:
                del crcs[i]
                del names[i]
                return
            i += 1

    def _names_in_arcs_locked(self, arcs) -> list[str]:
        """Names whose hash lies inside any ``(lo, hi]`` ring arc
        (lo > hi wraps around zero)."""
        crcs, names = self._ensure_crc_index_locked()
        out: list[str] = []
        for lo, hi in arcs:
            if lo <= hi:
                a = bisect.bisect_right(crcs, lo)
                b = bisect.bisect_right(crcs, hi)
                out.extend(names[a:b])
            else:  # wraparound arc
                a = bisect.bisect_right(crcs, lo)
                out.extend(names[a:])
                b = bisect.bisect_right(crcs, hi)
                out.extend(names[:b])
        return out

    def reshard(self, target) -> list[str]:
        """Swap the ring keyspace for ``target`` (a ``shards.HashRing``
        with the same shard count), migrating ONLY the moved names:
        each name whose observation set changed gets a membership-dirty
        journal entry (and fence bumps) on both its old and new owners'
        journals, so incremental consumers add/drop exactly those rows
        — full-sweep invalidation never fires. The live ring object is
        updated in place (atomic state swap), so every ShardView /
        ShardSpec holding it re-reads the new ownership immediately.
        Returns the moved names."""
        with self._lock:
            ring = self._shard_keyspace
            if ring is None or self._shard_layout is None:
                raise ValueError(
                    "reshard requires a HashRing keyspace "
                    "(configure_shards(..., layout=HashRing(...)))"
                )
            if target.count != ring.count:
                raise ValueError(
                    f"reshard cannot change the shard count in place "
                    f"({ring.count} -> {target.count}); reconfigure the "
                    f"plane instead"
                )
            arcs = ring.moved_arcs(target)
            candidates = self._names_in_arcs_locked(arcs)
            cache = self._shard_owner_cache
            moved: list[str] = []
            for name in candidates:
                old_owners = ring.owners(name)
                new_owners = target.owners(name)
                if old_owners == new_owners:
                    continue
                moved.append(name)
                cache.pop(name, None)
                touched = set(old_owners) | set(new_owners)
                self._sched_version += 1
                self._node_version += 1
                self._note_dirty_locked(self._node_version, name, True)
                for s in touched:
                    self._shard_sched[s] += 1
                    self._shard_node[s] += 1
                    self._shard_dirty[s].note(
                        self._shard_node[s], name, True
                    )
            ring.adopt(target)
            return moved

    @property
    def node_set_version(self) -> int:
        """Bumps only on node add/delete — identity/address churn, not
        annotation patches. Lets sweep loops cache (name, ip) pair lists
        across |metrics| passes per cycle."""
        with self._lock:
            return self._node_set_version

    @property
    def node_version(self) -> int:
        """Bumps on any NODE change (membership, addresses, labels, or
        annotations) and on nothing else — the narrowest version a
        node-annotation consumer (the decoded-columns cache) can key
        on without being defeated by pod/event churn."""
        with self._lock:
            return self._node_version

    # -- nodes -------------------------------------------------------------

    def _drop_overlay_locked(self, name: str) -> None:
        """A newer authoritative write for ``name`` supersedes every
        EXISTING segment's values for it (O(segments), no column
        scans); later segments apply to the name again."""
        for seg in self._anno_segments:
            seg[3].add(name)

    def _pos_for_locked(self, names: list[str]) -> dict[str, int]:
        cache = self._names_pos_cache
        if cache is None or cache[0] is not names:
            # keyed on object identity; the strong ref in the cache
            # keeps the id stable while cached
            cache = (names, {n: i for i, n in enumerate(names)})
            self._names_pos_cache = cache
        return cache[1]

    def _merged_annotations_locked(self, node: Node):
        """Node's annotations with the overlay applied; returns the
        node's own mapping when the overlay has nothing for it."""
        merged = None
        name = node.name
        for names, pos, cols, dead in self._anno_segments:
            if name in dead:
                continue
            i = pos.get(name)
            if i is None:
                continue
            if merged is None:
                merged = dict(node.annotations)
            for key, values in cols.items():
                merged[key] = values[i]
        return merged if merged is not None else node.annotations

    def _fold_overlay_locked(self) -> None:
        """Materialize every overlay segment into the node objects (paid
        once per full read — list_nodes — instead of every flush)."""
        if not self._anno_segments:
            return
        segments, self._anno_segments = self._anno_segments, []
        nodes = self._nodes
        for name, node in nodes.items():
            anno = None
            for names, pos, cols, dead in segments:
                if name in dead:
                    continue
                i = pos.get(name)
                if i is None:
                    continue
                if anno is None:
                    anno = dict(node.annotations)
                for key, values in cols.items():
                    anno[key] = values[i]
            if anno is not None:
                new_node = object.__new__(Node)
                d = new_node.__dict__
                d.update(node.__dict__)
                d["annotations"] = anno
                nodes[name] = new_node

    def add_node(self, node: Node) -> None:
        with self._lock:
            prev = self._nodes.get(node.name)
            # the incoming object is authoritative (watch MODIFIED /
            # direct replace): stale overlay values must not shadow it
            self._drop_overlay_locked(node.name)
            self._nodes[node.name] = node
            self._sched_version += 1
            self._node_version += 1
            member = prev is None
            self._note_dirty_locked(self._node_version, node.name, member)
            self._bump_shards_locked(node.name, node=True, member=member)
            if member:
                self._crc_index_add_locked(node.name)
            # annotation-only updates (e.g. a kube mirror echoing the
            # annotator's own patches as MODIFIED events) must not defeat
            # (name, ip) pair caches keyed on node_set_version
            if prev is None or prev.addresses != node.addresses:
                self._node_set_version += 1
            if prev is None:
                self._note_pod_change_locked(node.name)  # new node row

    def delete_node(self, name: str) -> None:
        with self._lock:
            existed = name in self._nodes
            if existed:
                self._note_pod_change_locked(name)
            self._nodes.pop(name, None)
            self._drop_overlay_locked(name)
            self._sched_version += 1
            self._node_version += 1
            self._note_dirty_locked(self._node_version, name, existed)
            self._node_set_version += 1
            self._bump_shards_locked(name, node=True, member=existed)
            self._shard_owner_cache.pop(name, None)
            if existed:
                self._crc_index_remove_locked(name)

    def get_node(self, name: str) -> Node | None:
        with self._lock:
            node = self._nodes.get(name)
            if node is None or not self._anno_segments:
                return node
            merged = self._merged_annotations_locked(node)
            if merged is node.annotations:
                return node
            # fold this node so repeated reads stay cheap
            new_node = object.__new__(Node)
            d = new_node.__dict__
            d.update(node.__dict__)
            d["annotations"] = merged
            self._nodes[name] = new_node
            self._drop_overlay_locked(name)
            return new_node

    def list_nodes(self) -> list[Node]:
        with self._lock:
            self._fold_overlay_locked()
            return list(self._nodes.values())

    def node_names(self) -> list[str]:
        with self._lock:
            return list(self._nodes)

    def has_node(self, name: str) -> bool:
        """Membership test without materializing the node (the dirty
        journal's add/remove classifier; a ShardView overrides this
        with ring observation)."""
        with self._lock:
            return name in self._nodes

    # -- bulk transactions (relist / coalesced watch apply) ----------------
    #
    # The kube mirror's read path lands whole relists and drained watch
    # batches here as ONE transaction each: one lock hold and a single
    # sched_version bump per batch (the per-item primitives bump once per
    # object — at a 50k-node relist that is 50k lock round-trips and 50k
    # cache invalidations for what is semantically one state change).
    # node_set_version/pod_version keep their per-item semantics: the
    # first one tracks membership/address identity (bumped once per batch
    # when any changed), the second journals per-node changes for the
    # incremental NUMA path, which needs every entry.

    def _apply_node_change_locked(self, change_type: str, node: Node):
        """One watch-shaped node change (caller holds the lock). Returns
        ``(set_changed, member)``: whether the node SET (membership or
        addresses) changed, and whether the NAME set changed (the
        narrower membership bit the dirty journal carries)."""
        name = node.name
        if change_type == "DELETED":
            existed = name in self._nodes
            if existed:
                self._note_pod_change_locked(name)
                self._crc_index_remove_locked(name)
            self._nodes.pop(name, None)
            self._drop_overlay_locked(name)
            self._sched_version += 1
            self._bump_shards_locked(name, node=True, member=existed)
            return True, existed
        prev = self._nodes.get(name)
        self._drop_overlay_locked(name)
        self._nodes[name] = node
        self._sched_version += 1
        member = prev is None
        self._bump_shards_locked(name, node=True, member=member)
        if member:
            self._note_pod_change_locked(name)
            self._crc_index_add_locked(name)
        return member or prev.addresses != node.addresses, member

    def apply_node_changes(self, changes) -> None:
        """Coalesced watch apply: an ordered batch of ``(change_type,
        Node)`` pairs (DELETED removes, anything else add/replaces) as
        one transaction — one lock hold, one sched_version bump."""
        with self._lock:
            v0 = self._sched_version
            set_changed = False
            dirty: list[tuple[str, bool]] = []
            for change_type, node in changes:
                changed, member = self._apply_node_change_locked(
                    change_type, node
                )
                if changed:
                    set_changed = True
                dirty.append((node.name, member))
            if self._sched_version > v0:
                self._sched_version = v0 + 1
                self._node_version += 1
                v = self._node_version
                for name, member in dirty:
                    self._note_dirty_locked(v, name, member)
            if set_changed:
                self._node_set_version += 1

    def apply_pod_changes(self, changes) -> None:
        """Pod twin of ``apply_node_changes`` (same event order and
        per-pod semantics as add_pod/delete_pod, one transaction)."""
        with self._lock:
            v0 = self._sched_version
            for change_type, pod in changes:
                if change_type == "DELETED":
                    self._delete_pod_locked(pod.key())
                else:
                    self._add_pod_locked(pod)
            if self._sched_version > v0:
                self._sched_version = v0 + 1

    def replace_nodes(self, nodes) -> None:
        """Relist apply: every listed node is added/updated and nodes
        absent from the list are pruned, as ONE transaction with a
        single sched_version bump (a relist is semantically one
        snapshot, however many rows it carries). Bulk-shaped: the new
        node table is built directly (a duplicate listing keeps the
        last entry, like sequential adds) instead of 50k per-name
        mutations — the per-item loop was a fifth of a 50k relist."""
        nodes = list(nodes)
        with self._lock:
            current = self._nodes
            new = {node.name: node for node in nodes}
            added = [name for name in new if name not in current]
            deleted = []
            if len(current) - (len(new) - len(added)):
                deleted = [name for name in current if name not in new]
            set_changed = bool(added or deleted)
            if not set_changed:
                # same membership: addresses are the remaining way the
                # node SET can have changed (annotation churn must not
                # defeat (name, ip) caches keyed on node_set_version)
                get = current.get
                for name, node in new.items():
                    if get(name).addresses != node.addresses:
                        set_changed = True
                        break
            for name in added:
                self._note_pod_change_locked(name)
            for name in deleted:
                self._note_pod_change_locked(name)
            # every listed name is replaced and the rest are pruned, so
            # clearing the overlay IS the per-name tombstone sweep
            self._anno_segments.clear()
            self._nodes = new
            self._sched_version += 1
            self._node_version += 1
            self._dirty_global.mark_bulk(self._node_version)
            if set_changed:
                self._crc_index = None  # rebuilt lazily at next reshard
            self._bump_shards_locked(None, node=True)  # relist: all fences
            if set_changed:
                self._node_set_version += 1

    def replace_pods(self, pods) -> None:
        """Pod twin of ``replace_nodes`` (burst rows the server no
        longer lists are retired too, like delete_pod would)."""
        pods = list(pods)
        with self._lock:
            v0 = self._sched_version
            for pod in pods:
                self._add_pod_locked(pod)
            live = {p.key() for p in pods}
            stale = [k for k in self._pods if k not in live]
            if self._bursts:
                stale += [
                    p.key() for p in self._burst_pods_locked(None)
                    if p.key() not in live
                ]
            for key in stale:
                self._delete_pod_locked(key)
            if self._sched_version > v0:
                self._sched_version = v0 + 1

    def patch_node_annotation(self, name: str, key: str, value: str) -> bool:
        """The controller's write primitive (ref: node.go:123-146)."""
        with self._lock:
            node = self._nodes.get(name)
            if node is None:
                return False
            anno = dict(self._merged_annotations_locked(node))
            anno[key] = value
            self._drop_overlay_locked(name)
            self._nodes[name] = replace(node, annotations=anno)
            self._sched_version += 1
            self._node_version += 1
            self._note_dirty_locked(self._node_version, name)
            self._bump_shards_locked(name, node=True)
            return True

    def patch_node_annotations_bulk(self, per_node: Mapping[str, Mapping[str, str]]) -> int:
        """Batch annotation patch: one lock hold and one node-object copy
        per node for a whole sweep's writes (the per-(node, key) primitive
        costs a lock + full annotation copy each). Returns patched count;
        missing nodes are skipped like ``patch_node_annotation``'s False."""
        patched = 0
        with self._lock:
            nodes = self._nodes
            has_overlay = bool(self._anno_segments)
            patched_names: list[str] = []
            for name, kv in per_node.items():
                node = nodes.get(name)
                if node is None:
                    continue
                if has_overlay:
                    anno = dict(self._merged_annotations_locked(node))
                    self._drop_overlay_locked(name)
                else:
                    anno = dict(node.annotations)
                anno.update(kv)
                # raw copy (see bind_pods): field-identical to
                # replace(node, annotations=anno), minus __init__ overhead
                new_node = object.__new__(Node)
                d = new_node.__dict__
                d.update(node.__dict__)
                d["annotations"] = anno
                nodes[name] = new_node
                self._sched_version += 1
                self._bump_shards_locked(name, node=True)
                patched += 1
                patched_names.append(name)
            if patched:
                self._node_version += 1
                v = self._node_version
                for name in patched_names:
                    self._note_dirty_locked(v, name)
        return patched

    def patch_node_annotations_columns(
        self, names: list[str], columns: Mapping[str, list[str]]
    ) -> int:
        """Columnar batch patch: every column in ``columns`` is aligned
        with ``names`` (row i belongs to ``names[i]``). Lands in the
        lazy overlay as an O(keys) segment append/merge — NO per-node
        work at all (the reference pays a PATCH per (node, metric),
        node.go:101-121; the per-node dict-pivot this replaces
        dominated 50k-node flush profiles at ~7us/node). Readers fold
        segments lazily (see ``_anno_segments``). Returns the submitted
        row count; rows for unknown nodes are dropped at fold time."""
        with self._lock:
            segments = self._anno_segments
            if segments and segments[-1][0] is names and not segments[-1][3]:
                # steady state: same node-table object, no tombstones —
                # replace this sweep's columns in place
                segments[-1][2].update(columns)
            else:
                segments.append((
                    names, self._pos_for_locked(names), dict(columns), set(),
                ))
                if len(segments) > 8:
                    # churning node sets / tombstones: bound the read
                    # cost by materializing everything once
                    self._fold_overlay_locked()
            self._sched_version += len(names)
            self._node_version += 1
            # the sweep rewrites every listed row — journal coverage
            # would be the whole shard, so reset the floor instead
            self._dirty_global.mark_bulk(self._node_version)
            self._bump_shards_locked(None, node=True)  # sweep: all fences
        return len(names)

    def patch_node_annotation_groups(self, groups) -> int:
        """Apply several aligned column groups (``[(names, {key:
        values}), ...]`` — the annotator flush's shape when fallback
        filtering gives metrics different row sets) in one call. Each
        group is an O(keys) overlay segment here; the kube client's
        implementation instead pivots ALL groups into one HTTP patch
        per node."""
        patched = 0
        for names, columns in groups:
            patched += self.patch_node_annotations_columns(names, columns)
        return patched

    # -- pods --------------------------------------------------------------

    def _index_remove(self, pod: Pod) -> None:
        if pod.node_name:
            keys = self._pods_by_node.get(pod.node_name)
            if keys is not None:
                keys.pop(pod.key(), None)
                if not keys:
                    del self._pods_by_node[pod.node_name]

    def _index_add(self, pod: Pod) -> None:
        if pod.node_name:
            self._pods_by_node.setdefault(pod.node_name, {})[pod.key()] = None

    def _shadow_burst_locked(self, key: str) -> bool:
        """An object pod added under a live burst key replaces the row
        (mirrors add_pod's replace semantics). Returns True when the
        retired row was bound — the caller must count that as replacing
        a bound pod for ``sched_version``."""
        hit = self._burst_lookup_locked(key)
        if hit is None:
            return False
        burst, row = hit
        was_bound = int(burst.node_ids[row]) >= 0
        self._burst_retire_row_locked(burst, row)
        if self._burst_index is not None:
            self._burst_index.pop(key, None)
        return was_bound

    def _add_pod_locked(self, pod: Pod) -> None:
        """The one add/replace implementation (callers hold the lock):
        shadow any live burst row, replace the object entry, and treat
        replacing a bound pod — object or burst row — as a bound-pod
        delete for snapshot versioning."""
        key = pod.key()
        prev_burst_bound = (
            self._shadow_burst_locked(key) if self._bursts else False
        )
        prev = self._pods.get(key)
        if prev is not None:
            self._index_remove(prev)
        self._pods[key] = pod
        self._index_add(pod)
        if (
            pod.node_name
            or (prev is not None and prev.node_name)
            or prev_burst_bound
        ):
            self._sched_version += 1
        # journal only REAL changes: a kube relist re-adding identical
        # bound pods (410 recovery at 50k nodes) must not flood the
        # journal and defeat the incremental NUMA path it feeds
        same = (
            prev is not None
            and prev.node_name == pod.node_name
            and prev.annotations == pod.annotations
            and prev.containers == pod.containers
            and prev.init_containers == pod.init_containers
            and prev.overhead == pod.overhead
        )
        if pod.node_name and not same:
            self._note_pod_change_locked(pod.node_name)
        if (
            prev is not None
            and prev.node_name
            and prev.node_name != pod.node_name
        ):
            self._note_pod_change_locked(prev.node_name)

    def add_pod(self, pod: Pod) -> None:
        with self._lock:
            self._add_pod_locked(pod)

    def add_pods(self, pods) -> None:
        """Batch ``add_pod``: one lock hold for a whole burst's pod
        creations (per-pod lock round-trips dominate 100k-pod cycles)."""
        with self._lock:
            for pod in pods:
                self._add_pod_locked(pod)

    def delete_pod(self, key: str) -> None:
        with self._lock:
            self._delete_pod_locked(key)

    def evict_pod(self, key: str, now: float | None = None) -> bool:
        """Eviction-subresource semantics for the in-memory apiserver:
        remove the pod and emit the ``Evicted`` event (the signal the
        closed placement loop observes). Returns False when the pod
        does not exist — the 404 the real subresource answers."""
        if now is None:
            now = time.time()
        with self._lock:
            pod = self._pods.get(key)
            if pod is None and self._bursts:
                hit = self._burst_lookup_locked(key)
                if hit is not None:
                    pod = hit[0].materialize(hit[1])
            if pod is None:
                return False
            node_name = pod.node_name
            self._delete_pod_locked(key)
        self.emit_event(
            Event(
                namespace=pod.namespace,
                name=f"{pod.name}.evicted",
                type="Normal",
                reason="Evicted",
                message=f"Evicted pod {key} from {node_name}",
                count=1,
                last_timestamp=now,
            )
        )
        return True

    def _delete_pod_locked(self, key: str) -> None:
        pod = self._pods.pop(key, None)
        if pod is None and self._bursts:
            hit = self._burst_lookup_locked(key)
            if hit is not None:
                burst, row = hit
                pod = burst.materialize(row)
                self._burst_retire_row_locked(burst, row)
                if self._burst_index is not None:
                    self._burst_index.pop(key, None)
                if pod.node_name:
                    self._sched_version += 1
                    self._bump_shards_locked(None, pod=True)
                return
        if pod is not None:
            self._index_remove(pod)
        if pod is not None and pod.node_name:
            self._sched_version += 1
            self._note_pod_change_locked(pod.node_name)

    def get_pod(self, key: str) -> Pod | None:
        with self._lock:
            pod = self._pods.get(key)
            if pod is None and self._bursts:
                hit = self._burst_lookup_locked(key)
                if hit is not None:
                    return hit[0].materialize(hit[1])
            return pod

    def list_pods(self, node_name: str | None = None) -> list[Pod]:
        with self._lock:
            if node_name is None:
                out = list(self._pods.values())
            else:
                keys = self._pods_by_node.get(node_name)
                out = [self._pods[k] for k in keys] if keys else []
            if self._bursts:
                out.extend(self._burst_pods_locked(node_name))
            return out

    def count_pods(self, node_name: str) -> int:
        """Bound pods on ``node_name`` — O(1) via the per-node index."""
        with self._lock:
            keys = self._pods_by_node.get(node_name)
            count = len(keys) if keys else 0
            burst_counts = self._burst_counts_locked()
            if burst_counts:
                count += burst_counts.get(node_name, 0)
            return count

    def count_pods_all(self) -> dict[str, int]:
        """Bound-pod counts for every node in ONE lock hold (a metric
        sweep reading counts per node x metric would otherwise take the
        lock |nodes|x|metrics| times)."""
        with self._lock:
            counts = {
                name: len(keys) for name, keys in self._pods_by_node.items()
            }
            burst_counts = self._burst_counts_locked()
            if burst_counts:
                for name, c in burst_counts.items():
                    counts[name] = counts.get(name, 0) + c
            return counts

    def patch_pod_annotation(self, key: str, anno_key: str, value: str) -> bool:
        """PreBind's write primitive (ref: noderesourcetopology/binder.go:19-65)."""
        with self._lock:
            pod = self._pods.get(key)
            if pod is None and self._bursts:
                hit = self._burst_lookup_locked(key)
                if hit is not None:
                    pod = self._materialize_out_locked(*hit)
            if pod is None:
                return False
            anno = dict(pod.annotations)
            anno[anno_key] = value
            self._pods[key] = replace(pod, annotations=anno)
            if pod.node_name:
                self._sched_version += 1
                # a bound pod's annotations feed NUMA usage
                # reconstruction (topology-result annotation)
                self._note_pod_change_locked(pod.node_name)
            return True

    def bind_pod(self, pod_key: str, node_name: str, now: float | None = None) -> bool:
        """Bind + emit the ``Scheduled`` event the annotator listens for
        (message contract ref: event.go:118-137; single source:
        ``bind_pods``)."""
        return bool(self.bind_pods(((pod_key, node_name),), now))

    def bind_pods(self, assignments, now: float | None = None,
                  notify: bool = True) -> list[str]:
        """Batch bind: one lock hold mutates every pod and stamps every
        ``Scheduled`` event, then handlers run outside the lock in bind
        order — semantically identical to calling ``bind_pod`` per pod
        (same events, same order, same feedback), minus per-pod lock
        round-trips that dominate 100k-pod bursts. ``assignments`` is a
        ``{pod_key: node_name}`` mapping (or iterable of pairs); returns
        the keys actually bound (missing pods are skipped, mirroring
        ``bind_pod``'s False).

        ``notify=False`` applies the placements WITHOUT recording or
        delivering Scheduled events — the kube client's batched
        optimistic mirror apply (the apiserver's authoritative events
        arrive through the watch; local emission would double-count hot
        values, exactly the ``bind_burst(notify=False)`` rule)."""
        if now is None:
            now = time.time()
        items = assignments.items() if hasattr(assignments, "items") else assignments
        bound: list[str] = []
        stamped: list[Event] = []
        with self._lock:
            pods = self._pods
            pods_by_node = self._pods_by_node
            events = self._events
            event_index = self._event_index
            for pod_key, node_name in items:
                pod = pods.get(pod_key)
                if pod is None:
                    if self._bursts:
                        hit = self._burst_lookup_locked(pod_key)
                        if hit is not None:
                            pod = self._materialize_out_locked(*hit)
                    if pod is None:
                        continue
                self._index_remove(pod)
                # dataclasses.replace() re-runs __init__ field machinery;
                # at 100k binds/cycle the raw-copy path below is the
                # difference between bind application being free and it
                # dominating the loop (field set identical to
                # replace(pod, node_name=node_name))
                new_pod = object.__new__(Pod)
                d = new_pod.__dict__
                d.update(pod.__dict__)
                d["node_name"] = node_name
                pods[pod_key] = new_pod
                # _index_add inlined with the already-known key
                per_node = pods_by_node.get(node_name)
                if per_node is None:
                    per_node = pods_by_node[node_name] = {}
                per_node[pod_key] = None
                self._sched_version += 1
                self._note_pod_change_locked(node_name)
                bound.append(pod_key)
                if not notify:
                    continue
                event = Event(
                    namespace=pod.namespace,
                    name=f"{pod.name}.scheduled",
                    type="Normal",
                    reason="Scheduled",
                    message=(
                        f"Successfully assigned {pod_key} to {node_name}"
                    ),
                    count=1,
                    last_timestamp=now,
                    resource_version=self._next_rv(),
                )
                # inline _record_event_locked minus the re-stamp replace():
                # the rv is already final
                events.append(event)
                event_index[f"{event.namespace}/{event.name}"] = event
                stamped.append(event)
            handlers = list(self._event_handlers)
            batch_handlers = list(self._batch_handlers)
        for event in stamped:
            for handler in handlers:
                handler(event)
        if stamped:
            for handler in batch_handlers:
                handler(stamped)
        return bound

    # -- columnar pod bursts -----------------------------------------------
    #
    # The TPU-native arrival path: a burst of bare pods lives as rows
    # (names + one int32 placement column), not 100k Python objects. Bind
    # application is one array transaction; event feedback is delivered
    # as columns to subscribers that opt in (subscribe_events_batch's
    # ``columnar=``) and materializes real Event objects only for the
    # bounded event log's tail and for legacy subscribers. Every read API
    # (get/list/count) sees burst pods; mutations materialize the row
    # into the object world first (copy-on-write). The text-message event
    # contract (ref: event.go:118-137) still holds wherever Event objects
    # surface — columnar delivery is an in-process fast path, the kube
    # boundary always carries real events.

    def add_pod_burst(self, namespace: str, names: list) -> _PodBurst:
        """Create a columnar burst of bare pending pods (no containers,
        no annotations — the bulk-arrival shape). Names must be unique
        within the namespace like any pod key."""
        burst = _PodBurst(namespace, list(names))
        with self._lock:
            self._bursts.append(burst)
            index = self._burst_index
            if index is not None:
                # extend the existing index instead of invalidating it —
                # a rebuild walks every live row of every burst
                ns = burst.namespace
                for row, name in enumerate(burst.names):
                    index[f"{ns}/{name}"] = (burst, row)
        return burst

    def _burst_lookup_locked(self, key: str):
        if not self._bursts:
            return None
        index = self._burst_index
        if index is None:
            index = {}
            for b in self._bursts:
                ns = b.namespace
                dead = b.dead
                for row, name in enumerate(b.names):
                    if row not in dead:
                        index[f"{ns}/{name}"] = (b, row)
            self._burst_index = index
        return index.get(key)

    def _burst_retire_row_locked(self, burst: _PodBurst, row: int) -> None:
        """Mark a row dead, keeping the incremental bound-counts true.
        A fully-dead burst is dropped so burst history can't grow
        lookup/materialization work without bound."""
        burst.dead.add(row)
        tid = int(burst.node_ids[row])
        if tid >= 0:
            name = burst.table[tid]
            slot = self._count_slot.get(name)
            if slot is not None and self._count_arr[slot] > 0:
                self._count_arr[slot] -= 1
            self._count_version += 1
        if len(burst.dead) == len(burst.names):
            try:
                self._bursts.remove(burst)
            except ValueError:  # pragma: no cover - already dropped
                pass

    def _materialize_out_locked(self, burst: _PodBurst, row: int) -> Pod:
        """Copy-on-write: move a burst row into the object world so
        object-path mutations (patch/delete/re-add) behave normally."""
        pod = burst.materialize(row)
        self._burst_retire_row_locked(burst, row)
        if self._burst_index is not None:
            self._burst_index.pop(pod.key(), None)
        self._pods[pod.key()] = pod
        self._index_add(pod)
        return pod

    def _count_slots_for_locked(self, table: list) -> np.ndarray:
        """Slot indices for a node table, cached on the table OBJECT
        (burst paths reuse one list per snapshot); assigns new slots and
        grows the count array as needed. Rebuilds when the table grew
        past the cached length (non-bulk binds append)."""
        cache = self._table_slots_cache
        if (cache is not None and cache[0] is table
                and len(cache[1]) == len(table)):
            return cache[1]
        slot = self._count_slot
        names_by_slot = self._slot_names
        out = np.empty((len(table),), dtype=np.int64)
        for j, name in enumerate(table):
            s = slot.get(name)
            if s is None:
                s = slot[name] = len(names_by_slot)
                names_by_slot.append(name)
            out[j] = s
        if len(self._count_arr) < len(names_by_slot):
            grown = np.zeros((len(names_by_slot),), dtype=np.int64)
            grown[: len(self._count_arr)] = self._count_arr
            self._count_arr = grown
        self._table_slots_cache = (table, out)
        return out

    def retire_burst_rows(self, burst: _PodBurst, rows) -> None:
        """Remove burst rows entirely (e.g. pod creations an apiserver
        refused): marked dead, dropped from the key index, invisible to
        every read."""
        with self._lock:
            ns = burst.namespace
            for row in rows:
                self._burst_retire_row_locked(burst, row)
                if self._burst_index is not None:
                    self._burst_index.pop(f"{ns}/{burst.names[row]}", None)

    def _burst_counts_locked(self) -> dict[str, int] | None:
        """Bound-pod counts contributed by live burst rows, as a dict —
        rebuilt lazily from the slot array and cached on the counts
        version (scalar readers; vectorized readers use
        ``bound_counts_for``)."""
        if not self._bursts:
            return None
        cache = self._count_dict_cache
        if cache is None or cache[0] != self._count_version:
            arr = self._count_arr
            names_by_slot = self._slot_names
            merged = {
                names_by_slot[i]: int(arr[i])
                for i in np.nonzero(arr)[0].tolist()
            }
            cache = (self._count_version, merged)
            self._count_dict_cache = cache
        return cache[1]

    def bound_counts_for(self, names: list) -> np.ndarray:
        """Vectorized bound-pod counts aligned with ``names`` (object
        pods + burst rows): one gather through a per-``names``-object
        cached slot index — no 50k-entry dict build per read. ``names``
        is treated as a stable, immutable list (callers pass a cached
        table)."""
        with self._lock:
            out = np.zeros((len(names),), dtype=np.int64)
            pbn = self._pods_by_node
            if pbn:
                get = pbn.get
                out += np.fromiter(
                    (len(get(n) or ()) for n in names),
                    dtype=np.int64, count=len(names),
                )
            if self._bursts and len(self._count_arr):
                cache = self._gather_cache
                n_slots = len(self._count_slot)
                if (cache is None or cache[0] is not names
                        or cache[1] != n_slots):
                    sget = self._count_slot.get
                    idx = np.fromiter(
                        (sget(n, -1) for n in names),
                        dtype=np.int64, count=len(names),
                    )
                    cache = (names, n_slots, idx)
                    self._gather_cache = cache
                idx = cache[2]
                valid = idx >= 0
                out[valid] += self._count_arr[idx[valid]]
            return out

    def _burst_pods_locked(self, node_name: str | None) -> list[Pod]:
        """Materialize burst rows (all, or those bound to ``node_name``)."""
        out: list[Pod] = []
        for b in self._bursts:
            if node_name is None:
                rows = range(len(b.names))
                if b.dead:
                    rows = (r for r in rows if r not in b.dead)
            else:
                tid = b.table_map.get(node_name)
                if tid is None:
                    continue
                rows = np.nonzero(b.node_ids == tid)[0]
                if b.dead:
                    rows = (int(r) for r in rows if int(r) not in b.dead)
            out.extend(b.materialize(int(r)) for r in rows)
        return out

    def bind_burst(self, burst: _PodBurst, node_table: list, node_idx,
                   now=None, notify: bool = True):
        """Columnar bind: row ``i`` -> ``node_table[node_idx[i]]``
        (``-1`` leaves the row pending). One lock transaction applies the
        whole column, stamps ``sched_version``/resourceVersions exactly
        like per-pod ``bind_pods``, materializes Events only for the
        bounded log's tail (the deque would evict the rest anyway) and
        for subscribers without columnar support, and hands columnar
        subscribers ``(node_table, node_idx_bound, now)``. Returns the
        bound row indices (ascending = event order).

        ``notify=False`` applies placements WITHOUT recording or
        delivering Scheduled events: the kube client's optimistic
        mirror apply uses it — the apiserver emits the authoritative
        event, which arrives through the watch (exactly the per-pod
        ``bind_pod`` rule; local emission would double-count hot
        values)."""
        if now is None:
            now = time.time()
        node_idx = np.asarray(node_idx, dtype=np.int32)
        with self._lock:
            table_map = burst.table_map
            table = burst.table
            slots_key = None
            if not table_map:
                # first bind of the burst (the common case: one bind per
                # burst): bulk-adopt the whole node table — C-speed
                # extend/zip instead of a 50k-iteration Python loop.
                # len(table_map) != len(node_table) detects duplicate
                # names in O(1); duplicates take the dedup loop below.
                table_map.update(zip(node_table, range(len(node_table))))
                if len(table_map) == len(node_table):
                    table.extend(node_table)
                    remap = np.arange(len(node_table), dtype=np.int32)
                    # table contents == node_table here, so slot lookup
                    # can key on the CALLER's table object (the burst
                    # path reuses one list per snapshot -> cache hits
                    # across bursts; burst.table is fresh per burst)
                    slots_key = node_table
                else:
                    table_map.clear()
            if slots_key is None:
                remap = np.empty((len(node_table),), dtype=np.int32)
                for j, name in enumerate(node_table):
                    tid = table_map.get(name)
                    if tid is None:
                        tid = table_map[name] = len(table)
                        table.append(name)
                    remap[j] = tid
                slots_key = table
            eligible = (node_idx >= 0) & (burst.node_ids[: len(node_idx)] == -1)
            if burst.dead:
                dead_rows = np.fromiter(burst.dead, dtype=np.int64)
                eligible[dead_rows[dead_rows < len(eligible)]] = False
            rows = np.nonzero(eligible)[0]
            bound_idx = node_idx[rows]
            burst.node_ids[rows] = remap[bound_idx]
            n = len(rows)
            # incremental bound-count maintenance: one bincount + one
            # vectorized slot-array add per bind (slots are unique per
            # table, so fancy-index += is exact)
            bc = np.bincount(remap[bound_idx], minlength=len(table))
            slots = self._count_slots_for_locked(slots_key)
            self._count_arr[slots] += bc
            self._count_version += 1
            self._sched_version += n
            # burst binds skip the pod journal by design; shard fences
            # can't attribute them, so every fence moves
            self._bump_shards_locked(None, pod=True)
            rv_base = self._rv_next
            self._rv_next += n
            if notify:
                handlers = list(self._event_handlers)
                batch = list(zip(self._batch_handlers, self._batch_columnar))
            else:
                handlers, batch = [], []
            need_full = bool(handlers) or any(c is None for _, c in batch)
            # materialize the log tail (bounded: the deque would evict
            # everything older) — or everything if a legacy subscriber
            # needs per-Event delivery
            maxlen = self._events.maxlen or n
            if not notify:
                first = n  # no local events at all: the server's arrive
            elif need_full:
                first = 0
            else:
                first = max(0, n - maxlen)
            tail_events: list[Event] = []
            ns = burst.namespace
            names = burst.names
            for k in range(first, n):
                row = int(rows[k])
                pod_name = names[row]
                node_name = node_table[int(bound_idx[k])]
                ev = object.__new__(Event)
                ev.__dict__.update(
                    namespace=ns,
                    name=f"{pod_name}.scheduled",
                    type="Normal",
                    reason="Scheduled",
                    message=(
                        f"Successfully assigned {ns}/{pod_name} "
                        f"to {node_name}"
                    ),
                    count=1,
                    event_time=0.0,
                    last_timestamp=now,
                    resource_version=rv_base + k,
                )
                tail_events.append(ev)
            if notify:
                for ev in tail_events[-maxlen:] if need_full else tail_events:
                    self._events.append(ev)
                    self._event_index[f"{ev.namespace}/{ev.name}"] = ev
        if n:
            for ev in tail_events if need_full else ():
                for handler in handlers:
                    handler(ev)
            for handler, columnar in batch:
                if columnar is not None:
                    columnar(node_table, bound_idx, now)
                elif tail_events:
                    handler(tail_events)
        return rows

    # -- events ------------------------------------------------------------

    def _next_rv(self) -> int:
        v = self._rv_next
        self._rv_next = v + 1
        return v

    def _record_event_locked(self, event: Event) -> Event:
        """Stamp + append + index an event; the recording invariant lives
        only here (callers hold the lock)."""
        event = replace(event, resource_version=self._next_rv())
        self._events.append(event)
        self._event_index[f"{event.namespace}/{event.name}"] = event
        return event

    def emit_event(self, event: Event) -> None:
        with self._lock:
            event = self._record_event_locked(event)
            handlers = list(self._event_handlers)
            batch_handlers = list(self._batch_handlers)
        for handler in handlers:
            handler(event)
        single = [event]
        for handler in batch_handlers:
            handler(single)

    def emit_events(self, events) -> None:
        """Batched emit: stamp + record every event under ONE lock hold,
        then deliver — per-event handlers in order, batch handlers once
        with the whole list (a ``bind_pods``-shaped delivery). The kube
        mirror's coalesced event watch lands a drained backlog here as
        one transaction instead of |events| lock round-trips."""
        events = list(events)
        if not events:
            return
        with self._lock:
            stamped = [self._record_event_locked(e) for e in events]
            handlers = list(self._event_handlers)
            batch_handlers = list(self._batch_handlers)
        for event in stamped:
            for handler in handlers:
                handler(event)
        for handler in batch_handlers:
            handler(stamped)

    def get_event(self, key: str) -> Event | None:
        with self._lock:
            return self._event_index.get(key)

    def list_events(self) -> list[Event]:
        with self._lock:
            return list(self._events)

    def subscribe_events(self, handler: EventHandler) -> None:
        """Informer-style subscription (new events only, like a watch)."""
        with self._lock:
            self._event_handlers.append(handler)

    def subscribe_events_batch(
        self,
        handler: Callable[[list[Event]], None],
        columnar: Callable | None = None,
    ) -> None:
        """Like ``subscribe_events`` but delivered in bursts: a single
        emit arrives as a 1-element list, ``bind_pods`` delivers the
        whole burst in one call (event order preserved).

        ``columnar``: optional fast-path alternative for columnar binds
        (``bind_burst``) — called as ``columnar(node_table, node_idx,
        ts)`` instead of materializing one Event per pod for ``handler``.
        Subscribers without it still get full Event lists."""
        with self._lock:
            self._batch_handlers.append(handler)
            self._batch_columnar.append(columnar)
