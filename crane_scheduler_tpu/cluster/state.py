"""In-memory cluster state: the framework's kube-apiserver stand-in.

The reference coordinates its two processes exclusively through the
Kubernetes API — the controller JSON-patches node annotations
(ref: pkg/controller/annotator/node.go:123-146) and watches ``Scheduled``
events (ref: cmd/controller/app/options/factory.go:25-33); the scheduler
plugin reads nodes from its informer snapshot. ``ClusterState`` models
exactly that surface: nodes with annotations and addresses, pods with owner
references and containers, a bounded event log with subscriber callbacks,
and thread-safe patch/bind operations that emit the same
"Successfully assigned <ns/pod> to <node>" events the reference parses.

In a real deployment this object is replaced by a k8s client hitting a live
apiserver; everything above it (annotator, scorer, framework) only sees
this interface.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from dataclasses import dataclass, field, replace
from typing import Callable, Mapping


@dataclass(frozen=True)
class NodeAddress:
    type: str  # "InternalIP", "Hostname", ...
    address: str


@dataclass(frozen=True)
class Node:
    name: str
    annotations: Mapping[str, str] = field(default_factory=dict)
    labels: Mapping[str, str] = field(default_factory=dict)
    addresses: tuple[NodeAddress, ...] = ()

    def internal_ip(self) -> str:
        """ref: node.go:179-187 — InternalIP, falling back to the name."""
        for addr in self.addresses:
            if addr.type == "InternalIP":
                return addr.address
        return self.name


@dataclass(frozen=True)
class OwnerReference:
    kind: str
    name: str = ""


@dataclass(frozen=True)
class ResourceRequirements:
    requests: Mapping[str, float] = field(default_factory=dict)
    limits: Mapping[str, float] = field(default_factory=dict)


@dataclass(frozen=True)
class Container:
    name: str
    resources: ResourceRequirements = field(default_factory=ResourceRequirements)


@dataclass(frozen=True)
class Pod:
    name: str
    namespace: str = "default"
    annotations: Mapping[str, str] = field(default_factory=dict)
    owner_references: tuple[OwnerReference, ...] = ()
    containers: tuple[Container, ...] = ()
    node_name: str = ""

    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    def is_daemonset_pod(self) -> bool:
        """ref: pkg/utils/utils.go:17-24."""
        return any(ref.kind == "DaemonSet" for ref in self.owner_references)


@dataclass(frozen=True)
class Event:
    namespace: str
    name: str
    type: str  # "Normal" | "Warning"
    reason: str
    message: str
    count: int = 1
    event_time: float = 0.0  # used when count == 0 (ref: event.go:131-137)
    last_timestamp: float = 0.0
    resource_version: int = 0


EventHandler = Callable[[Event], None]


class ClusterState:
    """Thread-safe cluster model with event subscription."""

    def __init__(self, max_events: int = 4096):
        self._lock = threading.RLock()
        self._nodes: dict[str, Node] = {}
        self._pods: dict[str, Pod] = {}
        # per-node bound-pod key index (insertion-ordered) so
        # list_pods(node) is O(pods on node), not O(all pods) — metric
        # streams and per-pod bind filters call it per node
        self._pods_by_node: dict[str, dict[str, None]] = {}
        self._events: deque[Event] = deque(maxlen=max_events)
        self._event_index: dict[str, Event] = {}
        self._event_handlers: list[EventHandler] = []
        self._batch_handlers: list[Callable[[list[Event]], None]] = []
        self._rv = itertools.count(1)
        self._sched_version = 0

    @property
    def sched_version(self) -> int:
        """Monotonic mutation counter (an informer resourceVersion
        stand-in) over changes a scheduling snapshot can observe: node
        add/delete/annotation, bound-pod add/delete/annotation, and
        binds. Adding or annotating a pending (unbound) pod does NOT
        bump it, so drip scheduling (add pod, schedule, repeat) can
        reuse a cached snapshot."""
        with self._lock:
            return self._sched_version

    # -- nodes -------------------------------------------------------------

    def add_node(self, node: Node) -> None:
        with self._lock:
            self._nodes[node.name] = node
            self._sched_version += 1

    def delete_node(self, name: str) -> None:
        with self._lock:
            self._nodes.pop(name, None)
            self._sched_version += 1

    def get_node(self, name: str) -> Node | None:
        with self._lock:
            return self._nodes.get(name)

    def list_nodes(self) -> list[Node]:
        with self._lock:
            return list(self._nodes.values())

    def node_names(self) -> list[str]:
        with self._lock:
            return list(self._nodes)

    def patch_node_annotation(self, name: str, key: str, value: str) -> bool:
        """The controller's write primitive (ref: node.go:123-146)."""
        with self._lock:
            node = self._nodes.get(name)
            if node is None:
                return False
            anno = dict(node.annotations)
            anno[key] = value
            self._nodes[name] = replace(node, annotations=anno)
            self._sched_version += 1
            return True

    # -- pods --------------------------------------------------------------

    def _index_remove(self, pod: Pod) -> None:
        if pod.node_name:
            keys = self._pods_by_node.get(pod.node_name)
            if keys is not None:
                keys.pop(pod.key(), None)
                if not keys:
                    del self._pods_by_node[pod.node_name]

    def _index_add(self, pod: Pod) -> None:
        if pod.node_name:
            self._pods_by_node.setdefault(pod.node_name, {})[pod.key()] = None

    def add_pod(self, pod: Pod) -> None:
        with self._lock:
            prev = self._pods.get(pod.key())
            if prev is not None:
                self._index_remove(prev)
            self._pods[pod.key()] = pod
            self._index_add(pod)
            # replacing a bound pod is a bound-pod delete for snapshots
            if pod.node_name or (prev is not None and prev.node_name):
                self._sched_version += 1

    def delete_pod(self, key: str) -> None:
        with self._lock:
            pod = self._pods.pop(key, None)
            if pod is not None:
                self._index_remove(pod)
            if pod is not None and pod.node_name:
                self._sched_version += 1

    def get_pod(self, key: str) -> Pod | None:
        with self._lock:
            return self._pods.get(key)

    def list_pods(self, node_name: str | None = None) -> list[Pod]:
        with self._lock:
            if node_name is None:
                return list(self._pods.values())
            keys = self._pods_by_node.get(node_name)
            if not keys:
                return []
            return [self._pods[k] for k in keys]

    def count_pods(self, node_name: str) -> int:
        """Bound pods on ``node_name`` — O(1) via the per-node index."""
        with self._lock:
            keys = self._pods_by_node.get(node_name)
            return len(keys) if keys else 0

    def patch_pod_annotation(self, key: str, anno_key: str, value: str) -> bool:
        """PreBind's write primitive (ref: noderesourcetopology/binder.go:19-65)."""
        with self._lock:
            pod = self._pods.get(key)
            if pod is None:
                return False
            anno = dict(pod.annotations)
            anno[anno_key] = value
            self._pods[key] = replace(pod, annotations=anno)
            if pod.node_name:
                self._sched_version += 1
            return True

    def bind_pod(self, pod_key: str, node_name: str, now: float | None = None) -> bool:
        """Bind + emit the ``Scheduled`` event the annotator listens for
        (message contract ref: event.go:118-137; single source:
        ``bind_pods``)."""
        return bool(self.bind_pods(((pod_key, node_name),), now))

    def bind_pods(self, assignments, now: float | None = None) -> list[str]:
        """Batch bind: one lock hold mutates every pod and stamps every
        ``Scheduled`` event, then handlers run outside the lock in bind
        order — semantically identical to calling ``bind_pod`` per pod
        (same events, same order, same feedback), minus per-pod lock
        round-trips that dominate 100k-pod bursts. ``assignments`` is a
        ``{pod_key: node_name}`` mapping (or iterable of pairs); returns
        the keys actually bound (missing pods are skipped, mirroring
        ``bind_pod``'s False)."""
        if now is None:
            now = time.time()
        items = assignments.items() if hasattr(assignments, "items") else assignments
        bound: list[str] = []
        stamped: list[Event] = []
        with self._lock:
            for pod_key, node_name in items:
                pod = self._pods.get(pod_key)
                if pod is None:
                    continue
                self._index_remove(pod)
                new_pod = replace(pod, node_name=node_name)
                self._pods[pod_key] = new_pod
                self._index_add(new_pod)
                self._sched_version += 1
                bound.append(pod_key)
                event = Event(
                    namespace=pod.namespace,
                    name=f"{pod.name}.scheduled",
                    type="Normal",
                    reason="Scheduled",
                    message=(
                        f"Successfully assigned {pod.namespace}/{pod.name} "
                        f"to {node_name}"
                    ),
                    count=1,
                    last_timestamp=now,
                )
                stamped.append(self._record_event_locked(event))
            handlers = list(self._event_handlers)
            batch_handlers = list(self._batch_handlers)
        for event in stamped:
            for handler in handlers:
                handler(event)
        if stamped:
            for handler in batch_handlers:
                handler(stamped)
        return bound

    # -- events ------------------------------------------------------------

    def _record_event_locked(self, event: Event) -> Event:
        """Stamp + append + index an event; the recording invariant lives
        only here (callers hold the lock)."""
        event = replace(event, resource_version=next(self._rv))
        self._events.append(event)
        self._event_index[f"{event.namespace}/{event.name}"] = event
        return event

    def emit_event(self, event: Event) -> None:
        with self._lock:
            event = self._record_event_locked(event)
            handlers = list(self._event_handlers)
            batch_handlers = list(self._batch_handlers)
        for handler in handlers:
            handler(event)
        single = [event]
        for handler in batch_handlers:
            handler(single)

    def get_event(self, key: str) -> Event | None:
        with self._lock:
            return self._event_index.get(key)

    def list_events(self) -> list[Event]:
        with self._lock:
            return list(self._events)

    def subscribe_events(self, handler: EventHandler) -> None:
        """Informer-style subscription (new events only, like a watch)."""
        with self._lock:
            self._event_handlers.append(handler)

    def subscribe_events_batch(self, handler: Callable[[list[Event]], None]) -> None:
        """Like ``subscribe_events`` but delivered in bursts: a single
        emit arrives as a 1-element list, ``bind_pods`` delivers the
        whole burst in one call (event order preserved)."""
        with self._lock:
            self._batch_handlers.append(handler)
