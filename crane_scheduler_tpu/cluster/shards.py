"""Deterministic node-shard ownership, shared by every layer.

The sharded placement plane (doc/sharding.md) needs ONE answer to
"which shard(s) observe node X" that the cluster mirror (per-shard
watch fences), the shard views (column membership), and the bench/smoke
drivers all agree on — a disagreement would silently desync a shard's
version fence from the columns built over it. Ownership is a pure
function of the node *name* (stable across relists, restarts, and
processes): primary shard = ``crc32(name) % count``, matching the
reference annotator's worker-pool hashing (ref:
pkg/controller/annotator/node.go:148-177) and Agon's partitioned
scheduler assignment (arxiv 2109.00665).

Overlap is opt-in competition: with ``overlap > 0`` a deterministic
fraction of each shard's nodes is *also* observed by the next shard
(ring order), so two schedulers can race for the same capacity and the
optimistic conflict protocol gets exercised instead of proven dead by
construction. ``overlap`` is a fraction in [0, 1): 0 = disjoint
partition, 0.25 = a quarter of the keyspace co-owned. Derived from a
second independent slice of the same CRC so the co-owned set is not
correlated with the primary assignment.
"""

from __future__ import annotations

import bisect
import zlib
from dataclasses import dataclass, field

__all__ = [
    "shard_of", "shard_owners", "name_point", "ShardSpec",
    "HashRing", "RingRebalancer",
]

# overlap is quantized to 1/1024ths of the keyspace: coarse enough to
# stay deterministic across platforms, fine enough for a 5% gate
_OVERLAP_QUANTA = 1024


def _crc(name: str) -> int:
    return zlib.crc32(name.encode("utf-8"))


def name_point(name: str) -> int:
    """A node name's position on the 32-bit hash ring (the same CRC the
    static modulo layout uses, so both keyspaces agree on the draw
    bits)."""
    return _crc(name)


def shard_of(name: str, count: int) -> int:
    """Primary owner shard for ``name`` under a ``count``-way layout."""
    if count <= 1:
        return 0
    return _crc(name) % count


def shard_owners(name: str, count: int, overlap: float = 0.0) -> tuple[int, ...]:
    """Every shard that observes ``name`` (primary first).

    With ``overlap`` > 0, a deterministic ``overlap`` fraction of names
    is co-owned by the ring successor of the primary shard. The
    co-ownership draw uses bits of the CRC independent of the modulus,
    so overlap membership is uncorrelated with primary assignment.
    """
    if count <= 1:
        return (0,)
    c = _crc(name)
    primary = c % count
    if overlap <= 0.0:
        return (primary,)
    draw = (c >> 12) % _OVERLAP_QUANTA
    if draw < int(overlap * _OVERLAP_QUANTA):
        return (primary, (primary + 1) % count)
    return (primary,)


@dataclass(frozen=True)
class ShardSpec:
    """One scheduler's slice of the node keyspace.

    ``index`` observes its primary partition plus (under overlap) the
    co-owned spill from its ring predecessor — i.e. ``observes(name)``
    iff ``index in shard_owners(name, count, overlap)``.
    """

    index: int
    count: int
    overlap: float = 0.0
    # optional dynamic keyspace (a HashRing shared with the cluster
    # mirror): when set, ownership follows the ring's CURRENT token
    # assignment — a reshard moves this spec's membership without
    # rebuilding the spec. Excluded from equality: two specs over the
    # same live ring object compare by slice, not ring state.
    layout: object | None = field(default=None, compare=False)

    def __post_init__(self):
        if not (0 <= self.index < self.count):
            raise ValueError(f"shard index {self.index} not in [0, {self.count})")
        if not (0.0 <= self.overlap < 1.0):
            raise ValueError(f"overlap {self.overlap} not in [0, 1)")
        if self.layout is not None and self.layout.count != self.count:
            raise ValueError(
                f"layout has {self.layout.count} shards, spec expects "
                f"{self.count}"
            )

    def observes(self, name: str) -> bool:
        return self.index in self.owners(name)

    def owners(self, name: str) -> tuple[int, ...]:
        if self.layout is not None:
            return self.layout.owners(name)
        return shard_owners(name, self.count, self.overlap)


_RING_SPACE = 1 << 32


class HashRing:
    """Consistent-hash node keyspace: ``count`` shards x ``vnodes``
    virtual tokens on the 32-bit CRC ring; a name is owned by the first
    token clockwise of ``name_point(name)``. Unlike the static modulo,
    ownership can MOVE: reassigning a token hands exactly that token's
    arc to another shard, so ``ClusterState.reshard`` migrates only the
    names hashed into the moved arcs (doc/sharding.md "Dynamic
    resharding").

    The live ring is mutable by ATOMIC STATE SWAP only (``adopt``):
    readers snapshot ``_state`` once per query, so ShardSpec/ShardView
    lookups racing a reshard see either the old or the new layout,
    never a torn one. Token positions are a pure function of (count,
    vnodes), and an explicit assignment vector captures moves — two
    processes given the same spec dict rebuild identical rings.

    Overlap keeps the static layout's semantics: the same independent
    CRC draw picks co-owned names, and the co-owner is the next
    DISTINCT shard clockwise of the owning token.
    """

    def __init__(
        self,
        count: int,
        vnodes: int = 64,
        overlap: float = 0.0,
        assignments: list[int] | None = None,
    ):
        if count < 1:
            raise ValueError(f"shard count must be >= 1, got {count}")
        if vnodes < 1:
            raise ValueError(f"vnodes must be >= 1, got {vnodes}")
        if not (0.0 <= overlap < 1.0):
            raise ValueError(f"overlap {overlap} not in [0, 1)")
        self.count = int(count)
        self.vnodes = int(vnodes)
        self.overlap = float(overlap)
        taken: dict[int, int] = {}
        for s in range(count):
            for j in range(vnodes):
                k = 0
                while True:
                    point = _crc(f"ring/{s}/{j}/{k}")
                    if point not in taken:
                        break
                    k += 1  # deterministic collision rehash
                taken[point] = s
        points = sorted(taken)
        owners = [taken[p] for p in points]
        if assignments is not None:
            if len(assignments) != len(points):
                raise ValueError(
                    f"{len(assignments)} assignments for {len(points)} tokens"
                )
            for s in assignments:
                if not (0 <= s < count):
                    raise ValueError(f"assignment {s} not in [0, {count})")
            owners = [int(s) for s in assignments]
        self._state = (tuple(points), tuple(owners), 0)

    # -- queries (lock-free: one atomic state snapshot per call) ---------

    @property
    def version(self) -> int:
        return self._state[2]

    def tokens(self) -> tuple[tuple[int, ...], tuple[int, ...]]:
        """(points, owners) — sorted ring tokens and their shard."""
        points, owners, _ = self._state
        return points, owners

    def _owner_index(self, points, point: int) -> int:
        i = bisect.bisect_left(points, point)
        return 0 if i == len(points) else i

    def _pair_at(self, points, owners, point: int) -> tuple[int, int]:
        """(primary, next-distinct) owner for a hash position — the
        full observation fingerprint a name at ``point`` can have."""
        i = self._owner_index(points, point)
        primary = owners[i]
        n = len(owners)
        for step in range(1, n):
            nxt = owners[(i + step) % n]
            if nxt != primary:
                return primary, nxt
        return primary, primary

    def owner(self, name: str) -> int:
        points, owners, _ = self._state
        return owners[self._owner_index(points, name_point(name))]

    def owners(self, name: str) -> tuple[int, ...]:
        points, owners, _ = self._state
        c = name_point(name)
        primary, nxt = self._pair_at(points, owners, c)
        if self.overlap <= 0.0 or nxt == primary:
            return (primary,)
        draw = (c >> 12) % _OVERLAP_QUANTA
        if draw < int(self.overlap * _OVERLAP_QUANTA):
            return (primary, nxt)
        return (primary,)

    def spec_dict(self) -> dict:
        """Serializable ring spec — a peer process rebuilds the exact
        ring with ``HashRing.from_spec`` (the cross-process reshard
        handshake in tools/reshard_smoke.py)."""
        points, owners, version = self._state
        return {
            "count": self.count,
            "vnodes": self.vnodes,
            "overlap": self.overlap,
            "assignments": list(owners),
            "version": version,
        }

    @classmethod
    def from_spec(cls, spec: dict) -> "HashRing":
        ring = cls(
            int(spec["count"]),
            int(spec.get("vnodes", 64)),
            float(spec.get("overlap", 0.0)),
            assignments=spec.get("assignments"),
        )
        points, owners, _ = ring._state
        ring._state = (points, owners, int(spec.get("version", 0)))
        return ring

    # -- moves (functional: each returns a DETACHED ring) ----------------

    def with_moves(self, moves) -> "HashRing":
        """New ring with tokens reassigned: ``moves`` is ``[(token_idx,
        new_shard), ...]`` over the sorted token order."""
        points, owners, version = self._state
        new_owners = list(owners)
        for idx, shard in moves:
            if not (0 <= idx < len(points)):
                raise ValueError(f"token index {idx} out of range")
            if not (0 <= shard < self.count):
                raise ValueError(f"shard {shard} not in [0, {self.count})")
            new_owners[idx] = int(shard)
        ring = HashRing.__new__(HashRing)
        ring.count, ring.vnodes, ring.overlap = (
            self.count, self.vnodes, self.overlap,
        )
        ring._state = (points, tuple(new_owners), version + 1)
        return ring

    def split(self, shard: int, into: int) -> "HashRing":
        """Hand every other of ``shard``'s tokens to ``into`` — the
        classic hot-shard split (both indices must already exist; a
        COUNT change is a plane reconfigure, not a move)."""
        points, owners, _ = self._state
        mine = [i for i, s in enumerate(owners) if s == shard]
        return self.with_moves([(i, into) for i in mine[1::2]])

    def merge(self, src: int, dst: int) -> "HashRing":
        """Hand ALL of ``src``'s tokens to ``dst`` (drain a shard)."""
        points, owners, _ = self._state
        return self.with_moves(
            [(i, dst) for i, s in enumerate(owners) if s == src]
        )

    def moved_arcs(self, other: "HashRing"):
        """Half-open arcs ``(lo, hi]`` (lo > hi wraps) where the
        (primary, next-distinct) observation fingerprint differs
        between this ring and ``other`` — the ONLY hash positions whose
        owners can change, so a reshard touches just the names inside
        them. A token owns the arc ENDING at it (first token clockwise
        of the key), so each segment between adjacent boundaries is
        evaluated at its upper end."""
        pa, oa, _ = self._state
        pb, ob, _ = other._state
        boundaries = sorted(set(pa) | set(pb))
        arcs: list[tuple[int, int]] = []
        n = len(boundaries)
        for i, lo in enumerate(boundaries):
            hi = boundaries[(i + 1) % n]
            if self._pair_at(pa, oa, hi) != other._pair_at(pb, ob, hi):
                # merge with the previous arc when contiguous
                if arcs and arcs[-1][1] == lo:
                    arcs[-1] = (arcs[-1][0], hi)
                else:
                    arcs.append((lo, hi))
        return arcs

    def adopt(self, other: "HashRing") -> None:
        """Atomically swap this live ring's state for ``other``'s (the
        commit step of ``ClusterState.reshard``; every ShardSpec /
        ShardView holding this object re-reads the new ownership on
        its next query)."""
        if other.count != self.count:
            raise ValueError(
                f"adopt cannot change the shard count "
                f"({self.count} -> {other.count})"
            )
        self._state = other._state

    def load_shares(self) -> list[float]:
        """Fraction of the 32-bit keyspace each shard owns (arc-length
        weighted) — the skew signal the rebalancer reacts to."""
        points, owners, _ = self._state
        shares = [0.0] * self.count
        n = len(points)
        for i, p in enumerate(points):
            prev = points[i - 1] if i else points[-1] - _RING_SPACE
            shares[owners[i]] += (p - prev) / _RING_SPACE
        return shares


class RingRebalancer:
    """Reacts to node churn and hot-shard skew: given a per-shard load
    signal (node counts, dirty rates, bind rates — anything additive),
    proposes token moves from the most- to the least-loaded shard until
    the max/mean ratio drops under ``1 + skew`` or ``max_moves`` tokens
    have moved. Returns a detached ring for ``ClusterState.reshard``,
    or None when the plane is already balanced."""

    def __init__(self, skew: float = 0.25, max_moves: int = 8):
        if skew <= 0:
            raise ValueError(f"skew must be > 0, got {skew}")
        self.skew = float(skew)
        self.max_moves = int(max_moves)

    def plan(self, ring: HashRing, load) -> HashRing | None:
        count = ring.count
        loads = [float(load.get(s, 0.0)) for s in range(count)] \
            if hasattr(load, "get") else [float(x) for x in load]
        if len(loads) != count:
            raise ValueError(f"{len(loads)} loads for {count} shards")
        total = sum(loads)
        if total <= 0 or count < 2:
            return None
        mean = total / count
        points, owners = ring.tokens()
        owners = list(owners)
        # per-token load estimate: the owner's measured load distributed
        # by ARC share, not split evenly — crc token spacing is
        # exponential, so the uniform estimate picks half-ring arcs and
        # overshoots the cold shard past the hot one
        arc = [0.0] * len(points)
        for i, p in enumerate(points):
            prev = points[i - 1] if i else points[-1] - _RING_SPACE
            arc[i] = (p - prev) / _RING_SPACE
        shard_arc = [0.0] * count
        for i, s in enumerate(owners):
            shard_arc[s] += arc[i]
        moves: list[tuple[int, int]] = []
        for _ in range(self.max_moves):
            hot = max(range(count), key=lambda s: loads[s])
            cold = min(range(count), key=lambda s: loads[s])
            if loads[hot] <= mean * (1.0 + self.skew):
                break
            hot_tokens = [i for i, s in enumerate(owners) if s == hot]
            if len(hot_tokens) <= 1:
                break  # never strand a shard with zero tokens

            def tok_load(i):
                if shard_arc[hot] <= 0:
                    return loads[hot] / len(hot_tokens)
                return loads[hot] * arc[i] / shard_arc[hot]

            # the ideal transfer closes both gaps at once; take the
            # token nearest it (ties: lowest index, deterministic)
            want = min(loads[hot] - mean, mean - loads[cold])
            token = min(
                hot_tokens, key=lambda i: (abs(tok_load(i) - want), i))
            delta = tok_load(token)
            if max(loads[hot] - delta, loads[cold] + delta) >= loads[hot]:
                break  # best available move no longer shrinks the spread
            owners[token] = cold
            loads[hot] -= delta
            loads[cold] += delta
            shard_arc[hot] -= arc[token]
            shard_arc[cold] += arc[token]
            moves.append((token, cold))
        if not moves:
            return None
        return ring.with_moves(moves)
