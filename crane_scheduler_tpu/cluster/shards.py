"""Deterministic node-shard ownership, shared by every layer.

The sharded placement plane (doc/sharding.md) needs ONE answer to
"which shard(s) observe node X" that the cluster mirror (per-shard
watch fences), the shard views (column membership), and the bench/smoke
drivers all agree on — a disagreement would silently desync a shard's
version fence from the columns built over it. Ownership is a pure
function of the node *name* (stable across relists, restarts, and
processes): primary shard = ``crc32(name) % count``, matching the
reference annotator's worker-pool hashing (ref:
pkg/controller/annotator/node.go:148-177) and Agon's partitioned
scheduler assignment (arxiv 2109.00665).

Overlap is opt-in competition: with ``overlap > 0`` a deterministic
fraction of each shard's nodes is *also* observed by the next shard
(ring order), so two schedulers can race for the same capacity and the
optimistic conflict protocol gets exercised instead of proven dead by
construction. ``overlap`` is a fraction in [0, 1): 0 = disjoint
partition, 0.25 = a quarter of the keyspace co-owned. Derived from a
second independent slice of the same CRC so the co-owned set is not
correlated with the primary assignment.
"""

from __future__ import annotations

import zlib
from dataclasses import dataclass

__all__ = ["shard_of", "shard_owners", "ShardSpec"]

# overlap is quantized to 1/1024ths of the keyspace: coarse enough to
# stay deterministic across platforms, fine enough for a 5% gate
_OVERLAP_QUANTA = 1024


def _crc(name: str) -> int:
    return zlib.crc32(name.encode("utf-8"))


def shard_of(name: str, count: int) -> int:
    """Primary owner shard for ``name`` under a ``count``-way layout."""
    if count <= 1:
        return 0
    return _crc(name) % count


def shard_owners(name: str, count: int, overlap: float = 0.0) -> tuple[int, ...]:
    """Every shard that observes ``name`` (primary first).

    With ``overlap`` > 0, a deterministic ``overlap`` fraction of names
    is co-owned by the ring successor of the primary shard. The
    co-ownership draw uses bits of the CRC independent of the modulus,
    so overlap membership is uncorrelated with primary assignment.
    """
    if count <= 1:
        return (0,)
    c = _crc(name)
    primary = c % count
    if overlap <= 0.0:
        return (primary,)
    draw = (c >> 12) % _OVERLAP_QUANTA
    if draw < int(overlap * _OVERLAP_QUANTA):
        return (primary, (primary + 1) % count)
    return (primary,)


@dataclass(frozen=True)
class ShardSpec:
    """One scheduler's slice of the node keyspace.

    ``index`` observes its primary partition plus (under overlap) the
    co-owned spill from its ring predecessor — i.e. ``observes(name)``
    iff ``index in shard_owners(name, count, overlap)``.
    """

    index: int
    count: int
    overlap: float = 0.0

    def __post_init__(self):
        if not (0 <= self.index < self.count):
            raise ValueError(f"shard index {self.index} not in [0, {self.count})")
        if not (0.0 <= self.overlap < 1.0):
            raise ValueError(f"overlap {self.overlap} not in [0, 1)")

    def observes(self, name: str) -> bool:
        return self.index in shard_owners(name, self.count, self.overlap)

    def owners(self, name: str) -> tuple[int, ...]:
        return shard_owners(name, self.count, self.overlap)
