"""Kubernetes apiserver client: the deployment-grade ClusterState.

The reference coordinates everything through the kube-apiserver —
client-go informers for reads, JSON merge-patches for annotation writes
(ref: pkg/controller/annotator/node.go:123-146), the pod ``binding``
subresource for binds, and a server-side-filtered Event watch
(ref: cmd/controller/app/options/factory.go:25-33). This module is the
same architecture in stdlib Python:

- **Reads are informer-style**: background watch threads mirror nodes,
  pods, and events into an in-memory ``ClusterState``; every consumer
  (annotator, scheduler, store refresh) reads the mirror exactly as it
  reads the simulator's cluster — snapshot semantics, no per-read HTTP.
- **Writes go through the API**: ``patch_node_annotation`` /
  ``patch_pod_annotation`` send strategic-merge patches
  (``{"metadata":{"annotations":{...}}}``), ``bind_pod(s)`` POSTs the
  ``binding`` subresource like the real scheduler; the mirror applies
  the change optimistically so the writer immediately observes its own
  write (client-go's informer eventually reflects it too). All writes
  ride a pool of ``concurrent_syncs`` keep-alive workers routed by
  object key (per-object FIFO ordering, cross-object parallelism) —
  the stdlib equivalent of the reference's ``--concurrent-syncs``
  workqueue workers over client-go's pooled HTTP/2 transport
  (ref: controller.go:74-77, node.go:29-42).
- **Events**: the watch is filtered server-side with
  ``fieldSelector=reason=Scheduled,type=Normal`` and feeds the same
  subscriber interface the in-memory cluster exposes, so the annotator's
  EventIngestor runs unchanged.

No external dependencies: urllib + the newline-delimited JSON watch
protocol. Auth: optional bearer token (in-cluster service-account file
or explicit). TLS contexts can be passed through ``context``.
Tested against a stub apiserver speaking the same wire protocol
(tests/kube_stub.py + tests/test_kube_client.py).
"""

from __future__ import annotations

import http.client
import json
import queue
import ssl
import threading
import time
import urllib.error
import urllib.request
from concurrent.futures import Future
from typing import Callable
from urllib.parse import urlsplit

from .state import (
    ClusterState,
    Container,
    Event,
    Node,
    NodeAddress,
    OwnerReference,
    Pod,
    ResourceRequirements,
)
from ..telemetry import Telemetry, active as active_telemetry

DEFAULT_TIMEOUT_SECONDS = 10.0
WATCH_TIMEOUT_SECONDS = 300.0
SERVICE_ACCOUNT_TOKEN = "/var/run/secrets/kubernetes.io/serviceaccount/token"  # noqa: S105
SERVICE_ACCOUNT_CA = "/var/run/secrets/kubernetes.io/serviceaccount/ca.crt"
NRT_RETRY_SECONDS = 60.0  # re-probe cadence while the CRD is absent

# deadline propagation (ISSUE 13): kube-bound POSTs forward the
# thread's remaining budget — or mint the client's configured default —
# beside traceparent. Lazily imported: the deadline module lives in the
# service package and the header is only needed on the write path.
_deadline_mod = None


def _deadline_pair(default_budget_ms) -> tuple[str, str] | None:
    global _deadline_mod
    if _deadline_mod is None:
        from ..service import deadline as _dm

        _deadline_mod = _dm
    dl = _deadline_mod.current()
    if dl is not None:
        return _deadline_mod.HEADER, dl.header_value()
    if default_budget_ms:
        return _deadline_mod.HEADER, f"{float(default_budget_ms):.3f}"
    return None


def node_from_json(obj: dict) -> Node:
    meta = obj.get("metadata", {})
    status = obj.get("status", {})
    return Node(
        name=meta.get("name", ""),
        annotations=dict(meta.get("annotations") or {}),
        labels=dict(meta.get("labels") or {}),
        addresses=tuple(
            NodeAddress(a.get("type", ""), a.get("address", ""))
            for a in status.get("addresses") or []
        ),
        allocatable=dict(status.get("allocatable") or {}),
    )


def _containers_from_json(items) -> tuple[Container, ...]:
    out = []
    for c in items or []:
        res = c.get("resources") or {}
        out.append(
            Container(
                name=c.get("name", ""),
                resources=ResourceRequirements(
                    requests=dict(res.get("requests") or {}),
                    limits=dict(res.get("limits") or {}),
                ),
            )
        )
    return tuple(out)


def pod_from_json(obj: dict) -> Pod:
    meta = obj.get("metadata", {})
    spec = obj.get("spec", {})
    return Pod(
        name=meta.get("name", ""),
        namespace=meta.get("namespace", "default"),
        annotations=dict(meta.get("annotations") or {}),
        owner_references=tuple(
            OwnerReference(kind=r.get("kind", ""), name=r.get("name", ""))
            for r in meta.get("ownerReferences") or []
        ),
        containers=_containers_from_json(spec.get("containers")),
        node_name=spec.get("nodeName", "") or "",
        init_containers=_containers_from_json(spec.get("initContainers")),
        overhead=dict(spec.get("overhead") or {}),
    )


def _parse_wall_time(value) -> float:
    """RFC3339 (k8s event timestamps) -> epoch seconds; 0.0 on absence."""
    if not value:
        return 0.0
    if isinstance(value, (int, float)):
        return float(value)
    from datetime import datetime

    s = str(value).replace("Z", "+00:00")
    try:
        return datetime.fromisoformat(s).timestamp()
    except ValueError:
        return 0.0


NRT_API_PATH = "/apis/topology.crane.io/v1alpha1/noderesourcetopologies"

# merge-patches are idempotent (last write wins byte-for-byte), so a
# response-phase transport failure can be blindly retried; the binding
# subresource POST is NOT (a duplicate bind 409s on a real apiserver and
# double-emits the Scheduled event on permissive servers), so POSTs only
# retry when the failure happened before a full request reached the wire
_IDEMPOTENT_METHODS = frozenset({"GET", "PUT", "PATCH", "DELETE"})

# status-aware retry policy (the reference's workqueue re-enqueues every
# failed sync with rate-limited backoff, node.go:35-36,68; here the
# write worker itself absorbs the transient-status classes the apiserver
# documents as retryable, so callers only see durable failures):
# 429 = explicitly not processed — safe for every method, POSTs included;
# 5xx = ambiguous (the request MAY have been applied behind a dying
# proxy) — retried only for idempotent merge-patches, never for binds.
_RETRYABLE_ANY = frozenset({429})
_RETRYABLE_IDEMPOTENT = frozenset({500, 502, 503, 504})
# batches at least this large ride the native C++ flush engine (when
# built and the scheme is plain http): below it, thread spawn + connect
# overhead beats the GIL savings
_NATIVE_FLUSH_MIN = 128
# batches at least this large ride the PYTHON pipelined multi-connection
# flush when the native engine is unavailable (https, or no .so): the
# pipelining win needs enough requests per connection to amortize the
# fill/drain machinery over the per-request round-trips it removes
_PIPELINE_FLUSH_MIN = 64
_MAX_STATUS_RETRIES = 3
# retained response-body prefix: enough for an apiserver Status object's
# message, small enough to be free on the hot path. Also caps the
# per-retry sleep below stop()'s 2.0s worker join: worst case
# 3 x 0.5s keeps a throttled worker's FIFO (and the shutdown sentinel
# queued behind it) bounded instead of parking ~6s on Retry-After.
_BODY_SNIPPET_CAP = 512
_MAX_RETRY_SLEEP = 0.5


class WriteResult:
    """Outcome of one pooled write. Truthy on success so boolean callers
    are unchanged; carries the final HTTP status, a snippet of the
    failure body (a 409 bind conflict is now distinguishable from a 422
    validation error or a transport failure), and the retry count."""

    __slots__ = ("ok", "status", "error", "retries")

    def __init__(self, ok: bool, status: int = 0, error: str = "",
                 retries: int = 0):
        self.ok = ok
        self.status = status
        self.error = error
        self.retries = retries

    def __bool__(self) -> bool:
        return self.ok

    def __repr__(self) -> str:
        if self.ok:
            return f"WriteResult(ok, {self.status})"
        return (f"WriteResult(failed, status={self.status}, "
                f"retries={self.retries}, error={self.error!r})")


class _RawResponse:
    """Pre-drained response for _RawHTTPConnection (module-level: the
    hot path must not pay __build_class__ per response)."""

    __slots__ = ("status", "will_close", "retry_after", "_body")

    def __init__(self, status: int, will_close: bool, retry_after, body: bytes):
        self.status = status
        self.will_close = will_close
        self.retry_after = retry_after
        self._body = body

    def read(self) -> bytes:
        return self._body  # already drained; bounded prefix


class _RawHTTPConnection:
    """Hand-rolled HTTP/1.1 keep-alive connection for the pooled write
    path. http.client routes every response's headers through
    email.feedparser (~100us of pure-Python work per response), which
    at annotation-storm rates makes the CLIENT the throughput cap; this
    builds each request in one ``sendall`` and parses responses with a
    minimal reader. Exposes the http.client subset ``_PooledWriter``
    uses (``request``/``getresponse``/``close``).

    With ``context`` the same framing runs over an ``ssl``-wrapped
    socket: after the one-time handshake, a TLS record wrap/unwrap is
    OpenSSL C code — orders cheaper than http.client's per-response
    Python parsing — so the production https path (the reference's
    client-go always talks TLS, options.go:91-136) inherits the same
    fast path as plain http instead of falling back to
    http.client.HTTPSConnection."""

    def __init__(self, host: str, port: int | None, timeout: float,
                 context: ssl.SSLContext | None = None):
        import socket

        self._sock = socket.create_connection(
            (host, port or (443 if context is not None else 80)),
            timeout=timeout,
        )
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        if context is not None:
            self._sock = context.wrap_socket(self._sock, server_hostname=host)
        self._rf = self._sock.makefile("rb")
        self._host_hdr = f"{host}:{port}" if port else host

    def send_raw(self, data: bytes) -> None:
        """Write pre-rendered request bytes (pipelined flush path)."""
        self._sock.sendall(data)

    def request(self, method: str, path: str, body=None, headers=None):
        data = body or b""
        lines = [
            f"{method} {path} HTTP/1.1",
            f"Host: {self._host_hdr}",
            f"Content-Length: {len(data)}",
        ]
        for k, v in (headers or {}).items():
            lines.append(f"{k}: {v}")
        self._sock.sendall(
            ("\r\n".join(lines) + "\r\n\r\n").encode("latin-1") + data
        )

    def getresponse(self):
        try:
            return self._getresponse()
        except (ValueError, IndexError) as exc:
            # malformed status line / header / chunk size: surface as an
            # HTTPException so _do's response-phase retry classification
            # applies (an idempotent PATCH gets its reconnect+retry)
            # instead of escaping to the worker's blanket except
            raise http.client.HTTPException(
                f"malformed response: {exc!r}"
            ) from exc

    def _getresponse(self):
        line = self._rf.readline(65537)
        if not line:
            raise http.client.BadStatusLine("connection closed")
        status = int(line.split(None, 2)[1])
        length = None
        chunked = False
        close = False
        retry_after = None
        while True:
            h = self._rf.readline(65537)
            if h in (b"\r\n", b"\n", b""):
                break
            k, _, v = h.partition(b":")
            k, v = k.strip().lower(), v.strip()
            if k == b"content-length":
                length = int(v)
            elif k == b"connection" and v.lower() == b"close":
                close = True
            elif k == b"transfer-encoding" and b"chunked" in v.lower():
                chunked = True
            elif k == b"retry-after":
                retry_after = v.decode("latin-1")
        # drain the body now so the connection is immediately reusable,
        # retaining a bounded prefix so failure statuses stay diagnosable
        kept: list[bytes] = []
        kept_len = 0

        def _keep(piece: bytes):
            nonlocal kept_len
            if kept_len < _BODY_SNIPPET_CAP and piece:
                kept.append(piece[: _BODY_SNIPPET_CAP - kept_len])
                kept_len += len(kept[-1])

        if chunked:
            while True:
                # chunk size may carry extensions ("1a;ext=1"): RFC 7230
                # says ignore them
                size_line = self._rf.readline(65537).partition(b";")[0]
                size = int(size_line.strip() or b"0", 16)
                if size == 0:
                    # trailer section: a server may emit trailer fields
                    # after the terminal chunk — consume lines until the
                    # blank line (or EOF), or the next keep-alive
                    # response on this connection parses as status 0
                    while True:
                        t = self._rf.readline(65537)
                        if t in (b"\r\n", b"\n", b""):
                            break
                    break
                _keep(self._rf.read(size))
                self._rf.readline(65537)  # chunk-trailing CRLF
        elif length is not None:
            _keep(self._rf.read(length))
        else:
            close = True  # read-to-EOF body: not reusable

        return _RawResponse(status, close, retry_after, b"".join(kept))

    def close(self):
        try:
            self._rf.close()
        finally:
            self._sock.close()


class _WatchStream:
    """Raw-socket streaming watch response with an incremental chunked
    de-framer. ``HTTPResponse.read1`` returns at most ONE chunk per call
    — and a watch line is one chunk on the real apiserver and the stub
    alike — so draining a storm through it paid two syscalls plus
    ~10us of http.client bookkeeping per EVENT (measured ~28k events/s
    ceiling). Here one ``recv`` pulls up to 64KB of raw stream and the
    de-framer hands back every payload byte it covers, so the syscall
    and parse cost amortize over the whole buffered backlog."""

    def __init__(self, host: str, port: int | None, path: str,
                 timeout: float, token: str | None = None,
                 context: ssl.SSLContext | None = None):
        import socket as _socket

        self._sock = _socket.create_connection(
            (host, port or (443 if context is not None else 80)),
            timeout=timeout,
        )
        self._sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
        if context is not None:
            self._sock = context.wrap_socket(self._sock, server_hostname=host)
        host_hdr = f"{host}:{port}" if port else host
        auth = f"Authorization: Bearer {token}\r\n" if token else ""
        # Connection: close — a watch stream is one-shot (urllib sent
        # the same); without it the server holds the drained socket
        # open for a next request and stream end is never observable
        self._sock.sendall(
            (f"GET {path} HTTP/1.1\r\nHost: {host_hdr}\r\n"
             f"Connection: close\r\n{auth}\r\n").encode("latin-1")
        )
        # response head: status line + headers (read through a small
        # line reader over recv; the body stays in OUR buffer)
        self._raw = bytearray()
        self._eof = False
        status_line = self._head_line()
        try:
            status = int(status_line.split(None, 2)[1])
        except (IndexError, ValueError) as exc:
            self.close()
            raise http.client.HTTPException(
                f"watch: malformed status line {status_line!r}"
            ) from exc
        self._chunked = False
        while True:
            h = self._head_line()
            if h in (b"\r\n", b"\n", b""):
                break
            k, _, v = h.partition(b":")
            if k.strip().lower() == b"transfer-encoding" \
                    and b"chunked" in v.strip().lower():
                self._chunked = True
        if status != 200:
            body = bytes(self._raw[:4096])
            self.close()
            raise urllib.error.HTTPError(
                path, status, body.decode("utf-8", "replace"), None, None
            )
        # de-chunker state
        self._chunk_left = 0  # payload bytes pending in current chunk
        self._skip = 0  # chunk-trailing CRLF bytes to discard
        self._in_trailers = False

    def _head_line(self) -> bytes:
        """One CRLF-terminated head line (blocking; head only)."""
        while True:
            idx = self._raw.find(b"\n")
            if idx >= 0:
                line = bytes(self._raw[: idx + 1])
                del self._raw[: idx + 1]
                return line
            d = self._sock.recv(1 << 16)
            if not d:
                return bytes(self._raw)
            self._raw += d

    def fileno(self) -> int:
        return self._sock.fileno()

    def _recv(self) -> bool:
        d = self._sock.recv(1 << 16)
        if not d:
            self._eof = True
            return False
        self._raw += d
        return True

    def _dechunk(self) -> bytes:
        """Consume as much of the raw buffer as the framing allows;
        returns the payload bytes covered (may be empty)."""
        raw = self._raw
        if not self._chunked:
            out = bytes(raw)
            raw.clear()
            return out
        out = bytearray()
        pos = 0
        n = len(raw)
        while pos < n:
            if self._chunk_left:
                take = min(self._chunk_left, n - pos)
                out += raw[pos:pos + take]
                pos += take
                self._chunk_left -= take
                if self._chunk_left:
                    break
                self._skip = 2
            if self._skip:
                take = min(self._skip, n - pos)
                pos += take
                self._skip -= take
                if self._skip:
                    break
            if self._in_trailers:
                # trailer lines until the blank line, then stream end
                ended = False
                while pos < n:
                    idx = raw.find(b"\n", pos)
                    if idx < 0:
                        n = pos  # retain the partial trailer line
                        break
                    line = raw[pos:idx]
                    pos = idx + 1
                    if line in (b"", b"\r"):
                        self._eof = True
                        ended = True
                        break
                if ended or pos >= n:
                    break
                continue
            idx = raw.find(b"\n", pos)
            if idx < 0:
                break  # partial chunk-size line: wait for more bytes
            size_str = bytes(raw[pos:idx]).partition(b";")[0].strip()
            pos = idx + 1
            if not size_str:
                continue
            try:
                size = int(size_str, 16)
            except ValueError:
                # mid-protocol garbage/EOF: end the stream cleanly,
                # like read1's IncompleteRead classification
                self._eof = True
                break
            if size == 0:
                self._in_trailers = True
            else:
                self._chunk_left = size
        del raw[:pos]
        return bytes(out)

    def read_some(self) -> bytes:
        """De-chunked payload after at most the necessary blocking
        ``recv``s (the socket timeout bounds each); b'' = stream end."""
        while True:
            out = self._dechunk()
            if out:
                return out
            if self._eof:
                return b""
            if not self._recv():
                return b""  # abrupt EOF: clean end (torn tail raises)

    def has_buffered(self) -> bool:
        return len(self._raw) > 0

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


class _PooledWriter(threading.Thread):
    """One write worker: a FIFO queue drained over a single persistent
    keep-alive connection.

    The pool routes every write for a given object key to the same
    worker, so writes to one node/pod stay FIFO-ordered process-wide
    while distinct objects patch/bind in parallel — the ordering
    contract the reference gets from client-go's workqueue (at most one
    item per key in flight: node.go:52-70) combined with its pooled
    HTTP/2 transport (``--concurrent-syncs`` workers,
    ref: controller.go:74-77, node.go:29-42). Connection reuse is the
    other half of the win: the round-3 write path paid TCP setup +
    teardown per PATCH through fresh urllib requests.
    """

    def __init__(
        self,
        base_url: str,
        token: str | None,
        context: ssl.SSLContext | None,
        timeout: float,
        retry_counter=None,
    ):
        super().__init__(daemon=True)
        # optional telemetry counter bumped per status-retry sleep
        self._retry_counter = retry_counter
        u = urlsplit(base_url)
        self._scheme = u.scheme
        self._host = u.hostname or "127.0.0.1"
        self._port = u.port
        self._token = token
        self._context = context
        self._timeout = timeout
        self._conn: http.client.HTTPConnection | None = None
        self.queue: queue.SimpleQueue = queue.SimpleQueue()
        # per-worker failure counts by HTTP status (0 = transport);
        # single-writer (this thread), aggregated lock-free by the
        # client's write_failures_by_status property
        self.status_failures: dict[int, int] = {}

    def _connect(self):
        if self._scheme == "https":
            # same raw framing over an ssl-wrapped socket (TCP_NODELAY
            # set before the wrap; every production HTTP client,
            # client-go included, disables Nagle on pooled connections)
            context = self._context
            if context is None:
                context = ssl.create_default_context()
            return _RawHTTPConnection(
                self._host, self._port, self._timeout, context=context
            )
        return _RawHTTPConnection(self._host, self._port, self._timeout)

    def run(self) -> None:
        while True:
            item = self.queue.get()
            if item is None:
                if self._conn is not None:
                    self._conn.close()
                return
            method, path, body, content_type, extra_headers, fut = item
            try:
                result = self._do(method, path, body, content_type,
                                  extra_headers)
            except Exception as exc:  # noqa: BLE001 — a worker must never die
                self._drop_conn()
                self.status_failures[0] = self.status_failures.get(0, 0) + 1
                result = WriteResult(False, 0, f"worker: {exc!r}")
            fut.set_result(result)

    def _drop_conn(self) -> None:
        if self._conn is not None:
            try:
                self._conn.close()
            except OSError:
                pass
            self._conn = None

    @staticmethod
    def _retry_delay(retry_after, backoff: float) -> float:
        """Honor a numeric Retry-After when present (capped so a
        misbehaving server can't park a worker), else the caller's
        exponential backoff."""
        if retry_after:
            try:
                return min(max(float(retry_after), 0.0), _MAX_RETRY_SLEEP)
            except ValueError:
                pass  # HTTP-date form: fall through to backoff
        return min(backoff, _MAX_RETRY_SLEEP)

    def _do(self, method: str, path: str, body, content_type: str,
            extra_headers: dict | None = None) -> WriteResult:
        if body is None:
            data = None
        elif isinstance(body, bytes):
            data = body  # pre-rendered payload (hot bind/patch paths)
        else:
            data = json.dumps(body).encode()
        headers = {}
        if data is not None:
            headers["Content-Type"] = content_type
        if self._token:
            headers["Authorization"] = f"Bearer {self._token}"
        if extra_headers:
            headers.update(extra_headers)  # e.g. traceparent (ISSUE 9)
        transport_retried = False
        status_retries = 0
        backoff = 0.05
        attempts = 0
        while True:
            attempts += 1
            if self._conn is None:
                self._conn = self._connect()
            try:
                self._conn.request(method, path, body=data, headers=headers)
            except (http.client.HTTPException, OSError) as exc:
                # send-phase failure: the server never saw a complete
                # request (the classic case is a keep-alive connection
                # the server idle-closed between our writes) — always
                # safe to reconnect and retry once, POSTs included
                self._drop_conn()
                if transport_retried:
                    self.status_failures[0] = (
                        self.status_failures.get(0, 0) + 1)
                    return WriteResult(
                        False, 0, f"send: {exc!r}", attempts - 1)
                transport_retried = True
                continue
            try:
                resp = self._conn.getresponse()
                payload = resp.read()  # drained; bounded snippet kept
            except (http.client.HTTPException, OSError) as exc:
                # response-phase failure: the request may have been
                # processed — retry only idempotent methods
                self._drop_conn()
                if transport_retried or method not in _IDEMPOTENT_METHODS:
                    self.status_failures[0] = (
                        self.status_failures.get(0, 0) + 1)
                    return WriteResult(
                        False, 0, f"recv: {exc!r}", attempts - 1)
                transport_retried = True
                continue
            if resp.will_close:
                self._drop_conn()
            # a full request/response cycle completed: the next attempt
            # (status retry) gets a fresh send-phase retry budget — a
            # Retry-After sleep routinely outlives the server's
            # keep-alive idle timeout, and that idle-close send failure
            # is always safe to retry
            transport_retried = False
            status = resp.status
            # only 2xx is success: kube API writes never legitimately
            # succeed via an unfollowed redirect — a 301/302 from an
            # ingress means the apiserver never applied the write
            if 200 <= status < 300:
                return WriteResult(True, status, "", attempts - 1)
            self.status_failures[status] = (
                self.status_failures.get(status, 0) + 1)
            retryable = status in _RETRYABLE_ANY or (
                status in _RETRYABLE_IDEMPOTENT
                and method in _IDEMPOTENT_METHODS
            )
            snippet = payload[:_BODY_SNIPPET_CAP].decode("utf-8", "replace")
            if not retryable or status_retries >= _MAX_STATUS_RETRIES:
                return WriteResult(False, status, snippet, attempts - 1)
            status_retries += 1
            if self._retry_counter is not None:
                self._retry_counter.inc()
            retry_after = getattr(resp, "retry_after", None)
            if retry_after is None and hasattr(resp, "getheader"):
                retry_after = resp.getheader("Retry-After")
            time.sleep(self._retry_delay(retry_after, backoff))
            backoff = min(backoff * 2, 1.0)


def nrt_from_json(obj: dict):
    """gocrane NodeResourceTopology CR -> topology model (ref: the
    gocrane/api CRD shape consumed at
    pkg/plugins/noderesourcetopology/plugin.go:31-71)."""
    from ..topology.types import (
        CraneManagerPolicy,
        NodeResourceTopology,
        Zone,
    )

    meta = obj.get("metadata", {})
    policy = obj.get("craneManagerPolicy", {}) or {}
    return NodeResourceTopology(
        name=meta.get("name", ""),
        crane_manager_policy=CraneManagerPolicy(
            cpu_manager_policy=policy.get("cpuManagerPolicy", ""),
            topology_manager_policy=policy.get("topologyManagerPolicy", ""),
        ),
        zones=tuple(Zone.from_wire(z) for z in obj.get("zones") or []),
    )


def event_from_json(obj: dict) -> Event:
    meta = obj.get("metadata", {})
    return Event(
        namespace=meta.get("namespace", "default"),
        name=meta.get("name", ""),
        type=obj.get("type", ""),
        reason=obj.get("reason", ""),
        message=obj.get("message", ""),
        count=int(obj.get("count") or 0),
        event_time=_parse_wall_time(obj.get("eventTime")),
        last_timestamp=_parse_wall_time(obj.get("lastTimestamp")),
    )


class _KubeBurstHandle:
    """Burst handle pairing the mirror's columnar burst with the rows
    whose creation POST the apiserver refused (never bound)."""

    __slots__ = ("burst", "failed")

    def __init__(self, burst, failed: set):
        self.burst = burst
        self.failed = failed


class KubeClusterClient:
    """Informer-backed cluster view + API write-through.

    Drop-in for ``ClusterState`` everywhere the framework reads or
    writes cluster data. ``start()`` performs the initial list + spawns
    watch threads; ``stop()`` tears them down. All read methods delegate
    to the internal mirror (including ``sched_version`` for the
    scheduler's snapshot cache and event subscription for the
    annotator), so consumers cannot tell it apart from the in-memory
    cluster — which is the point: SURVEY §1's "two processes communicate
    only through the Kubernetes API" contract, preserved.
    """

    @classmethod
    def from_flags(
        cls,
        master: str,
        token_file: str | None = None,
        concurrent_syncs: int = 4,
    ) -> "KubeClusterClient":
        """CLI/in-cluster construction: bearer token from ``token_file``
        or the mounted service-account token, and the in-cluster CA
        bundle when present (the apiserver's cert is signed by the
        cluster CA, not anything in the system trust store — without
        this, HTTPS in-cluster fails verification at the first list)."""
        import os

        token = None
        path = token_file or (
            SERVICE_ACCOUNT_TOKEN if os.path.exists(SERVICE_ACCOUNT_TOKEN) else None
        )
        if path:
            with open(path) as f:
                token = f.read().strip()
        context = None
        if os.path.exists(SERVICE_ACCOUNT_CA):
            context = ssl.create_default_context(cafile=SERVICE_ACCOUNT_CA)
        return cls(
            master, token=token, context=context,
            concurrent_syncs=concurrent_syncs,
        )

    def __init__(
        self,
        base_url: str,
        token: str | None = None,
        context: ssl.SSLContext | None = None,
        timeout: float = DEFAULT_TIMEOUT_SECONDS,
        seen_events_cap: int = 65536,
        list_page_limit: int = 500,
        concurrent_syncs: int = 4,
        pipeline_depth: int = 16,
        telemetry: Telemetry | None = None,
        read_breaker=None,
        write_breaker=None,
    ):
        self.base_url = base_url.rstrip("/")
        # ISSUE 13: default budget (ms) minted as crane-deadline-ms on
        # POSTs when no thread-local deadline is active (None = only
        # forward an inherited deadline, mint nothing)
        self.post_deadline_ms: float | None = None
        # ISSUE 8: per-fault-domain breakers. The read breaker sees one
        # outcome per LIST and per watch-stream iteration; the write
        # breaker one per pooled write. Both are OBSERVATIONAL on this
        # layer — the reflector loop keeps its own backoff and the write
        # path its indeterminate-response discipline — but their state
        # transitions drive /healthz and the degraded-mode interlocks,
        # and CLIs consult them before scheduling non-critical work.
        self.read_breaker = read_breaker
        self.write_breaker = write_breaker
        self._telemetry = (
            telemetry if telemetry is not None else active_telemetry()
        )
        # ISSUE 9: pod-lifecycle tracker — bind/evict POSTs carry the
        # pod's traceparent and the watch apply confirms placements
        self._lifecycle = getattr(self._telemetry, "lifecycle", None)
        self._m_flush_seconds = None
        self._m_status_retries = None
        self._m_native_failures = None
        self._m_pipeline_stalls = None
        self._m_pipeline_indeterminate = None
        self._m_pipeline_inflight = None
        self._m_list_decode_seconds = None
        self._m_watch_batch_pods = None
        self._m_watch_coalesced = None
        if self._telemetry is not None:
            reg = self._telemetry.registry
            self._m_flush_seconds = reg.histogram(
                "crane_kube_flush_seconds",
                "Write-through pool batch flush latency", ("kind",),
            )
            self._m_status_retries = reg.counter(
                "crane_kube_status_retries_total",
                "Pooled-writer retries on retryable HTTP statuses",
            )
            self._m_native_failures = reg.counter(
                "crane_kube_native_flush_failures_total",
                "Native flush-engine request failures", ("status",),
            )
            self._m_pipeline_stalls = reg.counter(
                "crane_kube_pipeline_stalls_total",
                "Full-depth response waits in the pipelined write path",
            )
            self._m_pipeline_indeterminate = reg.counter(
                "crane_kube_pipeline_indeterminate_total",
                "Pipelined non-idempotent requests whose outcome a "
                "transport failure made unknowable (never re-POSTed)",
            )
            self._m_pipeline_inflight = reg.gauge(
                "crane_kube_pipeline_inflight",
                "In-flight pipelined requests, by connection",
                ("conn",),
            )
            self._m_list_decode_seconds = reg.histogram(
                "crane_kube_list_decode_seconds",
                "Columnar LIST page decode latency", ("kind",),
            )
            self._m_watch_batch_pods = reg.histogram(
                "crane_kube_watch_apply_batch_pods",
                "Pod watch events applied per coalesced mirror "
                "transaction",
            )
            self._m_watch_coalesced = reg.counter(
                "crane_kube_watch_coalesced_total",
                "Watch apply batches that coalesced more than one "
                "buffered event into a single mirror transaction",
                ("kind",),
            )
        u = urlsplit(self.base_url)
        self._scheme = u.scheme
        self._host = u.hostname or "127.0.0.1"
        self._port = u.port
        self._token = token
        self._context = context
        self._timeout = timeout
        # native bulk flusher (GIL-free C++ fan-out for large batches;
        # plain-http only): built lazily, None-and-disabled on failure
        self._native_flusher = None
        self._native_flush_disabled = False
        self._native_status_failures: dict[int, int] = {}
        self._native_lock = threading.Lock()
        self._mirror = ClusterState()
        from ..topology.types import InMemoryNRTLister

        # NodeResourceTopology CRD mirror (ref: initTopologyInformer,
        # plugin.go:60-71); stays empty when the CRD isn't installed
        self.nrt_lister = InMemoryNRTLister()
        self._nrt_available = False
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self.watch_errors = 0
        self.relists = 0  # full LISTs triggered by watch (re)connects
        # read-path counters (cheap attributes so benches without
        # telemetry can still observe throughput and coalescing)
        self.watch_applied = 0  # non-bookmark events applied
        self.watch_batches = 0  # mirror transactions those rode in
        self.watch_coalesced = 0  # batches carrying >1 event
        # read-path knobs: _list_decode_disabled forces the round-6
        # per-object LIST path, _coalesce_disabled applies drained watch
        # events one transaction each (bench before/after comparisons;
        # not supported production knobs). _watch_timeout is the idle
        # watch read timeout (tests shrink it to exercise idle expiry).
        self._list_decode_disabled = False
        self._coalesce_disabled = False
        self._watch_timeout = WATCH_TIMEOUT_SECONDS
        # last relist's decoded node columns: (pages, mirror version,
        # merged columns) — consumable by the store's columnar refresh
        # while the mirror still holds exactly that state
        self._node_columns_cache = None
        # name -> resourceVersion as of the last relist: feeds the
        # decoder's rv-based instance reuse (an unchanged rv means an
        # unchanged object — the contract informers are built on).
        # Maintained by the node-relist path; any other node write
        # (watch apply, optimistic patch) invalidates its entries.
        self._node_rvs: dict[str, str] = {}
        # reflector state: last-seen resourceVersion per resource (set by
        # lists, advanced by watch deliveries incl. bookmarks); None =
        # must relist before watching (client-go's reflector contract,
        # which the reference gets from its informers — factory.go:16-33)
        self._rvs: dict[str, str | None] = {}
        self._list_page_limit = int(list_page_limit)
        # bounded identity memory so an event watch replaying a backlog
        # (e.g. after a 410 relist, where no rv continuation exists)
        # cannot double-count Scheduled events (hot values would inflate
        # otherwise); keyed on the apiserver resourceVersion when present
        self._seen_events: dict[tuple, None] = {}
        self._seen_events_cap = int(seen_events_cap)
        # rv watermark: a watch stream delivers events in resourceVersion
        # order, so any event at or below the highest rv already applied
        # is a replayed duplicate — exact dedup in O(1) memory, immune to
        # backlogs larger than the content-key cap. The API contract only
        # promises rvs are opaque, so the watermark is guarded: an rv
        # DECREASE on a live stream (outside the replay window that
        # follows a (re)connect or relist) is a monotonicity violation —
        # the server's integer rvs aren't etcd-ordered — and rv dedup is
        # permanently disabled in favor of the content-key map (which is
        # maintained in parallel the whole time, so the downgrade loses
        # no dedup continuity). Round-4 VERDICT item 6.
        self._event_rv_watermark = 0
        self._event_rv_trusted = True
        self._event_expect_replay = True  # initial list = a replay window
        self._seen_lock = threading.Lock()
        # bulk-bind echo suppression: pod_key -> node_name registered
        # BEFORE the binding POSTs go out. The stub/apiserver echoes the
        # bound pod on the watch within ~1 ms — often before
        # ``bind_pods`` reaches its own optimistic mirror apply — and
        # applying that echo as a change would bump pod_version a second
        # time per bind, tearing the scheduler's incremental fit-fold
        # discipline (each dispatch window would drop + rebuild the fit
        # column). An echo that matches the expected (key, node) IS the
        # optimistic apply, so it is confirmed (lifecycle) but not
        # re-applied. Entries are removed in the same bind_pods call.
        self._expected_binds: dict = {}
        self._expected_lock = threading.Lock()
        # crash-safe placement-intent journal (resilience.recovery):
        # when attached, every bind/eviction POST journals an intent
        # line BEFORE reaching the wire, an ack/nack/unresolved after,
        # and a tombstone when the watch confirms — the substrate
        # restart reconciliation replays. None = zero-cost.
        self._intent_journal = None
        # write pool: --concurrent-syncs keep-alive workers, spawned on
        # first write (read-only clients never pay the threads)
        self._write_workers = max(1, int(concurrent_syncs))
        # pipelined write path: max requests in flight per connection
        # (HTTP/1.1 pipelining with strict in-order response accounting).
        # _pipeline_disabled forces the round-5 serial engines (bench
        # before/after comparisons; not a supported production knob)
        self._pipeline_depth = max(1, int(pipeline_depth))
        self._pipeline_disabled = False
        self._pool: list[_PooledWriter] = []
        self._pool_closed = False
        self._pool_lock = threading.Lock()
        # workers that have been retired by stop() — their failure
        # counters still aggregate into write_failures_by_status
        self._retired_pool: list[_PooledWriter] = []

    # -- HTTP plumbing -----------------------------------------------------

    def _request(
        self,
        method: str,
        path: str,
        body=None,
        content_type="application/json",
        timeout: float | None = None,
    ):
        req = urllib.request.Request(
            self.base_url + path,
            method=method,
            data=None if body is None else json.dumps(body).encode(),
        )
        if body is not None:
            req.add_header("Content-Type", content_type)
        if self._token:
            req.add_header("Authorization", f"Bearer {self._token}")
        return urllib.request.urlopen(  # noqa: S310 — caller controls base_url
            req,
            timeout=self._timeout if timeout is None else timeout,
            context=self._context,
        )

    def _get_json(self, path: str) -> dict:
        with self._request("GET", path) as resp:
            return json.loads(resp.read())

    def _get_bytes(self, path: str) -> bytes:
        with self._request("GET", path) as resp:
            return resp.read()

    def _submit_write(
        self,
        key: str,
        method: str,
        path: str,
        body,
        content_type: str = "application/json",
        headers: dict | None = None,
    ) -> Future:
        """Route a write to the pool worker owning ``key``. All writes
        for one object land on one worker's FIFO queue, so per-object
        ordering is preserved no matter how many caller threads write
        concurrently; distinct objects spread across the pool."""
        fut: Future = Future()
        # worker selection AND enqueue happen under the pool lock so a
        # concurrent stop() can't swap the pool out from under us (a
        # lock-free read raced stop(): hash % 0, or an enqueue landing
        # AFTER the shutdown sentinel whose Future then never resolved
        # and blocked the caller's .result() forever)
        with self._pool_lock:
            if self._pool_closed:
                fut.set_result(WriteResult(False, 0, "client stopped"))
                return fut
            if not self._pool:
                workers = []
                for _ in range(self._write_workers):
                    w = _PooledWriter(
                        self.base_url, self._token, self._context,
                        self._timeout,
                        retry_counter=self._m_status_retries,
                    )
                    w.start()
                    workers.append(w)
                self._pool = workers
            worker = self._pool[hash(key) % len(self._pool)]
            if self.write_breaker is not None:
                fut.add_done_callback(self._record_write_outcome)
            worker.queue.put((method, path, body, content_type, headers, fut))
        return fut

    def _record_write_outcome(self, fut: Future) -> None:
        """Feed the kube-write breaker one outcome per pooled write."""
        try:
            result = fut.result()
        except Exception:
            self.write_breaker.record_failure()
            return
        if getattr(result, "ok", bool(result)):
            self.write_breaker.record_success()
        else:
            self.write_breaker.record_failure()

    def _write(
        self,
        key: str,
        method: str,
        path: str,
        body,
        content_type: str = "application/json",
        headers: dict | None = None,
    ) -> bool:
        return self._submit_write(
            key, method, path, body, content_type, headers
        ).result()

    # -- lifecycle ---------------------------------------------------------

    def _list_all(self, path: str) -> tuple[list[dict], str | None]:
        """Paginated LIST (``limit``/``continue``, like client-go's
        paginated initial lists): returns every item plus the list's
        resourceVersion — one bounded page per response instead of a
        single O(cluster) JSON decode."""
        items: list[dict] = []
        sep = "&" if "?" in path else "?"
        token = None
        rv = None
        breaker = self.read_breaker
        try:
            while True:
                url = f"{path}{sep}limit={self._list_page_limit}"
                if token:
                    url += f"&continue={token}"
                payload = self._get_json(url)
                items.extend(payload.get("items", []))
                meta = payload.get("metadata", {})
                rv = meta.get("resourceVersion", rv)
                token = meta.get("continue")
                if not token:
                    break
        except Exception:
            if breaker is not None:
                breaker.record_failure()
            raise
        if breaker is not None:
            breaker.record_success()
        return items, rv

    @staticmethod
    def _peek_continue(body: bytes):
        """The ``continue`` token from a page's HEAD, if trivially
        extractable (the list metadata precedes ``items`` on every real
        apiserver). Best-effort: None just means the prefetch waits for
        the decode; a hit is verified against the decoded page before
        its prefetch is used."""
        head = body[: body.find(b'"items"') if b'"items"' in body[:4096]
                    else 4096]
        import re

        m = re.search(rb'"continue"\s*:\s*"([^"\\]+)"', head)
        return m.group(1).decode("latin-1") if m else None

    def _list_pages(self, path: str, kind: int, known_rvs=None):
        """Paginated LIST decoded straight to columns: the body of each
        page goes through the streaming decoder (the CPython-API object
        builder, the ctypes columnar scanner, or the Python twin —
        ``native.listdecode``) instead of a monolithic ``json.loads``,
        so a 50k-node bootstrap never materializes the per-object dict
        trees it is about to throw away. The NEXT page prefetches on a
        helper thread while the current one decodes (its continue token
        rides the page head), overlapping wire time with decode time.
        Returns the decoded page list plus the list's resourceVersion."""
        from concurrent.futures import ThreadPoolExecutor

        from ..native.listdecode import decode_list_page

        pages = []
        sep = "&" if "?" in path else "?"
        rv = None
        m = self._m_list_decode_seconds
        kind_label = "nodes" if kind == 0 else "pods"

        def page_url(tok):
            url = f"{path}{sep}limit={self._list_page_limit}"
            if tok:
                url += f"&continue={tok}"
            return url

        pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="list-prefetch"
        )
        breaker = self.read_breaker
        try:
            body = self._get_bytes(page_url(None))
            while True:
                peeked = self._peek_continue(body)
                fut = (
                    pool.submit(self._get_bytes, page_url(peeked))
                    if peeked else None
                )
                t0 = time.perf_counter()
                page = decode_list_page(body, kind, known_rvs=known_rvs)
                if m is not None:
                    m.labels(kind=kind_label).observe(
                        time.perf_counter() - t0
                    )
                pages.append(page)
                if page.rv is not None:
                    rv = page.rv
                token = page.cont
                if not token:
                    if fut is not None:
                        fut.cancel()
                    if breaker is not None:
                        breaker.record_success()
                    return pages, rv
                if fut is not None and peeked == token:
                    body = fut.result()
                else:
                    if fut is not None:
                        fut.cancel()
                    body = self._get_bytes(page_url(token))
        except Exception:
            if breaker is not None:
                breaker.record_failure()
            raise
        finally:
            pool.shutdown(wait=False)

    def _relist_nodes(self) -> None:
        """Resync nodes into the mirror (informer relist): adds/updates
        everything listed and prunes what disappeared, so deltas missed
        during a watch disconnect cannot linger (a dead node kept
        schedulable is the failure this prevents). Only the NODE watch
        thread calls this while ITS stream is down, so no concurrent
        node delivery can race the prune; other resources are never
        touched from here.

        Since round 7 the pages stream through the columnar LIST
        decoder and land as ONE mirror transaction
        (``ClusterState.replace_nodes``: one lock, one version bump);
        the decoded annotation columns stay cached for the batch
        scheduler's columnar store refresh
        (``node_annotation_columns``)."""
        self.relists += 1
        if self._list_decode_disabled:
            # round-6 comparison path: monolithic json.loads +
            # per-object mirror apply
            raw, rv = self._list_all("/api/v1/nodes")
            nodes = [node_from_json(i) for i in raw]
            for node in nodes:
                self._mirror.add_node(node)
            live = {n.name for n in nodes}
            for name in [n.name for n in self._mirror.list_nodes()]:
                if name not in live:
                    self._mirror.delete_node(name)
            self._node_columns_cache = None
            self._node_rvs = {}
            self._rvs["nodes"] = rv
            return
        known = self._node_rvs
        pages, rv = self._list_pages(
            "/api/v1/nodes", 0, known_rvs=known or None
        )
        new_rvs: dict[str, str] = {}
        nodes = []
        mirror_get = self._mirror.get_node
        for page in pages:
            objs = page.materialize()
            page_rvs = getattr(page, "rvs", None)
            for i, obj in enumerate(objs):
                if isinstance(obj, str):
                    # rv-reuse marker: the server's rv matched the last
                    # relist's — keep the existing mirror instance
                    node = mirror_get(obj)
                    if node is None:
                        # the mirror lost it since the map was built
                        # (concurrent delete): rebuild from the span
                        for row, a, b in page._reused:
                            if row == i:
                                node = node_from_json(
                                    json.loads(page._body[a:b])
                                )
                                break
                        if node is None:  # pragma: no cover - paranoia
                            continue
                    else:
                        new_rvs[obj] = known[obj]
                    objs[i] = node
                    obj = node
                elif page_rvs is not None and page_rvs[i] is not None:
                    new_rvs[obj.name] = page_rvs[i]
                nodes.append(obj)
        self._mirror.replace_nodes(nodes)
        self._node_rvs = new_rvs
        # bounded-map invariant: new_rvs is built from listed (live)
        # pages only, but a concurrent watch delete can land between
        # the list and here — prune against the reconciled mirror
        self.prune_node_rvs()
        # keyed on node_version (NOT sched_version): pod/event churn
        # must not invalidate node columns that didn't change
        self._node_columns_cache = (pages, self._mirror.node_version, None)
        self._rvs["nodes"] = rv

    def node_annotation_columns(self):
        """The last relist's decoded annotation columns — ``(version,
        names, keys, values, offsets)`` with row ``i`` owning
        ``keys[offsets[i]:offsets[i+1]]`` — valid only while the mirror
        still holds exactly that state (any watch delivery or write
        invalidates it). ``BatchScheduler.refresh`` consumes this to
        feed ``NodeLoadStore.ingest_annotation_columns`` directly,
        skipping the Node-object round-trip after a bootstrap/relist;
        returns None whenever the mirror has moved on (callers fall
        back to ``list_nodes``)."""
        cache = self._node_columns_cache
        if cache is None:
            return None
        pages, version, merged = cache
        if version != self._mirror.node_version:
            self._node_columns_cache = None
            return None
        if merged is None:
            import numpy as _np

            names: list[str] = []
            keys: list = []
            values: list = []
            offset_parts = [_np.zeros(1, dtype=_np.int64)]
            total = 0
            for page in pages:
                pn, pk, pv, po = page.node_annotation_columns()
                names.extend(pn)
                keys.extend(pk)
                values.extend(pv)
                offset_parts.append(po[1:] + total)
                total += int(po[-1]) if len(po) > 1 else 0
            offsets = _np.concatenate(offset_parts)
            merged = (names, keys, values, offsets)
            self._node_columns_cache = (pages, version, merged)
        return (version,) + merged

    def _relist_pods(self) -> None:
        """Pod twin of ``_relist_nodes`` (called only by the pod watch
        thread while its own stream is down)."""
        self.relists += 1
        if self._list_decode_disabled:
            raw, rv = self._list_all("/api/v1/pods")
            pods = [pod_from_json(i) for i in raw]
            for pod in pods:
                self._mirror.add_pod(pod)
            live = {p.key() for p in pods}
            for key in [p.key() for p in self._mirror.list_pods()]:
                if key not in live:
                    self._mirror.delete_pod(key)
            self._rvs["pods"] = rv
            return
        pages, rv = self._list_pages("/api/v1/pods", 1)
        pods = [p for page in pages for p in page.materialize()]
        self._mirror.replace_pods(pods)
        self._rvs["pods"] = rv

    def _relist_events(self) -> None:
        """Event twin: the reference's event informer also list+watches
        (factory.go:25-33), so Scheduled events emitted while the watch
        was down (or before start) are recovered by a list instead of
        silently undercounting hot values. Entries sorted by rv before
        ingestion — the dedup watermark assumes monotonic delivery, and a
        list's iteration order is not rv order."""
        self.relists += 1
        raw, rv = self._list_all(
            "/api/v1/events?fieldSelector=reason%3DScheduled%2Ctype%3DNormal"
        )

        def rv_of(obj) -> int:
            try:
                return int(obj.get("metadata", {}).get("resourceVersion", 0))
            except (TypeError, ValueError):
                return 0

        self._mark_event_stream_restart()  # the list IS a replay
        for obj in sorted(raw, key=rv_of):
            self._apply_event("ADDED", obj)
        self._rvs["events"] = rv

    def _relist_nrt(self) -> None:
        """NRT CRD twin of ``_relist_nodes`` (NRT watch thread only)."""
        self.relists += 1
        raw, rv = self._list_all(NRT_API_PATH)
        items = [nrt_from_json(i) for i in raw]
        for nrt in items:
            self.nrt_lister.upsert(nrt)
        live = {nrt.name for nrt in items}
        for name in [n for n in self.nrt_lister.names() if n not in live]:
            self.nrt_lister.delete(name)
        self._rvs["nrts"] = rv

    def start(self) -> None:
        """Initial list of nodes + pods (+ NRT CRs when the CRD is
        installed), then watch threads for each resource plus Scheduled
        events (server-side filtered; its list+watch recovers events
        missed while disconnected, like the reference's event informer —
        factory.go:25-33)."""
        self._relist_nodes()
        self._relist_pods()
        watches = [
            (
                "/api/v1/nodes?watch=1",
                self._apply_node_batch,
                self._relist_nodes,
                "nodes",
            ),
            (
                "/api/v1/pods?watch=1",
                self._apply_pod_batch,
                self._relist_pods,
                "pods",
            ),
            (
                "/api/v1/events?watch=1&fieldSelector="
                "reason%3DScheduled%2Ctype%3DNormal",
                self._apply_event_batch,
                self._relist_events,
                "events",
            ),
        ]
        crd_absent = False
        try:
            self._relist_nrt()
            self._nrt_available = True
        except urllib.error.HTTPError as e:
            if e.code == 404:
                # CRD not installed (normal for Dynamic-only clusters):
                # don't 404-loop a watch; a prober re-checks so a CRD
                # applied later still gets mirrored without a restart
                crd_absent = True
            else:
                self.watch_errors += 1  # transient 5xx / RBAC gap
        except (urllib.error.URLError, OSError):
            self.watch_errors += 1  # network blip: the watch loop retries
        if crd_absent:
            t = threading.Thread(target=self._nrt_prober, daemon=True)
        else:
            watches.append(
                (
                    f"{NRT_API_PATH}?watch=1",
                    self._apply_nrt_batch,
                    self._relist_nrt,
                    "nrts",
                )
            )
            t = None
        for path, apply, relist, rv_key in watches:
            wt = threading.Thread(
                target=self._watch_loop,
                args=(path, apply, relist, rv_key),
                daemon=True,
            )
            wt.start()
            self._threads.append(wt)
        if t is not None:
            t.start()
            self._threads.append(t)

    def _nrt_prober(self) -> None:
        """Waits for the NRT CRD to appear (installed after this process
        started), then becomes the NRT watch thread."""
        while not self._stop.wait(timeout=NRT_RETRY_SECONDS):
            try:
                self._relist_nrt()
            except urllib.error.HTTPError as e:
                if e.code == 404:
                    continue  # still absent
                self.watch_errors += 1
                continue
            except (urllib.error.URLError, OSError):
                self.watch_errors += 1
                continue
            self._nrt_available = True
            self._watch_loop(
                f"{NRT_API_PATH}?watch=1",
                self._apply_nrt_batch,
                self._relist_nrt,
                "nrts",
            )
            return

    def stop(self) -> None:
        self._stop.set()
        # watch threads are daemons blocked in reads up to the watch
        # timeout; a short join reaps the responsive ones without
        # stalling shutdown on the rest
        for t in self._threads:
            t.join(timeout=0.2)
        self._threads.clear()
        with self._pool_lock:
            self._pool_closed = True
            pool, self._pool = self._pool, []
            self._retired_pool.extend(pool)
        for w in pool:
            w.queue.put(None)  # drains queued writes first (FIFO)
        for w in pool:
            w.join(timeout=2.0)

    # -- native bulk flush -------------------------------------------------

    def _get_native_flusher(self):
        """The C++ bulk flush engine (native/crane_native.cpp
        crane_http_flush), or None when the scheme is https, the batch
        machinery failed to build, or the library is unavailable. The
        Python pool stays the slow path and the owner of status-based
        retry semantics."""
        if self._native_flush_disabled or self._scheme != "http":
            return None
        with self._native_lock:
            if self._native_flusher is None and not self._native_flush_disabled:
                try:
                    from ..native.httpflush import NativeHTTPFlusher

                    # connection count honors --concurrent-syncs (the
                    # operator's parallelism contract). The round-5
                    # max(workers, 8) floor oversubscribed small
                    # apiservers: against a single-core server, 8
                    # concurrently-busy connections convoy its handler
                    # threads into a ~6x throughput collapse (measured on
                    # the wire stub), while the pipeline depth below
                    # keeps each connection saturated without adding
                    # server-side concurrency.
                    self._native_flusher = NativeHTTPFlusher(
                        self._host, self._port or 80,
                        workers=self._write_workers,
                        timeout=self._timeout,
                        pipeline_depth=self._pipeline_depth,
                    )
                except (RuntimeError, OSError):
                    self._native_flush_disabled = True
            return self._native_flusher

    def _render_request(self, method: str, path: str, body,
                        content_type: str = "application/json",
                        extra_headers: dict | None = None) -> bytes:
        data = body if isinstance(body, bytes) else json.dumps(body).encode()
        host = f"{self._host}:{self._port}" if self._port else self._host
        auth = f"Authorization: Bearer {self._token}\r\n" if self._token else ""
        extra = ""
        if extra_headers:
            extra = "".join(f"{k}: {v}\r\n" for k, v in extra_headers.items())
        return (
            f"{method} {path} HTTP/1.1\r\nHost: {host}\r\n"
            f"Content-Length: {len(data)}\r\n"
            f"Content-Type: {content_type}\r\n{auth}{extra}\r\n"
        ).encode("latin-1") + data

    @staticmethod
    def _json_name(name: str) -> str:
        """K8s object names are DNS labels, so embedding them in a JSON
        template without escaping is exact; anything else (tests,
        adversarial input) falls back to the real encoder."""
        if '"' in name or "\\" in name or any(ord(c) < 0x20 for c in name):
            return json.dumps(name)[1:-1]
        return name

    def _render_binding_body(self, namespace: str, name: str,
                             node_name: str) -> bytes:
        """The binding subresource body, rendered from a literal
        template: at bind-burst rates ``json.dumps`` per pod is a
        measurable share of the one host core the stub benchmarks pin.
        Byte-compatible JSON (the apiserver parses it; nothing diffs the
        exact encoder output)."""
        return (
            '{"metadata": {"name": "%s", "namespace": "%s"}, '
            '"target": {"kind": "Node", "name": "%s"}}'
            % (self._json_name(name), self._json_name(namespace),
               self._json_name(node_name))
        ).encode()

    def _note_pipeline_stats(self, flusher) -> None:
        """Fold the engine's cumulative pipelined counters into the
        telemetry registry (delta since the last fold)."""
        if self._m_pipeline_stalls is None or flusher is None:
            return
        stats = getattr(flusher, "last_stats", None)
        if stats is None:
            return
        last = getattr(flusher, "_telemetry_folded", None)
        if last is None:
            last = flusher._telemetry_folded = {
                "stalls": 0, "indeterminate": 0}
        d = stats["stalls"] - last["stalls"]
        if d > 0:
            self._m_pipeline_stalls.inc(d)
            last["stalls"] = stats["stalls"]
        d = stats["indeterminate"] - last["indeterminate"]
        if d > 0:
            self._m_pipeline_indeterminate.inc(d)
            last["indeterminate"] = stats["indeterminate"]

    # -- Python pipelined multi-connection flush ---------------------------

    def _connect_raw(self) -> _RawHTTPConnection:
        if self._scheme == "https":
            context = self._context
            if context is None:
                context = ssl.create_default_context()
            return _RawHTTPConnection(
                self._host, self._port, self._timeout, context=context
            )
        return _RawHTTPConnection(self._host, self._port, self._timeout)

    def _pipelined_flush(self, rendered: list[bytes],
                         idempotent: bool) -> list[int]:
        """Pipelined fan-out in pure Python (the https / no-.so twin of
        the native engine): the batch stripes across up to
        ``concurrent_syncs`` keep-alive connections, each connection
        keeps up to ``pipeline_depth`` requests in flight, and responses
        are accounted strictly in request order.

        POST-safety contract (shared with the native engine): a
        response-phase transport failure marks the awaited request and
        everything already sent behind it on that connection
        INDETERMINATE — non-idempotent requests (binds) are never
        re-POSTed (status 0); idempotent merge-patches retry once on a
        fresh connection. A send-phase failure only ever reroutes
        requests the server cannot have parsed completely (each request
        is its own ``sendall``, so the failed one was at most partially
        written). Returns per-request statuses (0 = transport failure /
        indeterminate); status-based retry stays with the caller."""
        n = len(rendered)
        statuses = [0] * n
        conns = max(1, min(self._write_workers, n))
        bounds = [n * w // conns for w in range(conns + 1)]
        stall_total = [0] * conns
        indet_total = [0] * conns
        threads = []
        for w in range(conns):
            t = threading.Thread(
                target=self._pipelined_conn_worker,
                args=(w, rendered, range(bounds[w], bounds[w + 1]),
                      statuses, idempotent, stall_total, indet_total),
                daemon=True,
            )
            t.start()
            threads.append(t)
        for t in threads:
            t.join()
        if self._m_pipeline_stalls is not None:
            stalls = sum(stall_total)
            if stalls:
                self._m_pipeline_stalls.inc(stalls)
            indet = sum(indet_total)
            if indet:
                self._m_pipeline_indeterminate.inc(indet)
        return statuses

    def _pipelined_conn_worker(self, conn_id: int, rendered, indices,
                               statuses, idempotent: bool,
                               stall_total, indet_total) -> None:
        from collections import deque

        gauge = None
        if self._m_pipeline_inflight is not None:
            gauge = self._m_pipeline_inflight.labels(conn=str(conn_id))
        depth = self._pipeline_depth
        local: deque = deque()  # (idx, attempt) retries, served first
        todo = iter(indices)
        inflight: deque = deque()
        conn = None

        def claim():
            if local:
                return local.popleft()
            nxt = next(todo, None)
            return None if nxt is None else (nxt, 0)

        def drop_conn():
            nonlocal conn
            if conn is not None:
                try:
                    conn.close()
                except OSError:
                    pass
                conn = None

        def fail_inflight():
            # transport failure: everything in flight is indeterminate
            while inflight:
                idx, attempt = inflight.popleft()
                if idempotent and attempt < 1:
                    local.append((idx, attempt + 1))
                else:
                    statuses[idx] = 0
                    if not idempotent:
                        indet_total[conn_id] += 1

        try:
            while True:
                # fill phase: pipeline up to depth requests
                batch = []
                while len(inflight) + len(batch) < depth:
                    item = claim()
                    if item is None:
                        break
                    batch.append(item)
                if batch and conn is None:
                    try:
                        conn = self._connect_raw()
                    except OSError:
                        for idx, _ in batch:
                            statuses[idx] = 0
                        if not inflight and not local:
                            return
                        continue
                send_failed = False
                for item in batch:
                    try:
                        conn.send_raw(rendered[item[0]])
                    except (OSError, http.client.HTTPException):
                        # the failed request was at most partially
                        # written (its own sendall) — the server cannot
                        # have parsed it: always safe to reroute, like
                        # everything after it that was never sent
                        drop_conn()
                        at = item[1]
                        for b in [item] + batch[batch.index(item) + 1:]:
                            if b[1] < 1:
                                local.append((b[0], b[1] + 1))
                            else:
                                statuses[b[0]] = 0
                        fail_inflight()
                        send_failed = True
                        break
                    inflight.append(item)
                if send_failed:
                    continue
                if not inflight:
                    if not local:
                        return
                    continue
                if gauge is not None:
                    gauge.set(len(inflight))
                # drain phase: responses strictly in request order
                if len(inflight) >= depth:
                    stall_total[conn_id] += 1
                while inflight:
                    try:
                        resp = conn.getresponse()
                    except (OSError, http.client.HTTPException):
                        drop_conn()
                        fail_inflight()
                        break
                    idx, _ = inflight.popleft()
                    statuses[idx] = resp.status
                    if resp.will_close:
                        # server ends the connection: responses behind
                        # this one will never arrive
                        drop_conn()
                        fail_inflight()
                        break
                if gauge is not None:
                    gauge.set(0)
        finally:
            drop_conn()

    def _count_native_failure(self, status: int) -> None:
        with self._native_lock:
            self._native_status_failures[status] = (
                self._native_status_failures.get(status, 0) + 1)
        if self._m_native_failures is not None:
            self._m_native_failures.labels(status=str(status)).inc()

    @property
    def write_failures_by_status(self) -> dict[int, int]:
        """Aggregate failed-write counts by HTTP status across the pool
        (0 = transport-level failure). Observability the reference leaves
        to client-go logs; a 409 bind conflict is countable separately
        from a 5xx or a dead connection."""
        with self._pool_lock:
            workers = list(self._pool) + list(self._retired_pool)
        out: dict[int, int] = {}
        for w in workers:
            # snapshot before iterating: the worker thread may insert a
            # first-seen status key mid-iteration (dict(d) is a single
            # C-level copy, safe against concurrent inserts)
            for status, n in dict(w.status_failures).items():
                out[status] = out.get(status, 0) + n
        with self._native_lock:
            for status, n in self._native_status_failures.items():
                out[status] = out.get(status, 0) + n
        return out

    def pending_writes(self) -> int:
        """Writes enqueued on the pooled workers but not yet sent — the
        bind-plane depth signal for overload backpressure (ISSUE 13):
        ``Scheduler.bind_backpressure`` can pause dispatch windows while
        this sits above a watermark instead of letting an admission
        storm grow the write queues without bound."""
        with self._pool_lock:
            workers = list(self._pool)
        return sum(w.queue.qsize() for w in workers)

    @staticmethod
    def _reconnect_immediately(delivered: bool, failures: int,
                               lived: float, idle_expired: bool) -> bool:
        """Zero-delay reconnect policy: a healthy LONG-LIVED stream
        (delivered something, incl. bookmarks, and stayed up a while)
        reconnects immediately — an rv-resumed reconnect is cheap and
        waiting delays the next delta. A stream that expired IDLE
        (read timeout, nothing to say) is long-lived by construction —
        it held the socket the whole watch timeout — so it reconnects
        immediately too (it used to eat one backoff sleep, delaying the
        next real event by up to 1s on a quiet cluster). Short-lived
        streams back off exponentially even when they delivered (a
        server answering each watch with one bookmark then EOF must not
        drive a zero-delay reconnect hot loop), as does anything that
        failed."""
        return failures == 0 and (
            idle_expired or (delivered and lived >= 1.0)
        )

    def _drain_lines(self, stream: "_WatchStream", tail: bytes):
        """Read everything the stream already has — one blocking
        ``read_some``, then keep pulling while the stream holds
        undrained raw bytes or a zero-timeout ``select`` says more are
        on the wire — and split out the complete lines. The drain never
        waits for data that has not arrived, so a quiet stream applies
        immediately and a storm's whole buffered backlog lands in one
        batch. A line torn across chunks stays in ``tail`` until its
        terminator arrives. Returns (complete_lines, tail, eof)."""
        import select

        chunk = stream.read_some()
        if not chunk:
            return [], tail, True
        # chunks accumulate in a LIST and join once: += on bytes is
        # quadratic, and a sustained storm feeds thousands of chunks
        # into one drain
        parts = [tail, chunk]
        size = len(tail) + len(chunk)
        fd = stream.fileno()
        while size < (1 << 20):  # bound one transaction
            if not stream.has_buffered():
                try:
                    if not select.select([fd], [], [], 0)[0]:
                        break
                except (OSError, ValueError):
                    break
            chunk = stream.read_some()
            if not chunk:
                break  # EOF: deliver the drained lines, report it next call
            parts.append(chunk)
            size += len(chunk)
        buf = b"".join(parts)
        if b"\n" not in buf:
            return [], buf, False
        *lines, tail = buf.split(b"\n")
        return lines, tail, False

    def _open_watch_stream(self, path: str) -> "_WatchStream":
        context = None
        if self._scheme == "https":
            context = self._context
            if context is None:
                context = ssl.create_default_context()
        return _WatchStream(
            self._host, self._port, path, self._watch_timeout,
            token=self._token, context=context,
        )

    def _consume_watch_r06(self, url: str, apply_batch, rv_key: str):
        """The round-6 per-line stream loop, kept verbatim behind the
        ``_coalesce_disabled`` comparison knob: urllib response
        iteration, one apply transaction and rv update per line.
        Returns (delivered, failed, stopped)."""
        delivered = False
        failed = False
        with self._request("GET", url, timeout=self._watch_timeout) as resp:
            for line in resp:
                if self._stop.is_set():
                    return delivered, failed, True
                line = line.strip()
                if not line:
                    continue
                change = json.loads(line)
                change_type = change.get("type", "")
                obj = change.get("object", {})
                if change_type == "ERROR":
                    if obj.get("code") == 410:
                        self._rvs[rv_key] = None
                    else:
                        self.watch_errors += 1
                        failed = True
                    break
                obj_rv = obj.get("metadata", {}).get("resourceVersion")
                if change_type != "BOOKMARK":
                    apply_batch([(change_type, obj)])
                    self.watch_applied += 1
                    self.watch_batches += 1
                if obj_rv is not None:
                    self._rvs[rv_key] = obj_rv
                delivered = True
        return delivered, failed, False

    def _consume_watch(self, url: str, apply_batch, rv_key: str):
        """Coalesced stream consumption (round 7): each wakeup drains
        every line the socket already buffered and applies them as one
        mirror transaction — one lock, one version bump, one batched
        subscriber notify — in delivery order, so a watch storm costs
        the mirror O(wakeups) transactions instead of O(events). rv
        bookkeeping advances after the batch lands, exactly as far as
        the batch did; an ERROR event splits the batch (everything
        before it applies first), preserving the per-line 410/backoff
        semantics. Returns (delivered, failed, stopped)."""
        delivered = False
        failed = False
        stream = self._open_watch_stream(url)
        try:
            tail = b""
            while True:
                if self._stop.is_set():
                    return delivered, failed, True
                lines, tail, eof = self._drain_lines(stream, tail)
                if eof:
                    if tail.strip():
                        # connection cut mid-line: surface the same
                        # JSONDecodeError the per-line iterator hit on
                        # a torn final line
                        json.loads(tail)
                    return delivered, failed, False
                batch, last_rv, error_obj, n_seen, model = (
                    self._parse_watch_lines(lines, rv_key)
                )
                if n_seen:
                    delivered = True
                if batch:
                    if model:
                        self._apply_model_batch(rv_key, batch)
                    else:
                        apply_batch(batch)
                    self.watch_batches += 1
                    self.watch_applied += len(batch)
                    if len(batch) > 1:
                        self.watch_coalesced += 1
                        if self._m_watch_coalesced is not None:
                            self._m_watch_coalesced.labels(kind=rv_key).inc()
                if last_rv is not None:
                    self._rvs[rv_key] = last_rv
                if error_obj is not None:
                    if error_obj.get("code") == 410:
                        # resume window expired: relist once
                        self._rvs[rv_key] = None
                    else:
                        self.watch_errors += 1
                        failed = True
                    return delivered, failed, False
        finally:
            stream.close()

    def _watch_loop(
        self,
        path: str,
        apply_batch: Callable[[list], None],
        relist: Callable[[], None] | None,
        rv_key: str,
    ) -> None:
        """Reflector semantics (client-go's contract, which the reference
        inherits from its informers — ref: factory.go:16-33): list once,
        then watch from the list's resourceVersion with bookmarks;
        reconnects resume from the last delivered rv (no relist); only a
        410 Gone (resume point expired server-side) forces one relist.
        Stream consumption is COALESCED since round 7 (_consume_watch);
        the round-6 per-line path survives behind _coalesce_disabled
        for benchmark comparison."""
        import time as _time

        failures = 0
        delivered = False  # anything (incl. bookmarks) on the last stream
        breaker = self.read_breaker
        while not self._stop.is_set():
            delivered = False
            idle_expired = False
            failures_before = failures
            connected_at = _time.monotonic()
            try:
                if relist is not None and self._rvs.get(rv_key) is None:
                    # first connect or post-410: one full (paginated)
                    # list establishes the resume point; everything after
                    # it arrives through the watch replay
                    relist()
                rv = self._rvs.get(rv_key)
                url = path + "&allowWatchBookmarks=true"
                if rv is not None:
                    url += f"&resourceVersion={rv}"
                consume = (
                    self._consume_watch_r06 if self._coalesce_disabled
                    else self._consume_watch
                )
                delivered, failed, stopped = consume(url, apply_batch, rv_key)
                if stopped:
                    return
                if delivered:
                    # reset only on DELIVERED events, not on mere
                    # connection establishment: a flapping apiserver
                    # that accepts watches then fails the stream must
                    # still escalate the backoff
                    failures = 0
                if failed:
                    failures += 1
            except TimeoutError:
                # normal idle-watch expiry on a quiet cluster (the read
                # blocked the whole watch timeout with nothing to say) —
                # NOT a failure, and the stream was healthy: reconnect
                # immediately (see _reconnect_immediately)
                idle_expired = True
            except urllib.error.HTTPError as e:
                if e.code == 410:
                    self._rvs[rv_key] = None  # relist on reconnect
                else:
                    self.watch_errors += 1
                    failures += 1
            except (urllib.error.URLError, OSError, json.JSONDecodeError):
                self.watch_errors += 1
                failures += 1
            if breaker is not None:
                # one breaker outcome per stream iteration: a failed
                # stream counts against the kube-read fault domain, a
                # healthy one (delivered, or clean idle expiry) clears it
                if failures > failures_before:
                    breaker.record_failure()
                elif delivered or idle_expired:
                    breaker.record_success()
            lived = _time.monotonic() - connected_at
            if self._reconnect_immediately(
                delivered, failures, lived, idle_expired
            ):
                continue
            if self._stop.wait(timeout=min(30.0, 1.0 * (2 ** min(failures, 5)))):
                return

    _WATCH_KINDS = {"nodes": 0, "pods": 1}

    def _parse_watch_lines(self, lines: list, rv_key: str):
        """Parse one drained batch of watch lines. Node/pod streams
        parse in ONE CPython-API call when the decoder is available
        (``decode_watch_lines``: final model objects, no per-line
        json.loads); everything else — events, NRTs, fallback lines,
        no-decoder hosts — takes the per-line JSON path with identical
        semantics. Returns ``(batch, last_rv, error_obj, n_seen,
        model)`` where ``model=True`` means batch entries carry built
        Node/Pod objects (apply via _apply_model_batch) and False means
        raw dicts (apply via the kind's batch applier)."""
        kind = self._WATCH_KINDS.get(rv_key)
        if kind is not None:
            from ..native.listdecode import decode_watch_lines

            joined = b"\n".join(lines)
            res = decode_watch_lines(joined, kind)
            if res is not None:
                from_json = node_from_json if kind == 0 else pod_from_json
                types, objects, rvs, fallbacks = res
                fb_spans = {row: (a, b) for row, a, b in fallbacks}
                batch = []
                last_rv = None
                error_obj = None
                n_seen = 0
                for i, change_type in enumerate(types):
                    n_seen += 1
                    if i in fb_spans:
                        a, b = fb_spans[i]
                        change = json.loads(joined[a:b])
                        change_type = change.get("type", "")
                        obj = change.get("object", {})
                        if change_type == "ERROR":
                            error_obj = obj
                            break
                        if change_type != "BOOKMARK":
                            batch.append((change_type, from_json(obj)))
                        obj_rv = obj.get("metadata", {}).get(
                            "resourceVersion"
                        )
                        if obj_rv is not None:
                            last_rv = obj_rv
                        continue
                    if objects[i] is not None:
                        batch.append((change_type, objects[i]))
                    if rvs[i] is not None:
                        last_rv = rvs[i]
                return batch, last_rv, error_obj, n_seen, True
        batch = []
        last_rv = None
        error_obj = None
        n_seen = 0
        for line in lines:
            line = line.strip()
            if not line:
                continue
            n_seen += 1
            change = json.loads(line)
            change_type = change.get("type", "")
            obj = change.get("object", {})
            if change_type == "ERROR":
                error_obj = obj
                break
            if change_type != "BOOKMARK":
                batch.append((change_type, obj))
            obj_rv = obj.get("metadata", {}).get("resourceVersion")
            if obj_rv is not None:
                last_rv = obj_rv
        return batch, last_rv, error_obj, n_seen, False

    def _apply_model_batch(self, rv_key: str, batch: list) -> None:
        """Apply a batch of (change_type, Node/Pod) pairs — the models
        are already built (decode_watch_lines) — as one transaction."""
        if rv_key == "nodes":
            self._invalidate_node_rvs(n.name for _, n in batch)
            self._mirror.apply_node_changes(batch)
        else:
            if self._m_watch_batch_pods is not None:
                self._m_watch_batch_pods.observe(len(batch))
            self._confirm_placements(batch)
            batch = self._drop_expected_echoes(batch)
            if batch:
                self._mirror.apply_pod_changes(batch)

    def _invalidate_node_rvs(self, names) -> None:
        """Drop rv-reuse entries for nodes touched outside the relist
        path: the next relist rebuilds them from the wire (GIL-atomic
        per-name pops; conservative — a dropped entry only costs one
        rebuild)."""
        rvs = self._node_rvs
        if rvs:
            for name in names:
                rvs.pop(name, None)

    def rv_reuse_size(self) -> int:
        """Current size of the resourceVersion-reuse map (bounded-map
        regression gate: must track the live node count — see
        ``prune_node_rvs``). Pods intentionally have no such map: the
        native decoder keys reuse by bare object name, which collides
        across pod namespaces (doc/read-path.md)."""
        return len(self._node_rvs)

    def prune_node_rvs(self) -> int:
        """Evict rv-reuse entries whose node left the mirror. Every
        delete path already pops its own entry (watch applies, patches,
        relist reconciliation), so this is the hard backstop that turns
        "should stay bounded" into an invariant: after any relist the
        map holds only live nodes, no matter what interleaving of
        watch churn and relist raced before it. O(map); runs once per
        relist. Returns the evicted count."""
        rvs = self._node_rvs
        if not rvs:
            return 0
        get = self._mirror.get_node
        dead = [name for name in rvs if get(name) is None]
        for name in dead:
            rvs.pop(name, None)
        return len(dead)

    def _apply_node(self, change_type: str, obj: dict) -> None:
        node = node_from_json(obj)
        self._invalidate_node_rvs((node.name,))
        if change_type == "DELETED":
            self._mirror.delete_node(node.name)
        else:
            self._mirror.add_node(node)

    def _apply_node_batch(self, changes: list) -> None:
        """Coalesced node watch apply: the whole drained batch decodes
        first, then lands as ONE mirror transaction
        (``ClusterState.apply_node_changes``)."""
        decoded = [(t, node_from_json(o)) for t, o in changes]
        self._invalidate_node_rvs(n.name for _, n in decoded)
        self._mirror.apply_node_changes(decoded)

    def _confirm_placements(self, decoded: list) -> None:
        """Watch-CONFIRMED hook: a non-DELETED pod event carrying a
        nodeName is the authoritative end of a placement (lifecycle
        confirmation + intent-journal tombstone); a DELETED event
        tombstones any open eviction intent. Untracked keys cost one
        dict miss inside one lock."""
        lc = self._lifecycle
        journal = self._intent_journal
        if lc is None and journal is None:
            return
        placed = [
            (pod.key(), pod.node_name)
            for t, pod in decoded
            if t != "DELETED" and pod.node_name
        ]
        if lc is not None and placed:
            lc.confirmed_batch(placed)
        if journal is not None:
            if placed:
                journal.tombstone_batch(placed)
            for t, pod in decoded:
                if t == "DELETED":
                    journal.tombstone_deleted(pod.key())

    def _drop_expected_echoes(self, decoded: list) -> list:
        """Filter watch pod changes that are echoes of an in-flight
        ``bind_pods`` batch (same pod, same node as the registered
        expectation): the optimistic mirror apply IS that change, so
        applying the echo too would double-bump pod_version per bind.
        Lifecycle confirmation must still run on the full list —
        callers confirm BEFORE filtering."""
        if not self._expected_binds:
            return decoded
        with self._expected_lock:
            expected = dict(self._expected_binds)
        return [
            (t, pod) for t, pod in decoded
            if t == "DELETED"
            or not pod.node_name
            or expected.get(pod.key()) != pod.node_name
        ]

    def _apply_pod(self, change_type: str, obj: dict) -> None:
        pod = pod_from_json(obj)
        if change_type == "DELETED":
            if self._intent_journal is not None:
                self._confirm_placements(((change_type, pod),))
            self._mirror.delete_pod(pod.key())
        else:
            self._confirm_placements(((change_type, pod),))
            for _t, p in self._drop_expected_echoes(
                [(change_type, pod)]
            ):
                self._mirror.add_pod(p)

    def _apply_pod_batch(self, changes: list) -> None:
        if self._m_watch_batch_pods is not None:
            self._m_watch_batch_pods.observe(len(changes))
        decoded = [(t, pod_from_json(o)) for t, o in changes]
        self._confirm_placements(decoded)
        decoded = self._drop_expected_echoes(decoded)
        if decoded:
            self._mirror.apply_pod_changes(decoded)

    def _apply_nrt(self, change_type: str, obj: dict) -> None:
        nrt = nrt_from_json(obj)
        if change_type == "DELETED":
            self.nrt_lister.delete(nrt.name)
        else:
            self.nrt_lister.upsert(nrt)

    def _apply_nrt_batch(self, changes: list) -> None:
        for change_type, obj in changes:
            self._apply_nrt(change_type, obj)

    def _mark_event_stream_restart(self) -> None:
        """A new events stream (watch (re)connect or relist) may replay
        a prefix of already-applied history: rvs at or below the
        watermark inside this window are replays, not violations."""
        with self._seen_lock:
            self._event_expect_replay = True

    def _apply_event(self, change_type: str, obj: dict) -> None:
        event = self._dedup_event(change_type, obj)
        if event is not None:
            self._mirror.emit_event(event)

    def _apply_event_batch(self, changes: list) -> None:
        """Coalesced event apply: dedup each drained event in order (the
        rv watermark advances exactly as the per-event path would), then
        deliver the survivors as ONE batched emit — one mirror lock hold
        and one batch-subscriber call for the whole backlog."""
        deliver = []
        for change_type, obj in changes:
            event = self._dedup_event(change_type, obj)
            if event is not None:
                deliver.append(event)
        if deliver:
            self._mirror.emit_events(deliver)

    def _dedup_event(self, change_type: str, obj: dict):
        """Decode + dedup one watch event; returns the Event to deliver
        or None (duplicate/replayed/DELETED)."""
        if change_type == "DELETED":
            return None
        event = event_from_json(obj)
        # replayed backlogs (a no-rv connect or post-410 restart) must
        # not double-count. Primary dedup: the apiserver resourceVersion
        # watermark — streams deliver in rv order, so rv <= watermark is
        # a replay; exact in O(1) memory regardless of backlog size.
        # The content-key map runs in PARALLEL (bounded identity; the
        # mirror assigns its own resourceVersion, so that can't key):
        # it is the only dedup for rv-less/non-integer-rv servers, and
        # the fallback when the monotonicity guard trips (see __init__).
        server_rv = obj.get("metadata", {}).get("resourceVersion")
        rv_int = None
        if server_rv is not None:
            try:
                rv_int = int(server_rv)
            except (TypeError, ValueError):
                rv_int = None
        key = (
            event.namespace,
            event.name,
            event.count,
            event.last_timestamp,
            event.event_time,
            event.message,
        )
        deliver = False
        with self._seen_lock:
            if rv_int is not None and self._event_rv_trusted:
                if rv_int <= self._event_rv_watermark:
                    if self._event_expect_replay:
                        return None  # replayed prefix after a (re)connect
                    # rv went BACKWARD on a live stream: the server's
                    # integer rvs are not monotonic — never trust them
                    # again; this event falls through to content dedup
                    # (so it is NOT dropped if genuinely fresh)
                    self._event_rv_trusted = False
                else:
                    self._event_rv_watermark = rv_int
                    # past the watermark => past any replayed prefix
                    self._event_expect_replay = False
                    self._record_seen_locked(key)
                    deliver = True  # fresh rv wins even on a content
                    # collision: monotonic rvs mean new, and identical
                    # payloads DO recur (informers deliver them too)
            if not deliver:
                # content-key path: rv-less, non-integer, or untrusted
                if key in self._seen_events:
                    return None
                self._record_seen_locked(key)
        return event

    def _record_seen_locked(self, key: tuple) -> None:
        if key in self._seen_events:
            return
        if len(self._seen_events) >= self._seen_events_cap:
            self._seen_events.pop(next(iter(self._seen_events)))
        self._seen_events[key] = None

    # -- reads: the informer mirror ---------------------------------------

    @property
    def sched_version(self) -> int:
        return self._mirror.sched_version

    @property
    def node_set_version(self) -> int:
        return self._mirror.node_set_version

    @property
    def node_version(self) -> int:
        return self._mirror.node_version

    @property
    def pod_version(self) -> int:
        return self._mirror.pod_version

    def pod_changes_since(self, version: int):
        return self._mirror.pod_changes_since(version)

    def configure_shards(self, count: int, overlap: float = 0.0,
                         layout=None) -> None:
        self._mirror.configure_shards(count, overlap, layout=layout)

    def shard_layout(self):
        return self._mirror.shard_layout()

    def shard_keyspace(self):
        return self._mirror.shard_keyspace()

    def reshard(self, target):
        return self._mirror.reshard(target)

    def shard_versions(self, index: int) -> tuple[int, int, int]:
        return self._mirror.shard_versions(index)

    def dirty_nodes_since(self, version: int, shard: int | None = None):
        return self._mirror.dirty_nodes_since(version, shard)

    def dirty_journal_stats(self) -> dict[str, int]:
        return self._mirror.dirty_journal_stats()

    def has_node(self, name: str) -> bool:
        return self._mirror.has_node(name)

    def list_nodes(self):
        return self._mirror.list_nodes()

    def count_pods_all(self) -> dict[str, int]:
        return self._mirror.count_pods_all()

    def get_node(self, name: str):
        return self._mirror.get_node(name)

    def node_names(self):
        return self._mirror.node_names()

    def list_pods(self, node_name: str | None = None):
        return self._mirror.list_pods(node_name)

    def count_pods(self, node_name: str) -> int:
        return self._mirror.count_pods(node_name)

    def get_pod(self, key: str):
        return self._mirror.get_pod(key)

    def get_pod_live(self, key: str):
        """GET the pod from the apiserver, bypassing the mirror — the
        restart reconciler's read: a just-restarted process's mirror is
        cold, and classifying a crash-orphaned intent against stale
        state could re-POST a bind that already landed. 404 → None (pod
        gone); transport errors RAISE — reconciliation must fail loudly
        rather than misclassify an unreachable pod as deleted."""
        namespace, name = key.split("/", 1)
        try:
            obj = self._get_json(
                f"/api/v1/namespaces/{namespace}/pods/{name}"
            )
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return None
            raise
        return pod_from_json(obj)

    def attach_intent_journal(self, journal) -> None:
        """Install the crash-safety journal (resilience.recovery
        ``IntentJournal``). From this point every bind/eviction POST is
        journaled intent-before-wire; watch confirmations tombstone."""
        self._intent_journal = journal

    def list_events(self):
        return self._mirror.list_events()

    def get_event(self, key: str):
        return self._mirror.get_event(key)

    def subscribe_events(self, handler) -> None:
        self._mirror.subscribe_events(handler)

    def subscribe_events_batch(self, handler) -> None:
        self._mirror.subscribe_events_batch(handler)

    # -- writes: through the API ------------------------------------------

    # writes never raise: ClusterState's contract is a bool, and the
    # annotator's worker/ticker threads rely on skip-and-retry — an
    # escaping URLError would silently kill them for the process
    # lifetime. HTTP errors, refused connections, and timeouts all
    # report False (the workqueue backs off and retries). Every write
    # rides the keep-alive worker pool (``concurrent_syncs`` workers,
    # ref: controller.go:74-77), routed by object key so per-object
    # ordering holds while distinct objects write in parallel.

    def patch_node_annotation(self, name: str, key: str, value: str) -> bool:
        """Annotation merge-patch (ref: node.go:123-146)."""
        body = {"metadata": {"annotations": {key: value}}}
        if not self._write(
            name,
            "PATCH",
            f"/api/v1/nodes/{name}",
            body,
            "application/merge-patch+json",
        ):
            return False
        # optimistic local apply: the writer's next read sees its write
        # (the watch will deliver the authoritative object too). The API
        # write already succeeded, so report True even if the object has
        # not reached the mirror yet (watch lag) — a False here would
        # make callers retry an already-applied write.
        self._invalidate_node_rvs((name,))
        self._mirror.patch_node_annotation(name, key, value)
        return True

    def patch_node_annotations_bulk(self, per_node) -> int:
        """Batch annotation patch: ONE merge-patch per node carrying the
        whole sweep's keys (vs one HTTP round-trip per (node, key) — the
        reference pays 2x|nodes|x|syncPolicy| PATCHes per cycle,
        ref: node.go:123-146; batching them per node is the rebuild's
        sync-path win). All nodes are submitted to the pool up front and
        gathered after, so a sweep flush runs ``concurrent_syncs``-wide
        over pooled connections instead of one fresh round-trip at a
        time (the reference's concurrent-syncs workers over client-go's
        shared transport, node.go:29-42).

        Batches of >= _NATIVE_FLUSH_MIN over plain http ride the C++
        flush engine instead: the whole storm is one GIL-releasing call
        (send/parse/drain in native worker threads), with engine
        failures re-driven through the Python pool so they keep its
        status-aware retry/backoff semantics. Merge-patch ordering note:
        the annotator is the only node-annotation writer and flushes
        from one thread, so bypassing the per-key FIFO pool for the
        batch cannot reorder writes to a node."""
        m = self._m_flush_seconds
        if m is None:
            return self._patch_node_annotations_bulk_impl(per_node)
        t0 = time.perf_counter()
        try:
            return self._patch_node_annotations_bulk_impl(per_node)
        finally:
            m.labels(kind="annotations").observe(time.perf_counter() - t0)

    def _render_annotation_patch(self, name: str, kv) -> bytes:
        return self._render_request(
            "PATCH",
            f"/api/v1/nodes/{name}",
            {"metadata": {"annotations": dict(kv)}},
            "application/merge-patch+json",
        )

    def _patch_node_annotations_bulk_impl(self, per_node) -> int:
        items = list(per_node.items())
        patched = 0
        statuses = None
        if len(items) >= _PIPELINE_FLUSH_MIN:
            flusher = (
                self._get_native_flusher()
                if len(items) >= _NATIVE_FLUSH_MIN else None
            )
            if flusher is not None:
                reqs = [self._render_annotation_patch(n, kv)
                        for n, kv in items]
                if self._pipeline_disabled:
                    statuses = flusher.flush(reqs, idempotent=True).tolist()
                else:
                    statuses = flusher.flush_pipelined(
                        reqs, idempotent=True).tolist()
                    self._note_pipeline_stats(flusher)
            elif not self._pipeline_disabled:
                # https / no-.so / sub-native-threshold storm path:
                # Python pipelined fan-out with idempotent (merge-patch)
                # retry semantics
                reqs = [self._render_annotation_patch(n, kv)
                        for n, kv in items]
                statuses = self._pipelined_flush(reqs, idempotent=True)
        if statuses is not None:
            retry_items = []
            ok_updates: dict[str, dict] = {}
            for (name, kv), status in zip(items, statuses):
                if 200 <= status < 300:
                    ok_updates[name] = kv
                elif status == 0 or status in _RETRYABLE_ANY \
                        or status in _RETRYABLE_IDEMPOTENT:
                    # transport loss / transient status: re-drive
                    # through the pool, which owns backoff +
                    # Retry-After (transient statuses count here,
                    # matching the pool's per-occurrence counting;
                    # transport absorptions don't, also matching)
                    if status:
                        self._count_native_failure(int(status))
                    retry_items.append((name, kv))
                else:
                    # durable failure (404/422/...): count ONCE and
                    # drop — the pool wouldn't retry it either
                    self._count_native_failure(int(status))
            if ok_updates:
                self._invalidate_node_rvs(ok_updates)
                self._mirror.patch_node_annotations_bulk(ok_updates)
                patched += len(ok_updates)
            items = retry_items  # slow path owns retries/backoff
        futs = []
        for name, kv in items:
            body = {"metadata": {"annotations": dict(kv)}}
            futs.append((
                name,
                kv,
                self._submit_write(
                    name,
                    "PATCH",
                    f"/api/v1/nodes/{name}",
                    body,
                    "application/merge-patch+json",
                ),
            ))
        for name, kv, fut in futs:
            if fut.result():
                self._invalidate_node_rvs((name,))
                self._mirror.patch_node_annotations_bulk({name: kv})
                patched += 1
        return patched

    def patch_node_annotations_columns(self, names, columns) -> int:
        """Columnar flush entry (same contract as
        ``ClusterState.patch_node_annotations_columns``): HTTP
        merge-patches are per node, so pivot the aligned columns into
        per-node dicts here — the pivot is noise next to wire time on
        this path — and ride the bulk primitive (native engine when
        large)."""
        return self.patch_node_annotation_groups([(names, columns)])

    def patch_node_annotation_groups(self, groups) -> int:
        """Pivot ALL column groups into per-node dicts and issue ONE
        merge-patch per node. A sweep whose metrics carry different
        row sets (fallback-filtered nodes) produces one group per
        metric; applying them group-by-group would multiply the HTTP
        patch count per node by the group count (measured 6x at 5k
        nodes — the whole round-4 write-path win given back)."""
        per_node: dict[str, dict[str, str]] = {}
        for names, columns in groups:
            for key, values in columns.items():
                for name, value in zip(names, values):
                    d = per_node.get(name)
                    if d is None:
                        d = per_node[name] = {}
                    d[key] = value
        return self.patch_node_annotations_bulk(per_node)

    def patch_pod_annotation(self, key: str, anno_key: str, value: str) -> bool:
        """PreBind's pod-annotation patch (ref: binder.go:19-65)."""
        namespace, name = key.split("/", 1)
        body = {"metadata": {"annotations": {anno_key: value}}}
        if not self._write(
            key,
            "PATCH",
            f"/api/v1/namespaces/{namespace}/pods/{name}",
            body,
            "application/merge-patch+json",
        ):
            return False
        # API write succeeded; mirror apply is best-effort (watch lag —
        # the pod may not have reached the mirror yet).
        self._mirror.patch_pod_annotation(key, anno_key, value)
        return True

    def evict_pod(self, key: str, now: float | None = None) -> bool:
        """POST the eviction subresource (the descheduler's write).

        Evictions are NOT idempotent — a duplicate POST on a real
        apiserver races pod termination (409/404) and double-counts
        disruption budgets — so the request rides the pooled writer's
        POST discipline: a response-phase transport loss is
        indeterminate and is never blindly re-POSTed (only 429, which
        the apiserver documents as not-processed, re-drives). Same
        contract as the binding subresource (see _IDEMPOTENT_METHODS)."""
        namespace, name = key.split("/", 1)
        body = {
            "apiVersion": "policy/v1",
            "kind": "Eviction",
            "metadata": {"name": name, "namespace": namespace},
        }
        headers = self._trace_header(key)
        pod = self._mirror.get_pod(key)
        iid = self._journal_single(
            "evict", key, pod.node_name if pod is not None else None, headers
        )
        res = self._write(
            key,
            "POST",
            f"/api/v1/namespaces/{namespace}/pods/{name}/eviction",
            body,
            headers=headers,
        )
        self._journal_single_outcome(iid, res)
        if not res:
            return False
        # optimistic mirror apply; the watch's authoritative DELETED
        # event confirms (re-deleting an absent pod is a no-op)
        self._mirror.delete_pod(key)
        return True

    def add_pod(self, pod: Pod) -> None:
        """Create the pod via the API (primarily for tests/tools; real
        pods arrive through the watch). The body carries the FULL pod —
        containers/resources, ownerReferences, nodeName — because any
        later watch delivery rebuilds the mirror entry from the server's
        copy, and a stripped server copy would silently erase resource
        requests and daemonset detection."""
        body = {
            "metadata": {
                "name": pod.name,
                "namespace": pod.namespace,
                "annotations": dict(pod.annotations),
                "ownerReferences": [
                    {"kind": r.kind, "name": r.name}
                    for r in pod.owner_references
                ],
            },
            "spec": {
                "nodeName": pod.node_name,
                "containers": [
                    {
                        "name": c.name,
                        "resources": {
                            "requests": dict(c.resources.requests),
                            "limits": dict(c.resources.limits),
                        },
                    }
                    for c in pod.containers
                ],
                "initContainers": [
                    {
                        "name": c.name,
                        "resources": {
                            "requests": dict(c.resources.requests),
                            "limits": dict(c.resources.limits),
                        },
                    }
                    for c in pod.init_containers
                ],
                "overhead": dict(pod.overhead),
            },
        }
        if not self._write(
            pod.key(), "POST", f"/api/v1/namespaces/{pod.namespace}/pods", body
        ):
            # never raise (ClusterState.add_pod cannot fail); the pod is
            # simply not created — counted like any other failed write
            self.watch_errors += 1
            return
        self._mirror.add_pod(pod)

    def add_pods(self, pods) -> None:
        """Bulk twin of ``add_pod`` (``ClusterState.add_pods`` parity —
        the grouped gang bind creates each node group's copies through
        this). A pod whose creation POST fails is simply absent, so the
        subsequent binding POST for it fails too and the bind path
        reports it dropped."""
        for pod in pods:
            self.add_pod(pod)

    def _post_batch(self, items: list[tuple[str, str, dict]]) -> list[bool]:
        """THE non-idempotent POST batch: ``items`` are (key, path,
        body). Large plain-http batches ride the native engine; 429s —
        explicitly not processed, so safe to re-POST — re-drive through
        the Python pool (which honors Retry-After/backoff) exactly as
        small batches do; any other failure is durable. Single-sourced
        here so bind_pods/add_pod_burst/bind_burst can't drift apart in
        retry policy. Returns per-item success."""
        m = self._m_flush_seconds
        if m is None:
            return self._post_batch_impl(items)
        t0 = time.perf_counter()
        try:
            return self._post_batch_impl(items)
        finally:
            m.labels(kind="post_batch").observe(time.perf_counter() - t0)

    def _trace_header(self, key: str) -> dict | None:
        """``{"traceparent": ...}`` when the pod is lifecycle-tracked,
        plus the ``crane-deadline-ms`` budget (thread-local deadline,
        else the client's configured POST default)."""
        lc = self._lifecycle
        tp = lc.traceparent(key) if lc is not None else None
        headers = {"traceparent": tp} if tp else None
        dl = _deadline_pair(self.post_deadline_ms)
        if dl is not None:
            headers = headers or {}
            headers[dl[0]] = dl[1]
        return headers

    @staticmethod
    def _intent_op(path: str) -> str | None:
        """Which journal op a POST path is — None for idempotent-enough
        creations (a duplicate create is a 409, not a double bind)."""
        if path.endswith("/binding"):
            return "bind"
        if path.endswith("/eviction"):
            return "evict"
        return None

    def _journal_intents(self, items, tp) -> list:
        """One intent line per bind/eviction item, all under one fresh
        window id, before any wire traffic. Returns per-item intent ids
        (None for non-journaled items)."""
        journal = self._intent_journal
        window = journal.begin_window()
        ids: list = [None] * len(items)
        for i, (key, path, body) in enumerate(items):
            op = self._intent_op(path)
            if op is None:
                continue
            if op == "bind":
                # bodies arrive pre-rendered (the bind-burst template)
                doc = json.loads(body) if isinstance(body, (bytes, str)) else body
                node = doc.get("target", {}).get("name")
            else:
                pod = self._mirror.get_pod(key)
                node = pod.node_name if pod is not None else None
            ids[i] = journal.intent(
                op, key, node, trace=tp.get(key), window=window
            )
        return ids

    def _journal_outcomes(self, intent_ids, ok, final_status) -> None:
        """Resolve each journaled intent: 2xx → ack (applied), a real
        server status → nack (answered, not applied — re-drivable), 0 →
        unresolved (transport loss / pipelined indeterminate; only
        restart reconciliation may decide it)."""
        journal = self._intent_journal
        for iid, good, status in zip(intent_ids, ok, final_status):
            if iid is None:
                continue
            if good:
                journal.ack(iid)
            elif status > 0:
                journal.nack(iid, status)
            else:
                journal.unresolved(iid)

    def _journal_single(self, op: str, key: str, node, headers):
        """Intent line for a single-POST path (bind_pod / evict_pod)."""
        journal = self._intent_journal
        if journal is None:
            return None
        trace = headers.get("traceparent") if headers else None
        return journal.intent(op, key, node, trace=trace)

    def _journal_single_outcome(self, intent_id, result) -> None:
        if intent_id is None:
            return
        self._journal_outcomes(
            [intent_id], [bool(result)],
            [int(getattr(result, "status", 0) or 0)],
        )

    def _post_batch_impl(self, items: list[tuple[str, str, dict]]) -> list[bool]:
        n = len(items)
        ok = [False] * n
        retry: list[int] = []
        statuses = None
        lc = self._lifecycle
        # one lock for the whole batch; only tracked pods get headers
        tp = (
            lc.traceparent_batch([key for key, _, _ in items])
            if lc is not None else {}
        )

        dl = _deadline_pair(self.post_deadline_ms)

        def _hdr(key):
            v = tp.get(key)
            headers = {"traceparent": v} if v else None
            if dl is not None:
                headers = headers or {}
                headers[dl[0]] = dl[1]
            return headers

        # crash-safety: journal every bind/eviction intent BEFORE any
        # route puts bytes on the wire (a kill after this point leaves
        # a replayable record; a kill before it leaves nothing in
        # flight). final_status resolves each intent after the batch.
        journal = self._intent_journal
        intent_ids = (
            self._journal_intents(items, tp) if journal is not None else None
        )
        final_status = [0] * n  # 0 = indeterminate unless a route reports

        flusher = self._get_native_flusher()
        if flusher is not None and n >= _NATIVE_FLUSH_MIN:
            reqs = [
                self._render_request("POST", path, body,
                                     extra_headers=_hdr(key))
                for key, path, body in items
            ]
            if self._pipeline_disabled:
                statuses = flusher.flush(reqs, idempotent=False).tolist()
            else:
                statuses = flusher.flush_pipelined(
                    reqs, idempotent=False).tolist()
                self._note_pipeline_stats(flusher)
        elif n >= _PIPELINE_FLUSH_MIN and not self._pipeline_disabled:
            # no native engine (https, or no .so): the Python pipelined
            # fan-out still beats one-request-per-round-trip pooled
            # writers for storm-sized POST batches
            reqs = [
                self._render_request("POST", path, body,
                                     extra_headers=_hdr(key))
                for key, path, body in items
            ]
            statuses = self._pipelined_flush(reqs, idempotent=False)
        if statuses is None:
            retry = list(range(n))
        else:
            for i, status in enumerate(statuses):
                final_status[i] = int(status)
                if 200 <= status < 300:
                    ok[i] = True
                else:
                    # status 0 covers transport loss AND the pipelined
                    # indeterminate set: those POSTs are never re-driven
                    # (the server may have processed them; the watch
                    # delivers the authoritative outcome either way)
                    self._count_native_failure(int(status))
                    if status in _RETRYABLE_ANY:
                        retry.append(i)
        if retry:
            futs = [
                (i, self._submit_write(
                    items[i][0], "POST", items[i][1], items[i][2],
                    headers=_hdr(items[i][0])))
                for i in retry
            ]
            for i, fut in futs:
                res = fut.result()
                ok[i] = bool(res)
                final_status[i] = int(getattr(res, "status", 0) or 0)
        if intent_ids is not None:
            self._journal_outcomes(intent_ids, ok, final_status)
        if lc is not None and tp:
            posted = [
                (items[i][0], None) for i in range(n)
                if ok[i] and items[i][0] in tp
                and items[i][1].endswith("/binding")
            ]
            if posted:
                lc.posted_batch(posted)
        return ok

    # -- columnar bursts through the API -----------------------------------

    def add_pod_burst(self, namespace: str, names: list):
        """Columnar burst arrival through the API: one creation POST per
        pod streamed over the native engine (the apiserver has no bulk
        create), the mirror keeping the burst as rows. Rows whose POST
        failed are marked dead in the handle so ``bind_burst`` never
        binds a pod the server refused. Gives ``BatchScheduler``'s burst
        mode (schedule_pod_burst / schedule_bursts_pipelined) the same
        cluster contract the in-memory ClusterState provides.

        The mirror burst registers BEFORE the POSTs go out: watch
        echoes of the created pods then shadow existing rows through
        the normal ``_add_pod_locked`` path instead of racing ahead and
        leaving duplicate object+row entries. Rows the server refuses
        are retired immediately after (a refused row is mirror-visible
        only for the wire round-trip)."""
        path = f"/api/v1/namespaces/{namespace}/pods"
        burst = self._mirror.add_pod_burst(namespace, names)
        ok = self._post_batch([
            (f"{namespace}/{name}", path,
             {"metadata": {"name": name, "namespace": namespace},
              "spec": {}})
            for name in names
        ])
        failed = {row for row, good in enumerate(ok) if not good}
        if failed:
            # server refused those creations: the rows must not exist
            self._mirror.retire_burst_rows(burst, sorted(failed))
        return _KubeBurstHandle(burst, failed)

    def _burst_bind_items(self, handle, node_table, node_idx):
        """Shared front half of ``bind_burst``/``bind_bursts``: the
        bindable rows and their rendered POST items."""
        import numpy as _np2

        burst = handle.burst
        node_idx = _np2.asarray(node_idx, dtype=_np2.int32)
        rows = [
            row for row in range(len(node_idx))
            if node_idx[row] >= 0 and row not in handle.failed
        ]
        ns = burst.namespace
        names = burst.names
        items = []
        for row in rows:
            name = names[row]
            node_name = node_table[int(node_idx[row])]
            items.append((
                f"{ns}/{name}",
                f"/api/v1/namespaces/{ns}/pods/{name}/binding",
                self._render_binding_body(ns, name, node_name),
            ))
        return node_idx, rows, items

    def _burst_bind_apply(self, handle, node_table, node_idx, rows, ok,
                          now) -> list[int]:
        """Shared back half: optimistic mirror apply for the rows the
        server accepted, no local events. The pods watch echoes
        creations quickly, shadowing burst rows into object pods — the
        columnar apply covers rows still in burst form; echoed rows take
        one batched object-path apply, exactly like per-pod
        ``bind_pod``'s optimistic apply."""
        import numpy as _np2

        burst = handle.burst
        ns = burst.namespace
        names = burst.names
        ok_rows = sorted(row for row, good in zip(rows, ok) if good)
        if not ok_rows:
            return []
        mirror_idx = _np2.full((len(node_idx),), -1, dtype=_np2.int32)
        mirror_idx[ok_rows] = node_idx[ok_rows]
        columnar_bound = set(
            int(r) for r in self._mirror.bind_burst(
                burst, node_table, mirror_idx, now, notify=False
            )
        )
        echoed = [
            (f"{ns}/{names[row]}", node_table[int(node_idx[row])])
            for row in ok_rows if row not in columnar_bound
        ]
        if echoed:
            self._mirror.bind_pods(echoed, now, notify=False)
        # the SERVER's acceptance defines what bound (the mirror is a
        # cache in whatever form each row currently takes)
        return ok_rows

    def bind_burst(self, handle, node_table, node_idx, now=None) -> list[int]:
        """Columnar bind through the binding subresource: one POST per
        bound row streamed over the pipelined engine, the mirror
        applying placements for the rows the server accepted — WITHOUT
        local event emission (the apiserver's Scheduled events arrive
        through the watch, exactly like ``bind_pod``). Returns bound
        rows."""
        node_idx, rows, items = self._burst_bind_items(
            handle, node_table, node_idx
        )
        if not rows:
            return []
        ok = self._post_batch(items)
        return self._burst_bind_apply(
            handle, node_table, node_idx, rows, ok, now
        )

    def bind_bursts(self, bursts, now=None) -> list[list[int]]:
        """Coalesced multi-burst bind: ``bursts`` yields ``(handle,
        node_table, node_idx)`` triples whose binding POSTs ride ONE
        shared batch through the pipelined engine (a flush window's
        worth of cycles pays one engine crossing instead of one per
        burst), then each burst's mirror apply runs as usual. Returns
        one bound-rows list per burst, in input order."""
        prepped = []
        all_items: list = []
        for handle, node_table, node_idx in bursts:
            node_idx, rows, items = self._burst_bind_items(
                handle, node_table, node_idx
            )
            prepped.append(
                (handle, node_table, node_idx, rows, len(all_items),
                 len(items))
            )
            all_items.extend(items)
        ok = self._post_batch(all_items) if all_items else []
        out = []
        for handle, node_table, node_idx, rows, off, cnt in prepped:
            if not rows:
                out.append([])
                continue
            out.append(self._burst_bind_apply(
                handle, node_table, node_idx, rows, ok[off:off + cnt], now
            ))
        return out

    @staticmethod
    def _binding_request(pod_key: str, node_name: str) -> tuple[str, dict]:
        namespace, name = pod_key.split("/", 1)
        return (
            f"/api/v1/namespaces/{namespace}/pods/{name}/binding",
            {
                "metadata": {"name": name, "namespace": namespace},
                "target": {"kind": "Node", "name": node_name},
            },
        )

    def _apply_bound(self, pod_key: str, node_name: str) -> None:
        # optimistic placement apply (no event emission here — the event
        # is the apiserver's, delivered by the watch)
        pod = self._mirror.get_pod(pod_key)
        if pod is not None:
            from dataclasses import replace

            self._mirror.add_pod(replace(pod, node_name=node_name))

    def bind_pod(self, pod_key: str, node_name: str, now: float | None = None) -> bool:
        """POST the ``binding`` subresource — the scheduler's bind call.
        The apiserver emits the Scheduled event; it reaches subscribers
        through the event watch (the closed loop of SURVEY §3.4)."""
        path, body = self._binding_request(pod_key, node_name)
        headers = self._trace_header(pod_key)
        iid = self._journal_single("bind", pod_key, node_name, headers)
        res = self._write(pod_key, "POST", path, body, headers=headers)
        self._journal_single_outcome(iid, res)
        if not res:
            return False
        if self._lifecycle is not None:
            self._lifecycle.posted(pod_key, node=node_name)
        self._apply_bound(pod_key, node_name)
        return True

    def bind_pods(self, assignments, now: float | None = None) -> list[str]:
        """Bind a batch through the binding subresource: POSTs stream
        over the shared batch path (pipelined native engine when large,
        pooled workers otherwise; 429s re-driven — see ``_post_batch``),
        gathered in input order so the returned bound-key list is
        deterministic. The optimistic mirror apply for the accepted
        subset is ONE batched placement transaction (no local events —
        the apiserver's Scheduled events arrive through the watch)."""
        pairs = list(
            assignments.items() if hasattr(assignments, "items") else assignments
        )
        items = []
        for pod_key, node_name in pairs:
            namespace, name = pod_key.split("/", 1)
            items.append((
                pod_key,
                f"/api/v1/namespaces/{namespace}/pods/{name}/binding",
                self._render_binding_body(namespace, name, node_name),
            ))
        # register expectations BEFORE the POSTs: the apiserver echoes
        # each bound pod on the watch within ~1 ms — usually before this
        # thread reaches the optimistic apply below — and that echo must
        # not count as a second pod change (see _drop_expected_echoes)
        with self._expected_lock:
            self._expected_binds.update(pairs)
        try:
            ok = self._post_batch(items)
            bound = []
            bound_pairs = []
            for (pod_key, node_name), good in zip(pairs, ok):
                if good:
                    bound.append(pod_key)
                    bound_pairs.append((pod_key, node_name))
            if bound_pairs:
                self._mirror.bind_pods(bound_pairs, now, notify=False)
        finally:
            with self._expected_lock:
                for pod_key, _node in pairs:
                    self._expected_binds.pop(pod_key, None)
        return bound
