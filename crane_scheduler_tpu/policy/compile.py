"""Compile a DynamicSchedulerPolicy into tensor constants.

The reference walks Go slices per node per scheduling cycle
(ref: pkg/plugins/dynamic/stats.go:94-150). Here the policy is compiled
once into small dense vectors — metric column indices, thresholds, weights,
staleness windows — that parameterize a single batched tensor expression
over the whole node-by-metric load matrix.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .types import DynamicSchedulerPolicy
from ..constants import EXTRA_ACTIVE_PERIOD_SECONDS


@dataclass(frozen=True)
class PolicyTensors:
    """Dense form of a DynamicSchedulerPolicy.

    Axis conventions: ``M`` metric columns, ``P`` predicate entries,
    ``K`` priority entries, ``H`` hot-value entries. Entry arrays preserve
    policy list order — priority accumulation order is bit-significant.
    """

    metric_names: tuple[str, ...]
    metric_index: dict  # name -> column
    # Per-metric first nonzero sync period (+0 when absent); seconds.
    sync_seconds: np.ndarray  # [M] f64
    # Per-metric staleness window: first nonzero period + 5m, else 0 (=disabled)
    # (ref: stats.go:140-150 — zero-period entries are skipped by the scan).
    active_seconds: np.ndarray  # [M] f64
    pred_idx: np.ndarray  # [P] i32 metric column per predicate entry
    pred_threshold: np.ndarray  # [P] f64
    pred_active: np.ndarray  # [P] f64 staleness window per entry; 0 = entry skipped
    prio_idx: np.ndarray  # [K] i32
    prio_weight: np.ndarray  # [K] f64
    prio_active: np.ndarray  # [K] f64; 0 = entry scores 0 (weight still counts)
    weight_sum: float  # Σ weights accumulated in list order (f64)
    hv_range_seconds: np.ndarray  # [H] f64
    hv_count: np.ndarray  # [H] i64

    @property
    def num_metrics(self) -> int:
        return len(self.metric_names)


def compile_policy(policy: DynamicSchedulerPolicy) -> PolicyTensors:
    spec = policy.spec

    # Metric universe: first-appearance order over sync, predicate, priority.
    names: list[str] = []
    index: dict[str, int] = {}

    def intern(name: str) -> int:
        if name not in index:
            index[name] = len(names)
            names.append(name)
        return index[name]

    for sp in spec.sync_period:
        intern(sp.name)
    for pp in spec.predicate:
        intern(pp.name)
    for pr in spec.priority:
        intern(pr.name)

    m = len(names)
    sync_seconds = np.zeros((m,), dtype=np.float64)
    active_seconds = np.zeros((m,), dtype=np.float64)
    claimed: set[int] = set()
    for sp in spec.sync_period:
        col = index[sp.name]
        # First nonzero-period entry per name wins (ref: stats.go:140-150).
        # Track claims explicitly: a claimed window may itself compute to 0
        # (e.g. a pathological -5m period) and must not be overwritten.
        if col not in claimed and sp.period_seconds != 0.0:
            claimed.add(col)
            sync_seconds[col] = sp.period_seconds
            active_seconds[col] = sp.period_seconds + EXTRA_ACTIVE_PERIOD_SECONDS

    pred_idx = np.array([index[p.name] for p in spec.predicate], dtype=np.int32)
    pred_threshold = np.array([p.max_limit_percent for p in spec.predicate], dtype=np.float64)
    pred_active = (
        active_seconds[pred_idx] if len(pred_idx) else np.zeros((0,), dtype=np.float64)
    )

    prio_idx = np.array([index[p.name] for p in spec.priority], dtype=np.int32)
    prio_weight = np.array([p.weight for p in spec.priority], dtype=np.float64)
    prio_active = (
        active_seconds[prio_idx] if len(prio_idx) else np.zeros((0,), dtype=np.float64)
    )

    weight_sum = 0.0
    for p in spec.priority:
        weight_sum += p.weight  # list order, matching Go accumulation

    hv_range_seconds = np.array(
        [h.time_range_seconds for h in spec.hot_value], dtype=np.float64
    )
    hv_count = np.array([h.count for h in spec.hot_value], dtype=np.int64)

    return PolicyTensors(
        metric_names=tuple(names),
        metric_index=dict(index),
        sync_seconds=sync_seconds,
        active_seconds=active_seconds,
        pred_idx=pred_idx,
        pred_threshold=pred_threshold,
        pred_active=np.asarray(pred_active, dtype=np.float64),
        prio_idx=prio_idx,
        prio_weight=prio_weight,
        prio_active=np.asarray(prio_active, dtype=np.float64),
        weight_sum=weight_sum,
        hv_range_seconds=hv_range_seconds,
        hv_count=hv_count,
    )
