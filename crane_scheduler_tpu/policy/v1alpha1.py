"""Strict v1alpha1 YAML decoding for DynamicSchedulerPolicy.

Equivalent of the reference's policy scheme + UniversalDecoder path
(ref: pkg/plugins/dynamic/policyfile.go:11-33,
pkg/plugins/apis/policy/scheme/scheme.go:13-29): the decoder is *strict* —
unknown fields, wrong GVK, or malformed durations are errors, matching the
strict codec factory the reference builds its scheme with. Wire field names
follow pkg/plugins/apis/policy/v1alpha1/types.go:14-39, including the
``maxLimitPecent`` typo.
"""

from __future__ import annotations

from typing import Any, Mapping

import yaml

from ..utils.duration import DurationError, parse_go_duration
from .types import (
    DynamicSchedulerPolicy,
    HotValuePolicy,
    PolicySpec,
    PredicatePolicy,
    PriorityPolicy,
    SyncPolicy,
)

GROUP_VERSION = "scheduler.policy.crane.io/v1alpha1"
KIND = "DynamicSchedulerPolicy"


class PolicyDecodeError(ValueError):
    pass


def _require_mapping(obj: Any, where: str) -> Mapping:
    if not isinstance(obj, Mapping):
        raise PolicyDecodeError(f"{where}: expected a mapping, got {type(obj).__name__}")
    return obj


def _check_fields(obj: Mapping, allowed: set[str], where: str) -> None:
    unknown = set(obj) - allowed
    if unknown:
        raise PolicyDecodeError(f"{where}: unknown field(s) {sorted(unknown)}")


def _decode_duration(val: Any, where: str) -> float:
    if not isinstance(val, str):
        raise PolicyDecodeError(f"{where}: duration must be a string, got {val!r}")
    try:
        return parse_go_duration(val)
    except DurationError as e:
        raise PolicyDecodeError(f"{where}: {e}") from e


def _decode_float(val: Any, where: str) -> float:
    if isinstance(val, bool) or not isinstance(val, (int, float)):
        raise PolicyDecodeError(f"{where}: expected a number, got {val!r}")
    return float(val)


def load_policy(data: str | bytes) -> DynamicSchedulerPolicy:
    """Decode a v1alpha1 DynamicSchedulerPolicy YAML/JSON document."""
    try:
        doc = yaml.safe_load(data)
    except yaml.YAMLError as e:
        raise PolicyDecodeError(f"invalid YAML: {e}") from e
    doc = _require_mapping(doc, "document")
    _check_fields(doc, {"apiVersion", "kind", "spec", "metadata"}, "document")

    api_version = doc.get("apiVersion")
    kind = doc.get("kind")
    if api_version != GROUP_VERSION:
        raise PolicyDecodeError(
            f"unsupported apiVersion {api_version!r}, want {GROUP_VERSION!r}"
        )
    if kind != KIND:
        raise PolicyDecodeError(f"unsupported kind {kind!r}, want {KIND!r}")

    spec_doc = _require_mapping(doc.get("spec", {}), "spec")
    _check_fields(spec_doc, {"syncPolicy", "predicate", "priority", "hotValue"}, "spec")

    sync: list[SyncPolicy] = []
    for i, item in enumerate(spec_doc.get("syncPolicy") or []):
        item = _require_mapping(item, f"spec.syncPolicy[{i}]")
        _check_fields(item, {"name", "period"}, f"spec.syncPolicy[{i}]")
        sync.append(
            SyncPolicy(
                name=str(item.get("name", "")),
                period_seconds=_decode_duration(
                    item.get("period", "0"), f"spec.syncPolicy[{i}].period"
                ),
            )
        )

    predicate: list[PredicatePolicy] = []
    for i, item in enumerate(spec_doc.get("predicate") or []):
        item = _require_mapping(item, f"spec.predicate[{i}]")
        _check_fields(item, {"name", "maxLimitPecent"}, f"spec.predicate[{i}]")
        predicate.append(
            PredicatePolicy(
                name=str(item.get("name", "")),
                max_limit_percent=_decode_float(
                    item.get("maxLimitPecent", 0), f"spec.predicate[{i}].maxLimitPecent"
                ),
            )
        )

    priority: list[PriorityPolicy] = []
    for i, item in enumerate(spec_doc.get("priority") or []):
        item = _require_mapping(item, f"spec.priority[{i}]")
        _check_fields(item, {"name", "weight"}, f"spec.priority[{i}]")
        priority.append(
            PriorityPolicy(
                name=str(item.get("name", "")),
                weight=_decode_float(item.get("weight", 0), f"spec.priority[{i}].weight"),
            )
        )

    hot_value: list[HotValuePolicy] = []
    for i, item in enumerate(spec_doc.get("hotValue") or []):
        item = _require_mapping(item, f"spec.hotValue[{i}]")
        _check_fields(item, {"timeRange", "count"}, f"spec.hotValue[{i}]")
        count = item.get("count", 0)
        if isinstance(count, bool) or not isinstance(count, int):
            raise PolicyDecodeError(f"spec.hotValue[{i}].count: expected int, got {count!r}")
        hot_value.append(
            HotValuePolicy(
                time_range_seconds=_decode_duration(
                    item.get("timeRange", "0"), f"spec.hotValue[{i}].timeRange"
                ),
                count=count,
            )
        )

    return DynamicSchedulerPolicy(
        spec=PolicySpec(
            sync_period=tuple(sync),
            predicate=tuple(predicate),
            priority=tuple(priority),
            hot_value=tuple(hot_value),
        ),
        api_version=api_version,
        kind=kind,
    )


def load_policy_from_file(path: str) -> DynamicSchedulerPolicy:
    """ref: pkg/plugins/dynamic/policyfile.go:11-18."""
    with open(path, "rb") as f:
        return load_policy(f.read())
