from .types import (
    SyncPolicy,
    PredicatePolicy,
    PriorityPolicy,
    HotValuePolicy,
    PolicySpec,
    DynamicSchedulerPolicy,
    DEFAULT_POLICY,
)
from .v1alpha1 import load_policy, load_policy_from_file, PolicyDecodeError
from .compile import PolicyTensors, compile_policy

__all__ = [
    "SyncPolicy",
    "PredicatePolicy",
    "PriorityPolicy",
    "HotValuePolicy",
    "PolicySpec",
    "DynamicSchedulerPolicy",
    "DEFAULT_POLICY",
    "load_policy",
    "load_policy_from_file",
    "PolicyDecodeError",
    "PolicyTensors",
    "compile_policy",
]
