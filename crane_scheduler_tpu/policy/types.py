"""Internal DynamicSchedulerPolicy model.

Mirrors the reference's internal policy types
(ref: pkg/plugins/apis/policy/types.go:9-39): a spec with four ordered
lists — syncPolicy (metric name + refresh period), predicate (metric name +
max limit), priority (metric name + weight), hotValue (time range + count).
List order is semantically meaningful: priority scores accumulate in list
order (float addition order affects bit-exact results) and hot-value terms
sum in list order with per-entry integer division.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass(frozen=True)
class SyncPolicy:
    name: str
    period_seconds: float  # ref: SyncPolicy.Period (metav1.Duration)


@dataclass(frozen=True)
class PredicatePolicy:
    name: str
    max_limit_percent: float  # ref: PredicatePolicy.MaxLimitPecent (sic)


@dataclass(frozen=True)
class PriorityPolicy:
    name: str
    weight: float


@dataclass(frozen=True)
class HotValuePolicy:
    time_range_seconds: float  # ref: HotValuePolicy.TimeRange
    count: int


@dataclass(frozen=True)
class PolicySpec:
    sync_period: tuple[SyncPolicy, ...] = ()
    predicate: tuple[PredicatePolicy, ...] = ()
    priority: tuple[PriorityPolicy, ...] = ()
    hot_value: tuple[HotValuePolicy, ...] = ()


@dataclass(frozen=True)
class DynamicSchedulerPolicy:
    spec: PolicySpec = field(default_factory=PolicySpec)
    api_version: str = "scheduler.policy.crane.io/v1alpha1"
    kind: str = "DynamicSchedulerPolicy"


# The canonical default policy shipped with the reference
# (ref: deploy/manifests/dynamic/policy.yaml): 6 sync metrics at 3m/15m/3h,
# 4 predicate thresholds 0.65/0.75, 6 priority weights 0.2/0.3/0.5,
# hotValue 5m/5 + 1m/2.
DEFAULT_POLICY = DynamicSchedulerPolicy(
    spec=PolicySpec(
        sync_period=(
            SyncPolicy("cpu_usage_avg_5m", 180.0),
            SyncPolicy("cpu_usage_max_avg_1h", 900.0),
            SyncPolicy("cpu_usage_max_avg_1d", 10800.0),
            SyncPolicy("mem_usage_avg_5m", 180.0),
            SyncPolicy("mem_usage_max_avg_1h", 900.0),
            SyncPolicy("mem_usage_max_avg_1d", 10800.0),
        ),
        predicate=(
            PredicatePolicy("cpu_usage_avg_5m", 0.65),
            PredicatePolicy("cpu_usage_max_avg_1h", 0.75),
            PredicatePolicy("mem_usage_avg_5m", 0.65),
            PredicatePolicy("mem_usage_max_avg_1h", 0.75),
        ),
        priority=(
            PriorityPolicy("cpu_usage_avg_5m", 0.2),
            PriorityPolicy("cpu_usage_max_avg_1h", 0.3),
            PriorityPolicy("cpu_usage_max_avg_1d", 0.5),
            PriorityPolicy("mem_usage_avg_5m", 0.2),
            PriorityPolicy("mem_usage_max_avg_1h", 0.3),
            PriorityPolicy("mem_usage_max_avg_1d", 0.5),
        ),
        hot_value=(
            HotValuePolicy(300.0, 5),
            HotValuePolicy(60.0, 2),
        ),
    )
)
