"""Seeded open-loop load generation and slow-client injection (ISSUE 13).

Closed-loop load (each client waits for its response before sending the
next request) self-throttles exactly when the server slows down, so it
can never reproduce congestion collapse. A storm is *open-loop*: the
arrival process does not negotiate. ``StormSchedule`` builds a seeded,
reproducible arrival timeline (Poisson within each rate phase) that two
independent harnesses consume:

- ``replay_admission`` — virtual-time replay: the arrivals run through
  a fresh ``AdmissionController`` state machine under an injected
  ``VirtualClock`` with a fixed service time. No sockets, no sleeps, no
  wall clock — the resulting admit/queue/shed timeline is a pure
  function of (schedule, controller parameters), which is what bench
  config 17's determinism gate compares across same-seed runs.
- ``run_open_loop`` — wire mode: fire the same arrivals as real HTTP
  POSTs against a live frontend, never waiting for one response before
  sending the next. Used by ``tools/overload_smoke.py`` and the storm
  tests to prove the IO-thread admission path sheds under real sockets.

``SlowClientSwarm`` is the slowloris injector: N connections that send
a partial request then stall, which is exactly the shape the frontend's
idle reaper must evict (a half-sent request must not pin a connection
slot forever).

Stdlib-only; nothing here imports the service package at module import
time (``replay_admission`` takes a controller factory).
"""

from __future__ import annotations

import heapq
import http.client
import random
import socket
import threading
import time
from dataclasses import dataclass
from typing import Callable, Iterable, List, Sequence, Tuple


@dataclass(frozen=True)
class Arrival:
    """One open-loop request: fires at ``t`` seconds from schedule
    start regardless of how earlier requests fared."""

    t: float
    tenant: str = "default"
    low_priority: bool = False
    deadline_ms: float | None = None


class StormSchedule:
    """A seeded open-loop arrival timeline with rate phases.

    ``phases`` is a sequence of ``(start_s, rps)`` pairs — e.g.
    ``[(0, 50), (2, 150), (6, 50)]`` is a 3x storm between t=2s and
    t=6s. Interarrivals inside a phase are exponential (Poisson
    process) from one seeded RNG, so the same seed always yields the
    same timeline, including tenant/priority assignment."""

    def __init__(
        self,
        seed: int,
        *,
        duration_s: float,
        phases: Sequence[Tuple[float, float]],
        tenants: Sequence[str] = ("default",),
        low_priority_frac: float = 0.0,
        deadline_ms: float | None = None,
    ):
        if not phases:
            raise ValueError("need at least one (start_s, rps) phase")
        self.seed = int(seed)
        self.duration_s = float(duration_s)
        self.phases = sorted((float(s), float(r)) for s, r in phases)
        self.tenants = tuple(tenants) or ("default",)
        self.low_priority_frac = float(low_priority_frac)
        self.deadline_ms = deadline_ms
        self.arrivals: List[Arrival] = self._build()

    @staticmethod
    def storm(
        seed: int,
        *,
        baseline_rps: float,
        storm_x: float = 3.0,
        warm_s: float = 1.0,
        storm_s: float = 3.0,
        cool_s: float = 1.0,
        **kw,
    ) -> "StormSchedule":
        """The canonical shape: warm at baseline, storm at
        ``storm_x * baseline``, cool back down."""
        return StormSchedule(
            seed,
            duration_s=warm_s + storm_s + cool_s,
            phases=[
                (0.0, baseline_rps),
                (warm_s, baseline_rps * storm_x),
                (warm_s + storm_s, baseline_rps),
            ],
            **kw,
        )

    def _rate_at(self, t: float) -> float:
        rate = self.phases[0][1]
        for start, rps in self.phases:
            if t >= start:
                rate = rps
            else:
                break
        return rate

    def _build(self) -> List[Arrival]:
        rng = random.Random(self.seed)
        arrivals: List[Arrival] = []
        t = 0.0
        while t < self.duration_s:
            rate = self._rate_at(t)
            if rate <= 0:
                # dead phase: jump to the next phase boundary
                nxt = [s for s, _ in self.phases if s > t]
                if not nxt:
                    break
                t = nxt[0]
                continue
            t += rng.expovariate(rate)
            if t >= self.duration_s:
                break
            tenant = self.tenants[rng.randrange(len(self.tenants))]
            low = rng.random() < self.low_priority_frac
            arrivals.append(
                Arrival(t, tenant, low, self.deadline_ms)
            )
        return arrivals

    def __len__(self) -> int:
        return len(self.arrivals)

    def __iter__(self):
        return iter(self.arrivals)


# -- virtual-time replay ----------------------------------------------------


class VirtualClock:
    """An injectable monotonic clock the replay advances by hand."""

    __slots__ = ("now",)

    def __init__(self, now: float = 0.0):
        self.now = float(now)

    def __call__(self) -> float:
        return self.now


def arrival_headers(a: Arrival) -> dict:
    """The wire headers an ``Arrival`` carries (lower-cased keys, the
    frontend's parse convention)."""
    headers = {"crane-tenant": a.tenant}
    if a.low_priority:
        headers["crane-priority"] = "low"
    if a.deadline_ms is not None:
        headers["crane-deadline-ms"] = f"{a.deadline_ms:.3f}"
    return headers


def replay_admission(
    arrivals: Iterable[Arrival],
    admission_factory: Callable[[Callable[[], float]], object],
    *,
    service_time_s: float = 0.01,
    target: str = "/score/batch",
) -> List[Tuple[float, str, str]]:
    """Run an arrival schedule through an admission state machine in
    virtual time. Returns the decision timeline: ``(t, event, tenant)``
    tuples where event is ``admit`` / ``queue`` / ``dequeue`` /
    ``shed:<reason>``, in event order.

    ``admission_factory(clock)`` must return a fresh AdmissionController
    (or compatible) built on the provided clock — fresh state per call
    is what makes same-seed replays bit-identical."""
    clock = VirtualClock()
    adm = admission_factory(clock)
    timeline: List[Tuple[float, str, str]] = []
    done_heap: List[Tuple[float, int, Arrival]] = []  # (t, seq, arrival)
    seq = 0
    it = iter(sorted(arrivals, key=lambda a: a.t))
    nxt = next(it, None)
    while nxt is not None or done_heap:
        take_done = done_heap and (
            nxt is None or done_heap[0][0] <= nxt.t
        )
        if take_done:
            t, _, fin = heapq.heappop(done_heap)
            clock.now = t
            adm.observe(service_time_s)
            handed = adm.finish()
            if handed is not None:
                timeline.append((t, "dequeue", handed.tenant))
                seq += 1
                heapq.heappush(done_heap, (t + service_time_s, seq, handed))
            continue
        a, nxt = nxt, next(it, None)
        clock.now = a.t
        decision = adm.classify("POST", target, arrival_headers(a), now=a.t)
        if decision is not None:
            adm.count_shed(decision.reason)
            timeline.append((a.t, f"shed:{decision.reason}", a.tenant))
        elif adm.acquire():
            timeline.append((a.t, "admit", a.tenant))
            seq += 1
            heapq.heappush(done_heap, (a.t + service_time_s, seq, a))
        elif adm.queue(a.tenant, a):
            timeline.append((a.t, "queue", a.tenant))
        else:
            adm.count_shed("queue_full")
            timeline.append((a.t, "shed:queue_full", a.tenant))
    return timeline


def timeline_counts(timeline: Sequence[Tuple[float, str, str]]) -> dict:
    """Event counts (``admit``/``queue``/``dequeue``/``shed:*`` keys)."""
    counts: dict = {}
    for _, event, _ in timeline:
        counts[event] = counts.get(event, 0) + 1
    return counts


# -- wire mode --------------------------------------------------------------


@dataclass
class WireResult:
    """One open-loop request's outcome on the wire."""

    t: float
    status: int  # 0 = transport error
    latency_s: float
    error: str | None = None


def run_open_loop(
    host: str,
    port: int,
    arrivals: Iterable[Arrival],
    *,
    target: str = "/score/batch",
    body: bytes = b"{}",
    body_fn: "Callable[[int, Arrival], bytes] | None" = None,
    time_scale: float = 1.0,
    timeout_s: float = 10.0,
) -> List[WireResult]:
    """Fire the schedule as real HTTP POSTs, one thread per in-flight
    request, never waiting for a response before the next send — the
    open-loop property. ``time_scale`` compresses the schedule (0.1 =
    10x faster than nominal). ``body_fn(index, arrival)`` builds a
    per-request body (e.g. a unique ``now`` to defeat the response
    cache so every accepted request costs a real render); when None,
    ``body`` is sent verbatim. Returns results in arrival order."""
    ordered = sorted(arrivals, key=lambda a: a.t)
    results: List[WireResult | None] = [None] * len(ordered)
    threads: List[threading.Thread] = []
    start = time.monotonic()

    def fire(i: int, a: Arrival) -> None:
        sent = time.monotonic()
        try:
            conn = http.client.HTTPConnection(host, port, timeout=timeout_s)
            try:
                headers = dict(arrival_headers(a))
                headers["Content-Type"] = "application/json"
                payload = body_fn(i, a) if body_fn is not None else body
                conn.request("POST", target, body=payload, headers=headers)
                resp = conn.getresponse()
                resp.read()
                results[i] = WireResult(
                    a.t, resp.status, time.monotonic() - sent
                )
            finally:
                conn.close()
        except Exception as exc:  # noqa: BLE001 — outcome, not failure
            results[i] = WireResult(
                a.t, 0, time.monotonic() - sent, error=repr(exc)
            )

    for i, a in enumerate(ordered):
        delay = start + a.t * time_scale - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        th = threading.Thread(target=fire, args=(i, a), daemon=True)
        th.start()
        threads.append(th)
    for th in threads:
        th.join(timeout_s)
    return [
        r if r is not None else WireResult(ordered[i].t, 0, 0.0, "no result")
        for i, r in enumerate(results)
    ]


# -- slowloris --------------------------------------------------------------


class SlowClientSwarm:
    """N connections that send a partial request then stall — the
    attack shape the frontend's idle reaper must break. The preamble
    advertises a Content-Length that never arrives, so the server's
    parser (correctly) keeps waiting; only the idle timeout can free
    the slot."""

    PREAMBLE = (
        b"POST /score/batch HTTP/1.1\r\n"
        b"Host: storm\r\n"
        b"Content-Type: application/json\r\n"
        b"Content-Length: 1048576\r\n"
        b"\r\n"
        b'{"partial'
    )

    def __init__(self, host: str, port: int, count: int = 4,
                 connect_timeout_s: float = 5.0):
        self.socks: List[socket.socket] = []
        for _ in range(max(1, int(count))):
            s = socket.create_connection((host, port), connect_timeout_s)
            s.sendall(self.PREAMBLE)
            s.setblocking(False)
            self.socks.append(s)

    def poll_closed(self) -> int:
        """How many of the stalled connections the server has closed
        (recv returning b'' / a reset). Non-blocking."""
        closed = 0
        for s in self.socks:
            try:
                data = s.recv(4096)
                if data == b"":
                    closed += 1
                # a response (408/timeout close) followed by FIN also
                # counts once the FIN lands on a later poll
            except BlockingIOError:
                pass
            except OSError:
                closed += 1
        return closed

    def wait_closed(self, count: int, timeout_s: float = 10.0,
                    poll_s: float = 0.05) -> int:
        """Poll until >= ``count`` connections are server-closed or the
        timeout lapses; returns the final closed count."""
        deadline = time.monotonic() + timeout_s
        closed = self.poll_closed()
        while closed < count and time.monotonic() < deadline:
            time.sleep(poll_s)
            closed = self.poll_closed()
        return closed

    def close(self) -> None:
        for s in self.socks:
            try:
                s.close()
            except OSError:
                pass
        self.socks = []

    def __enter__(self) -> "SlowClientSwarm":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
