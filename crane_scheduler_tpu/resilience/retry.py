"""Bounded retries: full-jitter exponential backoff under a deadline.

Replaces ad-hoc single-attempt call sites (one ``urllib`` attempt in
``PrometheusClient`` used to fail an entire annotator sync cycle).
Design points:

- **Full jitter** (AWS architecture-blog style): sleep is uniform in
  ``[0, min(max_delay, base * 2**attempt))`` — decorrelates retry
  storms from many annotator replicas hitting the same Prometheus.
- **Deadline budget**: the whole call (attempts + sleeps) must fit in
  ``deadline_s``; a retry that could not complete before the deadline
  is not attempted. Keeps sync cycles bounded during outages.
- **Retry-After awareness**: if the raised exception carries a
  ``retry_after_s`` attribute (429/503 with a Retry-After header, or a
  ``BreakerOpenError``), it floors the next sleep.
- Deterministic under test: RNG is a seeded ``random.Random`` and both
  ``sleep`` and ``clock`` are injectable.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Optional, Tuple, Type


class RetryBudgetExceeded(Exception):
    """All attempts failed (or the deadline expired). ``last`` holds the
    final underlying exception."""

    def __init__(self, attempts: int, last: Exception):
        super().__init__(
            f"retries exhausted after {attempts} attempt(s): {last!r}"
        )
        self.attempts = attempts
        self.last = last


class RetryPolicy:
    def __init__(
        self,
        *,
        max_attempts: int = 3,
        base_delay_s: float = 0.1,
        max_delay_s: float = 5.0,
        deadline_s: float = 30.0,
        retryable: Tuple[Type[BaseException], ...] = (Exception,),
        seed: Optional[int] = None,
        sleep: Callable[[float], None] = time.sleep,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.max_attempts = max(1, int(max_attempts))
        self.base_delay_s = float(base_delay_s)
        self.max_delay_s = float(max_delay_s)
        self.deadline_s = float(deadline_s)
        self.retryable = retryable
        self._rng = random.Random(seed)
        self._sleep = sleep
        self._clock = clock

    def backoff_s(self, attempt: int, retry_after_s: float = 0.0) -> float:
        """Sleep before attempt ``attempt+1`` (attempt is 0-based).

        A server Retry-After is a FLOOR, not a schedule: sleeping
        exactly the advertised value re-synchronizes every client a
        mass-shed event turned away — they all come back in the same
        instant and shed again (ISSUE 13). The jitter is added ON TOP
        of the floor, so the server's minimum is always honored and
        the retry wave spreads across a full jitter window."""
        cap = min(self.max_delay_s, self.base_delay_s * (2.0**attempt))
        jittered = self._rng.uniform(0.0, cap)
        if retry_after_s > 0.0:
            return retry_after_s + jittered
        return jittered

    def call(self, fn, *args, on_retry=None, **kwargs):
        """Run ``fn`` with bounded retries. Non-retryable exceptions
        propagate immediately; exhaustion raises ``RetryBudgetExceeded``."""
        start = self._clock()
        last: Optional[Exception] = None
        for attempt in range(self.max_attempts):
            try:
                return fn(*args, **kwargs)
            except self.retryable as exc:  # noqa: PERF203
                last = exc
                if attempt + 1 >= self.max_attempts:
                    break
                retry_after = float(getattr(exc, "retry_after_s", 0.0) or 0.0)
                delay = self.backoff_s(attempt, retry_after)
                elapsed = self._clock() - start
                if elapsed + delay >= self.deadline_s:
                    break
                if on_retry is not None:
                    try:
                        on_retry(attempt, exc, delay)
                    except Exception:
                        pass
                if delay > 0:
                    self._sleep(delay)
        raise RetryBudgetExceeded(
            min(attempt + 1, self.max_attempts), last  # noqa: F821
        )
