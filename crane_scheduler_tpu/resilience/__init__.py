"""Shared fault-domain resilience layer (ISSUE 8).

The reference decouples the metrics sync path from the scheduling hot
path through node annotations, so the system's real failure modes are
*partial*: Prometheus down but the apiserver fine, annotations stale
cluster-wide while binds still flow, 429/5xx storms against live
eviction budgets. Before this layer each component handled its own
slice ad hoc (per-node fail-open staleness in the oracle, workqueue
backoff in the annotator, indeterminate-response discipline in the
write path); nothing reasoned about a fault domain as a whole.

Four pieces, shared by every component:

- ``CircuitBreaker`` — closed/open/half-open over a sliding failure
  window, one instance per fault target (``prometheus``, ``kube-read``,
  ``kube-write``, ``device-dispatch``);
- ``RetryPolicy`` — full-jitter exponential backoff with per-call
  deadline budgets and ``Retry-After`` awareness;
- ``HealthRegistry`` — aggregates component states
  (healthy/degraded/failed, with reasons), served on ``/healthz`` and
  exported as ``crane_health_state{component}`` gauges;
- ``DegradedModeController`` — cluster-wide staleness tracker over the
  ``value,timestamp`` annotations with enter/exit hysteresis; while
  active the Dynamic plugin serves resource-fit + spread-only scores
  and the descheduler hard-suspends evictions.

``chaos`` holds the deterministic seeded ``ChaosPlan`` harness that
drives the kube/prometheus stubs to prove the above under injected
faults (tests/test_chaos.py, tools/chaos_smoke.py, bench config 12).

``loadgen`` (ISSUE 13) is the serving-plane counterpart of ``chaos``:
a seeded open-loop ``StormSchedule`` (arrival timelines that do not
negotiate with a slowing server), ``replay_admission`` for virtual-time
deterministic replays of the admission state machine, ``run_open_loop``
for firing the same schedule on real sockets, and ``SlowClientSwarm``
as the slowloris injector the frontend's idle reaper must defeat.

``recovery`` (ISSUE 12) extends resilience from remote faults to the
process's own death: the crash-safe placement-intent journal
(``IntentJournal``), restart reconciliation (``Reconciler``), and the
warm-standby failover coordinator (``WarmStandby``), with
``KillSwitch`` as the deterministic SIGKILL-at-offset injector.
"""

from .breaker import BreakerOpenError, BreakerState, CircuitBreaker
from .chaos import ChaosEvent, ChaosPlan
from .degraded import DegradedModeController
from .health import HealthRegistry, HealthState
from .loadgen import (
    Arrival,
    SlowClientSwarm,
    StormSchedule,
    VirtualClock,
    WireResult,
    replay_admission,
    run_open_loop,
    timeline_counts,
)
from .recovery import (
    IntentJournal,
    JournalReplay,
    KillSwitch,
    ReconcileReport,
    Reconciler,
    SimulatedCrash,
    WarmStandby,
    replay_journal,
)
from .retry import RetryBudgetExceeded, RetryPolicy

__all__ = [
    "Arrival",
    "BreakerOpenError",
    "BreakerState",
    "CircuitBreaker",
    "ChaosEvent",
    "ChaosPlan",
    "DegradedModeController",
    "HealthRegistry",
    "HealthState",
    "IntentJournal",
    "JournalReplay",
    "KillSwitch",
    "ReconcileReport",
    "Reconciler",
    "RetryBudgetExceeded",
    "RetryPolicy",
    "SimulatedCrash",
    "SlowClientSwarm",
    "StormSchedule",
    "VirtualClock",
    "WarmStandby",
    "WireResult",
    "replay_admission",
    "replay_journal",
    "run_open_loop",
    "timeline_counts",
]
