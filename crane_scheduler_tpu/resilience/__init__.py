"""Shared fault-domain resilience layer (ISSUE 8).

The reference decouples the metrics sync path from the scheduling hot
path through node annotations, so the system's real failure modes are
*partial*: Prometheus down but the apiserver fine, annotations stale
cluster-wide while binds still flow, 429/5xx storms against live
eviction budgets. Before this layer each component handled its own
slice ad hoc (per-node fail-open staleness in the oracle, workqueue
backoff in the annotator, indeterminate-response discipline in the
write path); nothing reasoned about a fault domain as a whole.

Four pieces, shared by every component:

- ``CircuitBreaker`` — closed/open/half-open over a sliding failure
  window, one instance per fault target (``prometheus``, ``kube-read``,
  ``kube-write``, ``device-dispatch``);
- ``RetryPolicy`` — full-jitter exponential backoff with per-call
  deadline budgets and ``Retry-After`` awareness;
- ``HealthRegistry`` — aggregates component states
  (healthy/degraded/failed, with reasons), served on ``/healthz`` and
  exported as ``crane_health_state{component}`` gauges;
- ``DegradedModeController`` — cluster-wide staleness tracker over the
  ``value,timestamp`` annotations with enter/exit hysteresis; while
  active the Dynamic plugin serves resource-fit + spread-only scores
  and the descheduler hard-suspends evictions.

``chaos`` holds the deterministic seeded ``ChaosPlan`` harness that
drives the kube/prometheus stubs to prove the above under injected
faults (tests/test_chaos.py, tools/chaos_smoke.py, bench config 12).

``recovery`` (ISSUE 12) extends resilience from remote faults to the
process's own death: the crash-safe placement-intent journal
(``IntentJournal``), restart reconciliation (``Reconciler``), and the
warm-standby failover coordinator (``WarmStandby``), with
``KillSwitch`` as the deterministic SIGKILL-at-offset injector.
"""

from .breaker import BreakerOpenError, BreakerState, CircuitBreaker
from .chaos import ChaosEvent, ChaosPlan
from .degraded import DegradedModeController
from .health import HealthRegistry, HealthState
from .recovery import (
    IntentJournal,
    JournalReplay,
    KillSwitch,
    ReconcileReport,
    Reconciler,
    SimulatedCrash,
    WarmStandby,
    replay_journal,
)
from .retry import RetryBudgetExceeded, RetryPolicy

__all__ = [
    "BreakerOpenError",
    "BreakerState",
    "CircuitBreaker",
    "ChaosEvent",
    "ChaosPlan",
    "DegradedModeController",
    "HealthRegistry",
    "HealthState",
    "IntentJournal",
    "JournalReplay",
    "KillSwitch",
    "ReconcileReport",
    "Reconciler",
    "RetryBudgetExceeded",
    "RetryPolicy",
    "SimulatedCrash",
    "WarmStandby",
    "replay_journal",
]
