"""Cluster-wide degraded-mode controller.

The oracle already fails open *per node*: a stale ``value,timestamp``
annotation just contributes the neutral score. But when the annotator
(or Prometheus) is down cluster-wide, *every* node silently degrades
to neutral — load-aware scoring becomes noise with no signal, no
hysteresis, and no safety interlock on the descheduler, which would
happily evict on stale load data.

This controller tracks the stale fraction across the node set using
the oracle's exact staleness semantics (``get_active_duration`` +
``in_active_period``: strict ``now < ts + active_duration``) and flips
one explicit mode bit with enter/exit hysteresis:

- **enter** degraded when stale_fraction > ``enter_fraction``;
- **exit** when stale_fraction < ``exit_fraction`` (< enter_fraction,
  so a cluster hovering at the threshold doesn't flap).

While degraded:

- the Dynamic plugin switches from load-aware scoring to
  resource-fit + spread-only scoring (one mode transition, not
  per-node neutral drift);
- the descheduler hard-suspends evictions (the one unsafe action in
  the system on stale data).

Telemetry: ``crane_degraded_mode`` gauge (0/1),
``crane_degraded_stale_fraction`` gauge, and
``crane_degraded_transitions_total{to}`` counter. When a
``HealthRegistry`` is attached the ``annotations`` component flips
degraded/healthy with the mode.
"""

from __future__ import annotations

import threading
from typing import Callable, Iterable, Optional, Tuple

from ..policy.types import PolicySpec
from ..scorer.oracle import get_active_duration, in_active_period
from .health import HealthState


class DegradedModeController:
    def __init__(
        self,
        spec: PolicySpec,
        *,
        enter_fraction: float = 0.5,
        exit_fraction: float = 0.25,
        min_nodes: int = 1,
        min_eval_interval_s: float = 5.0,
        telemetry=None,
        health=None,
        health_component: str = "annotations",
        on_transition: Optional[Callable[[bool, float], None]] = None,
    ):
        if not (0.0 <= exit_fraction <= enter_fraction <= 1.0):
            raise ValueError(
                "need 0 <= exit_fraction <= enter_fraction <= 1, got "
                f"exit={exit_fraction} enter={enter_fraction}"
            )
        self.spec = spec
        self.enter_fraction = float(enter_fraction)
        self.exit_fraction = float(exit_fraction)
        self.min_nodes = max(1, int(min_nodes))
        self.min_eval_interval_s = float(min_eval_interval_s)
        self._health = health
        self._health_component = health_component
        self._on_transition = on_transition
        self._lock = threading.Lock()
        self._active = False
        self._stale_fraction = 0.0
        self._last_eval_at = float("-inf")

        # metric names with a nonzero sync period: the ones the oracle
        # would actually read. A node is fresh iff at least one of them
        # carries a valid in-active-period annotation.
        self._tracked: Tuple[Tuple[str, float], ...] = tuple(
            (sp.name, get_active_duration(spec.sync_period, sp.name))
            for sp in spec.sync_period
            if sp.period_seconds != 0
        )

        self._m_mode = None
        if telemetry is not None:
            reg = telemetry.registry
            self._m_mode = reg.gauge(
                "crane_degraded_mode",
                "Cluster-wide degraded scheduling mode (0 off, 1 on)",
            )
            self._m_fraction = reg.gauge(
                "crane_degraded_stale_fraction",
                "Fraction of nodes with no fresh load annotation",
            )
            self._m_transitions = reg.counter(
                "crane_degraded_transitions_total",
                "Degraded-mode transitions",
                ("to",),
            )
            self._m_mode.set(0)

    # -- reads -------------------------------------------------------------

    @property
    def active(self) -> bool:
        with self._lock:
            return self._active

    @property
    def stale_fraction(self) -> float:
        with self._lock:
            return self._stale_fraction

    # -- staleness classification -----------------------------------------

    def node_is_stale(self, anno: Optional[dict], now: float) -> bool:
        """True when no tracked metric annotation would pass the oracle's
        active-period check (same semantics the score path applies)."""
        if not self._tracked:
            return False  # no sync policy => nothing can be stale
        if not anno:
            return True
        for name, active_duration in self._tracked:
            raw = anno.get(name)
            if raw is None:
                continue
            parts = raw.split(",")
            if len(parts) != 2:
                continue
            if in_active_period(parts[1], active_duration, now):
                return False
        return True

    # -- evaluation --------------------------------------------------------

    def update(
        self, annotations: Iterable[Optional[dict]], now: float
    ) -> bool:
        """Re-evaluate the stale fraction over one annotation sweep and
        apply hysteresis. Returns the (possibly new) mode."""
        total = 0
        stale = 0
        for anno in annotations:
            total += 1
            if self.node_is_stale(anno, now):
                stale += 1
        with self._lock:
            self._last_eval_at = now
            if total < self.min_nodes:
                # too few nodes to call a cluster-wide verdict; hold mode
                return self._active
            fraction = stale / total
            self._stale_fraction = fraction
            if self._m_mode is not None:
                self._m_fraction.set(fraction)
            if not self._active and fraction > self.enter_fraction:
                self._set_active(True, fraction)
            elif self._active and fraction < self.exit_fraction:
                self._set_active(False, fraction)
            return self._active

    def maybe_update(
        self, annotations_fn: Callable[[], Iterable[Optional[dict]]], now: float
    ) -> bool:
        """Throttled ``update`` for hot paths: re-evaluates at most every
        ``min_eval_interval_s``; otherwise returns the cached mode."""
        with self._lock:
            if now - self._last_eval_at < self.min_eval_interval_s:
                return self._active
        return self.update(annotations_fn(), now)

    # -- internals ---------------------------------------------------------

    def _set_active(self, active: bool, fraction: float) -> None:
        # caller holds self._lock
        self._active = active
        if self._m_mode is not None:
            self._m_mode.set(1 if active else 0)
            self._m_transitions.labels(
                to="degraded" if active else "healthy"
            ).inc()
        if self._health is not None:
            if active:
                self._health.set(
                    self._health_component,
                    HealthState.DEGRADED,
                    f"{fraction:.0%} of nodes stale; fit+spread scoring",
                )
            else:
                self._health.set(self._health_component, HealthState.HEALTHY)
        cb = self._on_transition
        if cb is not None:
            try:
                cb(active, fraction)
            except Exception:
                pass
