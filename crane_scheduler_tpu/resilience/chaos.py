"""Deterministic, seeded chaos plans.

A ``ChaosPlan`` is a reproducible fault timeline: a sorted list of
``ChaosEvent``\\ s, each tagged with the simulation step at which it
fires. The plan itself is pure data — it knows nothing about the kube
or Prometheus stubs. The driver (tests/test_chaos.py, the chaos smoke
tool, bench config 12) registers one *applier* callable per event kind
and calls ``apply(step, appliers)`` at each step boundary.

Event kinds the harness understands (appliers may support a subset;
unknown kinds raise so a typo'd plan fails loudly):

- ``prom_outage`` / ``prom_heal``     — Prometheus hard down / back up
- ``prom_storm(count, status)``       — N responses of 429/5xx
- ``prom_slow(delay_s)``              — slow responses
- ``kube_read_storm(count, status)``  — LIST/GET fault burst
- ``kube_write_storm(count, status)`` — PATCH/POST fault burst
- ``kube_slow(delay_s)``              — slow apiserver responses
- ``torn_watch(count)``               — watch frames torn mid-line
- ``close_watches``                   — all watch streams dropped
- ``watch_410(after)``                — watch resumes answered 410 Gone
- ``skew_annotations(offset_s)``      — node stamps written clock-skewed
- ``request_storm(rate_x, duration)`` — open-loop serving storm at
  ``rate_x`` times baseline capacity for ``duration`` steps (ISSUE 13;
  the applier points at a ``StormSchedule``/load driver, not a stub)
- ``slow_client(count, stall_s)``     — slowloris: N connections that
  send a partial request then stall, pinning frontend conn slots

``ChaosPlan.generate(seed, ...)`` builds a randomized-but-reproducible
plan: every fault event is paired with a heal inside the horizon, so
any seed converges by construction and the invariants (no duplicate
binds/evictions, zero evictions while degraded, mirror converges after
heal) are checkable for all of them.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Mapping, Tuple


@dataclass(frozen=True)
class ChaosEvent:
    at_step: int
    kind: str
    params: Tuple[Tuple[str, object], ...] = ()

    def param(self, name: str, default=None):
        for k, v in self.params:
            if k == name:
                return v
        return default

    @staticmethod
    def make(at_step: int, kind: str, **params) -> "ChaosEvent":
        return ChaosEvent(at_step, kind, tuple(sorted(params.items())))


# fault kinds generate() may emit, with their paired heal (None = the
# fault is a self-clearing burst and needs no heal event)
_FAULT_KINDS: Tuple[Tuple[str, object], ...] = (
    ("prom_outage", "prom_heal"),
    ("prom_storm", None),
    ("prom_slow", "prom_heal"),
    ("kube_read_storm", None),
    ("kube_write_storm", None),
    ("torn_watch", None),
    ("close_watches", None),
    ("watch_410", None),
    ("skew_annotations", None),
    # crash-safety (PR 12): kill the scheduler process mid-write — the
    # param is a placement-intent-journal byte offset for the recovery
    # harness's KillSwitch (SIGKILL-at-offset), restart_process is the
    # paired heal (reconcile-then-reopen). Process-level, not a wire
    # fault: only emitted when the caller opts in via kinds=, so plans
    # generated for wire-stub drivers never require a kill applier.
    ("kill_process", "restart_process"),
    # overload (ISSUE 13): serving-plane faults — an open-loop request
    # storm and slowloris clients. Opt-in like kill_process: they need
    # a serving frontend to point at, which the wire-stub drivers for
    # the kube/prom kinds don't have.
    ("request_storm", "storm_heal"),
    ("slow_client", None),
)

_OPT_IN_KINDS = frozenset({"kill_process", "request_storm", "slow_client"})


@dataclass
class ChaosPlan:
    seed: int
    steps: int
    events: List[ChaosEvent] = field(default_factory=list)

    def add(self, at_step: int, kind: str, **params) -> "ChaosPlan":
        self.events.append(ChaosEvent.make(at_step, kind, **params))
        self.events.sort(key=lambda e: e.at_step)
        return self

    def events_at(self, step: int) -> List[ChaosEvent]:
        return [e for e in self.events if e.at_step == step]

    def apply(
        self,
        step: int,
        appliers: Mapping[str, Callable[[ChaosEvent], None]],
    ) -> List[ChaosEvent]:
        """Fire every event scheduled for ``step``. Returns those fired."""
        fired = self.events_at(step)
        for event in fired:
            applier = appliers.get(event.kind)
            if applier is None:
                raise KeyError(
                    f"no applier registered for chaos kind {event.kind!r}"
                )
            applier(event)
        return fired

    def last_fault_step(self) -> int:
        """Step of the last fault/heal event — recovery is measured from
        here (everything after is the heal window)."""
        return max((e.at_step for e in self.events), default=0)

    def describe(self) -> str:
        lines = [f"ChaosPlan(seed={self.seed}, steps={self.steps})"]
        for e in self.events:
            kv = " ".join(f"{k}={v}" for k, v in e.params)
            lines.append(f"  step {e.at_step:4d}: {e.kind} {kv}".rstrip())
        return "\n".join(lines)

    @staticmethod
    def generate(
        seed: int,
        steps: int = 60,
        n_faults: int = 4,
        kinds: Tuple[str, ...] | None = None,
        quiet_tail: int = 10,
    ) -> "ChaosPlan":
        """A reproducible random plan: ``n_faults`` faults in the first
        ``steps - quiet_tail`` steps, every heal-paired fault healed
        before the tail so the plan converges by construction."""
        rng = random.Random(seed)
        plan = ChaosPlan(seed=seed, steps=steps)
        fault_horizon = max(1, steps - quiet_tail)
        if kinds is not None:
            wanted = set(kinds)
        else:
            wanted = {k for k, _ in _FAULT_KINDS} - _OPT_IN_KINDS
        pool = [(k, heal) for k, heal in _FAULT_KINDS if k in wanted]
        if not pool:
            raise ValueError(f"no chaos kinds match {kinds!r}")
        for _ in range(n_faults):
            kind, heal = pool[rng.randrange(len(pool))]
            at = rng.randrange(0, fault_horizon)
            params: Dict[str, object] = {}
            if kind in ("prom_storm", "kube_read_storm", "kube_write_storm"):
                params["count"] = rng.randint(2, 8)
                params["status"] = rng.choice((429, 500, 502, 503))
            elif kind in ("prom_slow",):
                params["delay_s"] = round(rng.uniform(0.05, 0.3), 3)
            elif kind == "torn_watch":
                params["count"] = rng.randint(1, 4)
            elif kind == "watch_410":
                params["after"] = rng.randint(1, 3)
            elif kind == "skew_annotations":
                # skew far enough that stamps look expired to the oracle
                params["offset_s"] = rng.choice((-3600.0, -7200.0))
            elif kind == "request_storm":
                # rate multiplier vs. baseline capacity; duration in
                # steps (the paired storm_heal marks the calm point,
                # the burst itself ends after ``duration``)
                params["rate_x"] = rng.choice((2.0, 3.0, 5.0))
                params["duration"] = rng.randint(3, 10)
            elif kind == "slow_client":
                params["count"] = rng.randint(2, 8)
                params["stall_s"] = round(rng.uniform(1.0, 10.0), 3)
            elif kind == "kill_process":
                # absolute journal byte offset for the KillSwitch: any
                # offset is legal (the crash-safety contract is "kill at
                # ANY byte"), so sample widely across a small journal
                params["offset"] = rng.randrange(1, 4096)
            plan.add(at, kind, **params)
            if heal is not None:
                heal_at = rng.randrange(at + 1, fault_horizon + 1)
                plan.add(heal_at, heal)
        if any(e.kind == "skew_annotations" for e in plan.events):
            # skew is healed by the next honest annotation sweep; mark an
            # explicit heal point so recovery measurement has an anchor
            last = max(
                e.at_step
                for e in plan.events
                if e.kind == "skew_annotations"
            )
            plan.add(min(fault_horizon, last + 1), "skew_heal")
        return plan
