"""Health registry: one place that knows how degraded the process is.

Components report ``healthy`` / ``degraded`` / ``failed`` with a
reason; the registry aggregates (overall = worst component) and is
served on every ``/healthz`` plus exported as
``crane_health_state{component}`` gauges (0 healthy / 1 degraded /
2 failed).

A breaker can be bound to a component with ``watch_breaker`` so its
open/half-open/closed transitions flip health automatically:
open -> degraded ("fail-open on <target>"), closed -> healthy.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional


class HealthState:
    HEALTHY = "healthy"
    DEGRADED = "degraded"
    FAILED = "failed"


_STATE_CODE = {
    HealthState.HEALTHY: 0,
    HealthState.DEGRADED: 1,
    HealthState.FAILED: 2,
}
_STATE_RANK = _STATE_CODE  # worst-of aggregation uses the same order


class HealthRegistry:
    def __init__(self, telemetry=None):
        self._lock = threading.Lock()
        self._components: Dict[str, tuple[str, str]] = {}
        self._m_state = None
        if telemetry is not None:
            self._m_state = telemetry.registry.gauge(
                "crane_health_state",
                "Component health (0 healthy, 1 degraded, 2 failed)",
                ("component",),
            )

    def set(
        self, component: str, state: str, reason: str = ""
    ) -> None:
        if state not in _STATE_CODE:
            raise ValueError(f"unknown health state {state!r}")
        with self._lock:
            self._components[component] = (state, reason)
        if self._m_state is not None:
            self._m_state.labels(component=component).set(_STATE_CODE[state])

    def get(self, component: str) -> Optional[tuple[str, str]]:
        with self._lock:
            return self._components.get(component)

    def overall(self) -> str:
        with self._lock:
            if not self._components:
                return HealthState.HEALTHY
            return max(
                (s for s, _ in self._components.values()),
                key=_STATE_RANK.__getitem__,
            )

    def snapshot(self) -> dict:
        """The ``/healthz`` payload."""
        with self._lock:
            components = {
                name: {"state": state, "reason": reason}
                for name, (state, reason) in sorted(self._components.items())
            }
        if not components:
            overall = HealthState.HEALTHY
        else:
            overall = max(
                (c["state"] for c in components.values()),
                key=_STATE_RANK.__getitem__,
            )
        return {"status": overall, "components": components}

    def watch_breaker(
        self, breaker, component: Optional[str] = None
    ) -> Callable[[str, str], None]:
        """Bind ``breaker`` transitions to ``component`` health. Installs
        (and returns) the transition callback; chains any callback the
        breaker already had."""
        name = component or breaker.target
        self.set(name, HealthState.HEALTHY)
        prev = getattr(breaker, "_on_transition", None)

        def _on_transition(target: str, to: str) -> None:
            if to == "open":
                self.set(
                    name, HealthState.DEGRADED, f"breaker open on {target}"
                )
            elif to == "half-open":
                self.set(
                    name, HealthState.DEGRADED, f"probing {target}"
                )
            else:
                self.set(name, HealthState.HEALTHY)
            if prev is not None:
                prev(target, to)

        breaker._on_transition = _on_transition
        return _on_transition
