"""Circuit breaker: closed / open / half-open over a sliding window.

One instance per fault *target* (prometheus, kube-read, kube-write,
device-dispatch). The breaker never raises from ``record_*`` and is
safe to consult from hot paths: ``allow()`` is a couple of comparisons
under a lock.

State machine:

- **closed** — requests flow; failures land in a sliding time window.
  When the window holds >= ``failure_threshold`` failures AND at least
  ``min_calls`` total calls, trip to open.
- **open** — requests are rejected (``allow()`` False / ``call()``
  raises ``BreakerOpenError``) until ``reset_timeout_s`` elapses, then
  the next ``allow()`` transitions to half-open and admits it as the
  probe.
- **half-open** — up to ``half_open_max_probes`` in-flight probes are
  admitted; one success closes the breaker and clears the window, one
  failure re-opens it and restarts the timer.

Telemetry (all gated on a live registry): ``crane_breaker_state{target}``
gauge (0 closed / 1 half-open / 2 open), ``crane_breaker_transitions_total
{target,to}`` and ``crane_breaker_rejected_total{target}`` counters.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Optional


class BreakerState:
    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half-open"


# gauge encoding for crane_breaker_state
_STATE_CODE = {
    BreakerState.CLOSED: 0,
    BreakerState.HALF_OPEN: 1,
    BreakerState.OPEN: 2,
}


class BreakerOpenError(Exception):
    """Raised by ``call()`` when the breaker rejects the request."""

    def __init__(self, target: str, retry_after_s: float = 0.0):
        super().__init__(f"circuit breaker open for {target!r}")
        self.target = target
        self.retry_after_s = retry_after_s


class CircuitBreaker:
    def __init__(
        self,
        target: str,
        *,
        failure_threshold: int = 5,
        window_s: float = 30.0,
        reset_timeout_s: float = 15.0,
        min_calls: int = 1,
        half_open_max_probes: int = 1,
        clock: Callable[[], float] = time.monotonic,
        telemetry=None,
        on_transition: Optional[Callable[[str, str], None]] = None,
    ):
        self.target = target
        self.failure_threshold = max(1, int(failure_threshold))
        self.window_s = float(window_s)
        self.reset_timeout_s = float(reset_timeout_s)
        self.min_calls = max(1, int(min_calls))
        self.half_open_max_probes = max(1, int(half_open_max_probes))
        self._clock = clock
        self._on_transition = on_transition
        self._lock = threading.Lock()
        self._state = BreakerState.CLOSED
        self._failures: deque[float] = deque()  # failure timestamps
        self._calls: deque[float] = deque()  # all call timestamps
        self._opened_at = 0.0
        self._probes_in_flight = 0

        self._m_state = None
        self._m_transitions = None
        self._m_rejected = None
        if telemetry is not None:
            reg = telemetry.registry
            self._m_state = reg.gauge(
                "crane_breaker_state",
                "Circuit breaker state (0 closed, 1 half-open, 2 open)",
                ("target",),
            )
            self._m_transitions = reg.counter(
                "crane_breaker_transitions_total",
                "Circuit breaker state transitions",
                ("target", "to"),
            )
            self._m_rejected = reg.counter(
                "crane_breaker_rejected_total",
                "Requests rejected by an open circuit breaker",
                ("target",),
            )
            self._m_state.labels(target=target).set(0)

    # -- state inspection ------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            return self._effective_state(self._clock())

    def _effective_state(self, now: float) -> str:
        # open -> half-open is a lazy transition evaluated on read, so a
        # sleeping process doesn't need a timer thread to recover.
        if (
            self._state == BreakerState.OPEN
            and now - self._opened_at >= self.reset_timeout_s
        ):
            self._transition(BreakerState.HALF_OPEN)
        return self._state

    # -- admission -------------------------------------------------------

    def allow(self) -> bool:
        """Admit or reject one request. Must be paired with exactly one
        ``record_success``/``record_failure`` when admitted."""
        with self._lock:
            now = self._clock()
            state = self._effective_state(now)
            if state == BreakerState.CLOSED:
                return True
            if state == BreakerState.HALF_OPEN:
                if self._probes_in_flight < self.half_open_max_probes:
                    self._probes_in_flight += 1
                    return True
                if self._m_rejected is not None:
                    self._m_rejected.labels(target=self.target).inc()
                return False
            if self._m_rejected is not None:
                self._m_rejected.labels(target=self.target).inc()
            return False

    def retry_after_s(self) -> float:
        """Seconds until the breaker would admit a probe (0 if now)."""
        with self._lock:
            if self._state != BreakerState.OPEN:
                return 0.0
            return max(
                0.0, self.reset_timeout_s - (self._clock() - self._opened_at)
            )

    # -- outcome recording -----------------------------------------------

    def record_success(self) -> None:
        with self._lock:
            now = self._clock()
            state = self._effective_state(now)
            if state == BreakerState.HALF_OPEN:
                self._probes_in_flight = max(0, self._probes_in_flight - 1)
                self._failures.clear()
                self._calls.clear()
                self._transition(BreakerState.CLOSED)
                return
            self._calls.append(now)
            self._prune(now)

    def record_failure(self) -> None:
        with self._lock:
            now = self._clock()
            state = self._effective_state(now)
            if state == BreakerState.HALF_OPEN:
                self._probes_in_flight = max(0, self._probes_in_flight - 1)
                self._opened_at = now
                self._transition(BreakerState.OPEN)
                return
            if state == BreakerState.OPEN:
                return
            self._calls.append(now)
            self._failures.append(now)
            self._prune(now)
            if (
                len(self._failures) >= self.failure_threshold
                and len(self._calls) >= self.min_calls
            ):
                self._opened_at = now
                self._transition(BreakerState.OPEN)

    # -- convenience wrapper ----------------------------------------------

    def call(self, fn, *args, **kwargs):
        """Run ``fn`` under the breaker; raises ``BreakerOpenError`` when
        rejected, records the outcome otherwise and re-raises failures."""
        if not self.allow():
            raise BreakerOpenError(self.target, self.retry_after_s())
        try:
            result = fn(*args, **kwargs)
        except Exception:
            self.record_failure()
            raise
        self.record_success()
        return result

    # -- internals --------------------------------------------------------

    def _prune(self, now: float) -> None:
        horizon = now - self.window_s
        while self._failures and self._failures[0] < horizon:
            self._failures.popleft()
        while self._calls and self._calls[0] < horizon:
            self._calls.popleft()

    def _transition(self, to: str) -> None:
        # caller holds self._lock
        if self._state == to:
            return
        self._state = to
        if to != BreakerState.HALF_OPEN:
            self._probes_in_flight = 0
        if self._m_state is not None:
            self._m_state.labels(target=self.target).set(_STATE_CODE[to])
            self._m_transitions.labels(target=self.target, to=to).inc()
        cb = self._on_transition
        if cb is not None:
            try:
                cb(self.target, to)
            except Exception:
                pass
