"""Crash-safe placement plane (ISSUE 12): bind-intent journal, restart
reconciliation, and warm-standby scheduler failover.

PR 8 made the system resilient to *remote* failures; this layer makes
every process survivable to its *own* death. Three pieces:

- ``IntentJournal`` — a durable JSONL segment ring (the FlightRecorder
  write/rotate/torn-tail discipline, ``intent-<n>.jsonl`` segments)
  recording every non-idempotent POST *before* it reaches the wire: an
  ``intent`` line (pod key, node, window id, traceparent) ahead of each
  bind/eviction POST, an ``ack`` on a confirmed 2xx, a ``nack`` on a
  durable server error (the POST was answered and not applied — safe to
  re-drive), an ``unresolved`` mark for the pipelined write path's
  indeterminate outcomes, and a ``tombstone`` once the watch confirms
  the placement. Every line is one write+flush (opt-in ``fsync``); a
  crash can lose at most the torn tail.
- ``Reconciler`` — restart replay: walk the journal, classify each
  unresolved intent by GETting the live object (bound-as-intended →
  ack; bound-elsewhere → drop; unbound → safe to re-schedule; eviction
  with the pod still present → re-arm the node cooldown, never a
  second eviction POST), re-arm lifecycle traces on the same trace id
  with attempt+1, and journal a ``resolved`` line per intent so a
  second restart replays nothing. Only then may the scheduler open for
  new work — zero duplicate binding POSTs across a kill at any byte
  offset.
- ``WarmStandby`` — a second scheduler process holding the existing
  file-lock ``LeaderElector`` in standby: its mirror watch-follows the
  live cluster the whole time, and on lease loss it reconciles the dead
  leader's journal directory *before* its first bind, reporting
  ``crane_failover_seconds``.

``KillSwitch`` is the deterministic SIGKILL-at-offset injector the
chaos harness (``ChaosPlan`` kinds ``kill_process``/``restart_process``)
and bench config 16 use: it truncates the journal mid-line at an exact
byte offset and fires its action (SIGKILL by default, a
``SimulatedCrash`` in-process), so "kill at any byte offset" is a
sweepable test axis, proven against the stub's ``bind_posts`` oracle.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field

SCHEMA_VERSION = 1

_JSON_SEP = (",", ":")

# reconciliation outcomes (the crane_recovery_reconciled_total label set)
OUTCOME_BOUND_AS_INTENDED = "bound_as_intended"
OUTCOME_BOUND_ELSEWHERE = "bound_elsewhere"
OUTCOME_UNBOUND = "unbound_reschedulable"
OUTCOME_POD_GONE = "pod_gone"
OUTCOME_EVICTED = "evicted"
OUTCOME_EVICT_UNAPPLIED = "evict_unapplied"


class SimulatedCrash(BaseException):
    """In-process stand-in for SIGKILL: raised by a ``KillSwitch`` whose
    action is to abandon the process mid-write. Derives from
    BaseException so no library-level ``except Exception`` in the write
    path can swallow the "process died here" semantics."""


class KillSwitch:
    """SIGKILL-at-offset injection for the intent journal.

    Arms at an absolute journal byte offset. When a record write would
    cross the offset, only the bytes up to it are written (a torn tail,
    exactly what a real SIGKILL mid-``write(2)`` leaves) and ``action``
    fires — ``os.kill(getpid(), SIGKILL)`` by default, or any callable
    (tests raise ``SimulatedCrash`` and abandon the client without
    teardown)."""

    def __init__(self, at_bytes: int, action=None):
        self.at_bytes = int(at_bytes)
        self.tripped = False
        if action is None:
            import signal

            def action():
                os.kill(os.getpid(), signal.SIGKILL)

        self.action = action

    def cut(self, total_bytes: int, line_len: int) -> int | None:
        """How many bytes of the next ``line_len``-byte record may be
        written before the switch fires; None = the whole line fits.
        Once tripped the answer is always 0 — a dead process writes
        nothing, even when the test action didn't exit the interpreter."""
        if self.tripped:
            return 0
        if total_bytes >= self.at_bytes:
            return 0
        if total_bytes + line_len > self.at_bytes:
            return self.at_bytes - total_bytes
        return None

    def trip(self):
        self.tripped = True
        self.action()


class IntentJournal:
    """Durable placement-intent journal: a crash-safe JSONL segment ring
    (``intent-<n>.jsonl``) with the FlightRecorder's write/rotate/
    torn-tail discipline, plus per-line ``fsync`` opt-in (power loss,
    not just process death). Thread-safe; one instance per process."""

    def __init__(self, directory: str, max_segment_bytes: int = 4 << 20,
                 max_segments: int = 16, fsync: bool = False,
                 telemetry=None):
        self.directory = directory
        self.max_segment_bytes = int(max_segment_bytes)
        self.max_segments = int(max_segments)
        self.fsync = bool(fsync)
        self.kill_switch: KillSwitch | None = None
        os.makedirs(directory, exist_ok=True)
        self._lock = threading.Lock()
        indices = self._segment_indices()
        self._index = indices[-1] if indices else 1
        self._file = open(self._segment_path(self._index), "a")
        self._size = self._file.tell()
        # total bytes ever appended by this process — the KillSwitch
        # offset axis (restart-stable offsets would need the on-disk
        # size folded in; the harness arms fresh journals)
        self.bytes_written = 0
        # monotonic ids continue across restarts so a reconciler's
        # ``resolved`` lines can never collide with replayed intents
        self._seq = 0
        self._window = 0
        for rec in self.read(directory):
            if isinstance(rec.get("id"), int):
                self._seq = max(self._seq, rec["id"])
            if isinstance(rec.get("window"), int):
                self._window = max(self._window, rec["window"])
        # open intents awaiting their watch-confirm tombstone, bounded
        self._open_binds: dict[str, tuple[int, str]] = {}
        self._open_evicts: dict[str, int] = {}
        self._open_cap = 65536
        self._m_bytes = None
        if telemetry is not None:
            self._m_bytes = telemetry.registry.gauge(
                "crane_recovery_journal_bytes",
                "Bytes appended to the placement-intent journal",
            )

    # -- segment ring (FlightRecorder discipline) -------------------------

    def _segment_path(self, index: int) -> str:
        return os.path.join(self.directory, f"intent-{index:06d}.jsonl")

    def _segment_indices(self) -> list[int]:
        out = []
        for name in os.listdir(self.directory):
            if name.startswith("intent-") and name.endswith(".jsonl"):
                try:
                    out.append(int(name[len("intent-"):-len(".jsonl")]))
                except ValueError:
                    continue
        return sorted(out)

    def _append(self, obj: dict) -> None:
        line = json.dumps(obj, separators=_JSON_SEP, default=str) + "\n"
        with self._lock:
            ks = self.kill_switch
            if ks is not None:
                cut = ks.cut(self.bytes_written, len(line))
                if cut is not None:
                    # a real SIGKILL mid-write leaves exactly this torn
                    # prefix on disk
                    if cut:
                        self._file.write(line[:cut])
                        self._file.flush()
                        if self.fsync:
                            os.fsync(self._file.fileno())
                        self.bytes_written += cut
                        self._size += cut
                    ks.trip()
                    return  # only reachable with a non-exiting action
            self._file.write(line)
            self._file.flush()
            if self.fsync:
                os.fsync(self._file.fileno())
            self._size += len(line)
            self.bytes_written += len(line)
            if self._m_bytes is not None:
                self._m_bytes.set(self.bytes_written)
            if self._size >= self.max_segment_bytes:
                self._rotate_locked()

    def _rotate_locked(self) -> None:
        self._file.close()
        self._index += 1
        self._file = open(self._segment_path(self._index), "a")
        self._size = 0
        indices = self._segment_indices()
        while len(indices) > self.max_segments:
            oldest = indices.pop(0)
            try:
                os.unlink(self._segment_path(oldest))
            except OSError:
                pass

    def close(self) -> None:
        with self._lock:
            try:
                self._file.close()
            except OSError:
                pass

    # -- record API --------------------------------------------------------

    def begin_window(self) -> int:
        """A fresh window id for one POST batch / drip dispatch window;
        every intent of the batch carries it."""
        with self._lock:
            self._window += 1
            return self._window

    def intent(self, op: str, pod: str, node: str | None,
               trace: str | None = None, window: int | None = None) -> int:
        """Journal the intent to POST. MUST be called before the request
        reaches the wire — the crash-safety contract."""
        with self._lock:
            self._seq += 1
            iid = self._seq
        self._append({
            "v": SCHEMA_VERSION, "t": "intent", "id": iid, "op": op,
            "pod": pod, "node": node,
            "window": self._window if window is None else window,
            "trace": trace, "ts": time.time(),
        })
        with self._lock:
            if op == "bind":
                self._open_binds[pod] = (iid, node or "")
                while len(self._open_binds) > self._open_cap:
                    self._open_binds.pop(next(iter(self._open_binds)))
            elif op == "evict":
                self._open_evicts[pod] = iid
                while len(self._open_evicts) > self._open_cap:
                    self._open_evicts.pop(next(iter(self._open_evicts)))
        return iid

    def ack(self, intent_id: int) -> None:
        """The server confirmed the POST (2xx) — the write applied. The
        intent stays open in memory until the watch tombstones it."""
        self._append({"v": SCHEMA_VERSION, "t": "ack", "id": intent_id})

    def nack(self, intent_id: int, status: int) -> None:
        """The server answered a durable error (404/409/422/...): the
        POST was NOT applied and the caller may re-drive it."""
        self._append({"v": SCHEMA_VERSION, "t": "nack", "id": intent_id,
                      "status": int(status)})
        self._drop_open(intent_id)

    def unresolved(self, intent_id: int) -> None:
        """Transport loss / pipelined indeterminate: the server may or
        may not have processed the POST. Recorded explicitly (not just
        as an absent ack) so the journal reads as a decision log; the
        intent replays as unresolved either way."""
        self._append({"v": SCHEMA_VERSION, "t": "unresolved",
                      "id": intent_id})

    def resolved(self, intent_id: int, outcome: str) -> None:
        """Reconciliation verdict for a replayed intent — terminal, so a
        second restart replays nothing."""
        self._append({"v": SCHEMA_VERSION, "t": "resolved",
                      "id": intent_id, "outcome": outcome})
        self._drop_open(intent_id)

    def tombstone_batch(self, pairs) -> int:
        """Watch-confirm hook: ``(pod, node)`` placements the watch
        delivered. Pods without an open bind intent cost one dict miss."""
        n = 0
        for pod, node in pairs:
            with self._lock:
                open_intent = self._open_binds.get(pod)
                if open_intent is None:
                    continue
                del self._open_binds[pod]
            self._append({"v": SCHEMA_VERSION, "t": "tombstone",
                          "id": open_intent[0], "pod": pod, "node": node})
            n += 1
        return n

    def tombstone_deleted(self, pod: str) -> None:
        """Watch DELETED hook: confirms an open eviction intent."""
        with self._lock:
            iid = self._open_evicts.pop(pod, None)
        if iid is not None:
            self._append({"v": SCHEMA_VERSION, "t": "tombstone",
                          "id": iid, "pod": pod, "node": None})

    def _drop_open(self, intent_id: int) -> None:
        with self._lock:
            for d in (self._open_binds, self._open_evicts):
                for pod, val in list(d.items()):
                    iid = val[0] if isinstance(val, tuple) else val
                    if iid == intent_id:
                        del d[pod]

    # -- replay ------------------------------------------------------------

    @staticmethod
    def read(directory: str):
        """Yield records oldest-first across all segments, skipping torn
        or foreign lines (the FlightRecorder reader contract)."""
        if not os.path.isdir(directory):
            return
        names = sorted(
            n for n in os.listdir(directory)
            if n.startswith("intent-") and n.endswith(".jsonl")
        )
        for name in names:
            try:
                with open(os.path.join(directory, name)) as f:
                    for line in f:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            obj = json.loads(line)
                        except ValueError:
                            continue  # torn tail from a crash
                        if isinstance(obj, dict):
                            yield obj
            except OSError:
                continue


@dataclass
class JournalReplay:
    """Classified journal tail: what a restart must reconcile."""

    intents: dict = field(default_factory=dict)  # id -> intent record
    resolved_ids: set = field(default_factory=set)
    records_replayed: int = 0
    skipped_newer_schema: int = 0
    orphan_resolutions: int = 0  # ack/nack/tombstone with no intent line

    def unresolved(self) -> list[dict]:
        """Intent records with no terminal resolution, journal order."""
        return [
            rec for iid, rec in sorted(self.intents.items())
            if iid not in self.resolved_ids
        ]


def replay_journal(directory: str) -> JournalReplay:
    """Walk the journal ring and classify every intent. Records from a
    NEWER schema version are skipped and counted — an old binary must
    never misread a new journal as "nothing unresolved is mine"."""
    out = JournalReplay()
    for rec in IntentJournal.read(directory):
        t = rec.get("t")
        if t not in ("intent", "ack", "nack", "unresolved", "resolved",
                     "tombstone"):
            continue
        out.records_replayed += 1
        if int(rec.get("v", 0)) > SCHEMA_VERSION:
            out.skipped_newer_schema += 1
            continue
        iid = rec.get("id")
        if t == "intent":
            out.intents[iid] = rec
        elif t in ("ack", "nack", "resolved", "tombstone"):
            # ack/nack/resolved/tombstone are all terminal: the outcome
            # is known (applied / not applied / reconciled / confirmed)
            if iid not in out.intents:
                # the intent line rotated out of the ring, or this is a
                # foreign journal tail — nothing to reconcile, count it
                out.orphan_resolutions += 1
            out.resolved_ids.add(iid)
        # "unresolved" is an annotation, not a resolution: the intent
        # stays in the replay set
    return out


@dataclass
class ReconcileReport:
    """What reconciliation found and did. ``reschedule`` carries
    ``(pod_key, intended_node, trace_id, attempt)`` for pods that are
    provably unbound (safe to re-schedule, same trace, attempt+1);
    ``rearm_cooldowns`` carries node names whose eviction intent could
    not be confirmed (the descheduler must cool down, never re-POST)."""

    outcomes: dict = field(default_factory=dict)
    reschedule: list = field(default_factory=list)
    rearm_cooldowns: list = field(default_factory=list)
    intents_replayed: int = 0
    records_replayed: int = 0
    skipped_newer_schema: int = 0
    orphan_resolutions: int = 0
    elapsed_s: float = 0.0

    def total(self) -> int:
        return sum(self.outcomes.values())

    def as_dict(self) -> dict:
        return {
            "outcomes": dict(self.outcomes),
            "reschedule": [list(r) for r in self.reschedule],
            "rearm_cooldowns": list(self.rearm_cooldowns),
            "intents_replayed": self.intents_replayed,
            "records_replayed": self.records_replayed,
            "skipped_newer_schema": self.skipped_newer_schema,
            "orphan_resolutions": self.orphan_resolutions,
            "elapsed_s": round(self.elapsed_s, 4),
        }


def _trace_id_of(traceparent: str | None) -> str | None:
    """trace-id field of a W3C ``00-<trace>-<span>-01`` header value."""
    if not traceparent:
        return None
    parts = traceparent.split("-")
    return parts[1] if len(parts) >= 3 and parts[1] else None


class Reconciler:
    """Restart reconciliation: classify every unresolved intent against
    the LIVE object (``lookup(pod_key)`` must GET the apiserver, not a
    cold mirror), journal a terminal ``resolved`` line each, and hand
    the caller the re-schedulable set. Run this to completion BEFORE
    opening the scheduler for new work."""

    def __init__(self, journal: IntentJournal, lookup, lifecycle=None,
                 telemetry=None):
        self.journal = journal
        self.lookup = lookup
        self.lifecycle = lifecycle
        self._m_replayed = None
        self._m_outcomes = None
        if telemetry is not None:
            reg = telemetry.registry
            self._m_replayed = reg.counter(
                "crane_recovery_intents_replayed",
                "Journal intent records replayed at restart",
            )
            self._m_outcomes = reg.counter(
                "crane_recovery_reconciled_total",
                "Reconciled intents by classification",
                ("outcome",),
            )

    def reconcile(self, directory: str | None = None) -> ReconcileReport:
        t0 = time.perf_counter()
        replay = replay_journal(directory or self.journal.directory)
        report = ReconcileReport(
            intents_replayed=len(replay.intents),
            records_replayed=replay.records_replayed,
            skipped_newer_schema=replay.skipped_newer_schema,
            orphan_resolutions=replay.orphan_resolutions,
        )
        if self._m_replayed is not None:
            self._m_replayed.inc(len(replay.intents))
        for rec in replay.unresolved():
            outcome = self._classify(rec, report)
            report.outcomes[outcome] = report.outcomes.get(outcome, 0) + 1
            self.journal.resolved(rec["id"], outcome)
            if self._m_outcomes is not None:
                self._m_outcomes.labels(outcome=outcome).inc()
        report.elapsed_s = time.perf_counter() - t0
        return report

    def _classify(self, rec: dict, report: ReconcileReport) -> str:
        pod_key = rec.get("pod", "")
        intended = rec.get("node")
        pod = self.lookup(pod_key)
        if rec.get("op") == "evict":
            if pod is None:
                # the eviction landed (or the pod died another way):
                # either way it is gone — done
                return OUTCOME_EVICTED
            # the pod survives: the POST may still be racing through the
            # old apiserver queue. NEVER a second eviction POST — re-arm
            # the node cooldown and let the next sweep re-evaluate.
            node = intended or pod.node_name or ""
            if node:
                report.rearm_cooldowns.append(node)
            return OUTCOME_EVICT_UNAPPLIED
        # bind intent
        if pod is None:
            return OUTCOME_POD_GONE
        bound_node = getattr(pod, "node_name", None)
        if bound_node and bound_node == intended:
            return OUTCOME_BOUND_AS_INTENDED
        if bound_node:
            # another writer (or a prior life of this scheduler) bound
            # it elsewhere — drop our stale intent
            return OUTCOME_BOUND_ELSEWHERE
        # provably unbound: the POST never applied — safe to re-schedule
        trace = _trace_id_of(rec.get("trace"))
        attempt = int(rec.get("attempt") or 1)
        report.reschedule.append((pod_key, intended, trace, attempt))
        if self.lifecycle is not None and trace:
            # the re-placement continues the pod's trace at attempt+1
            self.lifecycle.rearm(pod_key, trace, attempt)
        return OUTCOME_UNBOUND


class WarmStandby:
    """Warm-standby failover coordinator for a second scheduler process.

    Holds the file-lock ``LeaderElector`` in standby while the caller's
    mirror watch-follows the live cluster (columns pre-built, kernels
    pre-jitted — the caller owns that client). On lease acquisition it
    reconciles the dead leader's journal directory FIRST, then invokes
    ``on_promote(report)`` and only after that flips ``ready`` — the
    caller must not bind before ``ready``, and once ``wait_ready``
    returns the promotion (journal attach, first bind) has completed.
    ``failover_seconds`` measures lease acquisition to
    reconciliation-complete (the bind path opening)."""

    def __init__(self, lock_path: str, identity: str, journal_dir: str,
                 lookup, lifecycle=None, telemetry=None, on_promote=None,
                 journal: IntentJournal | None = None,
                 lease_duration: float | None = None,
                 renew_deadline: float | None = None,
                 retry_period: float | None = None):
        from ..service.leader import (
            DEFAULT_LEASE_DURATION,
            DEFAULT_RENEW_DEADLINE,
            DEFAULT_RETRY_PERIOD,
            LeaderElector,
        )

        self.journal_dir = journal_dir
        self.lookup = lookup
        self.lifecycle = lifecycle
        self.telemetry = telemetry
        self.on_promote = on_promote
        self._journal = journal
        self.ready = threading.Event()
        self.report: ReconcileReport | None = None
        self.failover_seconds: float | None = None
        self._m_failover = None
        if telemetry is not None:
            self._m_failover = telemetry.registry.histogram(
                "crane_failover_seconds",
                "Standby lease acquisition to reconciled-and-ready",
                buckets=(0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0),
            )
        self.elector = LeaderElector(
            lock_path,
            identity=identity,
            on_started_leading=self._lead,
            lease_duration=(
                DEFAULT_LEASE_DURATION if lease_duration is None
                else lease_duration),
            renew_deadline=(
                DEFAULT_RENEW_DEADLINE if renew_deadline is None
                else renew_deadline),
            retry_period=(
                DEFAULT_RETRY_PERIOD if retry_period is None
                else retry_period),
        )
        self._thread: threading.Thread | None = None

    def start(self) -> "WarmStandby":
        self._thread = threading.Thread(
            target=self.elector.run, name="crane-standby", daemon=True
        )
        self._thread.start()
        return self

    def _lead(self, stop_event) -> None:
        t0 = time.perf_counter()
        journal = self._journal
        if journal is None:
            # take over the dead leader's ring: new lines (resolved
            # verdicts, our own intents) append to the same directory
            journal = self._journal = IntentJournal(
                self.journal_dir, telemetry=self.telemetry
            )
        self.report = Reconciler(
            journal, self.lookup,
            lifecycle=self.lifecycle, telemetry=self.telemetry,
        ).reconcile(self.journal_dir)
        self.failover_seconds = time.perf_counter() - t0
        if self._m_failover is not None:
            self._m_failover.observe(self.failover_seconds)
        # on_promote runs BEFORE ready flips: a caller returning from
        # wait_ready() may immediately tear things down, so anything
        # the promotion does (first bind, journal attach) must already
        # have happened
        try:
            if self.on_promote is not None:
                self.on_promote(self.report)
        finally:
            self.ready.set()
        stop_event.wait()

    @property
    def journal(self) -> IntentJournal | None:
        """The promoted leader's journal (None while still in standby)."""
        return self._journal

    def wait_ready(self, timeout: float | None = None) -> bool:
        return self.ready.wait(timeout)

    def stop(self) -> None:
        self.elector.stop()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if self._journal is not None:
            self._journal.close()
