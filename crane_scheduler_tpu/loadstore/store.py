"""Columnar node-load state: the HBM-resident node-by-metric tensor.

The reference's scoring inputs live as per-node annotation strings patched
one (node, metric) at a time (ref: pkg/controller/annotator/node.go:123-146)
and re-parsed per scheduling cycle (ref: pkg/plugins/dynamic/stats.go:51-76).
Here the same state is columnar: ``value[node, metric]`` and
``timestamp[node, metric]`` float64 matrices plus ``hot_value[node]`` /
``hot_ts[node]`` vectors, refreshed in bulk and uploaded to device as one
padded snapshot. Encoding:

- missing / structurally-invalid annotation -> ``ts = -inf`` (never fresh,
  so every reader takes the fail-open path, exactly like a parse error);
- a value string that parsed to NaN stays NaN with its real timestamp
  (Go lets NaN through the ``< 0`` check; we preserve that).

Padding discipline: snapshots round the node axis up to a bucket size so
jitted shapes stay stable as the cluster grows (no recompiles at 50k
nodes); padded rows carry ``node_valid = False``.
"""

from __future__ import annotations

import functools
import threading
from dataclasses import dataclass
from typing import Mapping

import numpy as np

from ..constants import NODE_HOT_VALUE_KEY
from ..policy.compile import PolicyTensors
from .codec import decode_annotation_or_missing

_NEG_INF = float("-inf")


def _locked(fn):
    """Run the method under the store's reentrant mutation lock."""

    @functools.wraps(fn)
    def wrapper(self, *args, **kwargs):
        with self._lock:
            return fn(self, *args, **kwargs)

    return wrapper


def _pad_bucket(n: int, bucket: int) -> int:
    if n <= 0:
        return bucket
    return ((n + bucket - 1) // bucket) * bucket


@dataclass(frozen=True)
class DeviceSnapshot:
    """A device-ready view of the store (numpy; callers jnp.asarray it)."""

    values: np.ndarray  # [Npad, M] f64
    ts: np.ndarray  # [Npad, M] f64 epoch seconds, -inf = missing
    hot_value: np.ndarray  # [Npad] f64
    hot_ts: np.ndarray  # [Npad] f64
    node_valid: np.ndarray  # [Npad] bool
    n_nodes: int
    node_names: tuple[str, ...]
    # store version the snapshot was taken at (-1 = synthetic snapshot):
    # response caches and device-resident copies key on this
    version: int = -1


class NodeLoadStore:
    """Mutable host-side store with amortized growth and bulk refresh."""

    def __init__(self, tensors: PolicyTensors, initial_capacity: int = 64):
        self.tensors = tensors
        # Guards every mutation and snapshot(): in threaded direct mode
        # annotator workers mutate (add_node may swap-grow the arrays)
        # while the scheduler thread snapshots. Reentrant because
        # ingest_* call set_* internally.
        self._lock = threading.RLock()
        m = tensors.num_metrics
        cap = max(initial_capacity, 1)
        self._cap = cap
        self._n = 0
        self._names: list[str] = []
        self._index: dict[str, int] = {}
        self.values = np.full((cap, m), np.nan, dtype=np.float64)
        self.ts = np.full((cap, m), _NEG_INF, dtype=np.float64)
        self.hot_value = np.full((cap,), np.nan, dtype=np.float64)
        self.hot_ts = np.full((cap,), _NEG_INF, dtype=np.float64)
        # per-node annotation-map identity for skip-unchanged refreshes
        self._last_anno: dict[str, object] = {}
        # monotonic mutation counter: snapshot/upload caches key on this,
        # so an unchanged store costs zero host->device traffic per cycle
        self._version = 0
        # delta-upload support: which version last touched each row, and
        # a separate counter for layout changes (row <-> name mapping) —
        # value edits can upload as row deltas, layout changes cannot
        self._row_versions = np.zeros((cap,), dtype=np.int64)
        self._layout_version = 0
        # column-write log (see _COLUMN_LOG_CAP): entries
        # (pre_version, post_version, col_or_None, ids, values_or_None,
        #  ts_or_None, hot_values_or_None, hot_ts_or_None). A consumer
        # needs a CONTIGUOUS pre/post chain from its version to the
        # current one — any foreign mutation breaks the chain by
        # construction, so no invalidation hooks are needed.
        self._column_log: list[tuple] = []
        # (names list identity, layout_version, ids) — bulk_set_by_name's
        # name->row resolution for the annotator's cached sweep list
        self._ids_cache: tuple | None = None

    # column-write log: bulk_set_by_name appends one entry per call so a
    # device snapshot can replay whole-column writes (the annotator's
    # sweep shape) instead of re-uploading full matrices. Bounded; any
    # other mutation breaks the version chain consumers require.
    _COLUMN_LOG_CAP = 128

    @property
    def version(self) -> int:
        """Bumped by every mutation that can change snapshot contents."""
        return self._version

    @property
    def layout_version(self) -> int:
        """Bumped when the row <-> node-name mapping changes (add/remove);
        device-resident snapshots can only delta-update while this is
        stable."""
        return self._layout_version

    def _touch(self, row: int) -> None:
        """Record that ``row`` changed at the current version (callers
        hold the lock and have already bumped ``_version``)."""
        self._row_versions[row] = self._version

    @_locked
    def column_delta_since(self, version: int):
        """Column-write replay from ``version`` to the current version:
        ``(current_version, layout_version, entries)`` where each entry is
        ``(col_or_None, ids, values_or_None, ts_or_None, hot_values_or_None,
        hot_ts_or_None)`` in application order — or ``None`` when the
        interval is not exactly covered by logged ``bulk_set_by_name``
        calls (any other mutation breaks the version chain)."""
        if version == self._version:
            return self._version, self._layout_version, []
        start = None
        for k, entry in enumerate(self._column_log):
            if entry[0] == version:
                start = k
                break
        if start is None:
            return None
        chain = []
        expect = version
        for entry in self._column_log[start:]:
            if entry[0] != expect:
                return None
            chain.append(entry[2:])
            expect = entry[1]
        if expect != self._version:
            return None
        return self._version, self._layout_version, chain

    @_locked
    def delta_since(self, version: int):
        """Rows whose contents changed after ``version``, with their
        current data, all under one lock hold:
        ``(current_version, layout_version, row_ids, values[k, M],
        ts[k, M], hot_value[k], hot_ts[k])``. Valid for delta-uploading a
        device snapshot taken at ``version`` ONLY while layout_version is
        unchanged (the caller checks)."""
        ids = np.nonzero(self._row_versions[: self._n] > version)[0].astype(
            np.int64
        )
        # fancy indexing already yields fresh arrays — no extra copies
        return (
            self._version,
            self._layout_version,
            ids,
            self.values[ids],
            self.ts[ids],
            self.hot_value[ids],
            self.hot_ts[ids],
        )

    # -- node membership ---------------------------------------------------

    def __len__(self) -> int:
        return self._n

    @property
    def node_names(self) -> tuple[str, ...]:
        return tuple(self._names)

    def node_id(self, name: str) -> int:
        return self._index[name]

    @_locked
    def add_node(self, name: str) -> int:
        if name in self._index:
            return self._index[name]
        if self._n == self._cap:
            self._grow(self._cap * 2)
        i = self._n
        self._n += 1
        self._names.append(name)
        self._index[name] = i
        self.values[i, :] = np.nan
        self.ts[i, :] = _NEG_INF
        self.hot_value[i] = np.nan
        self.hot_ts[i] = _NEG_INF
        self._version += 1
        self._layout_version += 1
        self._touch(i)
        return i

    @_locked
    def remove_node(self, name: str) -> None:
        """Swap-remove; row order is not part of the contract."""
        i = self._index.pop(name, None)
        self._last_anno.pop(name, None)
        if i is None:
            return
        last = self._n - 1
        if i != last:
            last_name = self._names[last]
            self.values[i] = self.values[last]
            self.ts[i] = self.ts[last]
            self.hot_value[i] = self.hot_value[last]
            self.hot_ts[i] = self.hot_ts[last]
            self._names[i] = last_name
            self._index[last_name] = i
        self._names.pop()
        self._n = last
        self._version += 1
        self._layout_version += 1
        self._row_versions[last] = 0
        if i != last:
            self._touch(i)  # row i now holds the moved node's data

    def _grow(self, new_cap: int) -> None:
        m = self.tensors.num_metrics
        for attr, fill, shape in (
            ("values", np.nan, (new_cap, m)),
            ("ts", _NEG_INF, (new_cap, m)),
            ("hot_value", np.nan, (new_cap,)),
            ("hot_ts", _NEG_INF, (new_cap,)),
            ("_row_versions", 0, (new_cap,)),
        ):
            old = getattr(self, attr)
            new = np.full(shape, fill, dtype=old.dtype)
            new[: self._n] = old[: self._n]
            setattr(self, attr, new)
        self._cap = new_cap

    # -- writes ------------------------------------------------------------

    @_locked
    def set_metric(
        self, node: str, metric: str, value: float, ts: float,
        create: bool = True,
    ) -> None:
        """``create=False`` drops the write when the node has no row —
        for writers racing a concurrent ``prune_absent`` (a deleted
        node's in-flight sync must not resurrect its row; a genuinely
        new node just waits for the next bulk tick to add it)."""
        i = self._index.get(node)
        if i is None:
            if not create:
                return
            i = self.add_node(node)
        self._last_anno.pop(node, None)
        col = self.tensors.metric_index.get(metric)
        if col is None:
            return  # metric not referenced by the policy: ignore
        self.values[i, col] = value
        self.ts[i, col] = ts
        self._version += 1
        self._touch(i)

    @_locked
    def set_hot_value(
        self, node: str, value: float, ts: float, create: bool = True
    ) -> None:
        i = self._index.get(node)
        if i is None:
            if not create:
                return
            i = self.add_node(node)
        self._last_anno.pop(node, None)
        self.hot_value[i] = value
        self.hot_ts[i] = ts
        self._version += 1
        self._touch(i)

    @_locked
    def ingest_annotation(self, node: str, key: str, raw: str) -> None:
        """Decode one ``"value,timestamp"`` annotation into the store."""
        value, ts = decode_annotation_or_missing(raw)
        if key == NODE_HOT_VALUE_KEY:
            self.set_hot_value(node, value, ts)
        else:
            self.set_metric(node, key, value, ts)

    @_locked
    def ingest_node_annotations(self, node: str, anno: Mapping[str, str] | None) -> None:
        """Bulk-ingest a node's full annotation map (the parity read path).

        The map is authoritative: keys absent from it are cleared, so a
        deleted annotation doesn't linger as live metric state. The
        node's annotations decode through the batch codec in one call
        (native, or the vectorized numpy fallback), like ``bulk_ingest``.
        """
        i = self.add_node(node)
        self._last_anno[node] = anno
        self.values[i, :] = np.nan
        self.ts[i, :] = _NEG_INF
        self.hot_value[i] = np.nan
        self.hot_ts[i] = _NEG_INF
        self._version += 1
        self._touch(i)
        if not anno:
            return
        from ..native.codec import bulk_parse_annotations

        raws: list[str] = []
        cols: list[int] = []  # -1 == hot value
        for key, raw in anno.items():
            if key == NODE_HOT_VALUE_KEY:
                raws.append(raw)
                cols.append(-1)
            else:
                col = self.tensors.metric_index.get(key)
                if col is not None:
                    raws.append(raw)
                    cols.append(col)
        if not raws:
            return
        values, ts = bulk_parse_annotations(raws)
        cols_arr = np.asarray(cols, dtype=np.int64)
        metric_mask = cols_arr >= 0
        self.values[i, cols_arr[metric_mask]] = values[metric_mask]
        self.ts[i, cols_arr[metric_mask]] = ts[metric_mask]
        hot = np.flatnonzero(~metric_mask)
        if hot.size:
            self.hot_value[i] = values[hot[-1]]
            self.hot_ts[i] = ts[hot[-1]]
        # the direct per-key writes this replaces dropped the node's
        # skip-unchanged marker as a side effect; preserve that so
        # bulk refresh behavior is unchanged
        self._last_anno.pop(node, None)

    @_locked
    def bulk_set_by_name(
        self,
        metric: str,
        names: list[str],
        values: np.ndarray,
        ts: float | np.ndarray,
        hot_values: np.ndarray | None = None,
        hot_ts: float | np.ndarray | None = None,
    ) -> None:
        """Atomic by-name column write: name->row resolution (adding
        missing nodes) and the metric/hot writes happen under one lock
        hold, so a concurrent ``prune_absent`` (which swap-removes rows)
        can never redirect a pre-resolved id to another node's row."""
        index = self._index
        pre_version = self._version
        pre_layout = self._layout_version
        # a sweep passes the same cached name list once per metric —
        # resolve name->row once per (list identity, layout)
        cached = self._ids_cache
        if (
            cached is not None
            and cached[0] is names
            and cached[1] == pre_layout
        ):
            ids = cached[2]
        else:
            ids = np.asarray(
                [
                    i if (i := index.get(n)) is not None else self.add_node(n)
                    for n in names
                ],
                dtype=np.int64,
            )
            if isinstance(names, list):
                self._ids_cache = (names, self._layout_version, ids)
        wrote = False
        col = self.tensors.metric_index.get(metric)
        if col is not None and len(ids):
            self.values[ids, col] = values
            self.ts[ids, col] = ts
            self._version += 1
            wrote = True
        if hot_values is not None and len(ids):
            self.hot_value[ids] = hot_values
            self.hot_ts[ids] = hot_ts
            self._version += 1
            wrote = True
        if wrote:
            self._row_versions[ids] = self._version
            if pre_layout == self._layout_version:
                # log the column write for device-side replay (arrays are
                # captured; callers build them fresh per call). A write
                # that added nodes changed the layout — not replayable.
                self._column_log.append((
                    pre_version,
                    self._version,
                    col,
                    ids,
                    np.broadcast_to(np.asarray(values, np.float64), ids.shape).copy()
                    if col is not None else None,
                    np.broadcast_to(np.asarray(ts, np.float64), ids.shape).copy()
                    if col is not None else None,
                    np.broadcast_to(np.asarray(hot_values, np.float64), ids.shape).copy()
                    if hot_values is not None else None,
                    np.broadcast_to(np.asarray(hot_ts, np.float64), ids.shape).copy()
                    if hot_values is not None else None,
                ))
                if len(self._column_log) > self._COLUMN_LOG_CAP:
                    del self._column_log[0]

    @_locked
    def prune_absent(self, live_names) -> int:
        """Remove rows for nodes not in ``live_names``; returns count."""
        live = set(live_names)
        stale = [n for n in self._names if n not in live]
        for name in stale:
            self.remove_node(name)
        return len(stale)

    @_locked
    def bulk_ingest(self, items, skip_unchanged: bool = True) -> None:
        """Ingest many (node_name, annotation_map) pairs with one native
        parse call (falls back to the Python codec transparently).

        Semantics identical to calling ``ingest_node_annotations`` per
        node: each map is authoritative for its node. With
        ``skip_unchanged`` (default), a node whose annotation map is the
        *same object* as last time is skipped — the cluster model replaces
        the map on every patch, so identity works like an informer's
        resourceVersion check and steady-state refreshes are O(changed).

        Membership adds, row resets, and version bookkeeping are batched
        (one version/layout bump for the whole call, one fancy-indexed
        reset pass) — the per-node ``add_node`` + four row writes were
        a third of the 50k-node cold refresh.
        """
        index = self._index
        last = self._last_anno
        metric_get = self.tensors.metric_index.get
        raws: list[str | None] = []
        rows: list[int] = []
        cols: list[int] = []  # -1 == hot value
        rapp, iapp, capp = raws.append, rows.append, cols.append
        touched: list[int] = []
        tapp = touched.append
        added = False
        for name, anno in items:
            i = index.get(name)
            if i is None:
                # batch-shaped add_node: membership bookkeeping inline,
                # row reset with the touched batch below, one
                # version/layout bump for the whole call
                if self._n == self._cap:
                    self._grow(self._cap * 2)
                i = self._n
                self._n += 1
                self._names.append(name)
                index[name] = i
                added = True
            elif skip_unchanged and last.get(name) is anno:
                continue
            last[name] = anno
            tapp(i)
            if not anno:
                continue
            for key, raw in anno.items():
                if key == NODE_HOT_VALUE_KEY:
                    rapp(raw)
                    iapp(i)
                    capp(-1)
                else:
                    col = metric_get(key)
                    if col is not None:
                        rapp(raw)
                        iapp(i)
                        capp(col)
        self._finish_ingest_locked(touched, raws, rows, cols, added)

    def _finish_ingest_locked(self, touched, raws, rows, cols,
                              added: bool) -> None:
        """Shared tail of the bulk ingest paths: batched version/layout
        bookkeeping, one fancy-indexed row reset, one batch parse call,
        scattered metric/hot writes (callers hold the lock)."""
        if not touched:
            return
        from ..native.codec import bulk_parse_annotations

        self._version += 1
        if added:
            self._layout_version += 1
        t_idx = np.asarray(touched, dtype=np.int64)
        self.values[t_idx] = np.nan
        self.ts[t_idx] = _NEG_INF
        self.hot_value[t_idx] = np.nan
        self.hot_ts[t_idx] = _NEG_INF
        self._row_versions[t_idx] = self._version
        if not raws:
            return
        values, ts = bulk_parse_annotations(raws)
        rows_arr = np.asarray(rows, dtype=np.int64)
        cols_arr = np.asarray(cols, dtype=np.int64)
        metric_mask = cols_arr >= 0
        self.values[rows_arr[metric_mask], cols_arr[metric_mask]] = values[metric_mask]
        self.ts[rows_arr[metric_mask], cols_arr[metric_mask]] = ts[metric_mask]
        hot_mask = ~metric_mask
        self.hot_value[rows_arr[hot_mask]] = values[hot_mask]
        self.hot_ts[rows_arr[hot_mask]] = ts[hot_mask]

    @_locked
    def ingest_annotation_columns(self, names, keys, values, offsets,
                                  only_names=None) -> None:
        """Columnar twin of ``bulk_ingest``: per-node annotation maps
        arrive as flat aligned key/value columns — row ``i`` owns
        ``keys[offsets[i]:offsets[i+1]]``, the LIST decoder's output
        shape (``DecodedPage.node_annotation_columns``) — so a
        relist-sized refresh reaches the matrices without building one
        per-node dict or Node object. Each row is authoritative for its
        node, exactly like ``ingest_node_annotations``. There is no
        identity skip (there are no map objects to compare): callers
        gate on the cluster version instead, as
        ``BatchScheduler.refresh`` does. ``only_names`` narrows the
        write to a dirty subset (the cluster's dirty-name journal):
        rows for other names are ignored, making a full-width column
        payload an O(dirty) patch."""
        index = self._index
        metric_get = self.tensors.metric_index.get
        raws: list = []
        rows: list[int] = []
        cols: list[int] = []  # -1 == hot value
        rapp, iapp, capp = raws.append, rows.append, cols.append
        touched: list[int] = []
        tapp = touched.append
        added = False
        off = offsets.tolist() if hasattr(offsets, "tolist") else list(offsets)
        last = self._last_anno
        for j, name in enumerate(names):
            if only_names is not None and name not in only_names:
                continue
            i = index.get(name)
            if i is None:
                if self._n == self._cap:
                    self._grow(self._cap * 2)
                i = self._n
                self._n += 1
                self._names.append(name)
                index[name] = i
                added = True
            else:
                last.pop(name, None)
            tapp(i)
            for k in range(off[j], off[j + 1]):
                key = keys[k]
                if key == NODE_HOT_VALUE_KEY:
                    rapp(values[k])
                    iapp(i)
                    capp(-1)
                else:
                    col = metric_get(key)
                    if col is not None:
                        rapp(values[k])
                        iapp(i)
                        capp(col)
        self._finish_ingest_locked(touched, raws, rows, cols, added)

    # -- snapshot ----------------------------------------------------------

    @_locked
    def snapshot(self, bucket: int = 2048) -> DeviceSnapshot:
        n = self._n
        npad = _pad_bucket(n, bucket)
        m = self.tensors.num_metrics
        values = np.full((npad, m), np.nan, dtype=np.float64)
        ts = np.full((npad, m), _NEG_INF, dtype=np.float64)
        hot_value = np.zeros((npad,), dtype=np.float64)
        hot_ts = np.full((npad,), _NEG_INF, dtype=np.float64)
        values[:n] = self.values[:n]
        ts[:n] = self.ts[:n]
        hot_value[:n] = self.hot_value[:n]
        hot_ts[:n] = self.hot_ts[:n]
        node_valid = np.zeros((npad,), dtype=bool)
        node_valid[:n] = True
        return DeviceSnapshot(
            values=values,
            ts=ts,
            hot_value=hot_value,
            hot_ts=hot_ts,
            node_valid=node_valid,
            n_nodes=n,
            node_names=tuple(self._names),
            version=self._version,
        )
