from .codec import (
    encode_annotation,
    decode_annotation,
    go_parse_float,
    format_metric_value,
)
from .store import NodeLoadStore, DeviceSnapshot

__all__ = [
    "encode_annotation",
    "decode_annotation",
    "go_parse_float",
    "format_metric_value",
    "NodeLoadStore",
    "DeviceSnapshot",
]
