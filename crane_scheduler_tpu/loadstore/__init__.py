from .codec import (
    encode_annotation,
    decode_annotation,
    decode_annotation_or_missing,
    bulk_decode_annotations,
    go_parse_float,
    format_metric_value,
)
from .store import NodeLoadStore, DeviceSnapshot

__all__ = [
    "encode_annotation",
    "decode_annotation",
    "decode_annotation_or_missing",
    "bulk_decode_annotations",
    "go_parse_float",
    "format_metric_value",
    "NodeLoadStore",
    "DeviceSnapshot",
]
