"""Annotation wire codec: ``"<float>,<localtime>"``.

The data-plane contract between the annotator and the scorer is a node
annotation map ``metricName -> "floatValue,timestamp"``
(written at ref: pkg/controller/annotator/node.go:123-146, parsed at
ref: pkg/plugins/dynamic/stats.go:51-76). This module reproduces both ends:

- encode: value rendered by the metrics source (5-decimal fixed for
  Prometheus, ref: pkg/controller/prometheus/prometheus.go:124) or a bare
  integer for hot values (ref: node.go:113-121), joined with the quirky
  local-time timestamp.
- decode: split on "," requiring exactly two parts; timestamp parsed
  separately from value so staleness can be evaluated at read time with a
  caller-supplied ``now``.
"""

from __future__ import annotations

import math
import re

import numpy as np

from ..utils.timeutil import format_local_time, parse_local_time

# Go 1.13+ numeric literal syntax: underscores may appear between digits
# of any group ("1_000.5", "1e1_0"); hex floats need a mandatory p-exponent.
_D = r"\d(?:_?\d)*"
_H = r"[0-9a-fA-F](?:_?[0-9a-fA-F])*"
_GO_FLOAT_RE = re.compile(
    rf"^[+-]?(?:{_D}(?:\.(?:{_D})?)?|\.{_D})(?:[eE][+-]?{_D})?$"
)
_GO_HEX_RE = re.compile(rf"^[+-]?0[xX](?:{_H}(?:\.(?:{_H})?)?|\.{_H})[pP][+-]?{_D}$")
_GO_SPECIAL_RE = re.compile(r"^[+-]?(inf(inity)?|nan)$", re.IGNORECASE)


def go_parse_float(s: str) -> float | None:
    """``strconv.ParseFloat(s, 64)`` equivalent; None on parse failure.

    Accepts decimal/exponent forms (with Go 1.13 underscore grouping),
    hex floats with p-exponent, and inf/infinity/nan (any case, optional
    sign). Rejects leading/trailing whitespace and malformed underscores,
    as Go does.
    """
    if not isinstance(s, str):
        return None
    # fast path: plain ASCII unsigned decimal (the Prometheus 5-decimal
    # rendering, by far the common case) — digits with at most one dot
    # is accepted identically by Go and float(); everything else (signs,
    # exponents, underscores, unicode digits, whitespace) falls through
    # to the exact-semantics matchers
    if s.isascii() and s.replace(".", "", 1).isdigit():
        return float(s)
    if _GO_FLOAT_RE.match(s):
        return float(s.replace("_", ""))
    if _GO_HEX_RE.match(s):
        return float.fromhex(s.replace("_", ""))
    if _GO_SPECIAL_RE.match(s):
        return float(s)
    return None


def format_metric_value(value: float) -> str:
    """Prometheus-side value serialization: 5-decimal fixed notation
    (ref: prometheus.go:124 ``strconv.FormatFloat(v, 'f', 5, 64)``)."""
    if math.isnan(value):
        return "NaN"
    if math.isinf(value):
        return "+Inf" if value > 0 else "-Inf"
    return f"{value:.5f}"


def encode_annotation(value_str: str, epoch_seconds: float | None = None) -> str:
    """``value + "," + localTime`` (ref: node.go:142)."""
    return f"{value_str},{format_local_time(epoch_seconds)}"


def decode_annotation(raw: str) -> tuple[float | None, float | None]:
    """Decode to ``(value, ts_epoch)``; either part is None if invalid.

    Mirrors ``getResourceUsage``'s structural checks
    (ref: stats.go:51-76): the string must split on "," into exactly two
    parts; the timestamp must parse under the local-TZ layout; the value
    must parse as a float. Semantic checks (staleness, negativity) are the
    reader's job — this function only decodes.
    """
    if not isinstance(raw, str):
        return None, None
    parts = raw.split(",")
    if len(parts) != 2:
        return None, None
    value = go_parse_float(parts[0])
    ts = parse_local_time(parts[1])
    return value, ts


def decode_annotation_or_missing(raw: str) -> tuple[float, float]:
    """Decode with the store's fail-open sentinel: a structurally invalid
    annotation becomes ``(nan, -inf)`` — never fresh, so every reader
    takes the fail-open path exactly like a parse error
    (ref: stats.go:96-99). The single source of the missing-value
    sentinels for both the re-ingest and direct-write paths."""
    value, ts = decode_annotation(raw)
    if value is None or ts is None:
        return float("nan"), float("-inf")
    return value, ts


# -- batch decode -----------------------------------------------------------
#
# ``bulk_decode_annotations`` is the pure-numpy twin of the native bulk
# parser (native/crane_native.cpp crane_parse_annotations): it decodes a
# whole column of wire strings in a handful of vectorized passes over one
# concatenated byte buffer, element-for-element identical to
# ``decode_annotation_or_missing``. The store's bulk ingest used to call
# the per-string decoder |nodes| x |metrics| times per refresh when the
# native library was unavailable; that Python loop dominated 50k-node
# cold refreshes.

_COMMA = 0x2C
_DOT = 0x2E
_ZERO = 0x30
_NINE = 0x39
_TS_LEN = 20  # canonical "YYYY-MM-DDTHH:MM:SSZ"
# 10^k exactly representable in int64/f64 for k <= 15: a plain decimal
# with <= 15 digits is (digits / 10^frac) with BOTH operands exact, so
# one IEEE division yields the correctly-rounded value — bit-identical
# to float(s) and Go's strconv.ParseFloat.
_MAX_FAST_DIGITS = 15
_POW10_F64 = np.power(
    10, np.arange(_MAX_FAST_DIGITS + 1), dtype=np.int64
).astype(np.float64)


def _bulk_decode_fallback(strs, values: np.ndarray, ts_out: np.ndarray) -> None:
    for i, s in enumerate(strs):
        if not s:
            continue
        v, t = decode_annotation(s)
        if v is None or t is None:
            continue
        values[i], ts_out[i] = v, t


def bulk_decode_annotations(raws) -> tuple[np.ndarray, np.ndarray]:
    """Decode a batch of ``"value,timestamp"`` strings (entries may be
    ``None``) into ``(values[n], ts[n])`` float64 arrays with the
    fail-open encoding: structurally invalid -> ``(nan, -inf)``; a value
    that parsed to NaN keeps its real timestamp.

    Bit-for-bit identical to ``decode_annotation_or_missing`` per entry:
    timestamps are parsed by ``parse_local_time`` itself (once per
    DISTINCT 20-byte timestamp — an annotator sweep repeats a handful of
    sync times across the whole cluster), and values take a vectorized
    exact-division fast path for plain unsigned decimals (<= 15 digits),
    falling back to ``go_parse_float`` per entry for everything else
    (signs, exponents, specials, over-long digit runs).
    """
    n = len(raws)
    values = np.full((n,), np.nan, dtype=np.float64)
    ts_out = np.full((n,), -np.inf, dtype=np.float64)
    if n == 0:
        return values, ts_out
    strs = [r if isinstance(r, str) else "" for r in raws]
    joined = "".join(strs)
    buffer = joined.encode("utf-8", "replace")
    if len(buffer) != len(joined):
        # non-ASCII input: byte offsets diverge from char offsets — rare
        # (never produced by our encoder); decode per entry, exactly
        _bulk_decode_fallback(strs, values, ts_out)
        return values, ts_out
    b = np.frombuffer(buffer, dtype=np.uint8)
    lens = np.fromiter(map(len, strs), dtype=np.int64, count=n)
    offsets = np.zeros((n + 1,), dtype=np.int64)
    np.cumsum(lens, out=offsets[1:])
    starts, ends = offsets[:-1], offsets[1:]

    # structural gate: the split on "," must yield exactly two parts
    commas = np.flatnonzero(b == _COMMA)
    if not commas.size:
        return values, ts_out
    owner = np.searchsorted(offsets, commas, side="right") - 1
    ccount = np.bincount(owner, minlength=n)[:n]
    ok = ccount == 1
    if not ok.any():
        return values, ts_out
    cpos = np.zeros((n,), dtype=np.int64)
    cpos[owner] = commas  # multi-comma rows are excluded by ``ok``

    # timestamp part. Canonical 20-byte stamps ("YYYY-MM-DDTHH:MM:SSZ")
    # are keyed by their 14 digits packed into one int64 (the punctuation
    # positions are fixed, so equal key + valid punctuation == identical
    # bytes); each DISTINCT stamp is parsed once by the exact per-string
    # parser (zone rules, strptime validity and all) and broadcast back.
    # An annotator sweep repeats a handful of sync times cluster-wide, so
    # this is O(distinct) Python work. A 20-char string failing the
    # digit/punctuation layout cannot parse under the strptime format
    # (every field is at its maximum width exactly when the total length
    # is 20), so those are -inf with no fallback needed; non-20 lengths
    # (exotic short-field strptime forms) parse per entry.
    tstart = cpos + 1
    tlen = ends - tstart
    canon = np.flatnonzero(ok & (tlen == _TS_LEN))
    if canon.size:
        cstart = tstart[canon]

        def at(j):
            return b[cstart + j]

        punct_ok = (
            (at(4) == 0x2D) & (at(7) == 0x2D) & (at(10) == 0x54)
            & (at(13) == 0x3A) & (at(16) == 0x3A) & (at(19) == 0x5A)
        )
        key = np.zeros(canon.size, dtype=np.int64)
        digits_ok = punct_ok
        for j in (0, 1, 2, 3, 5, 6, 8, 9, 11, 12, 14, 15, 17, 18):
            byte = at(j)
            digits_ok = digits_ok & (byte >= _ZERO) & (byte <= _NINE)
            key = key * 10 + (byte - _ZERO)
        kidx = np.flatnonzero(digits_ok)
        if kidx.size:
            uniq, first, inverse = np.unique(
                key[kidx], return_index=True, return_inverse=True
            )
            uts = np.empty((uniq.size,), dtype=np.float64)
            for j in range(uniq.size):
                s0 = int(cstart[kidx[first[j]]])
                t = parse_local_time(joined[s0:s0 + _TS_LEN])
                uts[j] = -np.inf if t is None else t
            ts_out[canon[kidx]] = uts[inverse]
    for i in np.flatnonzero(ok & (tlen != _TS_LEN)):
        t = parse_local_time(joined[tstart[i]:ends[i]])
        if t is not None:
            ts_out[i] = t
    tsok = ok & ~np.isneginf(ts_out)
    if not tsok.any():
        return values, ts_out

    # value part fast path: unsigned plain decimals, parsed by exact
    # left-to-right integer accumulation + one division (see
    # _MAX_FAST_DIGITS). One [k] gather per character position (value
    # strings are short); everything else (signs, exponents, specials,
    # over-long digit runs) falls back to the exact per-string parser.
    vlen = cpos - starts
    cand = np.flatnonzero(tsok & (vlen > 0) & (vlen <= _MAX_FAST_DIGITS + 1))
    fast_ok = np.zeros((n,), dtype=bool)
    if cand.size:
        cs, ce = starts[cand], cpos[cand]
        width = int(vlen[cand].max())
        num = np.zeros(cand.size, dtype=np.int64)
        ndig = np.zeros(cand.size, dtype=np.int64)
        ndot = np.zeros(cand.size, dtype=np.int64)
        frac = np.zeros(cand.size, dtype=np.int64)
        seen_dot = np.zeros(cand.size, dtype=bool)
        for j in range(width):
            pos = cs + j
            inreg = pos < ce
            byte = b[np.minimum(pos, b.size - 1)]
            isd = inreg & (byte >= _ZERO) & (byte <= _NINE)
            isp = inreg & (byte == _DOT)
            num = np.where(isd, num * 10 + (byte - _ZERO), num)
            ndig += isd
            ndot += isp
            seen_dot |= isp
            frac += isd & seen_dot
        good = (
            (ndig >= 1) & (ndig <= _MAX_FAST_DIGITS) & (ndot <= 1)
            & (ndig + ndot == vlen[cand])
        )
        gidx = cand[good]
        values[gidx] = num[good].astype(np.float64) / _POW10_F64[frac[good]]
        fast_ok[gidx] = True
    for i in np.flatnonzero(tsok & ~fast_ok):
        v = go_parse_float(joined[starts[i]:cpos[i]])
        if v is None:
            ts_out[i] = -np.inf  # unparseable value == structurally invalid
        else:
            values[i] = v
    return values, ts_out
