"""Annotation wire codec: ``"<float>,<localtime>"``.

The data-plane contract between the annotator and the scorer is a node
annotation map ``metricName -> "floatValue,timestamp"``
(written at ref: pkg/controller/annotator/node.go:123-146, parsed at
ref: pkg/plugins/dynamic/stats.go:51-76). This module reproduces both ends:

- encode: value rendered by the metrics source (5-decimal fixed for
  Prometheus, ref: pkg/controller/prometheus/prometheus.go:124) or a bare
  integer for hot values (ref: node.go:113-121), joined with the quirky
  local-time timestamp.
- decode: split on "," requiring exactly two parts; timestamp parsed
  separately from value so staleness can be evaluated at read time with a
  caller-supplied ``now``.
"""

from __future__ import annotations

import math
import re

from ..utils.timeutil import format_local_time, parse_local_time

# Go 1.13+ numeric literal syntax: underscores may appear between digits
# of any group ("1_000.5", "1e1_0"); hex floats need a mandatory p-exponent.
_D = r"\d(?:_?\d)*"
_H = r"[0-9a-fA-F](?:_?[0-9a-fA-F])*"
_GO_FLOAT_RE = re.compile(
    rf"^[+-]?(?:{_D}(?:\.(?:{_D})?)?|\.{_D})(?:[eE][+-]?{_D})?$"
)
_GO_HEX_RE = re.compile(rf"^[+-]?0[xX](?:{_H}(?:\.(?:{_H})?)?|\.{_H})[pP][+-]?{_D}$")
_GO_SPECIAL_RE = re.compile(r"^[+-]?(inf(inity)?|nan)$", re.IGNORECASE)


def go_parse_float(s: str) -> float | None:
    """``strconv.ParseFloat(s, 64)`` equivalent; None on parse failure.

    Accepts decimal/exponent forms (with Go 1.13 underscore grouping),
    hex floats with p-exponent, and inf/infinity/nan (any case, optional
    sign). Rejects leading/trailing whitespace and malformed underscores,
    as Go does.
    """
    if not isinstance(s, str):
        return None
    # fast path: plain ASCII unsigned decimal (the Prometheus 5-decimal
    # rendering, by far the common case) — digits with at most one dot
    # is accepted identically by Go and float(); everything else (signs,
    # exponents, underscores, unicode digits, whitespace) falls through
    # to the exact-semantics matchers
    if s.isascii() and s.replace(".", "", 1).isdigit():
        return float(s)
    if _GO_FLOAT_RE.match(s):
        return float(s.replace("_", ""))
    if _GO_HEX_RE.match(s):
        return float.fromhex(s.replace("_", ""))
    if _GO_SPECIAL_RE.match(s):
        return float(s)
    return None


def format_metric_value(value: float) -> str:
    """Prometheus-side value serialization: 5-decimal fixed notation
    (ref: prometheus.go:124 ``strconv.FormatFloat(v, 'f', 5, 64)``)."""
    if math.isnan(value):
        return "NaN"
    if math.isinf(value):
        return "+Inf" if value > 0 else "-Inf"
    return f"{value:.5f}"


def encode_annotation(value_str: str, epoch_seconds: float | None = None) -> str:
    """``value + "," + localTime`` (ref: node.go:142)."""
    return f"{value_str},{format_local_time(epoch_seconds)}"


def decode_annotation(raw: str) -> tuple[float | None, float | None]:
    """Decode to ``(value, ts_epoch)``; either part is None if invalid.

    Mirrors ``getResourceUsage``'s structural checks
    (ref: stats.go:51-76): the string must split on "," into exactly two
    parts; the timestamp must parse under the local-TZ layout; the value
    must parse as a float. Semantic checks (staleness, negativity) are the
    reader's job — this function only decodes.
    """
    if not isinstance(raw, str):
        return None, None
    parts = raw.split(",")
    if len(parts) != 2:
        return None, None
    value = go_parse_float(parts[0])
    ts = parse_local_time(parts[1])
    return value, ts


def decode_annotation_or_missing(raw: str) -> tuple[float, float]:
    """Decode with the store's fail-open sentinel: a structurally invalid
    annotation becomes ``(nan, -inf)`` — never fresh, so every reader
    takes the fail-open path exactly like a parse error
    (ref: stats.go:96-99). The single source of the missing-value
    sentinels for both the re-ingest and direct-write paths."""
    value, ts = decode_annotation(raw)
    if value is None or ts is None:
        return float("nan"), float("-inf")
    return value, ts
