"""Scheduler-framework core types.

A compact equivalent of the k8s scheduler framework surface the reference
plugins program against (ref: k8s.io/kubernetes/pkg/scheduler/framework):
``Status``/``Code`` verdicts, per-cycle ``CycleState``, ``NodeInfo``
snapshot entries, and the ``Resource`` accounting struct used by the NUMA
plugin (MilliCPU / Memory / EphemeralStorage / scalar resources).
"""

from __future__ import annotations

import enum
import threading
from dataclasses import dataclass, field
from typing import Any, Mapping

from ..cluster.state import Node, Pod
from ..utils.quantity import to_milli, to_value


class Code(enum.Enum):
    SUCCESS = 0
    ERROR = 1
    UNSCHEDULABLE = 2


@dataclass(frozen=True)
class Status:
    code: Code = Code.SUCCESS
    reason: str = ""

    @staticmethod
    def success() -> "Status":
        return Status(Code.SUCCESS, "")

    @staticmethod
    def error(reason: str) -> "Status":
        return Status(Code.ERROR, reason)

    @staticmethod
    def unschedulable(reason: str) -> "Status":
        return Status(Code.UNSCHEDULABLE, reason)

    def ok(self) -> bool:
        return self.code == Code.SUCCESS


class CycleState:
    """Per-scheduling-cycle key/value state (thread-safe like the
    framework's CycleState: Filter runs concurrently per node)."""

    def __init__(self):
        self._lock = threading.RLock()
        self._data: dict[str, Any] = {}

    def write(self, key: str, value: Any) -> None:
        with self._lock:
            self._data[key] = value

    def read(self, key: str) -> Any:
        with self._lock:
            if key not in self._data:
                raise KeyError(key)
            return self._data[key]

    def lock(self):
        return self._lock


@dataclass
class NodeInfo:
    """Informer-snapshot entry: a node plus the pods placed on it."""

    node: Node | None
    pods: list[Pod] = field(default_factory=list)


CPU = "cpu"
MEMORY = "memory"
EPHEMERAL_STORAGE = "ephemeral-storage"
PODS = "pods"
_HUGEPAGES_PREFIX = "hugepages-"


@dataclass
class Resource:
    """ref: k8s framework.Resource — integer accounting units:
    millicores for CPU, whole units (bytes) otherwise."""

    milli_cpu: int = 0
    memory: int = 0
    ephemeral_storage: int = 0
    allowed_pod_number: int = 0
    scalar_resources: dict[str, int] = field(default_factory=dict)

    def clone(self) -> "Resource":
        return Resource(
            self.milli_cpu,
            self.memory,
            self.ephemeral_storage,
            self.allowed_pod_number,
            dict(self.scalar_resources),
        )

    def add(self, resource_list: Mapping[str, Any]) -> None:
        """Accumulate a ResourceList (name -> quantity)."""
        for name, quantity in (resource_list or {}).items():
            if name == CPU:
                self.milli_cpu += to_milli(quantity)
            elif name == MEMORY:
                self.memory += to_value(quantity)
            elif name == EPHEMERAL_STORAGE:
                self.ephemeral_storage += to_value(quantity)
            elif name == PODS:
                self.allowed_pod_number += to_value(quantity)
            else:
                self.scalar_resources[name] = self.scalar_resources.get(
                    name, 0
                ) + to_value(quantity)


def resource_from_requests(resource_list: Mapping[str, Any] | None) -> Resource:
    r = Resource()
    if resource_list:
        r.add(resource_list)
    return r


def pod_effective_request(pod: Pod) -> Resource:
    """Sum of container requests (init containers not modeled)."""
    r = Resource()
    for c in pod.containers:
        r.add(c.resources.requests)
    return r


def is_hugepage_resource(name: str) -> bool:
    return name.startswith(_HUGEPAGES_PREFIX)
