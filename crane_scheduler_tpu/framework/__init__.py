from .types import (
    Code,
    Status,
    CycleState,
    NodeInfo,
    Resource,
    resource_from_requests,
    pod_effective_request,
)

__all__ = [
    "Code",
    "Status",
    "CycleState",
    "NodeInfo",
    "Resource",
    "resource_from_requests",
    "pod_effective_request",
]
