"""Sharded placement plane: N drip schedulers over node shards.

One scheduler over 250k nodes pays O(cluster) per column rebuild and
serializes every bind through one loop. This module splits the node
keyspace into ``count`` deterministic shards (``cluster.shards``) and
runs one unmodified ``framework.Scheduler`` per shard over a
``ShardView`` of the cluster mirror — the view narrows ``list_nodes()``
to the shard's nodes and swaps the version properties for the mirror's
per-shard watch fences (``ClusterState.configure_shards``), so each
scheduler's drip columns are 1/N-sized, rebuild only when ITS shard is
dirtied, and its snapshot cache survives the other schedulers' binds.

Concurrency is optimistic, Omega/Agon-style (arxiv 2109.00665):
schedulers place over possibly-stale shared state and validate at
commit. Two mechanisms:

* **Pod claims** (``BindArbiter``): an atomic first-writer-wins claim
  per pod key taken BEFORE the binding POST. Whatever pod sets two
  schedulers race for (overlapping queues, requeues, recovery replays),
  exactly one POST ever leaves the process — the stub's per-pod
  ``bind_posts == 1`` oracle is enforced here, not hoped for.
* **Version-stamp windows**: the dispatch window re-reads its shard's
  pod_version fence after the kernel and before the POSTs
  (``Scheduler.conflict_retry``). A competing binder moving a co-owned
  node (overlapping shards) bumps the fence of every observing shard,
  the window detects the mismatch against the fit-column stamp — the
  same pre -> pre+1 discipline the single-scheduler fold path already
  uses — and drops-and-retries the pods at queue position over rebuilt
  columns.

Placement is restricted to the shard's own nodes (the view filters
them); the documented tradeoff (doc/sharding.md) is that a pod handed
to shard i is placed on the best node IN shard i, not the global best.
Disjoint shards maximize throughput; overlap trades conflict retries
for a wider choice of nodes on the boundary.

Conflicts are counted per outcome in ``crane_shard_conflicts_total``:
``stale_window`` (fence moved pre-POST, window retried), ``claim_lost``
(another scheduler claimed the pod first; no POST), ``bind_failed``
(claim released after a failed write so the pod stays bindable).
"""

from __future__ import annotations

import threading

from ..cluster.shards import RingRebalancer, ShardSpec

__all__ = ["BindArbiter", "ShardView", "ShardedPlacementPlane"]


class BindArbiter:
    """Atomic per-pod bind claims shared by every scheduler in the
    plane. ``claim`` is first-writer-wins and idempotent for the
    holder; ``release`` returns the pod to the pool (failed POST)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._claims: dict[str, int] = {}
        self.contested = 0  # claim() calls that lost

    def claim(self, pod_key: str, owner: int) -> bool:
        with self._lock:
            cur = self._claims.setdefault(pod_key, owner)
            if cur == owner:
                return True
            self.contested += 1
            return False

    def release(self, pod_key: str, owner: int) -> None:
        with self._lock:
            if self._claims.get(pod_key) == owner:
                del self._claims[pod_key]

    def holder(self, pod_key: str) -> int | None:
        with self._lock:
            return self._claims.get(pod_key)

    def __len__(self) -> int:
        with self._lock:
            return len(self._claims)


class ShardView:
    """One shard's window onto a cluster mirror (ClusterState or
    KubeClusterClient): per-shard version fences, shard-filtered
    ``list_nodes``, claim-guarded binds. Everything else delegates to
    the wrapped cluster, so ``Scheduler``, ``DripColumns`` and
    ``FitTracker`` run over a view unchanged."""

    def __init__(self, cluster, spec: ShardSpec, arbiter: BindArbiter | None = None,
                 conflict_cb=None, bind_cb=None):
        self._inner = cluster
        self.spec = spec
        self._arbiter = arbiter
        self._nodes_cache: tuple[int, list] | None = None
        self._member: set[str] | None = None  # observed names (live set)
        self._member_key = None  # (node_set_version, ring version) at rehash
        self._pos: dict[str, int] | None = None  # name -> row (lazy)
        self.rehashes = 0  # full crc refilters (regression gate)
        self.incremental_refreshes = 0  # journal-driven cache patches
        self.conflicts: dict[str, int] = {}
        self._conflict_cb = conflict_cb
        self._bind_cb = bind_cb

    # -- per-shard fences --------------------------------------------------

    @property
    def sched_version(self) -> int:
        return self._inner.shard_versions(self.spec.index)[0]

    @property
    def pod_version(self) -> int:
        return self._inner.shard_versions(self.spec.index)[1]

    @property
    def node_version(self) -> int:
        return self._inner.shard_versions(self.spec.index)[2]

    @property
    def node_set_version(self) -> int:
        # membership-vs-annotation granularity is not tracked per shard;
        # the node fence is a safe (conservative) stand-in
        return self._inner.shard_versions(self.spec.index)[2]

    # -- shard-filtered reads ----------------------------------------------

    def dirty_nodes_since(self, version: int):
        """This shard's dirty-name journal tail (see
        ``ClusterState.dirty_nodes_since``); ``version`` is a value of
        THIS view's node fence."""
        return self._inner.dirty_nodes_since(version, self.spec.index)

    def has_node(self, name: str) -> bool:
        """Observed by this shard AND present in the mirror — the
        membership test the dirty-journal consumers classify against
        (ring ownership is read live, so a reshard moves the answer)."""
        return self.spec.observes(name) and self._inner.has_node(name)

    def list_nodes(self):
        """The shard's nodes, cached on the shard node fence.

        A fence move covered by the dirty-name journal patches the
        CACHED list in place — replace dirty rows, drop names the ring
        no longer routes here, append (sorted) names it now does — so a
        named write costs O(dirty) and a reshard costs O(moved), not a
        relist plus an O(cluster) crc refilter. The full rehash runs
        only when the journal can't localize the change (bulk relist,
        overrun) and is counted in ``rehashes``. Callers get the live
        list object, same as ``ClusterState.list_nodes`` returning its
        own fresh materialization each call."""
        ver = self.node_version
        cached = self._nodes_cache
        if cached is not None and cached[0] == ver:
            return cached[1]
        if cached is not None and self._member is not None:
            dirty = self.dirty_nodes_since(cached[0])
            if dirty is not None and self._patch_cache(cached[1], dirty):
                self._nodes_cache = (ver, cached[1])
                self._member_key = self._live_member_key()
                self.incremental_refreshes += 1
                return cached[1]
        inner_nodes = self._inner.list_nodes()
        # membership is a pure function of the node NAME: on a journal
        # miss the O(cluster) crc rehash reruns only when the node set
        # or the ring actually moved; an annotation-only bulk sweep
        # reuses the member set and pays one set-membership pass
        key = self._live_member_key()
        member = self._member
        if member is None or key != self._member_key:
            observes = self.spec.observes
            member = {n.name for n in inner_nodes if observes(n.name)}
            self._member = member
            self._member_key = key
            self.rehashes += 1
        nodes = [n for n in inner_nodes if n.name in member]
        self._nodes_cache = (ver, nodes)
        self._pos = None
        return nodes

    def _live_member_key(self):
        lay = self.spec.layout
        return (self._inner.node_set_version,
                lay.version if lay is not None else 0)

    def _pos_map(self, nodes) -> dict[str, int]:
        pos = self._pos
        if pos is None:
            pos = self._pos = {n.name: i for i, n in enumerate(nodes)}
        return pos

    def _patch_cache(self, nodes, dirty) -> bool:
        """Apply a covered journal interval to the cached node list in
        place; returns False when the delta is inconsistent and the
        caller must refilter."""
        touched, membership = dirty
        if not touched:
            return True
        member = self._member
        get_node = self._inner.get_node
        if not membership:
            pos = self._pos_map(nodes)
            for nm in touched:
                if nm not in member:
                    continue  # co-owner churn outside this shard's slice
                i = pos.get(nm)
                node = get_node(nm)
                if i is None or node is None:
                    return False  # membership drifted without the flag
                nodes[i] = node
            return True
        observes = self.spec.observes
        adds: list = []
        remove_rows: list[int] = []
        pos = self._pos_map(nodes)
        for nm in touched:
            node = get_node(nm)
            present = node is not None and observes(nm)
            if present and nm not in member:
                adds.append(node)
            elif not present and nm in member:
                i = pos.get(nm)
                if i is None:
                    return False
                remove_rows.append(i)
                member.discard(nm)
            elif present:
                i = pos.get(nm)
                if i is None:
                    return False
                nodes[i] = node
        for i in sorted(remove_rows, reverse=True):
            del nodes[i]
        # sorted appends: the same splice discipline DripColumns uses,
        # so view order and column order stay in lockstep across moves
        adds.sort(key=lambda n: n.name)
        for node in adds:
            member.add(node.name)
            nodes.append(node)
        if adds or remove_rows:
            self._pos = None
        return True

    # -- claim-guarded writes ----------------------------------------------

    def note_conflict(self, outcome: str) -> None:
        self.conflicts[outcome] = self.conflicts.get(outcome, 0) + 1
        if self._conflict_cb is not None:
            self._conflict_cb(outcome)

    def bind_pod(self, pod_key: str, node_name: str, now: float | None = None) -> bool:
        arb = self._arbiter
        if arb is not None and not arb.claim(pod_key, self.spec.index):
            self.note_conflict("claim_lost")
            return False
        ok = self._inner.bind_pod(pod_key, node_name, now)
        if ok:
            if self._bind_cb is not None:
                self._bind_cb(1)
        elif arb is not None:
            arb.release(pod_key, self.spec.index)
            self.note_conflict("bind_failed")
        return ok

    def bind_pods(self, assignments, now: float | None = None):
        assignments = list(assignments)
        arb = self._arbiter
        if arb is None:
            bound = self._inner.bind_pods(assignments, now)
            if bound and self._bind_cb is not None:
                self._bind_cb(len(bound))
            return bound
        mine = []
        for key, node in assignments:
            if arb.claim(key, self.spec.index):
                mine.append((key, node))
            else:
                self.note_conflict("claim_lost")
        if not mine:
            return []
        bound = self._inner.bind_pods(mine, now)
        if len(bound) < len(mine):
            ok = set(bound)
            for key, _node in mine:
                if key not in ok:
                    arb.release(key, self.spec.index)
                    self.note_conflict("bind_failed")
        if bound and self._bind_cb is not None:
            self._bind_cb(len(bound))
        return bound

    def __getattr__(self, name):
        return getattr(self._inner, name)


class ShardedPlacementPlane:
    """Owner of the N-scheduler arrangement: configures the mirror's
    per-shard fences, builds the views and the shared bind arbiter,
    wires conflict telemetry, and (optionally) runs a threaded storm.

    ``factory(view)`` must return a fully-registered ``Scheduler`` over
    the given view (the plane flips ``conflict_retry`` on and wires
    ``conflict_cb`` afterwards); plugin sets are the caller's business.
    """

    def __init__(self, cluster, count: int, overlap: float = 0.0,
                 telemetry=None, mesh=None, layout=None):
        if count < 1:
            raise ValueError(f"scheduler count must be >= 1, got {count}")
        cluster.configure_shards(count, overlap, layout=layout)
        self.cluster = cluster
        self.count = count
        self.overlap = overlap
        self.layout = layout
        self.mesh = mesh
        self.arbiter = BindArbiter()
        self._telemetry = telemetry
        self._m_conflicts = None
        self._m_binds = None
        self._m_overruns = None
        self._overruns_seen = 0
        self._m_resharded = None
        if telemetry is not None:
            reg = telemetry.registry
            self._m_conflicts = reg.counter(
                "crane_shard_conflicts_total",
                "Optimistic bind conflicts across the shard plane",
                ("outcome",),
            )
            self._m_binds = reg.counter(
                "crane_shard_binds_total",
                "Accepted binds per shard",
                ("shard",),
            )
            reg.gauge(
                "crane_shard_schedulers",
                "Configured scheduler count in the shard plane",
            ).set(count)
            self._g_nodes = reg.gauge(
                "crane_shard_nodes",
                "Nodes observed per shard",
                ("shard",),
            )
            self._m_overruns = reg.counter(
                "crane_dirty_journal_overruns_total",
                "Dirty-name journal evictions forcing an identity sweep",
            )
            self._g_journal_depth = reg.gauge(
                "crane_dirty_journal_depth",
                "Entries currently buffered in the global dirty-name journal",
            )
            self._m_resharded = reg.counter(
                "crane_reshard_moved_names_total",
                "Node names migrated between shards by ring repartitions",
            )
        self.views = [
            ShardView(
                cluster,
                ShardSpec(i, count, overlap, layout=layout),
                self.arbiter,
                conflict_cb=self._conflict_noter(),
                bind_cb=self._bind_noter(i),
            )
            for i in range(count)
        ]
        self.schedulers: list = []

    def _conflict_noter(self):
        m = self._m_conflicts
        if m is None:
            return None
        return lambda outcome: m.labels(outcome=outcome).inc()

    def _bind_noter(self, index: int):
        m = self._m_binds
        if m is None:
            return None
        lab = m.labels(shard=str(index))
        return lambda n: lab.inc(n)

    def add_scheduler(self, factory):
        """Build one scheduler per shard via ``factory(view)`` (call
        once; returns the scheduler list)."""
        for view in self.views:
            sched = factory(view)
            sched.conflict_retry = True
            sched.conflict_cb = view.note_conflict
            if self.mesh is not None:
                sched._kernel_mesh = self.mesh
            self.schedulers.append(sched)
        return self.schedulers

    def refresh_node_gauges(self) -> None:
        if self._telemetry is None:
            return
        for view in self.views:
            self._g_nodes.labels(shard=str(view.spec.index)).set(
                len(view.list_nodes())
            )
        stats = getattr(self.cluster, "dirty_journal_stats", None)
        if stats is not None:
            s = stats()
            self._g_journal_depth.set(s["depth"])
            # counters are monotonic; the mirror reports a running
            # total, so publish only the delta since the last refresh
            new = s["overruns"] - self._overruns_seen
            if new > 0:
                self._m_overruns.inc(new)
                self._overruns_seen = s["overruns"]

    def reshard(self, target) -> list[str]:
        """Adopt ``target`` (a detached ring from ``with_moves``/
        ``split``/``merge``/a rebalancer plan) as the live keyspace.
        Migration cost is O(moved): only names whose owner set changed
        are journaled as membership-dirty on their old and new shards,
        and every view/column patches just those rows on its next
        refresh. Returns the moved names."""
        if self.layout is None:
            raise ValueError("plane was built without a ring layout")
        # the live ring object is shared by the mirror and every view's
        # spec; ClusterState.reshard atomically swaps its state in
        moved = self.cluster.reshard(target)
        if self._m_resharded is not None and moved:
            self._m_resharded.inc(len(moved))
        return moved

    def rebalance(self, skew: float = 0.25, max_moves: int = 8):
        """One rebalancer step against the observed per-shard node
        counts; adopts and returns the moved names, or ``None`` when
        the plane is already within the skew envelope."""
        if self.layout is None:
            raise ValueError("plane was built without a ring layout")
        load = {
            view.spec.index: len(view.list_nodes()) for view in self.views
        }
        plan = RingRebalancer(skew=skew, max_moves=max_moves).plan(
            self.layout, load)
        if plan is None:
            return None
        return self.reshard(plan)

    def conflict_stats(self) -> dict[str, int]:
        """Aggregate per-outcome conflict counts across all views."""
        out: dict[str, int] = {}
        for view in self.views:
            for outcome, n in view.conflicts.items():
                out[outcome] = out.get(outcome, 0) + n
        return out

    def run_storm(self, pod_lists, window: int = 32, threaded: bool = True):
        """Drive every scheduler's ``schedule_queue`` over its pod list
        (``pod_lists[i]`` goes to shard i). Threaded by default — the
        point is concurrent binders racing through the arbiter and the
        version fences; pass ``threaded=False`` for deterministic
        debugging. Returns the per-shard result lists."""
        if len(pod_lists) != len(self.schedulers):
            raise ValueError(
                f"{len(pod_lists)} pod lists for {len(self.schedulers)} schedulers"
            )
        results: list = [None] * len(self.schedulers)
        if not threaded:
            for i, (sched, pods) in enumerate(zip(self.schedulers, pod_lists)):
                results[i] = sched.schedule_queue(pods, window=window)
            return results
        errors: list = []

        def run(i, sched, pods):
            try:
                results[i] = sched.schedule_queue(pods, window=window)
            except BaseException as e:  # surfaced after join
                errors.append((i, e))

        threads = [
            threading.Thread(target=run, args=(i, s, p), daemon=True)
            for i, (s, p) in enumerate(zip(self.schedulers, pod_lists))
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[0][1]
        return results
